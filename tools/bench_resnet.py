#!/usr/bin/env python
"""ResNet-50 @ 224² on the real chip — the bn-vs-nf byte-reduction A/B.

Round-4 verdict #6: the roofline retired the Pallas-kernel path (76.5 %
of step time bandwidth-bound at 86 % of the HBM roof ⇒ ~35 % MFU ceiling
for BatchNorm semantics) and named "BN-free variants" as the only lever
that moves fewer bytes. This benchmark measures that lever:
``--resnet_norm nf`` (scaled weight standardization + SkipInit,
models/resnet.py) against the BN baseline on identical geometry.

Method matches the ladder rows (BASELINE.md): synthetic ImageNet-shaped
uint8 records resident in HBM, in-scan device decode, K-step chunk,
bf16 compute, 3 timed repetitions with min/median/max.

Usage: python tools/bench_resnet.py [--batch 256] [--k 20] [--chunks 6]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(norm: str, batch: int, k: int, chunks: int, reps: int,
            depth: int = 50, hw: int = 224, classes: int = 1000,
            s2d: bool = False) -> dict:
    import jax
    import numpy as np

    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            OptimConfig, ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib
    from dml_cnn_cifar10_tpu.utils.profiling import (abstractify,
                                                     compiled_flops)

    name = f"resnet{depth}"
    mesh = mesh_lib.build_mesh(ParallelConfig())
    model_cfg = ModelConfig(name=name, logit_relu=False,
                            compute_dtype="bfloat16", num_classes=classes,
                            resnet_norm=norm, resnet_s2d=s2d, remat=False)
    data_cfg = DataConfig(image_height=hw, image_width=hw, crop_height=hw,
                          crop_width=hw, num_classes=classes,
                          normalize="scale")
    optim_cfg = OptimConfig(learning_rate=0.1)
    model_def = get_model(name)

    # Persistent compile cache, shared with bench.py's dir convention:
    # re-runs skip recompiles where the platform allows and the FLOPs
    # probe below reads the entry's cost analysis instead of paying a
    # second AOT compile.
    from bench import _bench_cache_dir
    from dml_cnn_cifar10_tpu.compilecache import CompileCache
    cache = (CompileCache(_bench_cache_dir())
             if _bench_cache_dir() else None)

    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg,
                                        data_cfg, optim_cfg)
    state = step_lib.init_train_state(jax.random.key(0), model_def,
                                      model_cfg, data_cfg, optim_cfg, mesh,
                                      state_sharding=sh,
                                      compile_cache=cache)

    # Synthetic uint8 dataset resident in HBM (2 batches worth — the
    # gather indexes modulo n), decoded in-scan (the >1 GB rule).
    rng = np.random.default_rng(0)
    n = 2 * batch
    imgs = rng.integers(0, 256, (n, hw, hw, 3), dtype=np.uint8)
    lbls = rng.integers(0, classes, n).astype(np.int32)
    repl = mesh_lib.replicated(mesh)
    ds_images = jax.device_put(imgs, repl)
    ds_labels = jax.device_put(lbls, repl)
    chunk = step_lib.make_train_chunk_resident(
        model_def, model_cfg, optim_cfg, mesh, ds_images, ds_labels,
        state_sharding=sh, data_cfg=data_cfg,
        index_stream=(0, batch, k), compile_cache=cache)

    state, metrics = chunk(state)
    float(jax.device_get(metrics["loss"]))          # compile + drain
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(chunks):
            state, metrics = chunk(state)
        float(jax.device_get(metrics["loss"]))
        dt = time.perf_counter() - t0
        rates.append(chunks * k * batch / dt)
    med = statistics.median(rates)

    row = {
        "norm": norm,
        "img_s_median": round(med, 1),
        "img_s_min": round(min(rates), 1),
        "img_s_max": round(max(rates), 1),
        "reps": reps,
    }
    # FLOPs from the SCAN-FREE single step (the bench.py convention —
    # exact, no scan-body accounting assumption).
    train_step = step_lib.make_train_step(model_def, model_cfg, optim_cfg,
                                          mesh, state_sharding=sh,
                                          compile_cache=cache)
    img_abs = jax.ShapeDtypeStruct((batch, hw, hw, 3), np.float32)
    lab_abs = jax.ShapeDtypeStruct((batch,), np.int32)
    flops = compiled_flops(train_step,
                           (abstractify(state), img_abs, lab_abs))
    if flops:
        tflops = flops * (med / batch) / 1e12
        row["tflops_per_sec"] = round(tflops, 2)
        # Peak from the chip the bench actually ran on (bench.py's
        # device-kind lookup, BENCH_PEAK_TFLOPS overridable) — not a
        # hardcoded v5e constant.
        from bench import _peak_tflops
        peak = _peak_tflops(jax.devices()[0].device_kind)
        if peak:
            row["peak_tflops"] = peak
            row["mfu"] = round(tflops / peak, 4)
    return row


def main():
    # Before any jax backend use (see compilecache.arm_native_cache).
    from bench import _bench_cache_dir
    from dml_cnn_cifar10_tpu.compilecache import arm_native_cache
    arm_native_cache(_bench_cache_dir() or None)
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--k", type=int, default=20)
    ap.add_argument("--chunks", type=int, default=6)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--norms", type=str, nargs="+", default=["bn", "nf"])
    args = ap.parse_args()
    for norm in args.norms:
        row = measure(norm, args.batch, args.k, args.chunks, args.reps)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
