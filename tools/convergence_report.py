#!/usr/bin/env python
"""Generate docs/CONVERGENCE.md tables from metrics JSONL files.

The convergence study (SURVEY §7 hard part (b): async-PS vs sync
semantics) is driven by real training runs whose ``--metrics_jsonl``
streams are the source of truth; this tool turns those streams into the
markdown tables so the document is regenerable, not hand-typed.

Usage:
  python tools/convergence_report.py \
      --faithful PATH --faithful-early PATH \
      --group "LR 0.1:S=0=PATH,S=2=PATH,..." \
      --group "LR 0.02:..." > docs/CONVERGENCE_tables.md

Each table cell is the train loss at the given step ("NaN" when the run
went non-finite) and the sequence of full-split eval accuracies.
"""

from __future__ import annotations

import argparse
import json
import sys


def _rows(path: str, kind: str):
    out = []
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == kind:
                out.append(r)
    return out


def _f(x, nd=5):
    if x is None:
        return "NaN"
    return f"{x:.{nd}f}" if abs(x) < 1e4 else f"{x:.3g}"


def faithful_tables(path: str, early_path: str) -> str:
    tr = _rows(path, "train")
    early = _rows(early_path, "train")
    lines = ["| step | train loss | train-batch accuracy |",
             "|---|---|---|"]
    for r in early[:6]:
        lines.append(f"| {r['step']} | {_f(r['loss'])} | "
                     f"{_f(r['train_accuracy'], 3)} |")
    # The elided middle is summarized FROM the data, not asserted: only
    # claim "non-finite throughout" when every elided row really is.
    elided = tr[:-1]
    if elided and all(r["loss"] is None for r in elided):
        lines.append("| ... | non-finite at every logged step | "
                     f"chance (mean {sum(r['train_accuracy'] for r in elided) / len(elided):.3f}) |")
    else:
        for r in (tr[0], tr[len(tr) // 2]):
            lines.append(f"| {r['step']} | {_f(r['loss'])} | "
                         f"{_f(r['train_accuracy'], 3)} |")
    r = tr[-1]
    lines.append(f"| {r['step']} | {_f(r['loss'])} | "
                 f"{_f(r['train_accuracy'], 3)} |")
    return "\n".join(lines)


def staleness_table(spec: str) -> str:
    _, runs = spec.split(":", 1)  # the title is printed by main()
    steps = (100, 300, 500, 1000, 2000)
    rows = []
    cadences = set()
    for item in runs.split(","):
        label, path = item.rsplit("=", 1)  # labels may contain '='
        tr = _rows(path, "train")
        ev = _rows(path, "eval")
        if len(ev) > 1:
            cadences.add(ev[1]["step"] - ev[0]["step"])
        elif ev:
            cadences.add(ev[0]["step"])
        by_step = {r["step"]: r["loss"] for r in tr}
        # "—" = no row logged at that step (run stopped short / different
        # cadence) — NOT the same thing as a logged non-finite loss.
        cells = [_f(by_step[s]) if s in by_step else "—" for s in steps]
        accs = " → ".join("NaN" if e["test_accuracy"] is None
                          else f"{e['test_accuracy'] * 100:.2f}%"
                          for e in ev)
        rows.append(f"| {label} | " + " | ".join(cells) + f" | {accs} |")
    cadence = (f"every {cadences.pop()}" if len(cadences) == 1
               else "mixed cadence")
    return "\n".join(
        ["| staleness S | " + " | ".join(f"loss@{s}" for s in steps)
         + f" | full-split eval accuracy ({cadence}) |",
         "|---|" + "---|" * (len(steps) + 1)] + rows)


def plot_groups(groups, out_path: str) -> None:
    """Small-multiple loss curves, one panel per group (never dual-axis).

    Styling follows the dataviz method: categorical hues in fixed slot
    order (the validated default palette), thin 2 px lines, recessive
    grid, direct labels at line ends plus a legend, divergence marked
    with a text annotation (never color-alone).
    """
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    colors = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100"]  # slots 1-4
    fig, axes = plt.subplots(1, len(groups), figsize=(6.2 * len(groups), 4.2),
                             facecolor="#fcfcfb")
    if len(groups) == 1:
        axes = [axes]
    import math as _math
    for ax, spec in zip(axes, groups):
        title, runs = spec.split(":", 1)
        n_div = 0
        labeled_ends = []  # log10 of already direct-labeled end values
        for idx, item in enumerate(runs.split(",")):
            label, path = item.rsplit("=", 1)
            tr = _rows(path, "train")
            # NaN rows become gaps (matplotlib breaks the line), never
            # bridged; "diverges" is claimed only when the run ENDS
            # non-finite — a transient NaN that recovers is just a gap.
            steps = [r["step"] for r in tr]
            vals = [float("nan") if r["loss"] is None else r["loss"]
                    for r in tr]
            xs = [s for s, v in zip(steps, vals) if v == v]
            ys = [v for v in vals if v == v]
            c = colors[idx % len(colors)]
            ax.plot(steps, vals, color=c, linewidth=2, label=label)
            if tr and tr[-1]["loss"] is None:  # ends non-finite
                anchor = (xs[-1], ys[-1]) if xs else (tr[0]["step"], 20.0)
                # Name the series in the note and stagger repeats so two
                # diverging runs don't overprint each other.
                ax.annotate(f"{label}: diverges (NaN)", xy=anchor,
                            xytext=(8, -12 * n_div),
                            textcoords="offset points",
                            color="#52514e", fontsize=9, va="center")
                n_div += 1
            elif xs:
                # Direct-label only when the end value is visually clear
                # of already-labeled ends; the legend still carries
                # identity for the rest.
                end = _math.log10(max(ys[-1], 1e-12))
                if all(abs(end - e) > 0.25 for e in labeled_ends):
                    ax.annotate(label, xy=(xs[-1], ys[-1]),
                                xytext=(6, 0), textcoords="offset points",
                                color="#0b0b0b", fontsize=9, va="center")
                    labeled_ends.append(end)
        ax.set_yscale("log")
        ax.set_title(title, color="#0b0b0b", fontsize=11)
        ax.set_xlabel("step", color="#52514e")
        ax.set_ylabel("train loss (log)", color="#52514e")
        ax.grid(True, color="#e6e5e1", linewidth=0.6)
        for spine in ax.spines.values():
            spine.set_color("#c3c2b7")
        ax.tick_params(colors="#52514e")
        ax.set_facecolor("#fcfcfb")
        ax.legend(frameon=False, fontsize=9, labelcolor="#0b0b0b")
    fig.tight_layout()
    fig.savefig(out_path, dpi=130)
    print(f"wrote {out_path}", file=sys.stderr)  # stdout is the markdown


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--faithful", required=True)
    p.add_argument("--faithful-early", required=True)
    p.add_argument("--group", action="append", default=[],
                   help="'TITLE:LABEL=PATH,LABEL=PATH,...'")
    p.add_argument("--plot", default=None,
                   help="also write loss-curve small multiples (PNG), one "
                        "panel per --group")
    args = p.parse_args()
    if args.plot:
        if not args.group:
            p.error("--plot needs at least one --group to draw")
        plot_groups(args.group, args.plot)
    print("<!-- generated by tools/convergence_report.py -->")
    print("\n### Faithful trajectory (table)\n")
    print(faithful_tables(args.faithful, args.faithful_early))
    for g in args.group:
        print("\n### " + g.split(":", 1)[0] + "\n")
        print(staleness_table(g))
    return 0


if __name__ == "__main__":
    sys.exit(main())
