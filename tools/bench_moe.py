#!/usr/bin/env python
"""vit_moe on the real chip — the expert-parallelism ladder row.

Round-3 verdict #3: every other parallelism axis has a measured row;
ep was a correctness checkbox. This benchmark (a) trains ``vit_moe``
end to end on the chip and reports steady-state img/s + TF/s, (b)
sweeps capacity factor × expert count and reports the dropped-token
fraction — the routing-vs-capacity table that tells a user what
``--moe_capacity_factor`` actually buys.

TF/s uses the MoE step's ALGORITHMIC dense-equivalent flops from XLA
cost analysis of the single step (the expert einsums are dense ops of
static shape — no scan accounting involved; the ViT stack correction
applies as usual via the block probe in real Trainer runs; here depth
is small and unrolled... we report XLA's own count, honestly labeled).

Usage: python tools/bench_moe.py [--experts 2 4] [--steps 300]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bench_train(experts: int, steps: int, batch: int, capacity: float,
                dispatch: str = "einsum"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            OptimConfig, ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    mesh = mesh_lib.build_mesh(ParallelConfig())
    # remat is LOAD-BEARING here: without it the scan over blocks saves
    # each block's [T,E,C] dispatch/combine one-hots as autodiff
    # residuals — depth x T x E x capacity f32 (64 GB at batch 512,
    # E=2) — the first real run of this bench OOM'd exactly there.
    # Recomputing the block in the backward keeps only the block inputs.
    model_cfg = ModelConfig(name="vit_moe", pool="mean", logit_relu=False,
                            moe_experts=experts,
                            moe_capacity_factor=capacity,
                            compute_dtype="bfloat16", remat=True,
                            moe_dispatch=dispatch)
    data_cfg = DataConfig(crop_height=32, crop_width=32,
                          image_height=32, image_width=32)
    optim_cfg = OptimConfig(optimizer="adamw", learning_rate=1e-3)
    model_def = get_model("vit_moe")

    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg,
                                        data_cfg, optim_cfg)
    state = step_lib.init_train_state(jax.random.key(0), model_def,
                                      model_cfg, data_cfg, optim_cfg, mesh,
                                      state_sharding=sh)
    # Compile cache under bench.py's dir convention: the FLOPs probe
    # below is served from the cached entry instead of a second AOT
    # compile on re-runs.
    from bench import _bench_cache_dir
    from dml_cnn_cifar10_tpu.compilecache import CompileCache
    cache = (CompileCache(_bench_cache_dir())
             if _bench_cache_dir() else None)
    train = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh,
                                     state_sharding=sh,
                                     compile_cache=cache)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(0.5, 0.25, (batch, 32, 32, 3)),
                         jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, batch), jnp.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    # K steps per dispatch via a plain python loop with end drain (the
    # one-chip bench pattern; per-dispatch overhead amortizes over the
    # queued pipeline).
    state, metrics = train(state, im, lb)
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train(state, im, lb)
    float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    img_s = steps * batch / dt

    from dml_cnn_cifar10_tpu.utils.profiling import (abstractify,
                                                     compiled_flops)
    flops = compiled_flops(
        train, (abstractify(state), abstractify(im), abstractify(lb)))
    tf = (flops * (img_s / batch) / 1e12) if flops else None
    return {
        "experts": experts,
        "dispatch": dispatch,
        "capacity_factor": capacity,
        "images_per_sec": round(img_s, 1),
        "tflops_per_sec": round(tf, 2) if tf else None,
        "mfu_vs_197": round(tf / 197.0, 4) if tf else None,
    }


def drop_table(experts_list, capacities, tokens=8192, dim=192):
    """Dropped-token fraction of the STATIC-capacity router at a
    realistic activation distribution (unit-normal tokens through a
    fresh gate): fraction of top-1 assignments that overflow expert
    queues. The capacity trade: factor f keeps per-expert queues at
    f x (tokens/experts); overflow tokens pass through the residual
    unchanged (ops/moe.py docstring).

    Reads the LAYER'S OWN router stats (``moe_mlp``'s second return) —
    the numbers here are by construction the ones a Trainer run logs;
    there is no reimplemented dispatch twin to drift (round-4 verdict
    #1). ``tests/test_moe.py::test_drop_table_matches_layer_stats``
    pins this."""
    import jax
    import jax.numpy as jnp

    from dml_cnn_cifar10_tpu.ops import moe as moe_ops

    rows = []
    for e in experts_list:
        for cf in capacities:
            key = jax.random.PRNGKey(e * 31 + 1)
            params = moe_ops.init_moe_params(key, dim, 4 * dim, e)
            x = jax.random.normal(jax.random.PRNGKey(7),
                                  (8, tokens // 8, dim), jnp.float32)
            _, stats = moe_ops.moe_mlp(x, params, capacity_factor=cf,
                                       top_k=1)
            rows.append({
                "experts": e, "capacity_factor": cf,
                "dropped_frac": round(float(stats["dropped_frac"]), 4),
                "max_expert_load": round(
                    float(jnp.max(stats["expert_load"])), 4),
            })
    return rows


def main():
    # Before any jax backend use (see compilecache.arm_native_cache).
    from bench import _bench_cache_dir
    from dml_cnn_cifar10_tpu.compilecache import arm_native_cache
    arm_native_cache(_bench_cache_dir() or None)
    ap = argparse.ArgumentParser()
    ap.add_argument("--experts", type=int, nargs="+", default=[2, 4])
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--capacity", type=float, default=1.25)
    ap.add_argument("--skip-train", action="store_true")
    ap.add_argument("--dispatch", type=str, nargs="+",
                    default=["einsum", "scatter"])
    args = ap.parse_args()

    if not args.skip_train:
        for e in args.experts:
            for disp in args.dispatch:
                row = bench_train(e, args.steps, args.batch, args.capacity,
                                  dispatch=disp)
                print("train:", row, flush=True)

    print("\ndrop-rate vs capacity factor (fresh router, unit-normal "
          "tokens):")
    for row in drop_table(args.experts, [1.0, 1.25, 1.5, 2.0]):
        print("  ", row)


if __name__ == "__main__":
    main()
