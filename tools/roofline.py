#!/usr/bin/env python
"""Per-op roofline table from an xplane trace.

For every XLA op (fusion/conv/custom-call) in the profiled program:
device time share, achieved TFLOP/s, HBM bytes, arithmetic intensity
(flops/byte), and the roofline verdict at the chip's ridge point —
``compute-bound`` when intensity clears peak_flops/peak_bw, else
``bandwidth-bound`` with the % of peak HBM bandwidth it actually
achieved. This is the evidence table the round-3 ResNet-50 verdict asked
for: whether the remaining conv+BN fusions sit against the bandwidth
roof rather than the MXU roof.

Usage:
    python tools/roofline.py /path/to/*.xplane.pb [--peak-tflops 197]
        [--peak-gbps 819] [--top 25]

v5e defaults: 197 bf16 TFLOP/s, 819 GB/s HBM.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys


def load_ops(pb_path):
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data([pb_path], "op_profile", {})
    tree = json.loads(data.decode() if isinstance(data, bytes) else data)
    ops = []

    def walk(node, depth=0):
        m = node.get("metrics", {})
        xla = node.get("xla") or {}
        # leaves: nodes with xla info and occurrences
        if xla and m.get("occurrences"):
            ops.append({
                "name": node.get("name", "?"),
                "category": xla.get("category", "?"),
                "time_ps": m.get("rawTime", 0),
                "flops": m.get("rawFlops", 0),
                # [HBM, on-chip read, on-chip write] in the converter's
                # rawBytesAccessedArray
                "hbm_bytes": (m.get("rawBytesAccessedArray") or [0])[0],
                "occ": m.get("occurrences", 0),
            })
        for ch in node.get("children", []):
            walk(ch, depth + 1)

    walk(tree.get("byProgram", {}))
    # The tree nests op groups; leaves repeat at several levels. Keep the
    # deepest unique (name, time) rows.
    seen = {}
    for o in ops:
        key = (o["name"], o["time_ps"])
        seen[key] = o
    return list(seen.values())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("xplane", help="xplane.pb path (or glob)")
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--peak-gbps", type=float, default=819.0)
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    paths = sorted(glob.glob(args.xplane))
    if not paths:
        sys.exit(f"no xplane matches {args.xplane}")
    ops = load_ops(paths[0])
    total_ps = sum(o["time_ps"] for o in ops)
    ridge = args.peak_tflops * 1e12 / (args.peak_gbps * 1e9)  # flops/byte

    ops.sort(key=lambda o: -o["time_ps"])
    print(f"total device op time: {total_ps / 1e9:.2f} ms; ridge "
          f"intensity {ridge:.0f} flops/byte "
          f"({args.peak_tflops:.0f} TF/s / {args.peak_gbps:.0f} GB/s)\n")
    print("| % time | op | TF/s | GB/s | flops/byte | bound | % of roof |")
    print("|---|---|---|---|---|---|---|")
    for o in ops[:args.top]:
        t = o["time_ps"] / 1e12
        if t == 0:
            continue
        tf = o["flops"] / t / 1e12
        gb = o["hbm_bytes"] / t / 1e9
        inten = o["flops"] / o["hbm_bytes"] if o["hbm_bytes"] else float(
            "inf")
        if inten >= ridge:
            bound, roof = "compute", tf / args.peak_tflops
        else:
            bound, roof = "bandwidth", gb / args.peak_gbps
        name = o["name"][:48]
        print(f"| {o['time_ps'] / total_ps * 100:5.1f} | {name} | "
              f"{tf:6.1f} | {gb:6.0f} | {inten:8.1f} | {bound} | "
              f"{roof * 100:5.1f}% |")


if __name__ == "__main__":
    main()
