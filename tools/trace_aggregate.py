#!/usr/bin/env python
"""Merge the per-process JSONL streams (and Chrome traces) of a
multi-host run into ONE run-level timeline.

Cluster simulations (``parallel/cluster.py``), real multi-host jobs, and
serving fleets (``fleet/``) each scatter one ``--metrics_jsonl`` stream
(plus optional ``--trace_events_path`` Chrome traces) per process, every
stream with its OWN clock zero (``t`` is seconds since that logger
started). Post-mortems of cross-host behavior — who stalled, who
restarted whom, how far the hosts' steps skewed — need those streams on
one clock. This tool:

- recovers a per-stream unix offset from the ``heartbeat`` records'
  ``wallclock`` field (median of ``wallclock − t``), falling back to
  the ``serve``/``fleet`` window records' wallclock anchors for serving
  processes, which publish no heartbeats; streams with neither stay
  unaligned and are flagged,
- merges records onto one timeline keyed by ``(task, step)``, with a
  per-host step-skew table (first-seen wall-clock spread of each step
  observed on ≥ 2 aligned hosts) and a straggler bar view,
- collects the run's notable events (faults, peer losses, elastic
  restarts/expands, rejoins, autoscales, swaps) in aligned order,
- summarizes fleet request flow (serve windows per replica, router
  routing/eviction counters),
- optionally writes ONE merged Perfetto/Chrome trace (``--out``):
  host-loop span lanes per process (rebuilt from ``span`` records),
  instant events for the notable kinds, counter tracks for
  ``images_per_sec`` / ``device_step_ms``, request-tracing hop lanes
  rebuilt from ``rspan`` records with one Chrome flow arrow per
  ``trace_id`` linking a request's hops across processes — and, via
  ``--traces``, any per-process Chrome trace files shifted onto the
  same clock using their recorded ``epoch_unix_s``.

Usage:
  python tools/trace_aggregate.py logs_0/m.jsonl logs_1/m.jsonl \\
      [--out merged_trace.json] [--traces host0.json host1.json.task1] \\
      [--format text|json]

``tests/test_cluster.py`` runs this over the 2-process lockstep sim's
streams in tier-1 and pins that the merged per-host step counts match
the individual streams exactly.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
from typing import Dict, List, Optional

#: Record kinds surfaced on the merged event timeline.
EVENT_KINDS = ("fault", "recovery", "rollback", "peer_lost",
               "elastic_restart", "elastic_expand", "host_rejoin",
               "preempt", "numerics_halt", "scale", "swap",
               "swap_rejected", "ckpt_fallback")


def load_stream(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out


def _median(vals):
    vals = sorted(vals)
    if not vals:
        return None
    mid = len(vals) // 2
    return vals[mid] if len(vals) % 2 else (vals[mid - 1] + vals[mid]) / 2


#: Kinds whose ``wallclock`` anchors clock alignment, in preference
#: order: heartbeats when the stream has them (training / cluster),
#: else the serve/fleet window records — serving processes publish no
#: heartbeats, and without this fallback every fleet stream was flagged
#: unalignable.
ANCHOR_KINDS = (("heartbeat",), ("serve", "serve_done",
                                 "fleet", "fleet_done"))


def clock_offset(records: List[dict]) -> Optional[float]:
    """Unix seconds at this stream's ``t == 0``, recovered from the
    ``wallclock`` anchors of heartbeat records — or, for serve/fleet
    streams that have none, of their periodic window records. None when
    no anchor kind carries a wallclock."""
    for kinds in ANCHOR_KINDS:
        deltas = [r["wallclock"] - r["t"] for r in records
                  if r.get("kind") in kinds
                  and isinstance(r.get("wallclock"), (int, float))
                  and isinstance(r.get("t"), (int, float))]
        if deltas:
            return _median(deltas)
    return None


def summarize_host(path: str, records: List[dict]) -> dict:
    tasks = [r.get("task") for r in records if r.get("task") is not None]
    task = collections.Counter(tasks).most_common(1)[0][0] if tasks else 0
    kinds = collections.Counter(r.get("kind") for r in records)
    train_steps = [r.get("step") for r in records
                   if r.get("kind") == "train"]
    steps = [r.get("step") for r in records
             if isinstance(r.get("step"), int)]
    return {
        "path": path,
        "task": task,
        "records": len(records),
        "kinds": dict(kinds),
        "offset_unix": clock_offset(records),
        "train_rows": len(train_steps),
        "train_steps": train_steps,
        "last_step": max(steps) if steps else None,
        "heartbeats": kinds.get("heartbeat", 0),
    }


def aggregate(paths: List[str]) -> dict:
    """Merge streams → hosts summary, (task, step) timeline, step-skew
    table, aligned event list, fleet flow. Pure data (JSON-ready)."""
    streams = {p: load_stream(p) for p in paths}
    hosts = [summarize_host(p, recs) for p, recs in streams.items()]
    offsets = {h["path"]: h["offset_unix"] for h in hosts}
    aligned = [h for h in hosts if h["offset_unix"] is not None]
    # Wall zero: earliest aligned stream start (unaligned streams are
    # placed at 0 and flagged by offset_unix == null).
    wall0 = min((h["offset_unix"] for h in aligned), default=0.0)

    def wall(path, t):
        off = offsets.get(path)
        return round(((off - wall0) if off is not None else 0.0)
                     + (t or 0.0), 4)

    # Timeline keyed by (task, step): first-seen wall + the kinds each
    # host reported at that step. JSON has no tuple keys → nested dict.
    timeline: Dict[int, Dict[int, dict]] = {}
    first_seen: Dict[int, Dict[int, float]] = {}
    events = []
    for path, recs in streams.items():
        for r in recs:
            step = r.get("step")
            kind = r.get("kind")
            task = r.get("task", 0)
            w = wall(path, r.get("t"))
            if isinstance(step, int):
                ent = timeline.setdefault(task, {}).setdefault(
                    step, {"kinds": [], "wall_s": w})
                ent["kinds"].append(kind)
                ent["wall_s"] = min(ent["wall_s"], w)
                fs = first_seen.setdefault(step, {})
                if offsets.get(path) is not None:
                    fs[task] = min(fs.get(task, w), w)
            if kind in EVENT_KINDS:
                ev = {"task": task, "kind": kind, "step": step,
                      "wall_s": w}
                for key in ("fault", "reason", "action", "process_id",
                            "epoch", "world_size", "restore_step",
                            "replica_id", "version"):
                    if key in r:
                        ev[key] = r[key]
                events.append(ev)
    events.sort(key=lambda e: e["wall_s"])

    # Step skew: wall spread of each step seen on >= 2 ALIGNED hosts.
    per_step = []
    for step in sorted(first_seen):
        seen = first_seen[step]
        if len(seen) < 2:
            continue
        lo, hi = min(seen.values()), max(seen.values())
        per_step.append({"step": step, "hosts": len(seen),
                         "spread_s": round(hi - lo, 4),
                         "laggard": max(seen, key=seen.get)})
    skew = {
        "steps_compared": len(per_step),
        "max_spread_s": max((s["spread_s"] for s in per_step),
                            default=None),
        "mean_spread_s": round(sum(s["spread_s"] for s in per_step)
                               / len(per_step), 4) if per_step else None,
        "per_step": per_step,
    }
    # Straggler attribution: how often each task was the last to reach
    # a shared step.
    lag_counts = collections.Counter(s["laggard"] for s in per_step)
    skew["laggard_counts"] = dict(lag_counts)

    # Fleet request flow, when any stream carries the serving kinds.
    fleet: dict = {}
    serve_windows = {h["task"]: h["kinds"].get("serve", 0)
                     for h in hosts if h["kinds"].get("serve")}
    if serve_windows:
        fleet["serve_windows"] = serve_windows
    routed = rerouted = evictions = 0
    fleet_rows = 0
    for recs in streams.values():
        for r in recs:
            if r.get("kind") in ("fleet", "fleet_done"):
                fleet_rows += 1
                routed += r.get("routed") or 0
                rerouted += r.get("rerouted") or 0
                evictions += r.get("evictions") or 0
    if fleet_rows:
        fleet.update({"routed": routed, "rerouted": rerouted,
                      "evictions": evictions})

    return {"hosts": hosts, "timeline": timeline, "skew": skew,
            "events": events, "fleet": fleet,
            "aligned_hosts": len(aligned), "wall0_unix": wall0 or None}


# ---------------------------------------------------------------------------
# merged Perfetto trace
# ---------------------------------------------------------------------------

def _span_epoch_t(records: List[dict]) -> Optional[float]:
    """Estimate the SpanTracer epoch in stream-``t`` coordinates: every
    span record is flushed at/after its finish, so ``t − (start+dur)``
    upper-bounds nothing and lower-bounds the epoch — the minimum over
    spans converges on it."""
    cands = [r["t"] - (r["start_s"] + r["dur_s"]) for r in records
             if r.get("kind") == "span"
             and isinstance(r.get("t"), (int, float))
             and isinstance(r.get("start_s"), (int, float))
             and isinstance(r.get("dur_s"), (int, float))]
    return min(cands) if cands else None


def build_merged_trace(paths: List[str],
                       trace_paths: Optional[List[str]] = None) -> dict:
    """One Chrome/Perfetto document: per-process lanes rebuilt from the
    JSONL streams, plus (optionally) real per-process Chrome trace files
    shifted onto the shared clock via their ``epoch_unix_s``."""
    streams = {p: load_stream(p) for p in paths}
    offsets = {p: clock_offset(recs) for p, recs in streams.items()}
    known = [v for v in offsets.values() if v is not None]
    # rspan records carry ABSOLUTE wallclocks, so a stream that is
    # otherwise unalignable still places its request spans correctly —
    # include them when choosing the merged clock's zero.
    rspan_walls = [r["wallclock"] for recs in streams.values()
                   for r in recs
                   if r.get("kind") == "rspan"
                   and isinstance(r.get("wallclock"), (int, float))]
    wall0 = min(known + ([min(rspan_walls)] if rspan_walls else []),
                default=0.0)
    #: request-tracing lanes, one tid per hop, in causal order.
    hop_tid = {"client": 10, "router": 11, "server": 12, "worker": 12,
               "batcher": 13, "engine": 14, "batch": 15}
    flows: Dict[str, List[dict]] = {}
    events = []
    for path, recs in streams.items():
        tasks = [r.get("task") for r in recs if r.get("task") is not None]
        task = collections.Counter(tasks).most_common(1)[0][0] \
            if tasks else 0
        base_s = (offsets[path] - wall0) if offsets[path] is not None \
            else 0.0
        events.append({"ph": "M", "name": "process_name", "pid": task,
                       "args": {"name": f"task {task} ({os.path.basename(os.path.dirname(path)) or path})"}})
        epoch_t = _span_epoch_t(recs)
        for r in recs:
            kind = r.get("kind")
            ts_us = (base_s + (r.get("t") or 0.0)) * 1e6
            if kind == "span" and epoch_t is not None:
                events.append({
                    "ph": "X",
                    "name": r.get("name") or "span",
                    "pid": task, "tid": r.get("depth", 0),
                    "ts": round((base_s + epoch_t + r["start_s"]) * 1e6,
                                1),
                    "dur": round(r["dur_s"] * 1e6, 1),
                    **({"cat": r["cat"]} if r.get("cat") else {}),
                })
            elif kind == "train":
                for key in ("images_per_sec", "device_step_ms"):
                    if isinstance(r.get(key), (int, float)):
                        events.append({"ph": "C", "name": key,
                                       "pid": task, "tid": 0,
                                       "ts": round(ts_us, 1),
                                       "args": {key: r[key]}})
            elif kind == "rspan" \
                    and isinstance(r.get("wallclock"), (int, float)):
                # One hop of one traced request: placed by its ABSOLUTE
                # wallclock (no stream offset needed), one lane per
                # hop. The span is also registered under its trace_id
                # so the flow pass below can causally link the hops.
                hop = r.get("hop") or "hop"
                span = {
                    "ph": "X",
                    "name": f"{hop} {str(r.get('trace_id'))[:8]}",
                    "cat": "rspan",
                    "pid": task, "tid": hop_tid.get(hop, 19),
                    "ts": round((r["wallclock"] - wall0) * 1e6, 1),
                    "dur": round((r.get("dur_ms") or 0.0) * 1e3, 1),
                    "args": {k: v for k, v in r.items()
                             if k in ("trace_id", "hop", "dur_ms",
                                      "batch_id", "version", "shed",
                                      "attempt", "status", "replica_id",
                                      "error")},
                }
                events.append(span)
                if r.get("trace_id"):
                    flows.setdefault(str(r["trace_id"]), []).append(span)
            elif kind in EVENT_KINDS:
                events.append({"ph": "i", "s": "p",
                               "name": f"{kind}"
                               + (f"@{r['step']}"
                                  if isinstance(r.get("step"), int)
                                  else ""),
                               "pid": task, "tid": 0,
                               "ts": round(ts_us, 1)})
    # Causal links: one Chrome flow per trace_id, connecting its hop
    # spans in wallclock order (s → t... → f). Single-span traces (and
    # batch spans, whose batch_id is its own trace_id) need no arrow —
    # their membership is already in args.
    for flow_id, (trace_id, spans) in enumerate(sorted(flows.items()), 1):
        if len(spans) < 2:
            continue
        spans.sort(key=lambda s: s["ts"])
        for i, span in enumerate(spans):
            ph = "s" if i == 0 else ("f" if i == len(spans) - 1 else "t")
            ev = {"ph": ph, "name": "request", "cat": "rspan",
                  "id": flow_id, "pid": span["pid"], "tid": span["tid"],
                  "ts": round(span["ts"] + min(span["dur"], 1.0), 1)}
            if ph == "f":
                ev["bp"] = "e"
            events.append(ev)
    for idx, tpath in enumerate(trace_paths or []):
        try:
            with open(tpath) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[aggregate] skipping trace {tpath}: {e}",
                  file=sys.stderr)
            continue
        epoch = ((doc.get("otherData") or {}).get("epoch_unix_s"))
        shift_us = ((epoch - wall0) * 1e6
                    if isinstance(epoch, (int, float)) and known else 0.0)
        pid_base = 1000 * (idx + 1)
        for e in doc.get("traceEvents") or []:
            e = dict(e)
            e["pid"] = pid_base + int(e.get("pid") or 0)
            if isinstance(e.get("ts"), (int, float)):
                e["ts"] = round(e["ts"] + shift_us, 1)
            events.append(e)
        events.append({"ph": "M", "name": "process_name",
                       "pid": pid_base,
                       "args": {"name": f"trace {os.path.basename(tpath)}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"wall0_unix_s": wall0 or None,
                          "sources": list(paths)
                          + list(trace_paths or [])}}


# ---------------------------------------------------------------------------
# report rendering
# ---------------------------------------------------------------------------

def render(agg: dict) -> str:
    lines = ["== run-wide aggregation =="]
    for h in agg["hosts"]:
        off = ("aligned" if h["offset_unix"] is not None
               else "UNALIGNED (no wallclock anchors)")
        lines.append(
            f"  task {h['task']}: {h['records']} record(s), "
            f"{h['train_rows']} train row(s), last step "
            f"{h['last_step']}, {h['heartbeats']} heartbeat(s) [{off}]")
    skew = agg["skew"]
    if skew["steps_compared"]:
        lines.append(
            f"  step skew over {skew['steps_compared']} shared "
            f"step(s): max {skew['max_spread_s']:.3f} s, mean "
            f"{skew['mean_spread_s']:.3f} s")
        counts = skew.get("laggard_counts") or {}
        worst = max(counts.values(), default=0)
        for task in sorted(counts):
            n = counts[task]
            bar = "#" * max(1, round(20 * n / worst)) if worst else ""
            lines.append(f"    task {task} last to arrive {n:>4}x {bar}")
    elif agg["aligned_hosts"] < 2:
        lines.append("  step skew: n/a (< 2 clock-aligned hosts)")
    if agg["events"]:
        lines.append(f"  events ({len(agg['events'])}):")
        for e in agg["events"][:40]:
            detail = {k: v for k, v in e.items()
                      if k not in ("task", "kind", "step", "wall_s")}
            extra = f" {detail}" if detail else ""
            lines.append(
                f"    +{e['wall_s']:9.3f}s task {e['task']} "
                f"{e['kind']}@{e['step']}{extra}")
        if len(agg["events"]) > 40:
            lines.append(f"    ... {len(agg['events']) - 40} more")
    if agg["fleet"]:
        f = agg["fleet"]
        if "serve_windows" in f:
            per = ", ".join(f"replica {t}: {n}"
                            for t, n in sorted(f["serve_windows"].items()))
            lines.append(f"  fleet serve windows: {per}")
        if "routed" in f:
            lines.append(
                f"  fleet request flow: {f['routed']} routed, "
                f"{f['rerouted']} re-routed, {f['evictions']} "
                f"eviction(s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="merge per-process metrics JSONL streams (and "
                    "Chrome traces) into one run-level timeline")
    p.add_argument("streams", nargs="+", help="metrics JSONL files")
    p.add_argument("--out", default=None,
                   help="write the merged Perfetto/Chrome trace here")
    p.add_argument("--traces", nargs="*", default=None,
                   help="per-process Chrome trace files "
                        "(--trace_events_path outputs) to shift onto "
                        "the shared clock and merge into --out")
    p.add_argument("--format", choices=("text", "json"), default="text")
    args = p.parse_args(argv)
    agg = aggregate(args.streams)
    if args.format == "json":
        print(json.dumps(agg))
    else:
        print(render(agg))
    if args.out:
        doc = build_merged_trace(args.streams, args.traces)
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"merged trace ({len(doc['traceEvents'])} events) -> "
              f"{args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
