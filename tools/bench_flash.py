#!/usr/bin/env python
"""Flash-attention kernel benchmark — the sweep behind BASELINE.md's
round-3 attention tables.

Runs on the REAL chip (axon): forward-only and full fwd+bwd
(``jax.grad`` through the custom_vjp backward kernels) at the ladder
geometry [B=4, S, H=8, D=64] bf16, for full / causal / sliding-window
attention, optionally sweeping block sizes. Timing drains with a
``device_get`` of a value depending on every output — the only reliable
barrier on a tunneled TPU (ARCHITECTURE.md §3).

Usage:
    python tools/bench_flash.py                  # standard table
    python tools/bench_flash.py --blocks 512 1024  # block-size sweep
    python tools/bench_flash.py --seqs 8192 16384 --iters 20

TF/s columns use the ALGORITHMIC flop counts (4·B·H·S²·D forward;
3.5× that for fwd+bwd — dQ pass + dK/dV pass with recompute), so
causal/window rows show their *speedup* rather than inflated rates.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def bench(fn, *args, iters: int = 10) -> float:
    s = fn(*args)
    jax.device_get(s)                    # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        s = fn(*args)
    jax.device_get(s)                    # drain
    return (time.perf_counter() - t0) / iters


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", type=int, nargs="+",
                   default=[4096, 8192, 16384])
    p.add_argument("--blocks", type=int, nargs="+", default=[None],
                   help="explicit block sizes to sweep (default: auto)")
    p.add_argument("--windows", type=int, nargs="+", default=[1024, 4096])
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head_dim", type=int, default=64)
    args = p.parse_args()

    from dml_cnn_cifar10_tpu.ops import flash_attention as fa

    B, H, D = args.batch, args.heads, args.head_dim
    key = jax.random.PRNGKey(0)

    def grad_fn(blk, **kw):
        bkw = {} if blk is None else dict(block_q=blk, block_k=blk)

        @jax.jit
        def g(q, k, v):
            gr = jax.grad(lambda q, k, v: jnp.sum(
                fa.flash_attention(q, k, v, **bkw, **kw)
                .astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v)
            return sum(jnp.sum(t.astype(jnp.float32)) for t in gr)
        return g

    def fwd_fn(blk, **kw):
        bkw = {} if blk is None else dict(block_q=blk, block_k=blk)
        return jax.jit(lambda q, k, v: jnp.sum(
            fa.flash_attention(q, k, v, **bkw, **kw)
            .astype(jnp.float32)))

    print(f"[B={B}, S, H={H}, D={D}] bf16 on {jax.devices()[0].platform}; "
          f"{args.iters} timed iters\n")
    print("| S | block | variant | fwd ms | fwd+bwd ms | fwd+bwd TF/s | "
          "vs full |")
    print("|---|---|---|---|---|---|---|")
    for S in args.seqs:
        q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
                   for kk in jax.random.split(key, 3))
        algo = 3.5 * 4 * B * H * S * S * D
        for blk in args.blocks:
            variants = [("full", {})] + [("causal", dict(causal=True))] + [
                (f"W={w}", dict(window=w)) for w in args.windows
                if w < S] + [
                (f"W={w} causal", dict(window=w, causal=True))
                for w in args.windows if w < S]
            base = None
            for name, kw in variants:
                dt_f = bench(fwd_fn(blk, **kw), q, k, v, iters=args.iters)
                dt = bench(grad_fn(blk, **kw), q, k, v, iters=args.iters)
                base = dt if base is None else base
                bs = "auto" if blk is None else str(blk)
                print(f"| {S} | {bs} | {name} | {dt_f*1e3:.1f} | "
                      f"{dt*1e3:.1f} | {algo/dt/1e12:.1f} | "
                      f"{base/dt:.2f}x |")


if __name__ == "__main__":
    main()
