#!/usr/bin/env python
"""Flash-attention kernel benchmark — the sweep behind BASELINE.md's
attention tables.

Runs on the REAL chip (axon): forward-only and full fwd+bwd
(``jax.grad`` through the custom_vjp backward kernels) at the ladder
geometry [B=4, S, H=8, D=64] bf16, for full / causal / sliding-window
attention, optionally sweeping block sizes.

Timing is TRACE-BASED (round 4): each config runs 3× under
``jax.profiler``, and the reported milliseconds are the Pallas kernels'
own device time parsed from the xplane (xprof ``op_profile``). Wall-clock
deltas on this box include ~12-13 ms of PER-DISPATCH tunnel overhead
(axon): the round-3 numbers measured with dispatch timing were inflated
by exactly that constant, which also *understated* the causal/window
speedup ratios (the constant dilutes the denominator less than the
numerator). The wall column is still printed for context.

Usage:
    python tools/bench_flash.py                  # standard table
    python tools/bench_flash.py --blocks 512 1024  # block-size sweep
    python tools/bench_flash.py --seqs 8192 16384

TF/s columns use the ALGORITHMIC flop counts (4·B·H·S²·D forward;
3.5× that for fwd+bwd — dQ pass + dK/dV pass with recompute), so
causal/window rows show their *speedup* rather than inflated rates.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _kernel_ms(trace_dir: str, reps: int) -> float:
    """Sum the tpu_custom_call (Pallas) raw times in an xplane trace."""
    from xprof.convert import raw_to_tool_data as rtd

    pbs = glob.glob(f"{trace_dir}/plugins/profile/*/*.xplane.pb")
    data, _ = rtd.xspace_to_tool_data([pbs[0]], "op_profile", {})
    tree = json.loads(data.decode() if isinstance(data, bytes) else data)
    total_ps = 0.0

    def walk(node):
        nonlocal total_ps
        xla = node.get("xla") or {}
        m = node.get("metrics", {})
        if xla.get("category") == "custom-call" and \
                "tpu_custom_call" in xla.get("expression", ""):
            total_ps += m.get("rawTime", 0)
        for ch in node.get("children", []):
            walk(ch)

    walk(tree.get("byProgram", {}))
    return total_ps / 1e9 / reps


def bench(fn, *args, reps: int = 3, tag: str = "b") -> tuple[float, float]:
    """→ (kernel_ms, wall_ms_per_call)."""
    s = fn(*args)
    jax.device_get(s)                    # compile + warm
    d = f"/tmp/bench_flash_trace_{tag}"
    shutil.rmtree(d, ignore_errors=True)
    t0 = time.perf_counter()
    jax.profiler.start_trace(d)
    for _ in range(reps):
        s = fn(*args)
    jax.device_get(s)
    jax.profiler.stop_trace()
    wall = (time.perf_counter() - t0) / reps
    km = _kernel_ms(d, reps)
    shutil.rmtree(d, ignore_errors=True)
    return km, wall * 1e3


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", type=int, nargs="+",
                   default=[4096, 8192, 16384])
    p.add_argument("--blocks", type=int, nargs="+", default=[None],
                   help="explicit block sizes to sweep (default: auto)")
    p.add_argument("--windows", type=int, nargs="+", default=[1024, 4096])
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head_dim", type=int, default=64)
    args = p.parse_args()

    from dml_cnn_cifar10_tpu.ops import flash_attention as fa

    B, H, D = args.batch, args.heads, args.head_dim
    key = jax.random.PRNGKey(0)

    def grad_fn(blk, **kw):
        bkw = {} if blk is None else dict(block_q=blk, block_k=blk)

        @jax.jit
        def g(q, k, v):
            gr = jax.grad(lambda q, k, v: jnp.sum(
                fa.flash_attention(q, k, v, **bkw, **kw)
                .astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v)
            return sum(jnp.sum(t.astype(jnp.float32)) for t in gr)
        return g

    def fwd_fn(blk, **kw):
        bkw = {} if blk is None else dict(block_q=blk, block_k=blk)
        return jax.jit(lambda q, k, v: jnp.sum(
            fa.flash_attention(q, k, v, **bkw, **kw)
            .astype(jnp.float32)))

    print(f"[B={B}, S, H={H}, D={D}] bf16 on {jax.devices()[0].platform}; "
          f"kernel ms from xplane over {args.reps} reps\n")
    print("| S | block | variant | fwd ms | fwd+bwd ms | fwd+bwd wall ms "
          "| fwd+bwd TF/s | vs full |")
    print("|---|---|---|---|---|---|---|---|")
    for S in args.seqs:
        q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
                   for kk in jax.random.split(key, 3))
        algo = 3.5 * 4 * B * H * S * S * D
        for blk in args.blocks:
            variants = [("full", {})] + [("causal", dict(causal=True))] + [
                (f"W={w}", dict(window=w)) for w in args.windows
                if w < S] + [
                (f"W={w} causal", dict(window=w, causal=True))
                for w in args.windows if w < S]
            base = None
            for name, kw in variants:
                dt_f, _ = bench(fwd_fn(blk, **kw), q, k, v,
                                reps=args.reps, tag="f")
                dt, wall = bench(grad_fn(blk, **kw), q, k, v,
                                 reps=args.reps, tag="g")
                base = dt if base is None else base
                bs = "auto" if blk is None else str(blk)
                print(f"| {S} | {bs} | {name} | {dt_f:.2f} | "
                      f"{dt:.2f} | {wall:.1f} | "
                      f"{algo / (dt / 1e3) / 1e12:.1f} | "
                      f"{base / dt:.2f}x |", flush=True)


if __name__ == "__main__":
    main()
