#!/usr/bin/env python
"""Summarize a run's metrics JSONL into a goodput / run-health table.

Reads the stream written by ``--metrics_jsonl`` (schema:
``docs/OBSERVABILITY.md``) and answers "where did the wall-clock go?" and
"was this run healthy?" without loading a trace UI:

- goodput breakdown from the final ``goodput`` record (falling back to
  re-aggregating ``span`` records when a run died before the final
  flush),
- throughput from the ``train`` / ``done`` records (the drain-anchored
  figures BENCH_*.json quotes — see docs/OBSERVABILITY.md for how the
  two relate),
- training health (grad/param norm, update ratio) when the run compiled
  them in (``--health_metrics``),
- device-time attribution: the per-boundary ``device_step_ms`` /
  ``drain_wait_ms`` split (host-bound vs device-bound) from the train
  rows, and the per-op ``devtime`` table a ``--profile_at_steps``
  capture window emitted (utils/devprof.py),
- HBM peak from the ``hbm`` snapshots.

Usage: ``python tools/telemetry_report.py run.jsonl [more.jsonl ...]``
``--format json`` emits the same summary as one machine-readable JSON
document (``summarize_json``) for the perf gate / CI; the text renderer
stays the default. ``--follow`` switches to an incremental tail mode
that re-renders the summary as the stream grows (shared tailing helper
with ``tools/live_monitor.py``), exiting when the run's final record
lands. An alerts section reports what fired/resolved while the run was
live (``utils/alerts.py``) and which rules were still firing at stream
end.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dml_cnn_cifar10_tpu.utils.telemetry import (GOODPUT_CATEGORIES,  # noqa: E402
                                                 percentile)


def load_records(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _last(records: List[dict], kind: str) -> Optional[dict]:
    for rec in reversed(records):
        if rec.get("kind") == kind:
            return rec
    return None


def _goodput_from_spans(records: List[dict]) -> Optional[dict]:
    """Rebuild the cumulative breakdown from raw span records — the
    fallback when a run died before its final goodput flush. Wall-clock
    total comes from the last record's ``t`` offset."""
    spans = [r for r in records if r.get("kind") == "span"]
    if not spans:
        return None
    total = max((r.get("t") or 0.0) for r in records)
    if total <= 0:
        return None
    secs = dict.fromkeys(GOODPUT_CATEGORIES, 0.0)
    for s in spans:
        cat = s.get("cat")
        if cat in secs and s.get("depth") == 0:
            secs[cat] += s.get("dur_s") or 0.0
    out = {"total_s": total}
    for cat, v in secs.items():
        out[f"{cat}_frac"] = v / total
    out["train_frac"] = max(0.0, 1.0 - sum(secs.values()) / total)
    return out


def _device_split(trains: List[dict]) -> Optional[dict]:
    """Boundary-estimator aggregate over the train rows: p50
    ``device_step_ms`` / ``drain_wait_ms`` and the implied device-busy
    fraction of the step window (device wall per step vs total wall per
    step from ``images_per_sec``). None when no row carries the keys."""
    dev = [r["device_step_ms"] for r in trains
           if isinstance(r.get("device_step_ms"), (int, float))]
    if not dev:
        return None
    drain = [r["drain_wait_ms"] for r in trains
             if isinstance(r.get("drain_wait_ms"), (int, float))]
    out = {
        "boundaries": len(dev),
        "device_step_ms_p50": round(percentile(dev, 50), 4),
        "device_step_ms_p99": round(percentile(dev, 99), 4),
        "drain_wait_ms_p50": round(percentile(drain, 50), 3)
        if drain else None,
        "device_busy_frac": None,
    }
    # Host-idle share of each boundary window: drain_wait is the time
    # the host spent BLOCKED on the device at the fused fetch, and
    # device_step_ms x (steps between consecutive train rows) is the
    # window's wall (the estimator divides that wall by the same step
    # count). A share near 1 means the host idles on the device
    # (device-bound: the step itself must get faster); near 0 means the
    # device idles on the host (host-bound: feed it better).
    fracs = []
    for prev, cur in zip(trains, trains[1:]):
        d, w = cur.get("device_step_ms"), cur.get("drain_wait_ms")
        if not (isinstance(d, (int, float))
                and isinstance(w, (int, float))
                and isinstance(cur.get("step"), int)
                and isinstance(prev.get("step"), int)):
            continue
        steps = cur["step"] - prev["step"]
        if steps > 0 and d > 0:
            fracs.append(min(w / (d * steps), 1.0))
    if fracs:
        out["device_busy_frac"] = round(sum(fracs) / len(fracs), 4)
    return out


def _chaos_totals(records: List[dict]) -> Optional[dict]:
    """Sum every ``chaos_done`` record in the stream into one summary —
    a mixed campaign (`--scenario mixed`) writes one per scenario, and
    the section should report the whole campaign, not the last leg."""
    dones = [r for r in records if r.get("kind") == "chaos_done"]
    if not dones:
        return None
    by_kind: dict = {}
    for r in dones:
        for k, v in (r.get("faults_by_kind") or {}).items():
            by_kind[k] = by_kind.get(k, 0) + v
    return {
        "schedules": sum(r.get("schedules") or 0 for r in dones),
        "passed": sum(r.get("passed") or 0 for r in dones),
        "failed": sum(r.get("failed") or 0 for r in dones),
        "faults_by_kind": by_kind,
        "slowest_recovery_s": max(
            (r.get("slowest_recovery_s") or 0.0) for r in dones),
    }


def _hop_breakdown(records: List[dict]) -> Optional[dict]:
    """Per-hop request-latency breakdown from the ``rspan`` records
    (utils/reqtrace.py): span/trace counts, p50/p99 per hop, and a
    slowest-trace exemplar table (total = the sum of the trace's hop
    durations; its trace_id is directly findable in the merged Perfetto
    output). ``batch`` spans carry a batch_id as their trace_id and are
    counted as a hop but excluded from the per-trace totals."""
    spans = [r for r in records if r.get("kind") == "rspan"
             and isinstance(r.get("dur_ms"), (int, float))]
    if not spans:
        return None
    by_hop: dict = {}
    by_trace: dict = {}
    for r in spans:
        hop = r.get("hop") or "?"
        by_hop.setdefault(hop, []).append(r["dur_ms"])
        if hop != "batch" and r.get("trace_id"):
            ent = by_trace.setdefault(str(r["trace_id"]),
                                      {"total_ms": 0.0, "hops": {},
                                       "version": None})
            ent["hops"][hop] = round(
                ent["hops"].get(hop, 0.0) + r["dur_ms"], 3)
            ent["total_ms"] = round(ent["total_ms"] + r["dur_ms"], 3)
            if r.get("version") is not None:
                ent["version"] = r["version"]
    hops = [{"hop": hop, "spans": len(durs),
             "p50_ms": round(percentile(durs, 50), 3),
             "p99_ms": round(percentile(durs, 99), 3)}
            for hop, durs in sorted(by_hop.items())]
    slowest = [{"trace_id": tid, **ent}
               for tid, ent in sorted(by_trace.items(),
                                      key=lambda kv: -kv[1]["total_ms"])
               [:5]]
    return {"spans": len(spans), "traces": len(by_trace),
            "hops": hops, "slowest": slowest}


def _peer_summary(records: List[dict]) -> Optional[dict]:
    """Diskless-recovery rollup from ``peer_replica`` records plus the
    ``source`` field on adopted elastic restart/expand decisions
    (ckpt/peerstore.py). None when the stream carries neither — the
    report stays byte-identical for pre-redundancy streams."""
    peer_recs = [r for r in records if r.get("kind") == "peer_replica"]
    transitions = [r for r in records
                   if r.get("kind") in ("elastic_restart",
                                        "elastic_expand")]
    sourced = [r for r in transitions if r.get("source") is not None]
    if not peer_recs and not sourced:
        return None
    recon = [r for r in peer_recs if r.get("op") == "reconstruct"
             and r.get("secs") is not None]
    recon_s = [float(r["secs"]) for r in recon]
    decides = [r for r in peer_recs if r.get("op") == "decide"
               and r.get("ok") and r.get("staleness") is not None]
    out = {
        "peer_restores": sum(1 for r in sourced
                             if r.get("source") == "peer"),
        "disk_restores": sum(1 for r in transitions
                             if (r.get("source") or "disk") == "disk"),
        "pushes": sum(1 for r in peer_recs
                      if r.get("op") == "push" and r.get("ok")),
        "push_failures": sum(1 for r in peer_recs
                             if r.get("op") == "push"
                             and r.get("ok") is False),
        "fallbacks": sum(1 for r in peer_recs
                         if r.get("op") == "fallback"),
        "reconstructs": len(recon),
        "reconstruct_mean_s": round(sum(recon_s) / len(recon_s), 6)
        if recon_s else None,
        "reconstruct_max_s": round(max(recon_s), 6) if recon_s else None,
        # Staleness the chief saw at its LAST decide seam: how many
        # steps the beats were ahead of the replica set it restored.
        "decide_staleness": decides[-1].get("staleness")
        if decides else None,
    }
    return out


def _autopilot_summary(records: List[dict]) -> Optional[dict]:
    """Alert → remediation → outcome lineage from the ``remediation``
    records (autopilot/engine.py; docs/AUTOPILOT.md): per-policy action
    counts split by status (applied / noop / failed and the explicit
    cooldown/budget suppressions), plus each firing's full arc — the
    alert id it answered, the action taken, and whether that alert
    later resolved. None when the stream carries no remediation
    records — the report stays byte-identical for pre-autopilot
    streams."""
    rems = [r for r in records if r.get("kind") == "remediation"]
    if not rems:
        return None
    resolved_ids = {r.get("id") for r in records
                    if r.get("kind") == "alert_resolved"
                    and r.get("id")}
    by_policy: dict = {}
    counts: dict = {}
    for r in rems:
        st = r.get("status") or "?"
        counts[st] = counts.get(st, 0) + 1
        e = by_policy.setdefault(str(r.get("policy")),
                                 {"action": r.get("action"),
                                  "statuses": {}})
        e["statuses"][st] = e["statuses"].get(st, 0) + 1
    lineage = [{
        "alert_id": r.get("alert_id"), "rule": r.get("rule"),
        "step": r.get("step"), "policy": r.get("policy"),
        "action": r.get("action"), "status": r.get("status"),
        "detail": r.get("detail"), "postmortem": r.get("postmortem"),
        "outcome": (("resolved" if r.get("alert_id") in resolved_ids
                     else "unresolved at stream end")
                    if r.get("alert_id") else None),
    } for r in rems]
    return {"remediations": len(rems), "statuses": counts,
            "by_policy": by_policy, "lineage": lineage}


def _jobs_summary(records: List[dict]) -> Optional[dict]:
    """Unified-runtime rollup (``--mode run``; runtime/, docs/RUNTIME.md)
    from the ``job`` / ``job_done`` / ``publish`` records: per-job state
    timeline, completion verdicts, publish latency, and the
    alert→job→publish lineage for trigger-born jobs. None when the
    stream carries none of the three kinds — the report stays
    byte-identical for pre-runtime streams."""
    job_recs = [r for r in records if r.get("kind") == "job"]
    dones = [r for r in records if r.get("kind") == "job_done"]
    pubs = [r for r in records if r.get("kind") == "publish"]
    if not job_recs and not dones and not pubs:
        return None
    by_job: dict = {}

    def ent(name):
        return by_job.setdefault(str(name), {
            "jtype": None, "timeline": [], "trigger": None,
            "ok": None, "secs": None, "error": None, "publishes": 0,
            "versions": []})

    for r in job_recs:
        e = ent(r.get("job"))
        e["jtype"] = r.get("jtype") or e["jtype"]
        e["timeline"].append({"state": r.get("state"), "t": r.get("t")})
        if r.get("trigger"):
            e["trigger"] = r["trigger"]
    for r in dones:
        e = ent(r.get("job"))
        e["jtype"] = r.get("jtype") or e["jtype"]
        e["ok"], e["secs"] = r.get("ok"), r.get("secs")
        if r.get("error"):
            e["error"] = r["error"]
    for r in pubs:
        if r.get("job") is not None and str(r["job"]) in by_job:
            e = by_job[str(r["job"])]
            e["publishes"] += 1
            e["versions"].append(r.get("version"))
    latencies = [r["latency_ms"] for r in pubs
                 if isinstance(r.get("latency_ms"), (int, float))]
    publish = None
    if pubs:
        publish = {
            "publishes": len(pubs),
            "swapped": sum(1 for r in pubs if r.get("swapped")),
            "latency_ms_mean": round(sum(latencies) / len(latencies), 3)
            if latencies else None,
            "latency_ms_max": round(max(latencies), 3)
            if latencies else None,
            "last_version": pubs[-1].get("version"),
            "last_step": pubs[-1].get("step"),
        }
    # Trigger lineage: an alert-born job carries trigger=<rule> on its
    # `job` records and stamps job=<name> on the publishes it commits —
    # the full alert → job → publish arc, read straight off the stream.
    lineage = [{"rule": e["trigger"], "job": name,
                "versions": e["versions"]}
               for name, e in sorted(by_job.items()) if e["trigger"]]
    return {"jobs": by_job, "publish": publish, "lineage": lineage}


def _net_summary(records: List[dict]) -> Optional[dict]:
    """Network-health rollup (parallel/net.py, utils/netfaults.py):
    per-operation and per-link ok/fail counters with classified error
    reasons off the rate-limited ``net`` records, the injected
    partition timeline off ``fault`` records with a ``net_*`` kind,
    cross-cell failover counts off ``cell_route``, and classified torn
    beats off ``beat_decode_error``. None when the stream carries none
    of them — file-transport streams render byte-identical."""
    nets = [r for r in records if r.get("kind") == "net"]
    net_faults = [r for r in records if r.get("kind") == "fault"
                  and str(r.get("fault") or "").startswith("net_")]
    routes = [r for r in records if r.get("kind") == "cell_route"]
    torn = [r for r in records
            if r.get("kind") == "beat_decode_error"]
    if not nets and not net_faults and not routes and not torn:
        return None
    ops: dict = {}
    errors: dict = {}
    links: dict = {}
    for r in nets:
        op = ops.setdefault(str(r.get("op")), {"ok": 0, "failed": 0})
        link = links.setdefault(r.get("task"),
                                {"ok": 0, "failed": 0, "max_ms": 0.0})
        bucket = "ok" if r.get("ok") else "failed"
        op[bucket] += 1
        link[bucket] += 1
        if isinstance(r.get("ms"), (int, float)):
            link["max_ms"] = round(max(link["max_ms"], r["ms"]), 1)
        if not r.get("ok"):
            err = str(r.get("error"))
            errors[err] = errors.get(err, 0) + 1
    crossings: dict = {}
    for r in routes:
        key = f"{r.get('from_cell')}->{r.get('to_cell')}"
        crossings[key] = crossings.get(key, 0) + 1
    return {
        "ops": ops,
        "errors": errors,
        "links": {str(t): v for t, v in sorted(
            links.items(), key=lambda kv: str(kv[0]))},
        "partitions": [
            {"fault": r.get("fault"), "step": r.get("step"),
             "task": r.get("task"), "isolate": r.get("isolate"),
             "duration_s": r.get("duration_s")} for r in net_faults],
        "cell_routes": {"count": len(routes), "crossings": crossings},
        "beat_decode_errors": len(torn),
    }


def _fmt_bytes(n: Optional[int]) -> str:
    if not n:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024:
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} PiB"


def summarize(path: str) -> str:
    return summarize_records(load_records(path), path)


def _quant_summary(records: List[dict]) -> Optional[dict]:
    """Quantized-serving rollout view (docs/QUANT.md): calibration
    coverage (how many tensors, over how many batches, at what scales),
    publish-gate outcomes (a ``swap`` to a ``+int8`` version is an
    accept; ``quant_rejected`` is the gate holding the line), and how
    much traffic each variant actually answered — shared by the text
    and ``--format json`` paths. None when the stream has no
    quantization activity at all."""
    def _is_q(v) -> bool:
        return str(v).endswith("+int8")

    calibs = [r for r in records if r.get("kind") == "calibration"]
    rejects = [r for r in records if r.get("kind") == "quant_rejected"]
    accepts = [r for r in records if r.get("kind") == "swap"
               and _is_q(r.get("version"))]
    if not (calibs or rejects or accepts):
        return None
    out: dict = {}
    if calibs:
        acts = [r for r in calibs
                if str(r.get("tensor", "")).startswith("act/")]
        scales = [r.get("scale") for r in calibs
                  if isinstance(r.get("scale"), (int, float))]
        out["calibration"] = {
            "records": len(calibs),
            "weight_tensors": len(calibs) - len(acts),
            "act_tensors": len(acts),
            "batches": max((r.get("batches") or 0 for r in calibs),
                           default=0),
            "scale_min": min(scales) if scales else None,
            "scale_max": max(scales) if scales else None,
        }
    out["publishes"] = {"accepted": len(accepts),
                        "rejected": len(rejects)}
    if rejects:
        out["rejections"] = [
            {"version": r.get("version"),
             "replica_id": r.get("replica_id"),
             "delta": r.get("delta"), "max_delta": r.get("max_delta")}
            for r in rejects]
    # Traffic split: the fleet's cumulative version mix when the run
    # flushed one, summed windows otherwise (same fallback the fleet
    # health section uses).
    fleet_done = _last(records, "fleet_done")
    if fleet_done:
        mix = dict(fleet_done.get("version_mix") or {})
    else:
        mix = {}
        for r in records:
            if r.get("kind") == "fleet":
                for v, n in (r.get("version_mix") or {}).items():
                    mix[v] = mix.get(v, 0) + n
    if mix:
        out["traffic"] = {
            "by_version": mix,
            "int8": sum(n for v, n in mix.items() if _is_q(v)),
            "float": sum(n for v, n in mix.items() if not _is_q(v)),
        }
    return out


def summarize_records(records: List[dict], header: str) -> str:
    """The report body over an in-memory record list — the seam
    ``--follow`` re-renders from as the stream grows (no re-reading
    the whole file per refresh) and ``summarize`` wraps for the
    one-shot path."""
    lines = [f"== {header} =="]
    if not records:
        return "\n".join(lines + ["  (no records)"])

    done = _last(records, "done")
    trains = [r for r in records if r.get("kind") == "train"]
    if done or trains:
        step = (done or trains[-1]).get("step")
        lines.append(f"  steps: {step}")
    if done and done.get("images_per_sec"):
        lines.append(
            f"  run-average throughput: {done['images_per_sec']:.1f} "
            f"images/sec (drain-anchored, post-compile)")

    # Compile cost (compilecache/, docs/COMPILECACHE.md): where the
    # startup/restart compile seconds went and how much the cache saved
    # — the detail behind the goodput `compile` fraction below.
    compiles = [r for r in records if r.get("kind") == "compile"]
    if compiles:
        hits = [r for r in compiles if r.get("hit")]
        misses = [r for r in compiles if not r.get("hit")]
        total_s = sum(r.get("compile_s") or 0.0 for r in compiles)
        miss_s = sum(r.get("compile_s") or 0.0 for r in misses)
        lines.append(
            f"  compile cost: {len(compiles)} seam lookup(s), "
            f"{len(hits)} hit / {len(misses)} miss, {total_s:.2f} s "
            f"total ({miss_s:.2f} s compiling)")
        by_phase = {}
        for r in compiles:
            ph = by_phase.setdefault(r.get("phase") or "?",
                                     {"n": 0, "hits": 0, "s": 0.0})
            ph["n"] += 1
            ph["hits"] += 1 if r.get("hit") else 0
            ph["s"] += r.get("compile_s") or 0.0
        for phase in sorted(by_phase):
            d = by_phase[phase]
            lines.append(f"    {phase:<22} {d['n']:>3} lookup(s)  "
                         f"{d['hits']:>3} hit  {d['s']:8.2f} s")
        corrupt = sum(1 for r in compiles if r.get("source") == "corrupt")
        if corrupt:
            lines.append(f"    [{corrupt} corrupt cache entr"
                         f"{'y' if corrupt == 1 else 'ies'} dropped and "
                         f"recompiled (fail-open)]")

    gp = _last(records, "goodput") or _goodput_from_spans(records)
    if gp:
        total = gp.get("total_s") or 0.0
        lines.append(f"  goodput over {total:.2f} s wall-clock:")
        cats = ["train"] + list(GOODPUT_CATEGORIES)
        for cat in cats:
            frac = gp.get(f"{cat}_frac")
            if frac is None:
                continue
            lines.append(f"    {cat:<11} {100 * frac:6.2f} %"
                         f"  {frac * total:8.2f} s")
        covered = sum(gp.get(f"{c}_frac") or 0.0 for c in cats)
        lines.append(f"    {'(sum)':<11} {100 * covered:6.2f} %")
        if gp.get("dropped_spans"):
            lines.append(f"    [{gp['dropped_spans']} spans dropped by "
                         f"the ring buffer]")
    else:
        lines.append("  no goodput/span records (run without --telemetry)")

    health = [r for r in trains if "health_grad_norm" in r]
    if health:
        first, last = health[0], health[-1]
        gmax = max((r.get("health_grad_norm") or 0.0) for r in health)
        lines.append("  training health (first -> last boundary):")
        for key, label in (("health_grad_norm", "grad norm"),
                           ("health_param_norm", "param norm"),
                           ("health_update_ratio", "update ratio")):
            lines.append(f"    {label:<13} {first.get(key)} -> "
                         f"{last.get(key)}")
        lines.append(f"    max grad norm {gmax}")
    # Device-time split (utils/devprof.py): the always-on boundary
    # estimator answers device-bound vs host-bound; the devtime table
    # (a --profile_at_steps capture) answers WHICH ops own the device.
    dev_split = _device_split(trains)
    if dev_split:
        lines.append(
            f"  device step time (boundary estimator, "
            f"{dev_split['boundaries']} boundaries):")
        lines.append(
            f"    device_step p50 {dev_split['device_step_ms_p50']} ms, "
            f"drain-wait p50 {dev_split['drain_wait_ms_p50']} ms per "
            f"boundary")
        if dev_split.get("device_busy_frac") is not None:
            lines.append(
                f"    device-busy ~{100 * dev_split['device_busy_frac']:.0f} "
                f"% of the step window "
                f"({'device' if dev_split['device_busy_frac'] > 0.5 else 'host'}-bound)")
    devtimes = [r for r in records if r.get("kind") == "devtime"]
    if devtimes:
        lines.append("  device-time attribution (--profile_at_steps):")
        newest_step = max(r.get("step") or 0 for r in devtimes)
        for r in devtimes:
            if (r.get("step") or 0) != newest_step:
                continue
            lines.append(
                f"    {r.get('device')}: {r.get('total_ms')} ms "
                f"attributed (compute {r.get('compute_ms')} / "
                f"collective {r.get('collective_ms')} / infeed "
                f"{r.get('infeed_ms')}) over a {r.get('window_ms')} ms "
                f"window")
            for op in (r.get("top_ops") or [])[:5]:
                lines.append(
                    f"      {op.get('name', '?')[:44]:<44} "
                    f"{op.get('dur_ms', 0):>9.2f} ms "
                    f"{100 * (op.get('frac') or 0):5.1f}%  "
                    f"[{op.get('bucket')}] x{op.get('calls')}")
    serve = _last(records, "serve_done")
    if serve is None:
        # A server that died before the final flush still has windows.
        windows = [r for r in records if r.get("kind") == "serve"]
        if windows:
            serve = windows[-1]
    if serve:
        span = serve.get("total_s") or serve.get("window_s") or 0.0
        lines.append(f"  serving over {span:.2f} s "
                     f"({'final' if serve['kind'] == 'serve_done' else 'last window'}):")
        lines.append(
            f"    {serve.get('completed')}/{serve.get('requests')} "
            f"completed at {serve.get('qps')} qps; shed "
            f"{serve.get('shed_queue')} queue-full + "
            f"{serve.get('shed_deadline')} deadline")
        if serve.get("p50_ms") is not None:
            lines.append(
                f"    latency p50/p95/p99: {serve.get('p50_ms')} / "
                f"{serve.get('p95_ms')} / {serve.get('p99_ms')} ms "
                f"(queue-wait p50 {serve.get('queue_wait_p50_ms')} ms, "
                f"device p50 {serve.get('device_p50_ms')} ms)")
        if serve.get("batch_fill") is not None:
            lines.append(
                f"    {serve.get('batches')} batches, mean fill "
                f"{100 * serve['batch_fill']:.1f} %")
        warm = [r for r in compiles if r.get("phase") == "serve_warmup"]
        if warm:
            whits = sum(1 for r in warm if r.get("hit"))
            wtotal = sum(r.get("compile_s") or 0.0 for r in warm)
            lines.append(
                f"    warmup: {len(warm)} bucket(s) ready in "
                f"{wtotal:.2f} s total ({whits} cache hit(s), "
                f"{len(warm) - whits} compile(s))")
    # Request tracing (utils/reqtrace.py; docs/OBSERVABILITY.md
    # Request-tracing section): which hop ate a slow request's latency,
    # from this stream's rspan records.
    hopbd = _hop_breakdown(records)
    if hopbd:
        lines.append(
            f"  request tracing: {hopbd['spans']} span(s) across "
            f"{hopbd['traces']} trace(s)")
        for h in hopbd["hops"]:
            lines.append(
                f"    {h['hop']:<10} {h['spans']:>5} span(s)  "
                f"p50 {h['p50_ms']:>9.3f} ms  p99 {h['p99_ms']:>9.3f} ms")
        if hopbd["slowest"]:
            lines.append("    slowest traces (sum of hop durations):")
            for t in hopbd["slowest"]:
                per = ", ".join(f"{hop} {ms}"
                                for hop, ms in sorted(t["hops"].items()))
                ver = f" v{t['version']}" if t.get("version") else ""
                lines.append(
                    f"      {t['trace_id']}: {t['total_ms']:.3f} ms"
                    f"{ver} ({per})")
    # Fleet health (fleet/; docs/SERVING.md fleet section): replica
    # count over time, routing/eviction counters, hot-swap latency, and
    # what the autoscaler decided — the stream-side answer to "did the
    # fleet layer keep the rollout invisible to clients".
    fleets = [r for r in records if r.get("kind") == "fleet"]
    fleet_done = _last(records, "fleet_done")
    swaps = [r for r in records if r.get("kind") == "swap"]
    swap_rejects = [r for r in records
                    if r.get("kind") == "swap_rejected"]
    scales = [r for r in records if r.get("kind") == "scale"]
    publishes = [r for r in records if r.get("kind") == "fleet_publish"]
    if fleets or fleet_done or swaps or swap_rejects or scales \
            or publishes:
        lines.append("  fleet health:")
        if fleets or fleet_done:
            series = fleets or [fleet_done]
            live_series = [r.get("live") or 0 for r in series]
            last = series[-1]
            lines.append(
                f"    replicas over {len(series)} window(s): live "
                f"min {min(live_series)} / max {max(live_series)}, "
                f"final {last.get('live')}/{last.get('replicas')}")
            # Totals from the cumulative final record when the run
            # flushed one; summed per-window deltas otherwise (a
            # router that died mid-run).
            total = fleet_done or {
                k: sum(r.get(k) or 0 for r in fleets)
                for k in ("routed", "rerouted", "evictions", "shed")}
            lines.append(
                f"    routed {total.get('routed')} request(s), "
                f"{total.get('rerouted')} re-routed, "
                f"{total.get('evictions')} eviction(s), "
                f"{total.get('shed')} shed")
            if fleet_done:
                mix = dict(fleet_done.get("version_mix") or {})
            else:
                mix = {}
                for r in fleets:
                    for v, n in (r.get("version_mix") or {}).items():
                        mix[v] = mix.get(v, 0) + n
            if mix:
                per = ", ".join(f"v{v}: {n}"
                                for v, n in sorted(mix.items()))
                lines.append(f"    version mix: {per}")
        for r in publishes:
            lines.append(f"    published version {r.get('version')} "
                         f"(seq {r.get('seq')})")
        if swaps:
            ms = [r.get("swap_ms") or 0.0 for r in swaps]
            lines.append(
                f"    {len(swaps)} hot-swap(s), swap latency mean "
                f"{sum(ms) / len(ms):.1f} / max {max(ms):.1f} ms")
            for r in swaps:
                lines.append(
                    f"      replica {r.get('replica_id')}: "
                    f"{r.get('from_version')} -> {r.get('version')}")
        for r in swap_rejects:
            lines.append(
                f"    swap REJECTED on replica {r.get('replica_id')} "
                f"(version {r.get('version')}): {r.get('reason')}")
        for r in scales:
            lines.append(
                f"    autoscale {r.get('action')} "
                f"({r.get('reason')}) -> {r.get('replicas')} worker(s)")
        # Per-replica device time, from the newest fleet window that
        # carries the beats' advertised device_ms: a replica whose
        # device_ms is ~uniform with its peers but whose queue is deep
        # is overloaded (scale up); one whose device_ms is the outlier
        # is a slow DEVICE (drain + replace) — visible here without
        # raw beat-file spelunking.
        dev_rows = [r for r in fleets + ([fleet_done] if fleet_done
                                         else [])
                    if r.get("device_ms")]
        if dev_rows:
            per = ", ".join(
                f"r{rid}: {ms} ms" for rid, ms in
                sorted(dev_rows[-1]["device_ms"].items()))
            lines.append(f"    per-replica device_ms (beats, last "
                         f"window): {per}")
    # Quantized serving (quant/; docs/QUANT.md): calibration coverage,
    # what the publish-time accuracy gate decided, and the float/int8
    # traffic split — the stream-side answer to "is the fleet actually
    # serving the quantized variant, and did anything get rejected on
    # the way there".
    quant = _quant_summary(records)
    if quant:
        lines.append("  quantization (int8 serving):")
        cal = quant.get("calibration")
        if cal:
            rng = ""
            if cal["scale_min"] is not None:
                rng = (f", scales [{cal['scale_min']:.3g}, "
                       f"{cal['scale_max']:.3g}]")
            lines.append(
                f"    calibration: {cal['weight_tensors']} weight / "
                f"{cal['act_tensors']} activation tensor record(s) "
                f"over {cal['batches']} batch(es){rng}")
        pub = quant["publishes"]
        lines.append(f"    publish gate: {pub['accepted']} accepted, "
                     f"{pub['rejected']} rejected")
        for r in quant.get("rejections", []):
            lines.append(
                f"      REJECTED {r['version']} on replica "
                f"{r['replica_id']}: top-1 delta {r['delta']:+.4f} > "
                f"max {r['max_delta']:.4f}")
        tr = quant.get("traffic")
        if tr:
            lines.append(
                f"    traffic mix: {tr['int8']} int8 / {tr['float']} "
                f"float response(s)")
    # Alerting (utils/alerts.py; docs/OBSERVABILITY.md Alerting
    # section): what fired while the run was live, what resolved, and
    # what was STILL firing when the stream ended — the post-hoc view
    # of the live alert state.
    alert_recs = [r for r in records if r.get("kind") == "alert"]
    resolved_recs = [r for r in records
                     if r.get("kind") == "alert_resolved"]
    if alert_recs or resolved_recs:
        lines.append(f"  alerts: {len(alert_recs)} fired, "
                     f"{len(resolved_recs)} resolved")
        # Sequential pairing (fire/resolve/fire again = active): the
        # rules still firing are the ones whose LAST event is a fire.
        still_active = {}
        for r in records:
            if r.get("kind") == "alert":
                still_active[r.get("rule")] = r
            elif r.get("kind") == "alert_resolved":
                still_active.pop(r.get("rule"), None)
        for r in alert_recs:
            state = "STILL ACTIVE at stream end" \
                if still_active.get(r.get("rule")) is r else "resolved"
            lines.append(
                f"    [{r.get('severity')}] {r.get('rule')} fired at "
                f"t={r.get('t')}s (value {r.get('value')}, window "
                f"{r.get('window')}) — {state}")
    # Autopilot (--autopilot; autopilot/engine.py, docs/AUTOPILOT.md):
    # the alert → remediation → outcome lineage — which policy answered
    # each firing, what it did, whether the alert then resolved, and
    # how many firings the cooldown/budget gates suppressed.
    ap = _autopilot_summary(records)
    if ap:
        st = ap["statuses"]
        lines.append(
            f"  autopilot: {ap['remediations']} remediation(s) — "
            f"{st.get('applied', 0)} applied, "
            f"{st.get('noop', 0)} noop, {st.get('failed', 0)} failed, "
            f"{st.get('suppressed_cooldown', 0)} cooldown-suppressed, "
            f"{st.get('suppressed_budget', 0)} budget-suppressed")
        for name, e in sorted(ap["by_policy"].items()):
            per = ", ".join(f"{s}: {n}"
                            for s, n in sorted(e["statuses"].items()))
            lines.append(f"    policy {name} ({e['action']}): {per}")
        for arc in ap["lineage"]:
            pm = f", postmortem {arc['postmortem']}" \
                if arc.get("postmortem") else ""
            det = f" ({arc['detail']})" if arc.get("detail") else ""
            lines.append(
                f"    {arc['alert_id']} [{arc['rule']}] -> "
                f"{arc['policy']}/{arc['action']}: {arc['status']}"
                f"{det} — alert {arc['outcome']}{pm}")
    # Unified runtime (--mode run; runtime/, docs/RUNTIME.md): the job
    # lifecycle timeline, the in-process publish latency, and the
    # alert→job→publish lineage for any trigger-born fine-tunes.
    jobs = _jobs_summary(records)
    if jobs:
        lines.append("  runtime jobs:")
        for name, e in sorted(jobs["jobs"].items()):
            arc = " -> ".join(t["state"] for t in e["timeline"]) \
                or "(no transitions)"
            tail = ""
            if e["secs"] is not None:
                verdict = "ok" if e["ok"] else "FAILED"
                tail = f" ({verdict} in {e['secs']} s)"
            trig = f" [trigger: {e['trigger']}]" if e["trigger"] else ""
            npub = (f", {e['publishes']} publish(es)"
                    if e["publishes"] else "")
            lines.append(f"    {name} ({e['jtype']}): {arc}"
                         f"{tail}{trig}{npub}")
            if e["error"]:
                lines.append(f"      error: {e['error']}")
        pub = jobs["publish"]
        if pub:
            lines.append(
                f"    publishes: {pub['publishes']} "
                f"({pub['swapped']} swapped), latency mean "
                f"{pub['latency_ms_mean']} / max {pub['latency_ms_max']} "
                f"ms, last version {pub['last_version']} "
                f"(step {pub['last_step']})")
        for arc in jobs["lineage"]:
            vers = ", ".join(str(v) for v in arc["versions"]) or "none"
            lines.append(
                f"    lineage: alert {arc['rule']!r} -> {arc['job']} -> "
                f"published version(s) {vers}")
    # Resilience events (docs/RESILIENCE.md): how many faults the run
    # absorbed, and what the recovery path did about them.
    faults = [r for r in records if r.get("kind") == "fault"]
    recoveries = [r for r in records if r.get("kind") == "recovery"]
    fallbacks = [r for r in records if r.get("kind") == "ckpt_fallback"]
    prune_errs = [r for r in records
                  if r.get("kind") == "ckpt_prune_error"]
    if faults or recoveries or fallbacks or prune_errs:
        injected = sum(1 for r in faults if r.get("injected"))
        lines.append(
            f"  resilience: {len(faults)} fault(s) "
            f"({injected} injected), {len(recoveries)} recovery "
            f"action(s), {len(fallbacks)} checkpoint fallback(s)")
        for r in recoveries:
            lines.append(
                f"    step {r.get('step')}: {r.get('fault')} -> "
                f"{r.get('action')} (attempt {r.get('attempt')})")
        rb = _last(records, "rollback")
        if rb:
            lines.append(
                f"    last rollback restored step "
                f"{rb.get('restore_step')} at lr {rb.get('lr')}")
        if prune_errs:
            lines.append(
                f"    [{len(prune_errs)} checkpoint prune failure(s) — "
                f"old checkpoints may be accumulating]")
    # Restore source (ckpt/peerstore.py, docs/RESILIENCE.md diskless-
    # recovery section): which elastic restarts skipped checkpoint I/O
    # entirely (source=peer), how long lost-shard reconstruction took,
    # and how stale the replica set was at each decide seam.
    peer = _peer_summary(records)
    if peer:
        lines.append(
            f"  restore source: {peer['peer_restores']} peer / "
            f"{peer['disk_restores']} disk elastic restore(s), "
            f"{peer['pushes']} replica push(es), "
            f"{peer['fallbacks']} peer->disk fallback(s)")
        if peer.get("reconstructs"):
            lines.append(
                f"    lost-shard reconstructs: {peer['reconstructs']} "
                f"(mean {peer.get('reconstruct_mean_s')}s, max "
                f"{peer.get('reconstruct_max_s')}s)")
        if peer.get("decide_staleness") is not None:
            lines.append(
                f"    replica staleness at decide: "
                f"{peer['decide_staleness']} step(s) behind the beats")
    # Chaos campaign (tools/chaos.py; docs/RESILIENCE.md): schedules
    # run, the fault mix they injected, which invariants failed (with
    # the shrunk reproducer specs), and the slowest observed
    # fault→recovery latency.
    chaos_runs = [r for r in records if r.get("kind") == "chaos"]
    chaos_done = _chaos_totals(records)
    if chaos_runs or chaos_done:
        lines.append("  chaos campaign:")
        n = chaos_done.get("schedules") if chaos_done else len(chaos_runs)
        passed = chaos_done.get("passed") if chaos_done \
            else sum(1 for r in chaos_runs if r.get("ok"))
        failed = chaos_done.get("failed") if chaos_done \
            else sum(1 for r in chaos_runs if not r.get("ok"))
        lines.append(f"    {n} schedule(s) run: {passed} passed, "
                     f"{failed} failed")
        by_kind = (chaos_done or {}).get("faults_by_kind") or {}
        if by_kind:
            per = ", ".join(f"{k}: {v}"
                            for k, v in sorted(by_kind.items()))
            lines.append(f"    faults injected by kind: {per}")
        for r in chaos_runs:
            if r.get("ok"):
                continue
            lines.append(
                f"    FAILED seed {r.get('seed')} "
                f"[{r.get('scenario')}] \"{r.get('spec')}\": "
                f"{r.get('invariant')}")
            if r.get("reproducer"):
                lines.append(
                    f"      minimal reproducer: --fault_spec "
                    f"\"{r.get('reproducer')}\"")
        slow = (chaos_done or {}).get("slowest_recovery_s")
        if slow is not None:
            lines.append(f"    slowest recovery: {slow:.2f} s "
                         f"(fault record -> recovery record)")
    # Corrupt restart-decision reads (parallel/cluster.py sidecar
    # check): each one was classified and read as absent, never
    # adopted — but a recurring one means the shared filesystem is
    # serving garbage.
    dcorr = [r for r in records if r.get("kind") == "decision_corrupt"]
    if dcorr:
        lines.append(f"  decision-file corruption: {len(dcorr)} "
                     f"classified corrupt read(s)")
        for r in dcorr[:3]:
            lines.append(f"    {r.get('path')}: {r.get('error')}")
    # Cluster health (parallel/cluster.py): beat cadence per process,
    # straggler pressure, peer deaths, elastic restarts AND expands —
    # the stream-side answer to "did the cluster layer earn its keep".
    beats = [r for r in records if r.get("kind") == "heartbeat"]
    stragglers = [r for r in records if r.get("kind") == "straggler"]
    losses = [r for r in records if r.get("kind") == "peer_lost"]
    restarts = [r for r in records if r.get("kind") == "elastic_restart"]
    expands = [r for r in records if r.get("kind") == "elastic_expand"]
    rejoins = [r for r in records if r.get("kind") == "host_rejoin"]
    if beats or stragglers or losses or restarts or expands or rejoins:
        lines.append("  cluster health:")
        by_pid = {}
        for r in beats:
            by_pid.setdefault(r.get("process_id"), []).append(
                r.get("t") or 0.0)
        for pid in sorted(by_pid, key=lambda p: (p is None, p)):
            ts = by_pid[pid]
            gap = max((b - a for a, b in zip(ts, ts[1:])), default=0.0)
            lines.append(
                f"    process {pid}: {len(ts)} heartbeat(s), max gap "
                f"{gap:.2f} s")
        if stragglers:
            counts = {}
            for r in stragglers:
                counts[r.get("process_id")] = \
                    counts.get(r.get("process_id"), 0) + 1
            worst = max(r.get("behind_steps") or 0 for r in stragglers)
            per = ", ".join(f"proc {p}: {n}"
                            for p, n in sorted(counts.items(),
                                               key=lambda kv: str(kv[0])))
            lines.append(f"    stragglers: {len(stragglers)} event(s) "
                         f"({per}); worst lag {worst} step(s)")
        for r in losses:
            lines.append(
                f"    peer_lost: process {r.get('process_id')} at step "
                f"{r.get('step')} ({r.get('reason')})")
        for r in rejoins:
            lines.append(
                f"    host_rejoin: process {r.get('process_id')} "
                f"announced at step {r.get('step')} "
                f"(epoch {r.get('epoch')})")
        for r in restarts:
            lines.append(
                f"    elastic restart epoch {r.get('epoch')}: world "
                f"size {r.get('world_size')}, restored step "
                f"{r.get('restore_step')}")
        for r in expands:
            lines.append(
                f"    elastic expand epoch {r.get('epoch')}: world "
                f"size {r.get('world_size')} "
                f"(joined {r.get('joined')}), restored step "
                f"{r.get('restore_step')}")
        transitions = sorted(restarts + expands,
                             key=lambda r: (r.get("epoch") or 0))
        if transitions:
            # The world-size timeline in one line: every adopted
            # shrink/expand decision in epoch order.
            arc = " -> ".join(
                f"{r.get('world_size')}"
                f"[{'expand' if r.get('kind') == 'elastic_expand' else 'shrink'}"
                f"@{r.get('step')}]" for r in transitions)
            lines.append(f"    world-size timeline: {arc}")
    # Network health (parallel/net.py `net` records + injected net_*
    # faults + cell_route crossings): what the coordination transport
    # saw per link, and where the chaos partitions landed.
    net = _net_summary(records)
    if net:
        lines.append("  network health:")
        if net["ops"]:
            per = ", ".join(
                f"{op} {v['ok']} ok / {v['failed']} failed"
                for op, v in sorted(net["ops"].items()))
            lines.append(f"    transport ops: {per}")
        if net["errors"]:
            per = ", ".join(f"{e}: {n}" for e, n in
                            sorted(net["errors"].items()))
            lines.append(f"    classified errors: {per}")
        for task, v in net["links"].items():
            lines.append(
                f"    link proc {task}: {v['ok']} ok / "
                f"{v['failed']} failed, slowest {v['max_ms']:.1f} ms")
        for p in net["partitions"]:
            lines.append(
                f"    injected {p['fault']} at step {p['step']} "
                f"(proc {p['task']}, isolate {p['isolate']}, "
                f"duration {p['duration_s']} s)")
        if net["cell_routes"]["count"]:
            per = ", ".join(
                f"{k}: {n}" for k, n in
                sorted(net["cell_routes"]["crossings"].items()))
            lines.append(
                f"    cross-cell failovers: "
                f"{net['cell_routes']['count']} ({per})")
        if net["beat_decode_errors"]:
            lines.append(
                f"    torn beats classified: "
                f"{net['beat_decode_errors']} (beat_decode_error)")
    # Sharded fast-resume breakdown (ckpt/sharded.py `shard_io` rows):
    # how many shard files moved, how many bytes, and the slowest shard
    # — the wall-clock of a concurrent phase is its slowest member.
    sios = [r for r in records if r.get("kind") == "shard_io"]
    if sios:
        lines.append("  shard io:")
        for op in ("save", "restore"):
            rows = [r for r in sios if r.get("op") == op]
            if not rows:
                continue
            nbytes = sum(r.get("bytes") or 0 for r in rows)
            secs = [r.get("secs") or 0.0 for r in rows]
            fails = sum(1 for r in rows if r.get("verify") is False)
            lines.append(
                f"    {op}: {len(rows)} shard(s), {_fmt_bytes(nbytes)}, "
                f"{sum(secs):.3f} s io (slowest {max(secs):.3f} s), "
                f"{fails} verify failure(s)")
        legacy = [r for r in sios if r.get("op") == "legacy_glob"]
        for r in legacy:
            lines.append(
                f"    [legacy manifest without shard_files restored "
                f"via glob: {r.get('shard')}]")
    hbm = _last(records, "hbm")
    if hbm:
        if hbm.get("available"):
            lines.append(
                f"  HBM ({hbm.get('devices')} local devices): "
                f"{_fmt_bytes(hbm.get('bytes_in_use'))} in use, "
                f"peak {_fmt_bytes(hbm.get('peak_bytes'))}, "
                f"limit {_fmt_bytes(hbm.get('bytes_limit'))}")
        else:
            lines.append("  HBM: backend reports no memory stats")
    return "\n".join(lines)


def summarize_json(path: str) -> dict:
    """Machine-readable summary of one stream — the ``--format json``
    payload the perf gate / CI consumes. Same sections as the text
    renderer (which stays the default), plainly keyed."""
    records = load_records(path)
    out: dict = {"path": path, "records": len(records)}
    done = _last(records, "done")
    trains = [r for r in records if r.get("kind") == "train"]
    if done or trains:
        out["steps"] = (done or trains[-1]).get("step")
    if done:
        out["images_per_sec"] = done.get("images_per_sec")
    gp = _last(records, "goodput") or _goodput_from_spans(records)
    if gp:
        out["goodput"] = {k: v for k, v in gp.items()
                          if k not in ("kind", "t", "task")}
    compiles = [r for r in records if r.get("kind") == "compile"]
    if compiles:
        misses = [r for r in compiles if not r.get("hit")]
        out["compile"] = {
            "lookups": len(compiles),
            "hits": len(compiles) - len(misses),
            "misses": len(misses),
            "total_s": round(sum(r.get("compile_s") or 0.0
                                 for r in compiles), 3),
            "miss_s": round(sum(r.get("compile_s") or 0.0
                                for r in misses), 3),
        }
    health = [r for r in trains if "health_grad_norm" in r]
    if health:
        out["health"] = {
            "first_grad_norm": health[0].get("health_grad_norm"),
            "last_grad_norm": health[-1].get("health_grad_norm"),
            "max_grad_norm": max((r.get("health_grad_norm") or 0.0)
                                 for r in health),
            "last_update_ratio": health[-1].get("health_update_ratio"),
        }
    dev_split = _device_split(trains)
    if dev_split:
        out["device_split"] = dev_split
    devtimes = [r for r in records if r.get("kind") == "devtime"]
    if devtimes:
        out["devtime"] = [
            {k: v for k, v in r.items() if k not in ("kind", "t", "task")}
            for r in devtimes]
    serve = _last(records, "serve_done") or _last(records, "serve")
    if serve:
        out["serve"] = {k: v for k, v in serve.items()
                        if k not in ("kind", "t", "task")}
    hopbd = _hop_breakdown(records)
    if hopbd:
        out["request_tracing"] = hopbd
    fleet_done = _last(records, "fleet_done") \
        or _last(records, "fleet")
    if fleet_done:
        out["fleet"] = {k: v for k, v in fleet_done.items()
                        if k not in ("kind", "t", "task")}
        out["fleet"]["swaps"] = sum(1 for r in records
                                    if r.get("kind") == "swap")
        out["fleet"]["scales"] = sum(1 for r in records
                                     if r.get("kind") == "scale")
    quant = _quant_summary(records)
    if quant:
        out["quant"] = quant
    chaos_runs = [r for r in records if r.get("kind") == "chaos"]
    chaos_done = _chaos_totals(records)
    if chaos_runs or chaos_done:
        out["chaos"] = {
            "schedules": (chaos_done or {}).get("schedules",
                                                len(chaos_runs)),
            "passed": (chaos_done or {}).get(
                "passed", sum(1 for r in chaos_runs if r.get("ok"))),
            "failed": (chaos_done or {}).get(
                "failed",
                sum(1 for r in chaos_runs if not r.get("ok"))),
            "faults_by_kind": (chaos_done or {}).get("faults_by_kind"),
            "slowest_recovery_s": (chaos_done or {}).get(
                "slowest_recovery_s"),
            "failures": [
                {"seed": r.get("seed"), "spec": r.get("spec"),
                 "invariant": r.get("invariant"),
                 "reproducer": r.get("reproducer")}
                for r in chaos_runs if not r.get("ok")],
        }
    alert_recs = [r for r in records if r.get("kind") == "alert"]
    resolved_recs = [r for r in records
                     if r.get("kind") == "alert_resolved"]
    if alert_recs or resolved_recs:
        still_active = {}
        for r in records:
            if r.get("kind") == "alert":
                still_active[r.get("rule")] = r
            elif r.get("kind") == "alert_resolved":
                still_active.pop(r.get("rule"), None)
        out["alerts"] = {
            "fired": len(alert_recs),
            "resolved": len(resolved_recs),
            "active": [
                {"rule": r.get("rule"), "severity": r.get("severity"),
                 "value": r.get("value"), "window": r.get("window")}
                for r in still_active.values()],
        }
    ap = _autopilot_summary(records)
    if ap:
        out["autopilot"] = ap
    jobs = _jobs_summary(records)
    if jobs:
        out["jobs"] = jobs
    faults = [r for r in records if r.get("kind") == "fault"]
    recoveries = [r for r in records if r.get("kind") == "recovery"]
    if faults or recoveries:
        out["resilience"] = {
            "faults": len(faults),
            "injected": sum(1 for r in faults if r.get("injected")),
            "recoveries": len(recoveries),
            "ckpt_fallbacks": sum(1 for r in records
                                  if r.get("kind") == "ckpt_fallback"),
        }
    peer = _peer_summary(records)
    if peer:
        out.setdefault("resilience", {})["restore_source"] = peer
    beats = [r for r in records if r.get("kind") == "heartbeat"]
    losses = [r for r in records if r.get("kind") == "peer_lost"]
    transitions = [r for r in records
                   if r.get("kind") in ("elastic_restart",
                                        "elastic_expand")]
    if beats or losses or transitions:
        out["cluster"] = {
            "heartbeats": len(beats),
            "stragglers": sum(1 for r in records
                              if r.get("kind") == "straggler"),
            "peer_losses": [{"process_id": r.get("process_id"),
                             "step": r.get("step"),
                             "reason": r.get("reason")} for r in losses],
            "world_size_timeline": [
                {"kind": r["kind"], "epoch": r.get("epoch"),
                 "world_size": r.get("world_size"),
                 "step": r.get("step")}
                for r in sorted(transitions,
                                key=lambda r: (r.get("epoch") or 0))],
        }
    net = _net_summary(records)
    if net:
        out["network"] = net
    hbm = _last(records, "hbm")
    if hbm and hbm.get("available"):
        out["hbm"] = {k: hbm.get(k) for k in
                      ("devices", "bytes_in_use", "peak_bytes",
                       "bytes_limit")}
    return out


def follow(paths: List[str], refresh_s: float = 2.0,
           max_refreshes: Optional[int] = None, clear: bool = True,
           out=None) -> int:
    """Incremental tail mode (``--follow``): re-render the summary as
    the JSONL streams grow, sharing the live monitor's tailing helper
    (``tools/live_monitor.py``). Exits when every stream has flushed
    its final record (``done``/``serve_done``/``fleet_done``), on
    Ctrl-C, or after ``max_refreshes`` (test/batch bound)."""
    from tools.live_monitor import FINAL_KINDS, JsonlTail
    out = sys.stdout if out is None else out
    tails = {p: JsonlTail(p) for p in paths}
    records = {p: [] for p in paths}
    n = 0
    while True:
        for p, tail in tails.items():
            records[p].extend(tail.poll())
        if clear and n > 0 and out is sys.stdout:
            out.write("\x1b[2J\x1b[H")
        for p in paths:
            print(summarize_records(records[p],
                                    f"{p} (following)"), file=out)
        n += 1
        finished = all(
            any(r.get("kind") in FINAL_KINDS for r in records[p])
            for p in paths) and paths
        if finished or (max_refreshes is not None
                        and n >= max_refreshes):
            return 0
        try:
            time.sleep(refresh_s)
        except KeyboardInterrupt:
            return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fmt = "text"
    usage = ("usage: telemetry_report.py [--format text|json] "
             "[--follow [--refresh S]] run.jsonl [more.jsonl ...]")
    if "--format" in argv:
        i = argv.index("--format")
        try:
            fmt = argv[i + 1]
        except IndexError:
            fmt = ""
        del argv[i:i + 2]
        if fmt not in ("text", "json"):
            print(usage)
            return 2
    follow_mode = "--follow" in argv
    if follow_mode:
        argv.remove("--follow")
    refresh_s = 2.0
    if "--refresh" in argv:
        i = argv.index("--refresh")
        try:
            refresh_s = float(argv[i + 1])
        except (IndexError, ValueError):
            print(usage)
            return 2
        del argv[i:i + 2]
    if not argv:
        print(usage)
        return 2
    if follow_mode:
        if fmt != "text":
            print("--follow renders text only")
            return 2
        return follow(argv, refresh_s=refresh_s)
    if fmt == "json":
        docs = [summarize_json(path) for path in argv]
        print(json.dumps(docs[0] if len(docs) == 1
                         else {"reports": docs}))
        return 0
    for path in argv:
        print(summarize(path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
