#!/usr/bin/env python
"""Perf-regression gate: compare a fresh ``bench.py`` report against the
``BENCH_r*.json`` trajectory and exit nonzero on regression.

The headline bench has been flat for five rounds while every speed win
landed on opt-in side paths — partly because nothing FAILED when a round
came back slower. This gate is the missing release step: every metric
``bench.py`` reports is compared, per row, against the median of the
recorded trajectory with a per-metric tolerance, and any breach is a
nonzero exit (wire it after the bench in CI / the release checklist):

  python bench.py > /tmp/bench.json
  python tools/bench_gate.py /tmp/bench.json            # baselines: BENCH_r*.json
  python tools/bench_gate.py /tmp/bench.json --baselines BENCH_r0*.json

Checks (a metric absent from either side is skipped, never failed —
older rounds predate ``compile_s``/``step_ms_*``):

- headline ``value`` and per-row ``images_per_sec_per_chip``: candidate
  must be ≥ (1 − ``--tol-throughput``) × trajectory median,
- per-row ``mfu``: ≥ (1 − ``--tol-mfu``) × median,
- per-row ``compile_s``: ≤ max(median, 1 s) × ``--tol-compile`` (the
  floor keeps warm-cache jitter from flagging 0.2 s vs 0.05 s),
- per-row ``spread_pct``: ≤ ``--max-spread`` (absolute — a noisy
  measurement invalidates every other comparison),
- per-row ``step_ms_p99``: ≤ (1 + ``--tol-tail``) × median (the tail
  regression the mean hides; see bench.py's sampling-pass caveat).

Medians, not bests: one lucky round must not ratchet the bar to a level
the hardware only sometimes reaches (the v5e tunnel shows ~3% spread
run-to-run). ``--self-check`` runs a built-in decision table over
synthetic reports (tier-1 wired) so the gate's own logic is pinned.

Baseline files may be raw bench output or the driver's ``BENCH_r*.json``
wrappers (``{"parsed": {...}}``); both shapes load.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Benchmark rows a report may carry (bench.py main()).
ROW_KEYS = ("fp32", "bf16", "fp32_k320", "fp32_hostidx", "fp32_zero1",
            "int8_serve")

#: Default tolerances — one place, shared by the CLI and --self-check.
DEFAULTS = {
    "tol_throughput": 0.05,
    "tol_mfu": 0.07,
    "tol_compile": 2.0,
    "max_spread": 10.0,
    "tol_tail": 0.5,
    "min_int8_speedup": 1.5,
}

#: Per-row tolerance overrides, layered over DEFAULTS (and over any CLI
#: override). fp32_zero1 carries the ZeRO-1 reduce-scatter/all-gather
#: pair whose cost varies with interconnect weather more than the plain
#: all-reduce's — slightly wider floors keep the gate honest without
#: letting a real regression through. int8_serve times single-batch
#: serving dispatches (~ms each), jitterier than the amortized 100-step
#: train chunks. (Absent-metric skipping still applies: rounds before
#: a row existed simply don't gate it.)
ROW_TOLERANCES = {
    "fp32_zero1": {"tol_throughput": 0.08, "tol_mfu": 0.10},
    "int8_serve": {"tol_throughput": 0.10, "max_spread": 15.0},
}


def load_report(path: str) -> dict:
    """Load a bench report: raw ``bench.py`` stdout JSON, or a
    ``BENCH_r*.json`` wrapper (its ``parsed`` field)."""
    with open(path) as f:
        doc = json.load(f)
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    if doc.get("metric") != "train_throughput":
        raise ValueError(f"{path}: not a bench report "
                         f"(metric={doc.get('metric')!r})")
    return doc


def _median(vals: List[float]) -> Optional[float]:
    vals = sorted(v for v in vals if isinstance(v, (int, float)))
    if not vals:
        return None
    mid = len(vals) // 2
    return vals[mid] if len(vals) % 2 else (vals[mid - 1] + vals[mid]) / 2


def _get(report: dict, row: Optional[str], key: str):
    src = report if row is None else report.get(row)
    if not isinstance(src, dict):
        return None
    v = src.get(key)
    return v if isinstance(v, (int, float)) else None


def gate(candidate: dict, baselines: List[dict], **tol) -> List[dict]:
    """Run every applicable check → list of
    ``{check, row, candidate, baseline, limit, ok}`` dicts (the JSON the
    CI consumer reads; ``main`` renders them as a table)."""
    t = dict(DEFAULTS)
    t.update({k: v for k, v in tol.items() if v is not None})
    checks = []

    def add(check, row, cand, base, limit, ok):
        checks.append({"check": check, "row": row or "headline",
                       "candidate": cand, "baseline": base,
                       "limit": round(limit, 4), "ok": bool(ok)})

    def floor_check(check, row, key, tol_frac):
        cand = _get(candidate, row, key)
        med = _median([_get(b, row, key) for b in baselines])
        if cand is None or med is None:
            return
        limit = med * (1.0 - tol_frac)
        add(check, row, cand, med, limit, cand >= limit)

    # Headline throughput, then per-row metrics (per-row tolerance
    # entries in ROW_TOLERANCES layer over the CLI/default ones).
    floor_check("throughput", None, "value", t["tol_throughput"])
    for row in ROW_KEYS:
        if not isinstance(candidate.get(row), dict):
            continue
        tr = {**t, **ROW_TOLERANCES.get(row, {})}
        floor_check("throughput", row, "images_per_sec_per_chip",
                    tr["tol_throughput"])
        floor_check("mfu", row, "mfu", tr["tol_mfu"])
        cand = _get(candidate, row, "compile_s")
        med = _median([_get(b, row, "compile_s") for b in baselines])
        if cand is not None and med is not None:
            limit = max(med, 1.0) * tr["tol_compile"]
            add("compile_s", row, cand, med, limit, cand <= limit)
        spread = _get(candidate, row, "spread_pct")
        if spread is not None:
            add("spread", row, spread, None, tr["max_spread"],
                spread <= tr["max_spread"])
        cand = _get(candidate, row, "step_ms_p99")
        med = _median([_get(b, row, "step_ms_p99") for b in baselines])
        if cand is not None and med is not None:
            limit = med * (1.0 + tr["tol_tail"])
            add("step_tail_p99", row, cand, med, limit, cand <= limit)
    # Quantized-serving speedup floor (docs/QUANT.md): int8 must beat
    # the bf16 serving path by min_int8_speedup — an absolute contract,
    # not a trajectory comparison, because the whole point of shipping
    # the path is the speedup. TPU rows only: XLA's CPU int8 lowering
    # has no MXU advantage, so CPU rows (where the gate MACHINERY is
    # verified in tier-1) are recorded but not floored.
    row = candidate.get("int8_serve")
    if isinstance(row, dict):
        tr = {**t, **ROW_TOLERANCES.get("int8_serve", {})}
        sp = row.get("speedup_vs_bf16")
        if isinstance(sp, (int, float)) and row.get("backend") == "tpu":
            add("int8_speedup", "int8_serve", sp, None,
                tr["min_int8_speedup"], sp >= tr["min_int8_speedup"])
    return checks


def render(checks: List[dict]) -> str:
    lines = [f"{'check':<14} {'row':<13} {'candidate':>12} "
             f"{'baseline':>12} {'limit':>12}  verdict"]
    for c in checks:
        base = "-" if c["baseline"] is None else f"{c['baseline']:.4g}"
        lines.append(
            f"{c['check']:<14} {c['row']:<13} {c['candidate']:>12.4g} "
            f"{base:>12} {c['limit']:>12.4g}  "
            f"{'ok' if c['ok'] else 'REGRESSION'}")
    bad = sum(1 for c in checks if not c["ok"])
    lines.append(f"{len(checks)} check(s), {bad} regression(s): "
                 f"{'FAIL' if bad else 'PASS'}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# --self-check: the decision table that pins the gate's own logic
# ---------------------------------------------------------------------------

def _synth(ips=1000.0, mfu=0.30, compile_s=20.0, spread=2.0,
           p99=1.2, int8=None) -> dict:
    doc = {"metric": "train_throughput", "value": ips,
           "unit": "images/sec/chip",
           "fp32": {"images_per_sec_per_chip": ips, "mfu": mfu,
                    "compile_s": compile_s, "spread_pct": spread,
                    "step_ms_p50": 1.0, "step_ms_p99": p99}}
    if int8 is not None:   # (speedup_vs_bf16, backend)
        doc["int8_serve"] = {"images_per_sec_per_chip": 5000.0,
                             "speedup_vs_bf16": int8[0],
                             "backend": int8[1], "spread_pct": 2.0}
    return doc


#: (case name, candidate overrides, expected gate verdict).
SELF_CHECK_TABLE = (
    ("identical", {}, True),
    ("within_noise", {"ips": 980.0}, True),
    ("improvement", {"ips": 1200.0, "compile_s": 1.0}, True),
    ("throughput_-10%", {"ips": 900.0}, False),
    ("mfu_-10%", {"mfu": 0.27}, False),
    ("compile_3x", {"compile_s": 60.0}, False),
    ("spread_blowup", {"spread": 15.0}, False),
    ("tail_p99_2x", {"p99": 2.4}, False),
    ("warm_cache_compile_0", {"compile_s": 0.1}, True),
    # int8_serve speedup floor: absolute, TPU rows only (the row's own
    # backend key decides — a CPU row never trips it).
    ("int8_speedup_ok", {"int8": (1.8, "tpu")}, True),
    ("int8_speedup_low", {"int8": (1.2, "tpu")}, False),
    ("int8_cpu_not_floored", {"int8": (0.8, "cpu")}, True),
)


def self_check() -> int:
    """Run the decision table; nonzero when the gate's verdicts drift
    from the documented expectations."""
    baselines = [_synth(990.0), _synth(1000.0), _synth(1010.0)]
    failed = 0
    for name, overrides, expect_pass in SELF_CHECK_TABLE:
        checks = gate(_synth(**overrides), baselines)
        ok = all(c["ok"] for c in checks)
        verdict = "ok" if ok == expect_pass else "WRONG VERDICT"
        if ok != expect_pass:
            failed += 1
        print(f"  {name:<22} expected "
              f"{'pass' if expect_pass else 'fail'}, gate said "
              f"{'pass' if ok else 'fail'}: {verdict}")
    print(f"self-check: {len(SELF_CHECK_TABLE)} case(s), "
          f"{failed} wrong verdict(s)")
    return 1 if failed else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="gate a bench.py report against the BENCH_r*.json "
                    "trajectory (exit 1 on regression)")
    p.add_argument("candidate", nargs="?",
                   help="fresh report (bench.py stdout JSON or a "
                        "BENCH_r*.json wrapper)")
    p.add_argument("--baselines", default=os.path.join(REPO,
                                                       "BENCH_r*.json"),
                   help="glob of baseline reports (default: the repo's "
                        "BENCH_r*.json trajectory)")
    p.add_argument("--tol-throughput", type=float, default=None,
                   help=f"max fractional throughput drop vs median "
                        f"(default {DEFAULTS['tol_throughput']})")
    p.add_argument("--tol-mfu", type=float, default=None,
                   help=f"max fractional MFU drop "
                        f"(default {DEFAULTS['tol_mfu']})")
    p.add_argument("--tol-compile", type=float, default=None,
                   help=f"max compile_s vs max(median, 1 s) "
                        f"(default {DEFAULTS['tol_compile']}x)")
    p.add_argument("--max-spread", type=float, default=None,
                   help=f"max spread_pct, absolute "
                        f"(default {DEFAULTS['max_spread']})")
    p.add_argument("--tol-tail", type=float, default=None,
                   help=f"max fractional step_ms_p99 growth "
                        f"(default {DEFAULTS['tol_tail']})")
    p.add_argument("--min-int8-speedup", type=float, default=None,
                   help=f"int8_serve speedup_vs_bf16 floor, TPU rows "
                        f"only (default {DEFAULTS['min_int8_speedup']})")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--self-check", action="store_true",
                   help="run the built-in synthetic decision table "
                        "instead of gating a report")
    args = p.parse_args(argv)

    if args.self_check:
        return self_check()
    if not args.candidate:
        p.error("candidate report required (or --self-check)")
    baseline_paths = sorted(glob.glob(args.baselines))
    baselines = []
    for path in baseline_paths:
        try:
            baselines.append(load_report(path))
        except (OSError, ValueError) as e:
            print(f"[gate] skipping baseline {path}: {e}",
                  file=sys.stderr)
    if not baselines:
        print(f"[gate] no usable baselines match {args.baselines!r}",
              file=sys.stderr)
        return 2
    candidate = load_report(args.candidate)
    checks = gate(candidate, baselines,
                  tol_throughput=args.tol_throughput,
                  tol_mfu=args.tol_mfu, tol_compile=args.tol_compile,
                  max_spread=args.max_spread, tol_tail=args.tol_tail,
                  min_int8_speedup=args.min_int8_speedup)
    bad = any(not c["ok"] for c in checks)
    if args.format == "json":
        print(json.dumps({"candidate": args.candidate,
                          "baselines": baseline_paths,
                          "checks": checks,
                          "pass": not bad}))
    else:
        print(f"candidate {args.candidate} vs {len(baselines)} "
              f"baseline(s)")
        print(render(checks))
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
