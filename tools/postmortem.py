#!/usr/bin/env python
"""Render a flight-recorder post-mortem bundle into a human-readable
timeline (and, optionally, a merged Perfetto trace).

A bundle is the atomic directory ``utils/flightrec.py`` writes the
moment an alert fires: ``ring.jsonl`` (the last N records the process
logged, wallclock-stamped), ``alert.json`` (the firing that triggered
the capture), ``config.json`` / ``env.json`` / ``context.json`` (what
the process was, where it ran, what it was serving), and — for training
captures — a ``devprof/`` directory once the one-shot device-profile
window the capture armed has landed.

Usage:
  python tools/postmortem.py BUNDLE_DIR [more ...] [--out merged.json]

``--out`` funnels the ring through ``tools/trace_aggregate.py``'s
merged-trace builder, so ring ``rspan`` records become causally-linked
hop lanes and everything else becomes instants on the shared clock —
one file to open next to the run's other streams. With no bundle
argument, ``--scan DIR`` lists the bundles under a ``--postmortem_dir``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_bundle(bundle_dir: str) -> dict:
    """Parse one bundle directory into plain data (JSON-ready)."""
    ring = []
    ring_path = os.path.join(bundle_dir, "ring.jsonl")
    try:
        with open(ring_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        ring.append(json.loads(line))
                    except ValueError:
                        continue
    except OSError:
        pass
    devprof = os.path.join(bundle_dir, "devprof")
    return {
        "dir": bundle_dir,
        "alert": _read_json(os.path.join(bundle_dir, "alert.json")),
        "env": _read_json(os.path.join(bundle_dir, "env.json")),
        "context": _read_json(os.path.join(bundle_dir, "context.json")),
        "config": _read_json(os.path.join(bundle_dir, "config.json")),
        "ring": ring,
        "devprof": devprof if os.path.isdir(devprof) else None,
    }


def render_bundle(b: dict, ring_tail: int = 40) -> str:
    """The human timeline: what fired, who we were, and the ring's last
    records leading up to the capture (newest last — read bottom-up
    from the alert)."""
    lines = [f"== post-mortem bundle {b['dir']} =="]
    alert = b.get("alert") or {}
    if alert:
        lines.append(
            f"  alert: [{alert.get('severity')}] {alert.get('rule')} "
            f"(value {alert.get('value')}, window {alert.get('window')})")
        if alert.get("captured_wallclock"):
            lines.append(
                f"  captured at unix {alert['captured_wallclock']}")
    env = b.get("env") or {}
    if env:
        parts = [f"python {env.get('python')}"]
        if env.get("jax"):
            parts.append(f"jax {env['jax']}")
        parts.append(f"pid {env.get('pid')}")
        lines.append(f"  process: {', '.join(parts)}")
    context = b.get("context") or {}
    if context:
        per = ", ".join(f"{k}={v}" for k, v in sorted(context.items()))
        lines.append(f"  context: {per}")
    if b.get("devprof"):
        lines.append(f"  devprof window: {b['devprof']}")
    ring = b.get("ring") or []
    kinds = {}
    for r in ring:
        kinds[r.get("kind")] = kinds.get(r.get("kind"), 0) + 1
    per = ", ".join(f"{k}: {n}" for k, n in sorted(kinds.items(),
                                                   key=lambda kv: -kv[1]))
    lines.append(f"  ring: {len(ring)} record(s) ({per})")
    wall0 = next((r["wallclock"] for r in ring
                  if isinstance(r.get("wallclock"), (int, float))), None)
    tail = ring[-ring_tail:]
    if len(ring) > len(tail):
        lines.append(f"    ... {len(ring) - len(tail)} earlier "
                     f"record(s) omitted")
    for r in tail:
        w = r.get("wallclock")
        rel = (f"+{w - wall0:8.3f}s"
               if isinstance(w, (int, float)) and wall0 is not None
               else " " * 10)
        detail = {k: v for k, v in r.items()
                  if k not in ("kind", "wallclock")}
        lines.append(f"    {rel} {r.get('kind')} "
                     f"{json.dumps(detail, default=str)[:120]}")
    return "\n".join(lines)


def write_merged_trace(bundles: List[dict], out: str) -> int:
    """Funnel the rings through trace_aggregate's merged-trace builder:
    write each ring back out as a JSONL stream (its records already
    carry absolute wallclocks) and build one Perfetto document. Returns
    the event count."""
    import tempfile

    from tools.trace_aggregate import build_merged_trace

    paths = []
    with tempfile.TemporaryDirectory(prefix="postmortem_") as tmp:
        for i, b in enumerate(bundles):
            p = os.path.join(tmp, f"ring_{i}.jsonl")
            wmin = min((r["wallclock"] for r in b["ring"]
                        if isinstance(r.get("wallclock"), (int, float))),
                       default=0.0)
            with open(p, "w") as f:
                for r in b["ring"]:
                    # Ring records came through the observer hook, so
                    # they lack the logger-written base keys — rebuild
                    # `t` from the ring's wallclocks so the builder's
                    # anchor recovery (wallclock − t) lands every record
                    # at its true place on the merged clock.
                    w = r.get("wallclock")
                    t = (round(w - wmin, 6)
                         if isinstance(w, (int, float)) else 0.0)
                    rec = {"t": t, "task": i, **r}
                    f.write(json.dumps(rec, default=str) + "\n")
            paths.append(p)
        doc = build_merged_trace(paths)
        doc.setdefault("otherData", {})["bundles"] = \
            [b["dir"] for b in bundles]
        os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
        with open(out, "w") as f:
            json.dump(doc, f)
    return len(doc["traceEvents"])


def scan(postmortem_dir: str) -> List[str]:
    """Bundle directories under a ``--postmortem_dir``, oldest first
    (the ``<rule>_<seq>`` names sort in capture order per rule)."""
    try:
        names = sorted(os.listdir(postmortem_dir))
    except OSError:
        return []
    return [os.path.join(postmortem_dir, n) for n in names
            if os.path.isfile(os.path.join(postmortem_dir, n,
                                           "alert.json"))]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="render flight-recorder post-mortem bundles")
    p.add_argument("bundles", nargs="*",
                   help="bundle directories (flightrec captures)")
    p.add_argument("--scan", default=None,
                   help="list bundles under this --postmortem_dir "
                        "(and render them all)")
    p.add_argument("--out", default=None,
                   help="write a merged Perfetto trace of the rings")
    p.add_argument("--format", choices=("text", "json"), default="text")
    args = p.parse_args(argv)
    dirs = list(args.bundles)
    if args.scan:
        dirs.extend(scan(args.scan))
    if not dirs:
        p.error("no bundles given (pass directories or --scan DIR)")
    loaded = [load_bundle(d) for d in dirs]
    if args.format == "json":
        print(json.dumps([{k: v for k, v in b.items()
                           if k != "config"} for b in loaded],
                         default=str))
    else:
        for b in loaded:
            print(render_bundle(b))
    if args.out:
        n = write_merged_trace(loaded, args.out)
        print(f"merged trace ({n} events) -> {args.out}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
