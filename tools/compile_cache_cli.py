#!/usr/bin/env python
"""Inspect / verify / prune a persistent compilation cache directory
(``--compile_cache_dir``, ``compilecache/``; layout and failure modes in
``docs/COMPILECACHE.md``).

Subcommands (all operate on the flat on-disk layout, no JAX import —
usable on a machine without the accelerator stack):

- ``inspect DIR`` — one row per committed entry: key, phase, backend,
  executable/HLO sizes, compile seconds, hit count, last use.
- ``verify DIR`` — re-digest every entry's executable payload against
  its sha256 sidecar (the same walk the load path performs); exit 1
  when any entry fails. Corrupt entries are reported, not deleted —
  the fail-open load path drops them lazily, and ``prune --corrupt``
  does it eagerly.
- ``prune DIR [--max_bytes N] [--corrupt] [--all]`` — apply the LRU
  size bound offline / drop corrupt entries / wipe the cache.

Usage: ``python tools/compile_cache_cli.py verify /path/to/cache``
(exit 1 on violation; ``tests/test_compilecache.py`` runs the verify
smoke in the tier-1 suite).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dml_cnn_cifar10_tpu.compilecache import CompileCache  # noqa: E402


def _fmt_bytes(n) -> str:
    n = n or 0
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024:
            return f"{n:.0f} {unit}" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} TiB"


def _age(ts) -> str:
    if not ts:
        return "-"
    s = max(0.0, time.time() - float(ts))
    for div, unit in ((86400, "d"), (3600, "h"), (60, "m")):
        if s >= div:
            return f"{s / div:.1f}{unit}"
    return f"{s:.0f}s"


def cmd_inspect(cache: CompileCache) -> int:
    entries = sorted(cache.entries(),
                     key=lambda km: km[1].get("last_used", 0),
                     reverse=True)
    if not entries:
        print(f"{cache.cache_dir}: empty cache")
        return 0
    total = 0
    print(f"{'key':<34} {'phase':<22} {'backend':<8} {'exec':>10} "
          f"{'hlo':>10} {'compile_s':>9} {'hits':>5} {'last_used':>9}")
    for key, meta in entries:
        nbytes = cache.entry_bytes(key)
        total += nbytes
        print(f"{key:<34} {meta.get('phase') or '-':<22} "
              f"{meta.get('backend') or '-':<8} "
              f"{_fmt_bytes(meta.get('exec_bytes')):>10} "
              f"{_fmt_bytes(meta.get('hlo_bytes')):>10} "
              f"{meta.get('compile_s') if meta.get('compile_s') is not None else '-':>9} "
              f"{meta.get('hits') or 0:>5} "
              f"{_age(meta.get('last_used')):>9}")
    print(f"{len(entries)} entries, {_fmt_bytes(total)} on disk "
          f"(bound {_fmt_bytes(cache.max_bytes)})")
    return 0


def cmd_verify(cache: CompileCache) -> int:
    entries = cache.entries()
    if not entries:
        print(f"{cache.cache_dir}: empty cache")
        return 0
    bad = 0
    for key, _ in sorted(entries):
        ok, reason = cache.verify_entry(key)
        print(f"{key}: {'OK' if ok else 'CORRUPT'} ({reason})")
        if not ok:
            bad += 1
    print(f"{len(entries) - bad}/{len(entries)} entries verified"
          + (f"; {bad} CORRUPT (the load path will drop + recompile "
             f"them; `prune --corrupt` drops them now)" if bad else ""))
    return 1 if bad else 0


def cmd_prune(cache: CompileCache, wipe: bool, corrupt: bool) -> int:
    entries = cache.entries()
    before = sum(cache.entry_bytes(k) for k, _ in entries)
    dropped = 0
    if wipe:
        for key, _ in entries:
            cache.drop(key)
            dropped += 1
    else:
        if corrupt:
            for key, _ in entries:
                ok, _reason = cache.verify_entry(key)
                if not ok:
                    cache.drop(key)
                    dropped += 1
        n_before = len(cache.entries())
        cache._evict()
        dropped += n_before - len(cache.entries())
    after = sum(cache.entry_bytes(k) for k, _ in cache.entries())
    print(f"pruned {dropped} entr{'y' if dropped == 1 else 'ies'}: "
          f"{_fmt_bytes(before)} -> {_fmt_bytes(after)} "
          f"(bound {_fmt_bytes(cache.max_bytes)})")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="compile_cache_cli",
        description="inspect/verify/prune a --compile_cache_dir")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("inspect", "verify", "prune"):
        sp = sub.add_parser(name)
        sp.add_argument("dir", help="cache directory")
        if name == "prune":
            sp.add_argument("--max_bytes", type=int, default=None,
                            help="LRU bound to apply (default: the "
                                 "config default, 2e9)")
            sp.add_argument("--corrupt", action="store_true",
                            help="also drop entries that fail "
                                 "integrity verification")
            sp.add_argument("--all", action="store_true",
                            help="wipe every entry")
    args = p.parse_args(argv)
    if not os.path.isdir(args.dir):
        print(f"{args.dir}: not a directory", file=sys.stderr)
        return 2
    max_bytes = getattr(args, "max_bytes", None)
    cache = CompileCache(args.dir,
                         max_bytes=max_bytes if max_bytes is not None
                         else 2_000_000_000)
    if args.cmd == "inspect":
        return cmd_inspect(cache)
    if args.cmd == "verify":
        return cmd_verify(cache)
    return cmd_prune(cache, wipe=args.all, corrupt=args.corrupt)


if __name__ == "__main__":
    sys.exit(main())
