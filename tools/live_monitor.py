#!/usr/bin/env python
"""Cluster-wide live run monitor: one auto-refreshing terminal view of
everything the fleet is doing RIGHT NOW.

Every other observability tool here is post-hoc — reports and traces
read after the run. This one watches a run while it is live, from the
two surfaces the live layer exports:

- **`GET /metrics` endpoints** (``--endpoints``): the Prometheus-text
  registries served by ``--stats_port`` (trainer), ``--mode serve``,
  and the fleet router — scraped each refresh and parsed with the same
  :func:`~dml_cnn_cifar10_tpu.utils.metrics_registry.parse_prometheus_text`
  the exposition lint uses.
- **`--metrics_jsonl` streams** (positional paths): tailed
  incrementally (:class:`JsonlTail` — shared with
  ``tools/telemetry_report.py --follow``), each stream aligned onto one
  clock via its heartbeat wallclock anchors
  (``tools/trace_aggregate.py``'s alignment, reused).

The view: world size and epoch, per-task step / step rate / goodput
split, serve QPS/p99 per replica, fleet routing counters, and the
active alerts (``alert`` records not yet paired with an
``alert_resolved``). On a FINISHED run (every stream carries its final
record, no endpoints to poll) it degrades to a one-shot snapshot and
exits — the same renderer, no refresh loop.

Usage:
  python tools/live_monitor.py logs_0/m.jsonl logs_1/m.jsonl \\
      [--endpoints http://host:8080 ...] [--refresh 2] [--once] \\
      [--format text|json]

Pure seams for tests: :func:`build_state` (records + scrapes → plain
dict) and :func:`render_view` (dict → text).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dml_cnn_cifar10_tpu.utils.metrics_registry import \
    parse_prometheus_text  # noqa: E402
from tools.trace_aggregate import clock_offset  # noqa: E402

#: Record kinds that mark a stream as finished (one-shot degradation).
FINAL_KINDS = ("done", "serve_done", "fleet_done", "chaos_done")


class JsonlTail:
    """Incremental JSONL reader: each :meth:`poll` returns the records
    appended since the last one. Tolerates a file that does not exist
    yet (a worker still warming up) and a partial last line (a writer
    mid-append) — both simply wait for the next poll. Shared by this
    monitor and ``telemetry_report.py --follow``."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._partial = ""

    def poll(self) -> List[dict]:
        try:
            with open(self.path, "r") as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except OSError:
            return []
        if not chunk:
            return []
        text = self._partial + chunk
        lines = text.split("\n")
        # No trailing newline ⇒ the last element is a partial line the
        # writer has not finished; hold it for the next poll.
        self._partial = "" if text.endswith("\n") else lines.pop()
        out = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue   # torn write; the record is lost, not fatal
        return out


def scrape_endpoint(url: str, timeout_s: float = 2.0) -> dict:
    """One ``GET <url>/metrics`` scrape → ``{"url", "ok", "families"}``
    (families = parsed exposition doc; ``ok: False`` + ``error`` when
    the endpoint is unreachable — a dead endpoint is a finding, not a
    crash)."""
    target = url.rstrip("/")
    if not target.endswith("/metrics"):
        target += "/metrics"
    try:
        with urllib.request.urlopen(target, timeout=timeout_s) as resp:
            text = resp.read().decode("utf-8", errors="replace")
        return {"url": url, "ok": True,
                "families": parse_prometheus_text(text)}
    except Exception as e:
        return {"url": url, "ok": False, "error": str(e),
                "families": {}}


def _last(records: List[dict], kind: str) -> Optional[dict]:
    for r in reversed(records):
        if r.get("kind") == kind:
            return r
    return None


def active_alerts(records: List[dict]) -> List[dict]:
    """Alert firings not yet paired with a resolution, in fire order."""
    active: Dict[str, dict] = {}
    for r in records:
        if r.get("kind") == "alert":
            active[r.get("rule")] = r
        elif r.get("kind") == "alert_resolved":
            active.pop(r.get("rule"), None)
    return list(active.values())


def remediation_state(records: List[dict],
                      alert_id: Optional[str]) -> Optional[dict]:
    """The newest ``remediation`` record answering ``alert_id`` —
    the in-flight autopilot state for a still-active alert (policy,
    action, status; a cooldown suppression's ``detail`` carries the
    steps/seconds remaining). None when the autopilot has not
    answered (or is not armed)."""
    if not alert_id:
        return None
    for r in reversed(records):
        if r.get("kind") == "remediation" \
                and r.get("alert_id") == alert_id:
            return r
    return None


def stream_finished(records: List[dict]) -> bool:
    return any(r.get("kind") in FINAL_KINDS for r in records)


def build_state(streams: Dict[str, List[dict]],
                scrapes: List[dict] = (),
                now: Optional[float] = None) -> dict:
    """Fold the tailed streams + endpoint scrapes into one plain-dict
    view state (JSON-ready — ``--format json`` prints it verbatim)."""
    now = time.time() if now is None else now
    tasks = []
    world_size = None
    epoch = None
    alerts: List[dict] = []
    for path, records in streams.items():
        offset = clock_offset(records)
        last_t = max((r.get("t") or 0.0 for r in records), default=None)
        train = _last(records, "train")
        serve = _last(records, "serve")
        fleet = _last(records, "fleet")
        goodput = _last(records, "goodput")
        task_ids = [r.get("task") for r in records
                    if r.get("task") is not None]
        entry = {
            "path": path,
            "task": task_ids[-1] if task_ids else 0,
            "records": len(records),
            "finished": stream_finished(records),
            # Age of the newest record on the shared clock — only
            # computable for heartbeat-aligned streams.
            "age_s": (round(now - (offset + last_t), 1)
                      if offset is not None and last_t is not None
                      else None),
        }
        if train:
            entry["train"] = {
                k: train.get(k)
                for k in ("step", "loss", "images_per_sec",
                          "device_step_ms", "drain_wait_ms")}
        if goodput:
            entry["goodput"] = {
                k[:-len("_frac")]: v for k, v in goodput.items()
                if k.endswith("_frac")}
        if serve:
            entry["serve"] = {
                k: serve.get(k)
                for k in ("qps", "p50_ms", "p99_ms", "completed",
                          "shed_queue", "shed_deadline", "batch_fill")}
        if fleet:
            entry["fleet"] = {
                k: fleet.get(k)
                for k in ("replicas", "live", "routed", "rerouted",
                          "evictions", "shed", "device_ms")}
        tasks.append(entry)
        for decision_kind in ("elastic_expand", "elastic_restart"):
            d = _last(records, decision_kind)
            if d and (epoch is None or (d.get("epoch") or 0) > epoch):
                epoch = d.get("epoch")
                world_size = d.get("world_size")
        for a in active_alerts(records):
            alert = {"path": path, "rule": a.get("rule"),
                     "severity": a.get("severity"),
                     "value": a.get("value"),
                     "window": a.get("window"),
                     "id": a.get("id")}
            rem = remediation_state(records, a.get("id"))
            if rem is not None:
                alert["remediation"] = {
                    k: rem.get(k)
                    for k in ("policy", "action", "status", "detail")}
            alerts.append(alert)
    if world_size is None and tasks:
        # No restart decisions yet: approximate the world as the
        # distinct task indices observed across the streams.
        world_size = len({t["task"] for t in tasks})
    endpoints = []
    for s in scrapes:
        e = {"url": s.get("url"), "ok": s.get("ok", False)}
        if not s.get("ok"):
            e["error"] = s.get("error")
        fam = s.get("families") or {}

        def sample(name):
            f = fam.get(name)
            if not f or not f.get("samples"):
                return None
            return next(iter(f["samples"].values()))

        for name, key in (("dml_train_step", "step"),
                          ("dml_train_images_per_sec", "images_per_sec"),
                          ("dml_serve_qps", "qps"),
                          ("dml_serve_p99_ms", "p99_ms"),
                          ("dml_fleet_live_replicas", "live_replicas"),
                          ("dml_cluster_world_size", "world_size")):
            v = sample(name)
            if v is not None:
                e[key] = v
        fam_active = fam.get("dml_alert_active", {}).get("samples", {})
        firing = [dict(labels) for labels, v in fam_active.items()
                  if v == 1.0]
        if firing:
            e["alerts"] = firing
            for a in firing:
                alerts.append({"path": s.get("url"),
                               "rule": a.get("rule"),
                               "severity": a.get("severity"),
                               "value": None, "window": None})
        endpoints.append(e)
    return {
        "now_unix": round(now, 3),
        "world_size": world_size,
        "epoch": epoch,
        "tasks": sorted(tasks, key=lambda t: (t["task"], t["path"])),
        "endpoints": endpoints,
        "alerts": alerts,
        "finished": bool(tasks) and all(t["finished"] for t in tasks),
    }


def _fmt(v, digits=2):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{digits}f}"
    return str(v)


def render_view(state: dict) -> str:
    """The terminal view (pure: state dict → text)."""
    lines = []
    head = "== live run monitor"
    if state.get("world_size") is not None:
        head += f" · world size {state['world_size']}"
    if state.get("epoch") is not None:
        head += f" · epoch {state['epoch']}"
    if state.get("finished"):
        head += " · RUN FINISHED (one-shot view)"
    lines.append(head + " ==")
    for t in state.get("tasks", []):
        age = f" ({t['age_s']}s ago)" if t.get("age_s") is not None \
            else ""
        lines.append(f"  task {t['task']} [{t['path']}] "
                     f"{t['records']} records"
                     f"{' FINISHED' if t['finished'] else ''}{age}")
        tr = t.get("train")
        if tr:
            lines.append(
                f"    train: step {tr.get('step')}, "
                f"{_fmt(tr.get('images_per_sec'), 1)} img/s, loss "
                f"{_fmt(tr.get('loss'), 4)}, device step "
                f"{_fmt(tr.get('device_step_ms'))} ms "
                f"(drain-wait {_fmt(tr.get('drain_wait_ms'))} ms)")
        gp = t.get("goodput")
        if gp:
            split = " ".join(
                f"{cat} {100 * (gp.get(cat) or 0):.0f}%"
                for cat in ("train", "compile", "data", "eval",
                            "checkpoint", "sync") if cat in gp)
            lines.append(f"    goodput: {split}")
        sv = t.get("serve")
        if sv:
            lines.append(
                f"    serve: {_fmt(sv.get('qps'), 1)} qps, p50/p99 "
                f"{_fmt(sv.get('p50_ms'))}/{_fmt(sv.get('p99_ms'))} ms,"
                f" shed {(sv.get('shed_queue') or 0) + (sv.get('shed_deadline') or 0)},"
                f" fill {_fmt(sv.get('batch_fill'))}")
        fl = t.get("fleet")
        if fl:
            lines.append(
                f"    fleet: {fl.get('live')}/{fl.get('replicas')} "
                f"live, routed {fl.get('routed')} "
                f"(re-routed {fl.get('rerouted')}), evictions "
                f"{fl.get('evictions')}, shed {fl.get('shed')}")
            if fl.get("device_ms"):
                per = ", ".join(f"r{rid} {_fmt(ms, 1)} ms" for rid, ms
                                in sorted(fl["device_ms"].items()))
                lines.append(f"    fleet device_ms: {per}")
    for e in state.get("endpoints", []):
        if not e.get("ok"):
            lines.append(f"  endpoint {e['url']}: UNREACHABLE "
                         f"({e.get('error')})")
            continue
        bits = []
        for key, label in (("step", "step"),
                           ("images_per_sec", "img/s"),
                           ("qps", "qps"), ("p99_ms", "p99 ms"),
                           ("live_replicas", "live replicas"),
                           ("world_size", "world")):
            if key in e:
                bits.append(f"{label} {_fmt(e[key], 1)}")
        lines.append(f"  endpoint {e['url']}: "
                     + (", ".join(bits) if bits else "up"))
    alerts = state.get("alerts", [])
    if alerts:
        lines.append(f"  ACTIVE ALERTS ({len(alerts)}):")
        for a in alerts:
            lines.append(
                f"    [{a.get('severity')}] {a.get('rule')} "
                f"value={_fmt(a.get('value'), 4)} "
                f"window={a.get('window')} ({a.get('path')})")
            rem = a.get("remediation")
            if rem:
                detail = rem.get("detail")
                lines.append(
                    f"      autopilot: {rem.get('policy')}/"
                    f"{rem.get('action')} {rem.get('status')}"
                    + (f" ({detail})" if detail else ""))
    else:
        lines.append("  no active alerts")
    return "\n".join(lines)


def run_monitor(paths: List[str], endpoints: List[str],
                refresh_s: float = 2.0, once: bool = False,
                max_refreshes: Optional[int] = None,
                fmt: str = "text", out=None) -> int:
    """The monitor loop. ``once`` (or a finished run with no
    endpoints) renders a single snapshot; ``max_refreshes`` bounds the
    loop for tests/batch use."""
    out = sys.stdout if out is None else out
    tails = {p: JsonlTail(p) for p in paths}
    streams: Dict[str, List[dict]] = {p: [] for p in paths}
    n = 0
    while True:
        for p, tail in tails.items():
            streams[p].extend(tail.poll())
        scrapes = [scrape_endpoint(u) for u in endpoints]
        state = build_state(streams, scrapes)
        if fmt == "json":
            print(json.dumps(state), file=out)
        else:
            if not once and out is sys.stdout and n > 0:
                out.write("\x1b[2J\x1b[H")   # clear + home
            print(render_view(state), file=out)
        n += 1
        done = once \
            or (state["finished"] and not endpoints and paths) \
            or (max_refreshes is not None and n >= max_refreshes)
        if done:
            return 0
        try:
            time.sleep(refresh_s)
        except KeyboardInterrupt:
            return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="auto-refreshing live view over --metrics_jsonl "
                    "streams and GET /metrics endpoints")
    p.add_argument("streams", nargs="*",
                   help="--metrics_jsonl files to tail (they may not "
                        "exist yet — workers still warming up)")
    p.add_argument("--endpoints", nargs="*", default=[],
                   help="base URLs serving GET /metrics "
                        "(--stats_port trainers, serve servers, fleet "
                        "routers)")
    p.add_argument("--refresh", type=float, default=2.0,
                   help="seconds between refreshes")
    p.add_argument("--once", action="store_true",
                   help="render one snapshot and exit (automatic when "
                        "every stream is finished and there are no "
                        "endpoints)")
    p.add_argument("--max-refreshes", type=int, default=None,
                   help="stop after N refreshes (batch/test use)")
    p.add_argument("--format", choices=("text", "json"),
                   default="text")
    args = p.parse_args(argv)
    if not args.streams and not args.endpoints:
        p.error("nothing to watch: give JSONL stream paths and/or "
                "--endpoints")
    return run_monitor(args.streams, args.endpoints,
                       refresh_s=args.refresh, once=args.once,
                       max_refreshes=args.max_refreshes,
                       fmt=args.format)


if __name__ == "__main__":
    sys.exit(main())
