#!/usr/bin/env python
"""Generate docs/CLI.md from the argparse definition (single source of
truth). Run after changing cli/main.py flags; tests/test_cli_doc.py
fails when the doc drifts from the parser."""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def render() -> str:
    from dml_cnn_cifar10_tpu.cli.main import build_parser

    p = build_parser()
    lines = [
        "# CLI reference",
        "",
        "Generated from `cli/main.py` by `tools/gen_cli_doc.py` — do not",
        "edit by hand (`python tools/gen_cli_doc.py` regenerates;",
        "`tests/test_cli_doc.py` enforces freshness).",
        "",
        "The observability flags (`--metrics_jsonl`, `--telemetry`,",
        "`--trace_events_path`, `--health_metrics`, `--tensorboard_dir`,",
        "`--profile_dir`) are documented in depth in",
        "[OBSERVABILITY.md](OBSERVABILITY.md) (JSONL schema, goodput",
        "accounting, Perfetto workflow).",
        "",
        "| Flag | Default | Description |",
        "|---|---|---|",
    ]
    for action in p._actions:
        if isinstance(action, argparse._HelpAction):
            continue
        flag = ", ".join(f"`{s}`" for s in action.option_strings)
        if action.default is None:
            default = "—"
        elif action.default == "":
            default = '`""`'
        else:
            default = f"`{action.default}`"
        # argparse %-expands help at print time; mirror the escape rule.
        help_text = (action.help or "").replace("%%", "%")
        help_text = help_text.replace("|", "\\|")
        if action.choices:
            help_text += (" Choices: "
                          + ", ".join(f"`{c}`" for c in action.choices)
                          + ".")
        lines.append(f"| {flag} | {default} | {help_text} |")
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "CLI.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        f.write(render())
    print(f"wrote {out}")
