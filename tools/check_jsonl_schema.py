#!/usr/bin/env python
"""Lint a metrics JSONL stream against the documented schema.

The JSONL stream (``--metrics_jsonl``) is the contract every downstream
consumer — ``tools/telemetry_report.py``, ``tools/convergence_report.py``,
ad-hoc pandas — parses. This lint enforces the contract documented in
``docs/OBSERVABILITY.md``: every line is strict JSON (no NaN/Infinity
tokens — the writer maps non-finite floats to null), every record carries
the base keys, and each known ``kind`` carries its required keys.

Unknown kinds are tolerated by default (a stream from a NEWER build must
stay lintable by an older tool) but rejected under ``--strict``: a new
record kind must be added to ``KIND_KEYS`` here AND to the schema table
in the doc, which is exactly the drift strict mode exists to catch — a
typo'd kind never lints again. The tier-1 suite runs strict everywhere.

Usage: ``python tools/check_jsonl_schema.py [--strict] run.jsonl
[more.jsonl ...]`` (exit 1 on any violation). ``tests/test_telemetry.py``
runs it over a real training run's stream as part of the tier-1 suite.
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, List

# Keys every record must carry (utils/logging.py writes them).
BASE_KEYS = ("kind", "t", "task")

# Required keys per record kind. Values may be null (the writer maps
# NaN/Inf to null) but the KEY must be present.
KIND_KEYS = {
    # `device_step_ms`/`drain_wait_ms` are the always-on device
    # step-time estimate riding the fused boundary fetch
    # (utils/devprof.py; null before the first complete window).
    # `optimizer_ms` is the per-step device time inside the step's
    # jax.named_scope("optimizer"), from the last --profile_at_steps
    # capture window (null until one completes).
    "train": ("step", "loss", "train_accuracy", "images_per_sec", "lr",
              "device_step_ms", "drain_wait_ms", "optimizer_ms"),
    "eval": ("step", "test_accuracy"),
    "span": ("step", "name", "start_s", "dur_s", "depth"),
    "goodput": ("step", "total_s", "train_frac", "compile_frac",
                "data_frac", "eval_frac", "checkpoint_frac", "sync_frac"),
    "hbm": ("step", "available", "devices", "bytes_in_use", "peak_bytes",
            "bytes_limit"),
    "done": ("step", "images_per_sec"),
    "preempt": ("step", "signum"),
    "numerics_halt": ("step",),
    # Resilience layer (train/supervisor.py, utils/faults.py,
    # ckpt/checkpoint.py; docs/RESILIENCE.md). `fault` records both
    # injections (injected=true) and detections (injected=false);
    # `recovery` records the action taken (skip/restart/recovered);
    # `rollback` the supervisor's restore-point + LR decision;
    # `ckpt_fallback` a checkpoint skipped by the newest-verifiable
    # restore walk; `ckpt_prune_error` a retention prune that failed.
    "fault": ("step", "fault", "injected"),
    "recovery": ("step", "fault", "action", "attempt"),
    "rollback": ("step", "restore_step", "attempt", "lr"),
    "ckpt_fallback": ("step", "path", "error", "walk_ms"),
    "ckpt_prune_error": ("step", "path", "error"),
    # Cluster-resilience layer (parallel/cluster.py;
    # docs/RESILIENCE.md multi-host section). `heartbeat` is the
    # rate-limited JSONL mirror of the beat store; `straggler` names a
    # peer beating but behind at an overrun dispatch seam; `peer_lost`
    # records a stale-heartbeat death declaration, a watchdog abort, an
    # eviction fence, or a non-chief preemption exit (`reason` says
    # which); `elastic_restart` is the adopted coordinated-restart
    # decision (shrunken world, restore step, epoch).
    "heartbeat": ("step", "process_id", "phase", "wallclock"),
    "straggler": ("step", "process_id", "behind_steps", "beat_age_s"),
    "peer_lost": ("step", "process_id", "reason"),
    "elastic_restart": ("step", "restore_step", "world_size", "epoch",
                        "attempt", "source"),
    # Elastic scale-UP (--elastic_expand). `host_rejoin` is a rejoin
    # announcement — logged by the returning host when it starts
    # beating with phase "rejoin", and by the chief when its scan
    # detects one; `elastic_expand` is the adopted coordinated-expand
    # decision (grown world, restore step, epoch) — the scale-UP twin
    # of `elastic_restart`.
    "host_rejoin": ("step", "process_id", "epoch"),
    "elastic_expand": ("step", "restore_step", "world_size", "epoch",
                       "attempt", "source"),
    # A corrupt restart-decision file classified by the hardened
    # RestartCoordinator.read (undecodable payload or sha256-sidecar
    # mismatch): the decision reads as absent, the poll self-heals, and
    # this record is the evidence (rate-limited per payload digest).
    "decision_corrupt": ("path", "error"),
    # Chaos campaign driver (tools/chaos.py; docs/RESILIENCE.md chaos
    # section). `chaos` is one seeded schedule's verdict (`spec` is the
    # ready-to-paste --fault_spec, `invariant` the first violated
    # invariant or null, and on failure `reproducer` carries the shrunk
    # minimal spec); `chaos_done` the campaign summary (faults_by_kind
    # counts every fault the schedules injected, slowest_recovery_s the
    # worst fault→recovery latency observed across all runs).
    "chaos": ("seed", "scenario", "spec", "ok", "invariant", "secs"),
    "chaos_done": ("schedules", "passed", "failed", "faults_by_kind",
                   "slowest_recovery_s"),
    # Sharded-checkpoint fast-resume (ckpt/sharded.py). One record per
    # shard file written (`op: save` — verify null, the digest is being
    # created) or read (`op: restore` — verify true/false/null, null =
    # pre-integrity shard without a sidecar); `op: legacy_glob` flags a
    # manifest without `shard_files` restored via filename glob (bytes/
    # secs/verify null). `source` says where the bytes went/came from:
    # "disk" (the checkpoint dir) or "peer" (the peer-replica store —
    # a diskless restore shows ONLY source=peer records).
    "shard_io": ("op", "shard", "bytes", "secs", "verify", "source"),
    # Peer-redundancy layer (ckpt/peerstore.py; docs/RESILIENCE.md
    # diskless-recovery section). One record per replica operation:
    # `op` is push (a boundary payload committed to the ring-successor
    # store), verify (a replica read's sidecar check), reconstruct (a
    # lost host's shards rebuilt from its replica), decide (the chief's
    # source choice — `staleness` is beat-vs-replica step lag), or
    # fallback (a peer restore classified a miss and degraded to the
    # disk walk). `owner` is the payload's owning process id (null for
    # decide/fallback), `ok` the operation verdict, `error` the
    # classified reason when not ok.
    "peer_replica": ("op", "step", "owner", "bytes", "secs", "ok",
                     "error", "staleness"),
    # Compilation cache (compilecache/; docs/COMPILECACHE.md). One
    # record per compile-seam lookup: `key` is the program fingerprint
    # (null when no cache is configured but the seam still reports its
    # compile, e.g. serve warmup), `phase` names the seam (train_step /
    # train_chunk / train_chunk_resident / init / eval_* /
    # serve_warmup / analysis), `hit` whether an executable was reused,
    # `compile_s` the obtain time (trace + load or compile), `source`
    # one of memory | executable | stablehlo | miss | corrupt | error |
    # uncached.
    "compile": ("key", "phase", "hit", "compile_s", "source"),
    # Device-time attribution (utils/devprof.py; docs/OBSERVABILITY.md
    # device-time section). One record per trace lane of a
    # --profile_at_steps capture window: bucket totals in milliseconds
    # (compute / collective / infeed), the overlapping named-scope
    # total `optimizer_ms` (the weight-update tail), the lane's wall
    # window, and the top-k op table as a nested list of
    # {name, bucket, dur_ms, calls, frac}.
    "devtime": ("step", "device", "total_ms", "compute_ms",
                "collective_ms", "infeed_ms", "optimizer_ms",
                "window_ms", "top_ops"),
    # Streaming alert engine (utils/alerts.py; docs/OBSERVABILITY.md
    # Alerting section). `alert` fires when a rule's condition holds
    # (threshold on consecutive records / rate over a trailing
    # step-or-second window / record absence); `alert_resolved` pairs
    # it when the signal recovers. Emission is rate-limited per rule,
    # and a suppressed re-fire suppresses its resolution too, so the
    # emitted records are strictly paired. `window` is the rule's
    # window descriptor ("2 consecutive" / "50 steps" / "15s"),
    # `value` the reading that crossed (or recovered past) the line.
    # `id` is the firing's identity ("<rule>#<N>", monotonic per
    # engine): stamped on both records of an emitted pair, and the join
    # key remediation records point back at.
    "alert": ("rule", "severity", "window", "value", "id"),
    "alert_resolved": ("rule", "severity", "window", "value", "id"),
    # Autopilot remediation (autopilot/engine.py; docs/AUTOPILOT.md).
    # One record per qualifying alert firing per matching policy:
    # `alert_id` joins the firing `alert` record, `action` is the
    # policy's remediation, `status` one of applied | noop | failed |
    # suppressed_cooldown | suppressed_budget, `postmortem` the
    # flight-recorder bundle captured for the same firing (null when
    # the recorder is unarmed), `detail` the action's own summary,
    # `step` the global step snapshot at firing time.
    "remediation": ("policy", "rule", "alert_id", "action", "status",
                    "postmortem", "detail", "step"),
    # Serving runtime (serve/metrics.py; docs/SERVING.md). Percentile
    # values are null until the window has completions.
    "serve": ("requests", "completed", "shed_queue", "shed_deadline",
              "cache_hit", "qps", "p50_ms", "p95_ms", "p99_ms",
              "batch_fill", "window_s"),
    "serve_done": ("requests", "completed", "shed_queue",
                   "shed_deadline", "cache_hit", "qps", "p50_ms",
                   "p95_ms", "p99_ms", "batch_fill", "shed_fraction",
                   "total_s"),
    # Quantized serving (quant/; docs/QUANT.md). `calibration` is one
    # record per calibrated tensor (weights per-channel, activations
    # per-tensor; channels=0 marks a per-tensor scale); `quant_rejected`
    # is the accuracy-delta publish gate firing — the int8 candidate's
    # holdout top-1 trailed float by more than max_delta, so the
    # previous version keeps serving (the quantized `swap_rejected`).
    "calibration": ("tensor", "amax", "scale", "channels", "batches"),
    "quant_rejected": ("replica_id", "version", "float_top1",
                       "quant_top1", "delta", "max_delta", "reason"),
    # Serving fleet (fleet/; docs/SERVING.md fleet section). `fleet` is
    # the router's periodic window (replica membership + routing
    # counters; `fleet_done` the final cumulative one); `swap` a
    # worker's successful checkpoint hot-swap and `swap_rejected` a
    # candidate refused (contract mismatch / failed restore — the old
    # version keeps serving); `scale` an autoscaler action (up/down
    # with its decision-table reason); `fleet_publish` a checkpoint
    # version committed for the fleet to serve.
    "fleet": ("replicas", "live", "routed", "rerouted", "evictions",
              "shed", "version_mix", "window_s"),
    "fleet_done": ("replicas", "live", "routed", "rerouted",
                   "evictions", "shed", "version_mix", "window_s"),
    "swap": ("replica_id", "version", "from_version", "swap_ms"),
    "swap_rejected": ("replica_id", "version", "reason"),
    "scale": ("action", "reason", "replicas"),
    "fleet_publish": ("seq", "version", "step", "path"),
    # Distributed request tracing (utils/reqtrace.py;
    # docs/OBSERVABILITY.md Request-tracing section). One span per hop
    # a sampled-or-forced request crossed: `trace_id` is the join key
    # across process streams, `hop` the stage (client / router / server
    # / worker / batcher / engine / batch), `dur_ms` the hop's own
    # latency contribution, `wallclock` unix seconds at hop start (what
    # places the span on the merged timeline). Hop-specific context
    # (batch_id, version, shed, attempt, replica_id) rides as extra
    # keys.
    "rspan": ("trace_id", "hop", "dur_ms", "wallclock"),
    # Flight recorder (utils/flightrec.py). One record per post-mortem
    # bundle captured on an alert firing: the rule that fired, the
    # bundle directory, and how many ring records it snapshotted.
    "postmortem": ("rule", "dir", "records"),
    # Unified multi-job runtime (runtime/; docs/RUNTIME.md). `job` is a
    # job lifecycle transition (state: pending / running / done /
    # failed; alert-born jobs also carry `trigger=<rule>`); `job_done`
    # the completion summary (`ok` + wall seconds, `error` when not
    # ok); `publish` one committed checkpoint's weights installed into
    # the in-process serving engine via the locked pointer swap —
    # `source` is "live_params" (device buffers, zero checkpoint
    # reads), `swapped` whether the engine accepted the candidate, and
    # the extra `job`/`seq` keys stamp the alert→job→publish lineage.
    "job": ("job", "jtype", "state"),
    "job_done": ("job", "jtype", "ok", "secs"),
    "publish": ("step", "version", "source", "latency_ms", "swapped"),
    # Net coordination transport (parallel/net.py): one rate-limited
    # record per (operation, error) transition — `op` the client call
    # (publish/read/scan/record/...), `ok` whether it resolved; failed
    # ops carry the classified `error` reason (timeout, unreachable,
    # http_<code>, proto) plus attempts/ms, the partition-timeline
    # input for telemetry_report's network-health section.
    "net": ("op", "ok"),
    # Cross-cell failover: the router had to place a request tagged
    # `from_cell` (X-DML-Cell) onto a replica in `to_cell` because the
    # target cell had no live replica; always trace-forced.
    "cell_route": ("from_cell", "to_cell", "replica_id"),
    # A torn/undecodable heartbeat found mid-scan (HeartbeatStore
    # .read_all / the net scan): classified and skipped, never raised —
    # discovery keeps working through one corrupt beat file.
    "beat_decode_error": ("path", "error"),
}


def _reject_constant(name: str):
    raise ValueError(f"non-strict JSON constant {name}")


def check_lines(lines: Iterable[str], source: str = "<stream>",
                strict: bool = False) -> List[str]:
    """Validate JSONL lines; returns a list of human-readable errors.
    ``strict`` additionally rejects unknown kinds (see module
    docstring)."""
    errors = []
    for ln, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        where = f"{source}:{ln}"
        try:
            rec = json.loads(line, parse_constant=_reject_constant)
        except ValueError as e:
            errors.append(f"{where}: invalid strict JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{where}: record is not a JSON object")
            continue
        missing = [k for k in BASE_KEYS if k not in rec]
        if missing:
            errors.append(f"{where}: missing base keys {missing}")
        kind = rec.get("kind")
        if kind not in KIND_KEYS:
            if strict:
                errors.append(
                    f"{where}: unknown kind {kind!r} (add it to "
                    f"tools/check_jsonl_schema.py and "
                    f"docs/OBSERVABILITY.md)")
            continue
        missing = [k for k in KIND_KEYS[kind] if k not in rec]
        if missing:
            errors.append(f"{where}: kind {kind!r} missing keys {missing}")
        for k, v in rec.items():
            # json.loads only yields inf/nan via the constants rejected
            # above, but a float check keeps the rule explicit.
            if isinstance(v, float) and v != v:
                errors.append(f"{where}: key {k!r} is NaN")
    return errors


def check_file(path: str, strict: bool = False) -> List[str]:
    with open(path) as f:
        return check_lines(f, source=path, strict=strict)


def list_kinds() -> List[str]:
    """Every kind the lint knows, sorted — the machine-readable side of
    the drift contract with docs/OBSERVABILITY.md's kinds table
    (``tests/test_telemetry.py`` asserts the two match both ways)."""
    return sorted(KIND_KEYS)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv == ["--list-kinds"]:
        for kind in list_kinds():
            print(kind)
        return 0
    strict = False
    while "--strict" in argv:
        argv.remove("--strict")
        strict = True
    if not argv:
        print(__doc__.strip().splitlines()[0])
        print("usage: check_jsonl_schema.py [--strict] [--list-kinds] "
              "FILE.jsonl [...]")
        return 2
    failed = False
    for path in argv:
        errs = check_file(path, strict=strict)
        for e in errs:
            print(e)
        if errs:
            failed = True
        else:
            print(f"{path}: OK")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
