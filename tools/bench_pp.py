#!/usr/bin/env python
"""Pipeline-parallel vs data-parallel benchmark (round-1 #7; round-3 1F1B).

Times the full ViT training step at a fixed global batch over several
mesh layouts on the 8-virtual-device CPU mesh (the only multi-device
substrate on this box — one real TPU chip cannot host a pipe axis), and
reads the compiled step's TEMP-ALLOCATION bytes from XLA's memory
analysis — the live-activation footprint the 1F1B schedule exists to cap.

CPU timings are a schedule-overhead proxy, not TPU absolute numbers:
they expose the bubble compute (skipped by 1F1B, burned by GPipe) and
the ppermute/psum traffic, which is what the layout decision rides on.
The memory column is geometry, not timing, so it transfers to TPU
directly: GPipe-autodiff's saved scan carries grow O(M); 1F1B's ring
buffer is O(P), flat in M.

Usage: python tools/bench_pp.py [--steps 8] [--batch 32] [--depth 8]
Prints one markdown table.
"""

from __future__ import annotations

import argparse
import time

from dml_cnn_cifar10_tpu.utils.platform import force_cpu

force_cpu(virtual_devices=8)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,  # noqa: E402
                                        OptimConfig, ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model  # noqa: E402
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib  # noqa: E402
from dml_cnn_cifar10_tpu.parallel import step as step_lib  # noqa: E402


def time_layout(name, pcfg, model_cfg, batch, steps):
    mesh = mesh_lib.build_mesh(pcfg)
    data_cfg = DataConfig(crop_height=16, crop_width=16)
    optim_cfg = OptimConfig(learning_rate=0.01)
    model_def = get_model(model_cfg.name)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg,
                                        data_cfg, optim_cfg)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, optim_cfg,
        mesh, state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim_cfg,
                                     mesh, state_sharding=sh)
    rng = np.random.default_rng(0)
    im = rng.normal(0.5, 0.25, (batch, 16, 16, 3)).astype(np.float32)
    lb = rng.integers(0, 10, batch).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, im, lb)
    # Temp bytes of the compiled step: the transient (activation/workspace)
    # footprint — where the GPipe-vs-1F1B memory story shows up.
    # One AOT compile serves both the memory probe and the timed loop
    # (calling the jitted fn would compile the same program a second
    # time — the AOT path has its own executable cache).
    compiled = train.lower(state, im, lb).compile()
    temp_mb = None
    try:
        temp_mb = compiled.memory_analysis().temp_size_in_bytes / 2**20
    except Exception:
        pass
    state, m = compiled(state, im, lb)      # warm
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = compiled(state, im, lb)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / steps
    loss = float(jax.device_get(m["loss"]))
    return name, dt * 1e3, batch / dt, temp_mb, loss


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--dim", type=int, default=64,
                   help="vit_dim — the residual-ring-vs-recompute verdict "
                        "scales with it (ring IO is O(dim) per token, "
                        "recompute FLOPs O(dim^2))")
    args = p.parse_args()

    base = dict(name="vit_tiny", pool="mean", logit_relu=False,
                vit_depth=args.depth, vit_dim=args.dim, vit_heads=2,
                patch_size=4,
                use_pallas_attention=False)
    dp2pp4 = ParallelConfig(data_axis=2, pipe_axis=4)
    layouts = [
        ("dp=8", ParallelConfig(data_axis=8), ModelConfig(**base)),
        ("dp=4 x pp=2 1f1b (M=P)",
         ParallelConfig(data_axis=4, pipe_axis=2), ModelConfig(**base)),
        ("dp=2 x pp=4 gpipe (M=P)", dp2pp4,
         ModelConfig(**base, pipe_schedule="gpipe")),
        ("dp=2 x pp=4 1f1b-rec (M=P)", dp2pp4, ModelConfig(**base)),
        ("dp=2 x pp=4 1f1b-ring (M=P)", dp2pp4,
         ModelConfig(**base, pipe_schedule="1f1b_ring")),
        ("dp=2 x pp=4 gpipe (M=4P)", dp2pp4,
         ModelConfig(**base, pipe_schedule="gpipe", pipe_microbatches=16)),
        ("dp=2 x pp=4 1f1b-rec (M=4P)", dp2pp4,
         ModelConfig(**base, pipe_microbatches=16)),
        ("dp=2 x pp=4 1f1b-ring (M=4P)", dp2pp4,
         ModelConfig(**base, pipe_schedule="1f1b_ring",
                     pipe_microbatches=16)),
    ]
    rows = [time_layout(n, pc, mc, args.batch, args.steps)
            for n, pc, mc in layouts]
    ref = rows[0][1]
    print(f"\nViT depth={args.depth} dim={args.dim} global batch={args.batch}, "
          f"{args.steps} timed steps, 8 virtual CPU devices\n")
    print("| layout | step ms | images/sec | temp MiB | vs dp=8 | "
          "final loss |")
    print("|---|---|---|---|---|---|")
    for name, ms, ips, temp, loss in rows:
        t = f"{temp:.0f}" if temp is not None else "n/a"
        print(f"| {name} | {ms:.1f} | {ips:.0f} | {t} | {ref / ms:.2f}x | "
              f"{loss:.4f} |")


if __name__ == "__main__":
    main()
