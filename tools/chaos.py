#!/usr/bin/env python
"""Chaos campaign engine: randomized compound-fault fuzzing with
invariant checking over the CPU recovery sims.

The deterministic fault hooks (``utils/faults.py``) exercise recovery
paths one hand-picked fault at a time (``nan@15``, ``host_lost@15``).
This driver turns them into *systematic* coverage of the recovery state
space: ``FaultSchedule.generate(seed, budget)`` samples N seeded
compound-fault schedules — several faults at one step, faults that
strike inside recovery (``ckpt_corrupt@restore``,
``decision_corrupt@decide``), corruption of the coordination state
itself — and runs each through the existing CPU sims (1-process
supervised train, the 2-process cluster shrink drill, the 2→1→2
elastic-expand drill, and the 2-process diskless-recovery drill with
peer redundancy on and ``replica_corrupt``/``replica_stale`` in its
vocabulary), checking after every run that the resilience stack
actually held:

- **bit_identical** — a recoverable schedule must end with final params
  bit-identical to the fault-free reference run (the exact-resume
  contract, compounded);
- **completed** — the run reaches the requested step, exit 0, never
  fenced (the cluster scenario's backbone corpse excepted);
- **schema** — every process's JSONL stream passes
  ``tools/check_jsonl_schema.py``;
- **deadline** — no process outlives the per-run deadline (a hang is a
  failure, not a wait);
- **fault_pairing** — every step-triggered scheduled fault appears as
  an ``injected: true`` ``fault`` record, and every *detected* failure
  has a matching ``recovery`` record.

A failing schedule is automatically shrunk (greedy one-fault-removal
delta debugging) to a minimal reproducer emitted as a ready-to-paste
``--fault_spec``. The campaign's own telemetry rides a metrics JSONL
(``chaos`` per schedule, ``chaos_done`` summary;
``tools/telemetry_report.py`` renders the section).

Usage::

    python tools/chaos.py --seeds 50 --scenario mixed   # the slow campaign
    python tools/chaos.py --seeds 5 --scenario train    # the tier-1 smoke
    python tools/chaos.py --spec "nan@15,ckpt_corrupt@15"  # one schedule
    python tools/chaos.py --seeds 8 --scenario cluster  # 2-process shrink sims
    python tools/chaos.py --seeds 4 --scenario expand   # 2→1→2 scale-UP sims
    python tools/chaos.py --seeds 4 --scenario peer_recovery  # diskless-restore sims
    python tools/chaos.py --seeds 4 --scenario runtime  # --mode run (train+serve) sims
    python tools/chaos.py --seeds 4 --scenario autopilot  # alert->remediation sims
    python tools/chaos.py --seeds 4 --scenario net_partition  # partition/heal sims

Exit 1 when any schedule violates an invariant. ``--plant
no_decision_sidecar`` reverts the RestartCoordinator sidecar check
inside the workers (a named regression drill: the campaign must catch
it and shrink the failure to its ``decision_corrupt`` core);
``--plant no_autopilot_policy`` disarms the autopilot's rollback
policy (the autopilot campaign must catch the un-remediated alert);
``--plant no_net_timeout`` strips the net transport's per-request
socket timeout (the ``net_partition`` campaign must catch the hang as
a deadline-invariant hole — run it with a reduced ``--deadline_s``,
the failing probes each run to the deadline).

The ``net_partition`` scenario runs the 2-process lockstep sim over
the NET coordination transport (``--cluster_transport net``,
``parallel/net.py``): task 0 hosts the coordination service and is
fuzzed with the net vocabulary (delay/drop/dup on top of the expand
kinds), task 1 carries a ``net_partition@15`` backbone that cuts it
off from the service mid-run. The partitioned seat must classify the
silence (``peer_lost``), the majority side keeps the chief and shrinks,
the partition heals (``utils/netfaults.py`` PARTITION_HEAL_S) and the
cut-off seat rejoins through the PR-7 elastic-expand arc — BOTH seats
must finish bit-identical to the fault-free reference. Each
``net_partition`` campaign also runs ONE fleet-under-partition sim:
a 2-cell fleet with one cell's worker isolated must shed every tagged
request to the reachable cell with zero client failures (``cell_route``
records on the stream, all streams schema-strict).

The ``autopilot`` scenario is the ``runtime`` sim with the autopilot
armed (``--autopilot``) and a guaranteed ``nan@12`` backbone fault:
every qualifying alert firing must be answered by a ``remediation``
record citing its alert id, no remediation may fail, and every applied
remediation's alert must return to healthy (``alert_resolved``) before
run end — return-to-SLO with zero operator actions. The run gets a
60-step tail past the fuzz window so the ``nonfinite_burst`` rate
window (50 steps) can clear. (The flight recorder stays disarmed in
the sim — see the worker comment; the postmortem linkage is pinned by
the tier-1 acceptance smoke.)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dml_cnn_cifar10_tpu.utils import faults as faults_lib  # noqa: E402
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger  # noqa: E402
from tools import check_jsonl_schema  # noqa: E402

#: Fault kinds whose injection must provoke a recovery action (they
#: raise / poison the run); the others (ckpt_corrupt, decision_corrupt,
#: heartbeat_stall) corrupt state that may or may not be read later —
#: surviving them unnoticed is legitimate.
RECOVERY_PROVOKING = ("nan", "data_stall")

#: Named planted regressions for drill/self-test purposes: each value
#: is a Python snippet the worker preamble executes to REVERT one piece
#: of hardening, so a campaign can prove it catches the regression.
PLANTS = {
    # Revert the RestartCoordinator sha256-sidecar check: read() trusts
    # any decodable payload again, so a corrupted decision file (bogus
    # epoch, empty survivor set) is ADOPTED instead of classified — the
    # run fences itself and the bit-identity/completion invariants
    # fail.
    "no_decision_sidecar": """
from dml_cnn_cifar10_tpu.parallel import cluster as _cl
def _legacy_read(self):
    import json as _json
    try:
        with open(self.path) as f:
            return _cl.RestartDecision(**_json.load(f))
    except (OSError, ValueError, TypeError):
        return None
_cl.RestartCoordinator.read = _legacy_read
""",
    # Disarm the autopilot's rollback policy: nonfinite_burst firings
    # match nothing, so no remediation record answers them — the
    # autopilot scenario's alert-answered invariant must catch the
    # regression and shrink it to its nan core.
    "no_autopilot_policy": """
from dml_cnn_cifar10_tpu.autopilot import engine as _ap
_orig_default_policies = _ap.default_policies
def _no_rollback():
    return [p for p in _orig_default_policies()
            if p.action != "rollback"]
_ap.default_policies = _no_rollback
""",
    # Strip the net transport's per-request socket timeout: every
    # request waits forever, so a partition's held connection is a HANG
    # instead of a classified timeout — the net_partition campaign must
    # catch it as a deadline-invariant failure and shrink it to its
    # net_partition core. (timeout_s=None is the client's explicit
    # no-timeout mode; _DEFAULT means "use the configured bound".)
    "no_net_timeout": """
from dml_cnn_cifar10_tpu.parallel import net as _net
_orig_request = _net.CoordClient._request
def _unbounded_request(self, method, path, body=None,
                       timeout_s=_net._DEFAULT):
    return _orig_request(self, method, path, body=body, timeout_s=None)
_net.CoordClient._request = _unbounded_request
""",
}

# One worker script serves every scenario: task 0 is the seat under
# fuzz (its --fault_spec is the schedule), task 1 (cluster scenario)
# carries the backbone host_lost. Mirrors the tests' sim workers so
# chaos findings reproduce 1:1 under pytest.
WORKER = """
import json, os, sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
plant = os.environ.get("DML_CHAOS_PLANT")
task, n, data_dir, log_dir, cluster_dir, fault_spec, total_steps = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6], int(sys.argv[7]))
import hashlib
import numpy as np
import jax
from dml_cnn_cifar10_tpu.config import TrainConfig, DataConfig
from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised
if plant:
    exec(os.environ["DML_CHAOS_PLANT_CODE"])

cfg = TrainConfig(
    batch_size=32, total_steps=total_steps, output_every=10,
    eval_every=20, checkpoint_every=10, log_dir=log_dir,
    metrics_jsonl=f"{log_dir}/metrics.jsonl",
    data=DataConfig(dataset="synthetic", data_dir=data_dir,
                    synthetic_train_records=256,
                    synthetic_test_records=64,
                    normalize="scale", use_native_loader=False),
)
cfg.model.logit_relu = False
cfg.optim.learning_rate = 0.05
cfg.keep_checkpoints = 20
cfg.check_numerics = True
cfg.on_nonfinite = "rollback"
cfg.recovery_retries = 8        # a compound schedule may spend several
cfg.recovery_backoff_s = 0.05
cfg.recovery_backoff_max_s = 0.2
cfg.fault_spec = fault_spec or None
cfg.parallel.process_id = task
cfg.parallel.num_processes = n
if cluster_dir:
    cfg.parallel.cluster_dir = cluster_dir
    cfg.parallel.cluster_lockstep = n > 1
    # peer_recovery scenario: replicate shard payloads so the elastic
    # restart restores from peers (source=peer) instead of disk.
    cfg.parallel.peer_redundancy = bool(
        os.environ.get("DML_CHAOS_PEER")) and n > 1
    # Multi-seat sims may re-admit returning hosts (the expand
    # scenario's whole point); the 1-process scenario keeps the fence
    # so an adopted-bogus-decision regression fails FAST instead of
    # waiting out a rejoin nobody will grant.
    cfg.parallel.elastic_expand = n > 1
    cfg.parallel.heartbeat_interval_s = 0.1
    cfg.parallel.straggler_after_s = 0.4
    cfg.parallel.peer_dead_after_s = 2.5
    cfg.parallel.collective_timeout_s = 300.0
    if os.environ.get("DML_CHAOS_NET"):
        # net_partition scenario: coordinate over the socket transport
        # (task 0 hosts the service). Tight timeouts keep a partitioned
        # read's cost at ~1.5s so the peer_lost/rejoin arc fits the
        # sim's step budget.
        cfg.parallel.cluster_transport = "net"
        cfg.parallel.net_timeout_s = 0.5
        cfg.parallel.net_retries = 2

if os.environ.get("DML_CHAOS_RUNTIME") \
        or os.environ.get("DML_CHAOS_AUTOPILOT"):
    # Unified-runtime scenario: the same supervised training run, but
    # as a TrainJob inside --mode run with the in-process serving head
    # up — faults must recover AND the publish protocol must keep
    # committing versions (the harness checks the stream for both).
    cfg.supervise = True
    cfg.runtime.jobs = "train,serve"
    cfg.serve.port = 0          # ephemeral: campaign runs must not collide
    if os.environ.get("DML_CHAOS_AUTOPILOT"):
        # Autopilot scenario: the runtime sim with the policy engine
        # armed. rollback_lr_scale stays 1.0 so the applied rollback
        # remediation leaves the exact-resume contract intact (the
        # bit_identical oracle still holds). The flight recorder stays
        # DISARMED here: each capture arms a one-shot devprof window,
        # and on a starved CPU box the profiled dispatch outlives the
        # heartbeat_stale threshold, whose firing captures again — a
        # self-sustaining stall loop. The tier-1 acceptance smoke
        # (tests/test_autopilot.py) pins the postmortem linkage on a
        # short supervised run instead.
        cfg.autopilot.enabled = True
    from dml_cnn_cifar10_tpu.runtime import Runtime
    rt = Runtime(cfg, task_index=task)
    try:
        rt.start()
        rt.wait()
    finally:
        rt.close()
    train_jobs = [j for j in rt.scheduler.jobs if j.name == "train"]
    res = train_jobs[0].result if train_jobs else None
else:
    res = fit_supervised(cfg, task_index=task)
if res is None:
    print("RESULT " + json.dumps({"task": task, "fenced": True}))
    sys.exit(0)
h = hashlib.sha256()
for leaf in jax.tree.leaves(jax.device_get(res.state.params)):
    h.update(np.ascontiguousarray(leaf).tobytes())
print("RESULT " + json.dumps({
    "task": task, "fenced": False, "final_step": res.final_step,
    "digest": h.hexdigest()}))
"""

#: The cluster scenario's fixed backbone fault on task 1: dies abruptly
#: at step 15 so every schedule exercises the shrink protocol under its
#: sampled compound faults.
CLUSTER_BACKBONE = "host_lost@15"

#: The expand scenario's backbone choreography: task 1 dies at 15, the
#: surviving chief holds step 18 until the harness-respawned host
#: announces rejoin (the 2→1→2 drill from tests/test_elastic_expand.py)
#: — every schedule then fuzzes faults across shrink AND expand.
EXPAND_BACKBONE = "host_lost@15"
EXPAND_HOLD = "host_return@18"

#: The net_partition scenario's backbone on task 1: cut off from the
#: coordination service at step 15, heal after
#: ``netfaults.PARTITION_HEAL_S``, rejoin through the elastic-expand
#: arc. Task 0 (the service host) meanwhile holds step 18 until the
#: rejoin lands — without the hold it would checkpoint world-shrunk
#: solo progress past the shared restore point and break bit-identity
#: (the same choreography as the expand scenario).
NET_BACKBONE = "net_partition@15"
NET_HOLD = "host_return@18"

#: The autopilot scenario's guaranteed fault: every schedule carries a
#: nan so the nonfinite_burst alert fires and the remediation loop is
#: exercised on every run (a sampled schedule with no alert-provoking
#: fault would pass the autopilot invariants vacuously).
AUTOPILOT_BACKBONE = "nan@12"

#: Extra steps the autopilot sim runs past the fuzz window: the
#: nonfinite_burst rate window is 50 steps, so the run must outlive
#: the last detection by >50 steps for the alert to RESOLVE — the
#: return-to-healthy invariant needs the resolution on the stream.
AUTOPILOT_TAIL_STEPS = 60

#: Which reference digest oracles a scenario: all sims are numerically
#: identical replicas of the 1-process run (per-seat data seeds
#: coincide in the independent-world layout), so the expand and
#: peer_recovery scenarios reuse the train oracle — a peer-sourced
#: restore must be BIT-IDENTICAL to a disk restore, which the shared
#: oracle pins for free.
REF_ALIAS = {"expand": "train", "peer_recovery": "train",
             "runtime": "train", "net_partition": "train"}

#: Scenarios that run the 2-process shrink drill (task 1 carries the
#: backbone ``host_lost`` and must exit with its abrupt-death code).
TWO_SEAT_SCENARIOS = ("cluster", "peer_recovery")


@dataclasses.dataclass
class RunResult:
    """One sim execution of one fault spec."""

    ok: bool
    invariant: Optional[str]       # first violated invariant, or None
    secs: float
    recovery_s: float = 0.0        # slowest fault→recovery latency seen
    injected: Dict[str, int] = dataclasses.field(default_factory=dict)


class ChaosHarness:
    """Owns the campaign workdir: dataset, worker script, reference
    digests (one fault-free run per scenario, cached), and the spawn
    plumbing shared by campaign runs and shrink probes."""

    def __init__(self, workdir: str, total_steps: int = 40,
                 deadline_s: float = 300.0, plant: Optional[str] = None,
                 verbose: bool = True,
                 refs: Optional[Dict[str, str]] = None):
        self.workdir = workdir
        self.total_steps = total_steps
        self.deadline_s = deadline_s
        if plant is not None and plant not in PLANTS:
            raise ValueError(f"unknown plant {plant!r} "
                             f"(have {sorted(PLANTS)})")
        self.plant = plant
        self.verbose = verbose
        self._runs = 0
        # Pre-seeded per-scenario reference digests: the synthetic
        # dataset and worker config are fully deterministic, so a
        # digest computed by one harness is valid for any other with
        # the same total_steps (the tests share one across campaigns).
        self._refs: Dict[str, str] = dict(refs or {})
        os.makedirs(workdir, exist_ok=True)
        self.script = os.path.join(workdir, "chaos_worker.py")
        with open(self.script, "w") as f:
            f.write(WORKER)
        self.data_dir = os.path.join(workdir, "data")
        from dml_cnn_cifar10_tpu.config import DataConfig
        from dml_cnn_cifar10_tpu.data import ensure_dataset
        ensure_dataset(DataConfig(
            dataset="synthetic", data_dir=self.data_dir,
            synthetic_train_records=256, synthetic_test_records=64,
            use_native_loader=False))

    # -- process plumbing -------------------------------------------------

    def _spawn(self, args, planted: bool, peer: bool = False,
               runtime: bool = False, autopilot: bool = False,
               net: bool = False):
        env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("DML_CHAOS_PLANT", None)
        env.pop("DML_CHAOS_PLANT_CODE", None)
        env.pop("DML_CHAOS_PEER", None)
        env.pop("DML_CHAOS_RUNTIME", None)
        env.pop("DML_CHAOS_AUTOPILOT", None)
        env.pop("DML_CHAOS_NET", None)
        if peer:
            env["DML_CHAOS_PEER"] = "1"
        if runtime:
            env["DML_CHAOS_RUNTIME"] = "1"
        if autopilot:
            env["DML_CHAOS_AUTOPILOT"] = "1"
        if net:
            env["DML_CHAOS_NET"] = "1"
        if planted and self.plant:
            env["DML_CHAOS_PLANT"] = self.plant
            env["DML_CHAOS_PLANT_CODE"] = PLANTS[self.plant]
        return subprocess.Popen(
            [sys.executable, self.script] + [str(a) for a in args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO)

    @staticmethod
    def _read_result(out: str) -> Optional[dict]:
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("RESULT ")]
        if not lines:
            return None
        return json.loads(lines[-1][len("RESULT "):])

    # -- reference digests ------------------------------------------------

    def _steps_for(self, scenario: str) -> int:
        """Per-scenario run length: the autopilot sim outlives the fuzz
        window by the alert-resolution tail, everyone else runs the
        campaign's ``total_steps``."""
        if scenario == "autopilot":
            return self.total_steps + AUTOPILOT_TAIL_STEPS
        return self.total_steps

    def reference_digest(self, scenario: str) -> str:
        """Digest of the fault-free run of ``scenario``'s fuzzed seat
        (task 0), computed once per campaign. The exact-resume contract
        makes this the universal oracle: a recovered run — whatever
        checkpoint its restore walk actually landed on — must be
        bit-identical to the uninterrupted run from scratch. The
        reference never runs planted code: the plant is the regression
        under test, the oracle must stay sound."""
        scenario = REF_ALIAS.get(scenario, scenario)
        if scenario in self._refs:
            return self._refs[scenario]
        steps = self._steps_for(scenario)
        run_dir = os.path.join(self.workdir, f"ref_{scenario}")
        logs = os.path.join(run_dir, "logs_0")
        os.makedirs(logs, exist_ok=True)
        cluster = os.path.join(run_dir, "cluster")
        proc = self._spawn([0, 1, self.data_dir, logs, cluster, "",
                            steps], planted=False,
                           autopilot=scenario == "autopilot")
        out = proc.communicate(timeout=self.deadline_s)[0]
        if proc.returncode != 0:
            raise RuntimeError(f"fault-free reference run failed:\n{out}")
        res = self._read_result(out)
        if res is None or res.get("fenced") \
                or res["final_step"] != steps:
            raise RuntimeError(f"fault-free reference run did not "
                               f"complete:\n{out}")
        self._refs[scenario] = res["digest"]
        return res["digest"]

    # -- invariant checking -----------------------------------------------

    def _check_stream(self, path: str, events, planted: bool):
        """Schema + fault-pairing invariants over one JSONL stream.
        Returns (violation-or-None, injected-counts, slowest-recovery).
        """
        injected: Dict[str, int] = {}
        slowest = 0.0
        if not os.path.exists(path):
            return "schema: metrics stream missing", injected, slowest
        errs = check_jsonl_schema.check_file(path, strict=True)
        if errs:
            return f"schema: {errs[0]}", injected, slowest
        with open(path) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        inj = [r for r in recs if r.get("kind") == "fault"
               and r.get("injected")]
        for r in inj:
            injected[r["fault"]] = injected.get(r["fault"], 0) + 1
        detected = [r for r in recs if r.get("kind") == "fault"
                    and not r.get("injected")]
        recoveries = [r for r in recs if r.get("kind") == "recovery"]
        # Every step-triggered scheduled fault must have fired (phase
        # events legitimately stay pending when no recovery reaches
        # their seam; deferred ckpt_corrupt needs a checkpoint first —
        # by run end one exists, so it must have fired too).
        want: Dict[str, int] = {}
        for ev in events:
            if ev.phase is None:
                want[ev.kind] = want.get(ev.kind, 0) + 1
        for kind, n in want.items():
            if injected.get(kind, 0) < n:
                return (f"fault_pairing: scheduled {kind} x{n} but only "
                        f"{injected.get(kind, 0)} injected fault "
                        f"record(s)"), injected, slowest
        # Every detected failure must be answered by a recovery record,
        # and every recovery-provoking injection must lead to one.
        for r in detected:
            after = [v for v in recoveries if v["t"] >= r["t"]]
            if not after:
                return (f"fault_pairing: detected {r.get('fault')} at "
                        f"t={r.get('t')} has no recovery record"), \
                    injected, slowest
            slowest = max(slowest, after[0]["t"] - r["t"])
        for r in inj:
            if r["fault"] not in RECOVERY_PROVOKING:
                continue
            after = [v for v in recoveries if v["t"] >= r["t"]]
            if not after:
                return (f"fault_pairing: injected {r['fault']} has no "
                        f"matching recovery record"), injected, slowest
            slowest = max(slowest, after[0]["t"] - r["t"])
        # Replica faults (peer_recovery scenario) must be ANSWERED, not
        # absorbed silently: any elastic restart AFTER a damaged replica
        # set either reconstructs from a (re-pushed) replica or degrades
        # to an EXPLICIT disk fallback — both leave a peer_replica
        # record. A replica fault with no restart after it has nothing
        # to answer (the damage was never read).
        peer_answers = [v for v in recs
                        if v.get("kind") == "peer_replica"
                        and v.get("op") in ("reconstruct", "fallback")]
        restarts = [v for v in recs
                    if v.get("kind") in ("elastic_restart",
                                         "elastic_expand")]
        for r in inj:
            if r["fault"] not in ("replica_corrupt", "replica_stale"):
                continue
            if not [v for v in restarts if v["t"] >= r["t"]]:
                continue
            if not [v for v in peer_answers if v["t"] >= r["t"]]:
                return (f"fault_pairing: injected {r['fault']} followed "
                        f"by an elastic restart but no peer_replica "
                        f"reconstruct or disk-fallback record"), \
                    injected, slowest
        return None, injected, slowest

    @staticmethod
    def _check_autopilot(recs) -> Optional[str]:
        """Autopilot invariants over the fuzzed seat's stream
        (docs/AUTOPILOT.md): every firing of a policy-matched rule is
        answered by a ``remediation`` record citing its alert id (a
        cooldown/budget suppression IS an explicit answer), every
        remediation's lineage resolves to a real firing, no remediation
        fails outright, and every *applied* remediation's alert returns
        to healthy (``alert_resolved``) before run end — return-to-SLO
        with zero operator actions. Judged against the UNPLANTED
        default policies: a plant that disarms one inside the worker
        is exactly the regression this must catch."""
        from dml_cnn_cifar10_tpu.autopilot.engine import default_policies
        policies = default_policies()
        fired = [r for r in recs if r.get("kind") == "alert"]
        rems = [r for r in recs if r.get("kind") == "remediation"]
        resolved = {r.get("id") for r in recs
                    if r.get("kind") == "alert_resolved"}
        answered = {r.get("alert_id") for r in rems}
        alert_ids = {r.get("id") for r in fired}
        for r in fired:
            if not any(p.matches(r.get("rule") or "")
                       for p in policies):
                continue
            if r.get("id") not in answered:
                return (f"autopilot: alert {r.get('id')} "
                        f"[{r.get('rule')}] has no remediation record")
        for r in rems:
            if r.get("alert_id") not in alert_ids:
                return (f"autopilot: remediation {r.get('policy')} "
                        f"cites unknown alert id {r.get('alert_id')!r}")
            if r.get("status") == "failed":
                return (f"autopilot: remediation {r.get('policy')} for "
                        f"{r.get('alert_id')} failed "
                        f"({r.get('detail')})")
            if r.get("status") == "applied" \
                    and r.get("alert_id") not in resolved:
                return (f"autopilot: remediated alert "
                        f"{r.get('alert_id')} never returned to "
                        f"healthy")
        return None

    # -- one schedule -----------------------------------------------------

    def run_schedule(self, events: Sequence[faults_lib.FaultEvent],
                     scenario: str, tag: str,
                     backbone: str = CLUSTER_BACKBONE) -> RunResult:
        """Run one fault schedule through ``scenario``'s sim and check
        every invariant. ``tag`` names the run's directory;
        ``backbone`` is the cluster scenario's fixed fault on the peer
        seat."""
        self._runs += 1
        spec = faults_lib.format_fault_spec(events)
        run_dir = os.path.join(self.workdir,
                               f"run_{self._runs:03d}_{tag}")
        cluster = os.path.join(run_dir, "cluster")
        t0 = time.time()
        ref = self.reference_digest(scenario)
        if scenario == "expand":
            return self._run_expand(events, spec, run_dir, cluster,
                                    ref, t0)
        if scenario == "net_partition":
            return self._run_net_partition(events, run_dir, cluster,
                                           ref, t0)
        if scenario == "autopilot":
            # Merge the guaranteed alert-provoking backbone into the
            # sampled schedule (skipping exact duplicates so the
            # fault-pairing count stays honest).
            have = {(e.kind, e.step, e.phase) for e in events}
            events = list(events) + [
                e for e in faults_lib.parse_fault_spec(AUTOPILOT_BACKBONE)
                if (e.kind, e.step, e.phase) not in have]
            spec = faults_lib.format_fault_spec(events)

        steps = self._steps_for(scenario)
        n = 2 if scenario in TWO_SEAT_SCENARIOS else 1
        logs = [os.path.join(run_dir, f"logs_{t}") for t in range(n)]
        for d in logs:
            os.makedirs(d, exist_ok=True)
        specs = [spec] if n == 1 else [spec, backbone]
        procs = [self._spawn([t, n, self.data_dir, logs[t], cluster,
                              specs[t], steps], planted=True,
                             peer=scenario == "peer_recovery",
                             runtime=scenario == "runtime",
                             autopilot=scenario == "autopilot")
                 for t in range(n)]
        outs, timed_out = [], False
        for p in procs:
            try:
                outs.append(p.communicate(timeout=self.deadline_s)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
                timed_out = True
        secs = time.time() - t0

        def fail(inv):
            return RunResult(False, inv, secs)

        if timed_out:
            return fail(f"deadline: a process outlived "
                        f"{self.deadline_s:.0f}s")
        # The cluster backbone corpse is EXPECTED to die with the
        # abrupt-death code; everyone else must exit 0.
        if scenario in TWO_SEAT_SCENARIOS \
                and procs[1].returncode != faults_lib.EXIT_HOST_LOST:
            return fail(f"completed: backbone host exited "
                        f"{procs[1].returncode}, wanted "
                        f"{faults_lib.EXIT_HOST_LOST}")
        if procs[0].returncode != 0:
            tail = outs[0].strip().splitlines()[-1][:200] \
                if outs[0].strip() else ""
            return fail(f"completed: exit {procs[0].returncode} "
                        f"({tail})")
        res = self._read_result(outs[0])
        if res is None:
            return fail("completed: no RESULT line")
        if res.get("fenced"):
            return fail("completed: run fenced itself")
        if res["final_step"] != steps:
            return fail(f"completed: final step {res['final_step']} != "
                        f"{steps}")
        if res["digest"] != ref:
            return fail("bit_identical: final params differ from the "
                        "fault-free reference")
        if scenario in ("runtime", "autopilot"):
            # Runtime invariants (docs/RUNTIME.md): the publish
            # protocol must have committed at least one version into
            # the in-process serving engine, and no job — task or
            # service — may have failed.
            stream = os.path.join(logs[0], "metrics.jsonl")
            rrecs = []
            if os.path.exists(stream):
                with open(stream) as f:
                    rrecs = [json.loads(ln) for ln in f if ln.strip()]
            if not any(r.get("kind") == "publish" for r in rrecs):
                return fail("publish: runtime run committed no publish "
                            "record")
            bad = [r for r in rrecs
                   if r.get("kind") == "job_done" and not r.get("ok")]
            if bad:
                return fail(f"completed: job {bad[0].get('job')!r} "
                            f"failed ({bad[0].get('error')})")
            if scenario == "autopilot":
                inv = self._check_autopilot(rrecs)
                if inv is not None:
                    return fail(inv)
        injected: Dict[str, int] = {}
        slowest = 0.0
        for i, d in enumerate(logs):
            # The schedule's events only apply to stream 0; the
            # backbone stream is checked for schema + detected-fault
            # pairing only.
            evs = events if i == 0 else \
                faults_lib.parse_fault_spec(backbone)
            inv, inj, slow = self._check_stream(
                os.path.join(d, "metrics.jsonl"), evs, planted=True)
            if inv is not None:
                return fail(inv)
            for k, v in inj.items():
                injected[k] = injected.get(k, 0) + v
            slowest = max(slowest, slow)
        return RunResult(True, None, secs, recovery_s=slowest,
                         injected=injected)

    def _run_expand(self, events, spec: str, run_dir: str,
                    cluster: str, ref: str, t0: float) -> RunResult:
        """The 2→1→2 elastic scale-UP sim under a fuzz schedule: task 1
        dies at 15 (backbone), the surviving chief runs the schedule
        AND holds step 18 until the harness — playing the scheduler
        seat — respawns task 1 once the shrink decision is adopted;
        the chief expands the world back and BOTH seats must finish
        bit-identical to the reference."""
        logs = [os.path.join(run_dir, f"logs_{t}") for t in (0, 1)]
        for d in logs:
            os.makedirs(d, exist_ok=True)
        hold = faults_lib.parse_fault_spec(EXPAND_HOLD)
        spec0 = faults_lib.format_fault_spec(list(events) + hold)
        deadline = time.time() + self.deadline_s
        procs = [self._spawn([0, 2, self.data_dir, logs[0], cluster,
                              spec0, self.total_steps], planted=True),
                 self._spawn([1, 2, self.data_dir, logs[1], cluster,
                              EXPAND_BACKBONE, self.total_steps],
                             planted=True)]
        rejoined = None

        def fail(inv):
            for p in procs + ([rejoined] if rejoined else []):
                if p.poll() is None:
                    p.kill()
            return RunResult(False, inv, time.time() - t0)

        try:
            procs[1].wait(timeout=self.deadline_s)
        except subprocess.TimeoutExpired:
            return fail(f"deadline: backbone host outlived "
                        f"{self.deadline_s:.0f}s")
        if procs[1].returncode != faults_lib.EXIT_HOST_LOST:
            return fail(f"completed: backbone host exited "
                        f"{procs[1].returncode}, wanted "
                        f"{faults_lib.EXIT_HOST_LOST}")
        # Respawn gate: the survivor must have ADOPTED the shrink
        # before the host returns, else there is no expand to drill.
        # Gated on the stream (not the decision file — a
        # decision_corrupt schedule legitimately corrupts that).
        stream0 = os.path.join(logs[0], "metrics.jsonl")
        while True:
            shrunk = False
            if os.path.exists(stream0):
                with open(stream0, errors="replace") as f:
                    shrunk = '"elastic_restart"' in f.read()
            if shrunk:
                break
            if time.time() > deadline:
                return fail("deadline: survivor never adopted the "
                            "shrink decision")
            if procs[0].poll() is not None:
                out = procs[0].communicate()[0]
                tail = out.strip().splitlines()[-1][:200] \
                    if out.strip() else ""
                return fail(f"completed: survivor died before the "
                            f"shrink (exit {procs[0].returncode}: "
                            f"{tail})")
            time.sleep(0.1)
        rejoined = self._spawn([1, 2, self.data_dir, logs[1], cluster,
                                "", self.total_steps], planted=True)
        outs = []
        for p in (procs[0], rejoined):
            try:
                outs.append(p.communicate(timeout=self.deadline_s)[0])
            except subprocess.TimeoutExpired:
                return fail(f"deadline: a process outlived "
                            f"{self.deadline_s:.0f}s")
        secs = time.time() - t0
        for seat, (p, out) in enumerate(zip((procs[0], rejoined),
                                            outs)):
            if p.returncode != 0:
                tail = out.strip().splitlines()[-1][:200] \
                    if out.strip() else ""
                return RunResult(
                    False, f"completed: seat {seat} exit "
                           f"{p.returncode} ({tail})", secs)
            res = self._read_result(out)
            if res is None or res.get("fenced"):
                return RunResult(
                    False, f"completed: seat {seat} "
                           f"{'fenced' if res else 'no RESULT'}", secs)
            if res["final_step"] != self.total_steps:
                return RunResult(
                    False, f"completed: seat {seat} final step "
                           f"{res['final_step']}", secs)
            if res["digest"] != ref:
                return RunResult(
                    False, f"bit_identical: seat {seat} params differ "
                           f"from the fault-free reference", secs)
        injected: Dict[str, int] = {}
        slowest = 0.0
        for i, d in enumerate(logs):
            evs = (list(events) + hold) if i == 0 else \
                faults_lib.parse_fault_spec(EXPAND_BACKBONE)
            inv, inj, slow = self._check_stream(
                os.path.join(d, "metrics.jsonl"), evs, planted=True)
            if inv is not None:
                return RunResult(False, inv, secs)
            for k, v in inj.items():
                injected[k] = injected.get(k, 0) + v
            slowest = max(slowest, slow)
        return RunResult(True, None, secs, recovery_s=slowest,
                         injected=injected)

    def _run_net_partition(self, events, run_dir: str, cluster: str,
                           ref: str, t0: float) -> RunResult:
        """The 2-process partition/heal sim over the net transport:
        task 0 hosts the coordination service, runs the fuzz schedule
        plus the step-18 hold; task 1 is cut off at 15 (backbone),
        classifies the silence, heals after ``PARTITION_HEAL_S``, and
        rejoins through the elastic-expand arc. Unlike the expand drill
        there is no corpse and no respawn: the partitioned process
        stays alive the whole time, so BOTH seats must exit 0 and
        finish bit-identical to the reference."""
        logs = [os.path.join(run_dir, f"logs_{t}") for t in (0, 1)]
        for d in logs:
            os.makedirs(d, exist_ok=True)
        hold = faults_lib.parse_fault_spec(NET_HOLD)
        spec0 = faults_lib.format_fault_spec(list(events) + hold)
        procs = [self._spawn([0, 2, self.data_dir, logs[0], cluster,
                              spec0, self.total_steps], planted=True,
                             net=True),
                 self._spawn([1, 2, self.data_dir, logs[1], cluster,
                              NET_BACKBONE, self.total_steps],
                             planted=True, net=True)]
        outs, timed_out = [], False
        for p in procs:
            try:
                outs.append(p.communicate(timeout=self.deadline_s)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
                timed_out = True
        secs = time.time() - t0
        if timed_out:
            return RunResult(False, f"deadline: a process outlived "
                                    f"{self.deadline_s:.0f}s", secs)
        for seat, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0:
                tail = out.strip().splitlines()[-1][:200] \
                    if out.strip() else ""
                return RunResult(
                    False, f"completed: seat {seat} exit "
                           f"{p.returncode} ({tail})", secs)
            res = self._read_result(out)
            if res is None or res.get("fenced"):
                return RunResult(
                    False, f"completed: seat {seat} "
                           f"{'fenced' if res else 'no RESULT'}", secs)
            if res["final_step"] != self.total_steps:
                return RunResult(
                    False, f"completed: seat {seat} final step "
                           f"{res['final_step']}", secs)
            if res["digest"] != ref:
                return RunResult(
                    False, f"bit_identical: seat {seat} params differ "
                           f"from the fault-free reference", secs)
        injected: Dict[str, int] = {}
        slowest = 0.0
        for i, d in enumerate(logs):
            evs = (list(events) + hold) if i == 0 else \
                faults_lib.parse_fault_spec(NET_BACKBONE)
            inv, inj, slow = self._check_stream(
                os.path.join(d, "metrics.jsonl"), evs, planted=True)
            if inv is not None:
                return RunResult(False, inv, secs)
            for k, v in inj.items():
                injected[k] = injected.get(k, 0) + v
            slowest = max(slowest, slow)
        return RunResult(True, None, secs, recovery_s=slowest,
                         injected=injected)

    # -- the fleet-under-partition sim (once per net campaign) ------------

    def run_fleet_partition(self) -> Optional[str]:
        """One 2-cell fleet sim with one cell's worker partitioned off:
        every request tagged for the isolated cell must still be
        answered — shed to the reachable cell with a ``cell_route``
        record, zero client failures — and every stream must stay
        schema-strict. Returns the first violated invariant or None.

        Runs IN the driver process (the router and the netfault state
        live here; workers are real subprocesses), which is exactly
        what lets the harness arm ``utils/netfaults`` around the
        router's data plane deterministically."""
        import socket
        import threading

        import numpy as np

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from dml_cnn_cifar10_tpu.config import DataConfig, TrainConfig
        from dml_cnn_cifar10_tpu.fleet.controller import main_fleet
        from dml_cnn_cifar10_tpu.utils import netfaults
        from tools.loadgen import _HttpClient

        fdir = os.path.join(self.workdir, "fleet_partition")
        os.makedirs(fdir, exist_ok=True)
        stream = os.path.join(fdir, "router.jsonl")
        cfg = TrainConfig(
            log_dir=os.path.join(fdir, "logs"),
            metrics_jsonl=stream,
            data=DataConfig(dataset="synthetic",
                            data_dir=self.data_dir,
                            synthetic_train_records=256,
                            synthetic_test_records=64,
                            normalize="scale",
                            use_native_loader=False))
        cfg.model.logit_relu = False
        cfg.serve.buckets = (1, 4)
        cfg.serve.batch_window_ms = 1.0
        cfg.serve.metrics_every_s = 0.5
        cfg.serve.drain_deadline_s = 5.0
        cfg.fleet.dir = os.path.join(fdir, "fleet")
        # The controller binds but does not write the port back into
        # the config — reserve a free one up front (test_fleet idiom).
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            cfg.fleet.port = s.getsockname()[1]
        cfg.fleet.min_replicas = 2
        cfg.fleet.max_replicas = 2
        cfg.fleet.heartbeat_interval_s = 0.1
        cfg.fleet.replica_dead_after_s = 1.5
        cfg.fleet.metrics_every_s = 0.5
        cfg.fleet.cell = "cella,cellb"     # replica i -> cell i % 2
        cfg.parallel.cluster_transport = "net"
        cfg.parallel.net_timeout_s = 0.5
        cfg.parallel.net_retries = 2
        ready, stop = threading.Event(), threading.Event()
        thread = threading.Thread(
            target=lambda: main_fleet(cfg, ready_event=ready,
                                      stop_event=stop),
            name="chaos-fleet-partition", daemon=True)
        rng = np.random.default_rng(0)
        images = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
        deadline = time.time() + self.deadline_s
        try:
            thread.start()
            if not ready.wait(min(60.0, self.deadline_s)):
                return "deadline: fleet router never became ready"
            client = _HttpClient(f"http://127.0.0.1:{cfg.fleet.port}",
                                 target_cell="cellb")
            # Warm up: wait until the isolated-cell seat itself
            # answers, so the partition demonstrably takes a WORKING
            # cell out (and the pre-partition tag routes in-cell).
            while True:
                try:
                    outcome, _ = client.predict(images[0].tobytes())
                except OSError:
                    outcome = "connect"    # router/worker still booting
                if outcome == "ok":
                    break
                if time.time() > deadline:
                    return ("deadline: fleet never served the target "
                            "cell fault-free")
                time.sleep(0.5)
            netfaults.arm("net_partition", isolate=[1], duration_s=60.0)
            failures = 0
            for i in range(30):
                try:
                    outcome, _ = client.predict(images[i % 4].tobytes())
                except OSError:
                    outcome = "connect"
                if outcome != "ok":
                    failures += 1
                if time.time() > deadline:
                    return ("deadline: partitioned-fleet drive "
                            "outlived the budget")
            if failures:
                return (f"completed: {failures}/30 client requests "
                        f"failed under partition (want 0)")
        finally:
            netfaults.clear()
            stop.set()
            thread.join(timeout=60.0)
        with open(stream) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        routes = [r for r in recs if r.get("kind") == "cell_route"]
        if not routes:
            return ("cell_route: partitioned fleet shed cross-cell "
                    "but logged no cell_route record")
        bad = [r for r in routes if r.get("from_cell") != "cellb"
               or r.get("to_cell") == "cellb"]
        if bad:
            return (f"cell_route: crossing {bad[0]} does not leave "
                    f"the partitioned cell")
        streams = [stream]
        tdir = os.path.join(cfg.fleet.dir, "telemetry")
        if os.path.isdir(tdir):
            streams += [os.path.join(tdir, f)
                        for f in sorted(os.listdir(tdir))
                        if f.endswith(".jsonl")]
        for path in streams:
            errs = check_jsonl_schema.check_file(path, strict=True)
            if errs:
                return f"schema: {errs[0]}"
        return None

    # -- the quantized-publish fleet sim (once per net campaign) ----------

    def run_fleet_quant_publish(self) -> Optional[str]:
        """One fleet sim of the int8 rollout path (docs/QUANT.md): a
        fleet armed with ``--serve_quantize int8`` boots serving FLOAT
        (nothing published yet), a checkpoint lands mid-load, the
        directory publisher publishes its quantized variant, and the
        worker must calibrate + gate + hot-swap float→int8 between
        micro-batches. Invariants: zero failed client requests across
        the whole drive, the pre-publish responses carry the bare float
        version, the fleet demonstrably flips to a ``+int8``-suffixed
        version, no response ever carries any OTHER version, and every
        stream stays schema-strict. Returns the first violated
        invariant or None."""
        import copy
        import socket
        import threading

        import numpy as np

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
        from dml_cnn_cifar10_tpu.config import DataConfig, TrainConfig
        from dml_cnn_cifar10_tpu.fleet.controller import main_fleet
        from dml_cnn_cifar10_tpu.quant.convert import is_quantized_version
        from dml_cnn_cifar10_tpu.train.loop import Trainer
        from tools.loadgen import _HttpClient

        fdir = os.path.join(self.workdir, "fleet_quant")
        os.makedirs(fdir, exist_ok=True)
        stream = os.path.join(fdir, "router.jsonl")
        cfg = TrainConfig(
            log_dir=os.path.join(fdir, "logs"),
            metrics_jsonl=stream,
            data=DataConfig(dataset="synthetic",
                            data_dir=self.data_dir,
                            synthetic_train_records=256,
                            synthetic_test_records=64,
                            normalize="scale",
                            use_native_loader=False))
        cfg.model.logit_relu = False
        cfg.serve.buckets = (1, 4)
        cfg.serve.batch_window_ms = 1.0
        cfg.serve.metrics_every_s = 0.5
        cfg.serve.drain_deadline_s = 5.0
        cfg.serve.quantize = "int8"
        cfg.serve.quant_calib_batches = 2
        # The gate MECHANISM is under test, not the numeric threshold:
        # on untrained weights both accuracies sit at chance and the
        # delta is sampling noise, so a production-tight 0.5% would
        # make the sim a coin flip. The rejection path has its own
        # tier-1 test (tests/test_quant.py).
        cfg.serve.quant_max_delta = 0.5
        cfg.fleet.dir = os.path.join(fdir, "fleet")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            cfg.fleet.port = s.getsockname()[1]
        cfg.fleet.min_replicas = 1
        cfg.fleet.max_replicas = 1
        cfg.fleet.heartbeat_interval_s = 0.1
        cfg.fleet.replica_dead_after_s = 2.0
        cfg.fleet.metrics_every_s = 0.5
        cfg.fleet.swap_poll_s = 0.2
        cfg.fleet.publish_poll_s = 0.2
        # The checkpoint the sim drops mid-load: built through the same
        # Trainer the worker restores with, so the published candidate
        # is structurally exactly what a training run would publish.
        # Separate logger target — the driver must not interleave the
        # router's stream.
        tcfg = copy.deepcopy(cfg)
        tcfg.metrics_jsonl = None
        trainer = Trainer(tcfg)
        ckpt_state = trainer.init_or_restore()
        ready, stop = threading.Event(), threading.Event()
        thread = threading.Thread(
            target=lambda: main_fleet(cfg, ready_event=ready,
                                      stop_event=stop),
            name="chaos-fleet-quant", daemon=True)
        rng = np.random.default_rng(0)
        images = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
        deadline = time.time() + self.deadline_s
        float_version = None
        versions_seen: Dict[str, int] = {}
        try:
            thread.start()
            if not ready.wait(min(120.0, self.deadline_s)):
                return "deadline: fleet router never became ready"
            client = _HttpClient(f"http://127.0.0.1:{cfg.fleet.port}")
            # Pre-publish: the fleet must serve, and serve FLOAT.
            while True:
                try:
                    outcome, ver = client.predict(images[0].tobytes())
                except OSError:
                    outcome, ver = "connect", None
                if outcome == "ok":
                    break
                if time.time() > deadline:
                    return "deadline: fleet never served fault-free"
                time.sleep(0.5)
            if ver is None or is_quantized_version(str(ver)):
                return (f"float_first: pre-publish response carries "
                        f"version {ver!r} (want the bare float tag)")
            float_version = str(ver)
            versions_seen[float_version] = 1
            # Publish the quantized variant mid-load: the checkpoint
            # lands here; the controller's DirectoryPublisher (armed
            # with quantize="int8") takes it from there.
            ckpt_lib.save_checkpoint(tcfg.log_dir, ckpt_state, 5)
            failures = 0
            confirmed = 0   # +int8-versioned responses seen
            for i in range(400):
                try:
                    outcome, ver = client.predict(
                        images[i % 4].tobytes())
                except OSError:
                    outcome, ver = "connect", None
                if outcome != "ok":
                    failures += 1
                elif ver is not None:
                    key = str(ver)
                    versions_seen[key] = versions_seen.get(key, 0) + 1
                    if is_quantized_version(key):
                        confirmed += 1
                if confirmed >= 20:   # swap observed + held under load
                    break
                if time.time() > deadline:
                    return ("deadline: quantized-publish drive "
                            "outlived the budget")
                time.sleep(0.05)
            if failures:
                return (f"completed: {failures} client requests failed "
                        f"across the quantized hot-swap (want 0)")
            if not confirmed:
                return (f"quant_swap: fleet never served a +int8 "
                        f"version (saw {sorted(versions_seen)})")
            stray = [v for v in versions_seen
                     if v != float_version and not is_quantized_version(v)]
            if stray:
                return (f"version_suffix: responses carried "
                        f"unexpected version(s) {stray}")
        finally:
            stop.set()
            thread.join(timeout=60.0)
        streams = [stream]
        tdir = os.path.join(cfg.fleet.dir, "telemetry")
        if os.path.isdir(tdir):
            streams += [os.path.join(tdir, f)
                        for f in sorted(os.listdir(tdir))
                        if f.endswith(".jsonl")]
        for path in streams:
            errs = check_jsonl_schema.check_file(path, strict=True)
            if errs:
                return f"schema: {errs[0]}"
        return None

    # -- shrinking --------------------------------------------------------

    def shrink(self, events: List[faults_lib.FaultEvent], scenario: str,
               max_runs: int = 16) -> List[faults_lib.FaultEvent]:
        """Greedy one-fault-removal delta debugging: drop any fault
        whose removal keeps the schedule failing. The result is
        1-minimal (removing any single remaining fault makes the
        failure disappear) within the run budget."""
        events = list(events)
        runs = 0
        changed = True
        while changed and len(events) > 1 and runs < max_runs:
            changed = False
            for i in range(len(events)):
                candidate = events[:i] + events[i + 1:]
                runs += 1
                probe = self.run_schedule(
                    candidate, scenario, tag=f"shrink{runs}")
                if self.verbose:
                    print(f"[chaos]   shrink probe "
                          f"\"{faults_lib.format_fault_spec(candidate)}\""
                          f" -> {'still fails' if not probe.ok else 'passes'}")
                if not probe.ok:
                    events = candidate
                    changed = True
                    break
                if runs >= max_runs:
                    break
        return events


def run_campaign(seeds: Sequence[int], scenario: str, workdir: str,
                 budget: int = 3, total_steps: int = 40,
                 deadline_s: float = 300.0, plant: Optional[str] = None,
                 metrics_jsonl: Optional[str] = None,
                 shrink: bool = True, explicit_spec: Optional[str] = None,
                 verbose: bool = True,
                 refs: Optional[Dict[str, str]] = None) -> dict:
    """Run one chaos campaign; returns the summary dict (also logged as
    ``chaos``/``chaos_done`` JSONL when ``metrics_jsonl`` is set).
    ``explicit_spec`` replaces sampling with one fixed schedule per
    seed entry (reproducer replay)."""
    harness = ChaosHarness(workdir, total_steps=total_steps,
                           deadline_s=deadline_s, plant=plant,
                           verbose=verbose, refs=refs)
    logger = MetricsLogger(metrics_jsonl)
    vocab = {"train": faults_lib.CHAOS_VOCABULARY,
             "cluster": faults_lib.CHAOS_CLUSTER_VOCABULARY,
             "expand": faults_lib.CHAOS_EXPAND_VOCABULARY,
             "peer_recovery": faults_lib.CHAOS_PEER_VOCABULARY,
             "runtime": faults_lib.CHAOS_RUNTIME_VOCABULARY,
             # The autopilot sim is the runtime sim with the policy
             # engine armed; the guaranteed nan backbone rides on top
             # of the sampled schedule (run_schedule merges it).
             "autopilot": faults_lib.CHAOS_RUNTIME_VOCABULARY,
             # net_partition fuzzes the SERVER seat (task 0); the
             # partition backbone rides task 1. net_partition itself is
             # excluded from the fuzz vocabulary — see faults.py.
             "net_partition": faults_lib.CHAOS_NET_VOCABULARY}[scenario]
    results = []
    faults_by_kind: Dict[str, int] = {}
    slowest = 0.0
    try:
        for seed in seeds:
            if explicit_spec is not None:
                events = faults_lib.parse_fault_spec(explicit_spec)
                sched = faults_lib.FaultSchedule(seed, events)
            else:
                sched = faults_lib.FaultSchedule.generate(
                    seed, budget, vocabulary=vocab,
                    max_step=total_steps - 5)
            if verbose:
                print(f"[chaos] seed {seed} [{scenario}] "
                      f"\"{sched.spec}\"")
            r = harness.run_schedule(sched.events, scenario,
                                     tag=f"seed{seed}")
            reproducer = None
            if not r.ok and shrink and len(sched.events) > 1:
                minimal = harness.shrink(list(sched.events), scenario)
                reproducer = faults_lib.format_fault_spec(minimal)
            elif not r.ok:
                reproducer = sched.spec
            for k, v in r.injected.items():
                faults_by_kind[k] = faults_by_kind.get(k, 0) + v
            slowest = max(slowest, r.recovery_s)
            rec = {"seed": seed, "scenario": scenario,
                   "spec": sched.spec, "ok": r.ok,
                   "invariant": r.invariant,
                   "secs": round(r.secs, 2)}
            if reproducer is not None:
                rec["reproducer"] = reproducer
            logger.log("chaos", **rec)
            results.append(rec)
            if verbose:
                if r.ok:
                    print(f"[chaos]   OK in {r.secs:.1f}s "
                          f"(injected {r.injected})")
                else:
                    print(f"[chaos]   FAILED: {r.invariant}")
                    print(f"[chaos]   minimal reproducer: "
                          f"--fault_spec \"{reproducer}\"")
        if scenario == "net_partition" and explicit_spec is None:
            # Once per campaign (not per seed — the sim is fault-fixed,
            # only the schedules vary): the 2-cell fleet must shed a
            # partitioned cell's tagged load to the reachable cell with
            # zero client failures.
            if verbose:
                print("[chaos] fleet-under-partition sim "
                      "(2 cells, cellb isolated)")
            t0 = time.time()
            inv = harness.run_fleet_partition()
            rec = {"seed": -1, "scenario": scenario,
                   "spec": "fleet_partition", "ok": inv is None,
                   "invariant": inv,
                   "secs": round(time.time() - t0, 2)}
            if inv is not None:
                rec["reproducer"] = "fleet_partition"
            logger.log("chaos", **rec)
            results.append(rec)
            if verbose:
                print(f"[chaos]   {'OK' if inv is None else 'FAILED: '}"
                      f"{inv or ''} in {rec['secs']:.1f}s")
            # And the quantized-rollout sim (docs/QUANT.md): same
            # once-per-campaign rule — the fleet must hot-swap
            # float→int8 under load with zero client failures and
            # consistent version suffixes.
            if verbose:
                print("[chaos] fleet quantized-publish sim "
                      "(float→int8 hot-swap under load)")
            t0 = time.time()
            inv = harness.run_fleet_quant_publish()
            rec = {"seed": -2, "scenario": scenario,
                   "spec": "fleet_quant_publish", "ok": inv is None,
                   "invariant": inv,
                   "secs": round(time.time() - t0, 2)}
            if inv is not None:
                rec["reproducer"] = "fleet_quant_publish"
            logger.log("chaos", **rec)
            results.append(rec)
            if verbose:
                print(f"[chaos]   {'OK' if inv is None else 'FAILED: '}"
                      f"{inv or ''} in {rec['secs']:.1f}s")
        summary = {
            "schedules": len(results),
            "passed": sum(1 for r in results if r["ok"]),
            "failed": sum(1 for r in results if not r["ok"]),
            "faults_by_kind": faults_by_kind,
            "slowest_recovery_s": round(slowest, 3),
            "results": results,
            "reference_digests": dict(harness._refs),
        }
        logger.log("chaos_done",
                   **{k: v for k, v in summary.items()
                      if k not in ("results", "reference_digests")})
        return summary
    finally:
        logger.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="chaos campaign driver (docs/RESILIENCE.md)")
    p.add_argument("--seeds", type=int, default=5,
                   help="number of seeded schedules to run")
    p.add_argument("--seed_base", type=int, default=0,
                   help="first seed (seeds are seed_base..+N-1)")
    p.add_argument("--scenario", default="train",
                   choices=["train", "cluster", "expand",
                            "peer_recovery", "runtime", "autopilot",
                            "net_partition", "mixed"],
                   help="which sim to fuzz: 1-process supervised "
                        "train, the 2-process cluster shrink drill, "
                        "the 2→1→2 elastic-expand drill, the 2-process "
                        "diskless-recovery drill (peer redundancy on, "
                        "replica faults in vocabulary), the 1-process "
                        "unified runtime (--mode run: supervised train "
                        "+ in-process serving, publishes must survive "
                        "recovery), the runtime sim with the autopilot "
                        "armed (alerts must be answered by remediation "
                        "records and return to healthy), the 2-process "
                        "partition/heal sim over the net transport "
                        "(plus one fleet-under-partition sim per "
                        "campaign), or an alternating mix of all of "
                        "them")
    p.add_argument("--budget", type=int, default=3,
                   help="faults sampled per schedule")
    p.add_argument("--total_steps", type=int, default=40,
                   help="steps per sim run")
    p.add_argument("--deadline_s", type=float, default=300.0,
                   help="per-run wall-clock deadline; an overrun is an "
                        "invariant failure")
    p.add_argument("--workdir", default=None,
                   help="campaign working directory (default: a fresh "
                        "tmp dir)")
    p.add_argument("--metrics_jsonl", default=None,
                   help="write chaos/chaos_done JSONL records here")
    p.add_argument("--spec", default=None,
                   help="run this exact --fault_spec once instead of "
                        "sampling (reproducer replay)")
    p.add_argument("--no_shrink", action="store_true",
                   help="skip shrinking failing schedules")
    p.add_argument("--plant", default=None, choices=sorted(PLANTS),
                   help="revert a named piece of hardening inside the "
                        "workers (regression drill: the campaign must "
                        "catch it)")
    args = p.parse_args(argv)

    workdir = args.workdir
    if workdir is None:
        import tempfile
        workdir = tempfile.mkdtemp(prefix="dml_chaos_")
    scenarios = {"train": ["train"], "cluster": ["cluster"],
                 "expand": ["expand"],
                 "peer_recovery": ["peer_recovery"],
                 "runtime": ["runtime"],
                 "autopilot": ["autopilot"],
                 "net_partition": ["net_partition"],
                 "mixed": ["train", "cluster", "expand",
                           "peer_recovery", "runtime",
                           "autopilot", "net_partition"]}[args.scenario]
    seeds = list(range(args.seed_base, args.seed_base + args.seeds))
    if args.spec is not None:
        seeds = seeds[:1]
    failed = 0
    for i, scen in enumerate(scenarios):
        scen_seeds = seeds[i::len(scenarios)]
        if not scen_seeds:
            continue
        summary = run_campaign(
            scen_seeds, scen, os.path.join(workdir, scen),
            budget=args.budget, total_steps=args.total_steps,
            deadline_s=args.deadline_s, plant=args.plant,
            metrics_jsonl=args.metrics_jsonl,
            shrink=not args.no_shrink, explicit_spec=args.spec)
        failed += summary["failed"]
        print(f"[chaos] {scen}: {summary['passed']}/"
              f"{summary['schedules']} schedules passed; faults "
              f"injected: {summary['faults_by_kind']}; slowest "
              f"recovery {summary['slowest_recovery_s']:.2f}s")
    print(f"[chaos] campaign {'PASSED' if not failed else 'FAILED'} "
          f"({failed} failing schedule(s); workdir {workdir})")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
