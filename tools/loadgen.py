#!/usr/bin/env python
"""Load generator for the serving subsystem: closed- or open-loop
traffic against the micro-batching engine — single-profile runs or
multi-client traffic MIXES — with a BENCH-style report.

Two drive modes (the standard serving-bench dichotomy):

- **closed** (default): ``--concurrency`` client threads each submit
  one request, wait for its result, and immediately submit the next —
  throughput is whatever the engine sustains at that concurrency
  (latency and throughput are coupled).
- **open**: requests arrive on a fixed ``--qps`` schedule regardless of
  completions — the honest overload experiment: when the engine can't
  keep up, the queue grows until admission control sheds, and the
  report's ``shed_fraction`` says so (closed-loop clients would instead
  silently slow down — coordinated omission).

Traffic mixes (``--mix``): named open-loop profiles modeling real
multi-client traffic, one BENCH-style report row each
(``p50/p99/qps/shed/version_mix``):

- ``steady`` — constant ``--qps`` (the plain open loop);
- ``diurnal`` — a half-sine ramp 25% → 100% → 25% of ``--qps`` over the
  duration: the day/night cycle compressed, exercising the autoscaler's
  up AND down decisions in one run;
- ``burst`` — alternating 2x / 0.2x ``--qps`` eighth-duration phases:
  thundering herds against admission control;
- ``adversarial`` — steady rate with 25% oversize requests (wrong byte
  count): input validation under load; rejects are counted separately
  (``rejected``) and must never poison well-formed traffic.

Two targets:

- **in-process** (default): builds a CPU/TPU engine right here —
  ``--artifact PATH`` serves an ``export.py`` artifact, otherwise a
  fresh-initialized CNN (geometry from ``--image_size``) so the tool
  runs on a bare checkout.
- ``--target http://host:port``: drives a running ``--mode serve``
  server or ``--mode fleet`` router over HTTP (raw-bytes POST
  /predict), measuring end-to-end including transport.

Requests replay CIFAR test images (``--source dataset``, raw uint8 from
the on-disk records) or synthetic pixels (``--source random``). The
JSON report (``--report``) carries achieved QPS, latency percentiles,
shed fraction, batch-fill, and ``version_mix`` — the count of responses
per model version tag, which is how a zero-downtime hot-swap rollout is
measured from the client side.

Usage:
    python tools/loadgen.py --mode closed --concurrency 8 --duration_s 10
    python tools/loadgen.py --mode open --qps 500 --deadline_ms 50 \\
        --artifact /tmp/logs/model.jaxexport --report /tmp/serve_bench.json
    python tools/loadgen.py --mix diurnal,burst,adversarial --qps 200 \\
        --duration_s 10 --target http://localhost:8100
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Oversize fraction of the adversarial mix.
ADVERSARIAL_OVERSIZE = 0.25

#: mix name -> rate multiplier over u = elapsed/duration in [0, 1].
MIX_RATE = {
    "steady": lambda u: 1.0,
    "diurnal": lambda u: 0.25 + 0.75 * math.sin(math.pi * u),
    "burst": lambda u: 2.0 if int(u * 8) % 2 == 0 else 0.2,
    "adversarial": lambda u: 1.0,
}


def build_engine(args):
    from dml_cnn_cifar10_tpu.serve.engine import ServingEngine

    if args.artifact:
        return ServingEngine.from_artifact(args.artifact)
    import jax

    from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
    from dml_cnn_cifar10_tpu.models.registry import get_model

    model_def = get_model(args.model)
    model_cfg = ModelConfig(name=args.model, logit_relu=False)
    data_cfg = DataConfig(image_height=args.image_size,
                          image_width=args.image_size,
                          crop_height=args.crop_size,
                          crop_width=args.crop_size,
                          normalize="scale")
    params = model_def.init(jax.random.key(args.seed), model_cfg, data_cfg)
    mstate = model_def.init_state(params) if model_def.has_state else None
    return ServingEngine.from_params(model_def, model_cfg, data_cfg,
                                     params, mstate)


def load_images(args, image_shape):
    """[N, H, W, C] uint8 request pool."""
    import numpy as np

    if args.source == "dataset":
        from dml_cnn_cifar10_tpu.config import DataConfig
        from dml_cnn_cifar10_tpu.data import ensure_dataset, test_files
        from dml_cnn_cifar10_tpu.data.pipeline import _load_split

        h, w, c = image_shape
        cfg = DataConfig(dataset=args.dataset, data_dir=args.data_dir,
                         image_height=h, image_width=w, num_channels=c,
                         synthetic_test_records=512,
                         use_native_loader=False)
        ensure_dataset(cfg)
        images, _ = _load_split(test_files(cfg), cfg)
        return images
    rng = np.random.default_rng(args.seed)
    return rng.integers(0, 256, (256, *image_shape), dtype=np.uint8)


def load_check_set(path):
    """``--check_labels``: (images, {sha1(image bytes) -> label}) from
    an npz with ``images`` [N,H,W,C] uint8 + ``labels`` [N]. Keyed by
    request-body digest, not pool index, because the drive loops walk
    the shared pool concurrently — the label is recovered from the
    exact bytes each request carried."""
    import numpy as np

    with np.load(path) as z:
        images = np.ascontiguousarray(z["images"]).astype(np.uint8)
        labels = np.asarray(z["labels"]).astype(np.int64)
    if images.ndim != 4 or images.shape[0] != labels.shape[0]:
        raise SystemExit(
            f"--check_labels: want images [N,H,W,C] + labels [N], got "
            f"images {images.shape} / labels {labels.shape}")
    by_digest = {hashlib.sha1(images[i].tobytes()).hexdigest():
                 int(labels[i]) for i in range(images.shape[0])}
    return images, by_digest


class ClientStats:
    """Client-side accounting shared by every drive mode: completions
    with latency + the responding model version, sheds, and (the
    adversarial mix) malformed-request rejects."""

    def __init__(self):
        self.lock = threading.Lock()
        self.completed = 0
        self.shed = 0
        self.rejected = 0
        self.label_checked = 0
        self.label_correct = 0
        self.latencies = []
        self.samples = []   # (latency_s, trace_id, version) per completion
        self.versions = {}

    def record(self, outcome: str, dt: float = 0.0, version=None,
               trace_id=None, correct=None):
        with self.lock:
            if outcome == "ok":
                self.completed += 1
                self.latencies.append(dt)
                self.samples.append((dt, trace_id, version))
                if version is not None:
                    key = str(version)
                    self.versions[key] = self.versions.get(key, 0) + 1
                if correct is not None:   # --check_labels verification
                    self.label_checked += 1
                    self.label_correct += int(correct)
            elif outcome == "shed":
                self.shed += 1
            else:
                self.rejected += 1


class _HttpClient:
    """Blocking POST /predict against a serve worker or fleet router."""

    def __init__(self, target: str, target_cell=None):
        self.target = target.rstrip("/")
        # Cell preference (--target_cell): tagged on every request so
        # the fleet router prefers that cell's replicas and logs the
        # cell_route crossing when it must fail over out of it.
        self.target_cell = target_cell

    def predict(self, body: bytes, trace_header=None, full=False):
        """("ok", version) | ("shed", None) | ("rejected", None).
        ``full=True`` returns the whole response payload as the second
        element instead (the ``--check_labels`` path needs the
        predicted ``class`` too)."""
        import urllib.error
        import urllib.request

        headers = {"Content-Type": "application/octet-stream"}
        if self.target_cell:
            headers["X-DML-Cell"] = self.target_cell
        if trace_header:
            from dml_cnn_cifar10_tpu.utils import reqtrace
            headers[reqtrace.TRACE_HEADER] = trace_header
        req = urllib.request.Request(
            f"{self.target}/predict", data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
            return "ok", (payload if full else payload.get("version"))
        except urllib.error.HTTPError as e:
            if e.code == 503:
                return "shed", None
            if e.code == 400:
                return "rejected", None
            raise


def run_closed(submit, images, args, stats):
    """``--concurrency`` threads in submit→wait→repeat lockstep."""
    stop_at = time.perf_counter() + args.duration_s
    counter = {"i": 0}
    lock = threading.Lock()

    def worker():
        while time.perf_counter() < stop_at:
            with lock:
                idx = counter["i"] = (counter["i"] + 1) % len(images)
            submit(images[idx], stats, False)
    threads = [threading.Thread(target=worker)
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def run_open(submit, images, args, stats, rate_fn=None,
             oversize_frac: float = 0.0):
    """Open-loop arrivals, fire-and-collect: each request runs on its
    own short-lived thread so a slow engine cannot slow the arrival
    schedule (no coordinated omission). ``rate_fn(u)`` scales the
    ``--qps`` base rate over normalized elapsed time — the traffic-mix
    hook; ``oversize_frac`` of arrivals are malformed (adversarial)."""
    import numpy as np

    rate_fn = rate_fn or MIX_RATE["steady"]
    rng = np.random.default_rng(args.seed)
    t0 = time.perf_counter()
    t_end = t0 + args.duration_s
    pending = []
    i = 0
    next_at = t0
    while next_at < t_end:
        now = time.perf_counter()
        if now < next_at:
            time.sleep(next_at - now)
        oversize = bool(oversize_frac) and rng.random() < oversize_frac
        img = images[i % len(images)]
        i += 1
        th = threading.Thread(target=submit, args=(img, stats, oversize))
        th.start()
        pending.append(th)
        rate = max(args.qps * rate_fn((next_at - t0) / args.duration_s),
                   1e-6)
        next_at += 1.0 / rate
    for th in pending:
        th.join(timeout=30)


def _row(stats: ClientStats, wall: float, latency_summary) -> dict:
    total = stats.completed + stats.shed
    lat = latency_summary(stats.latencies)
    # The p99 exemplars: each slowest request's trace_id makes it
    # directly findable in the merged Perfetto trace
    # (tools/trace_aggregate.py --out), and its version says which
    # weights answered it.
    slowest = sorted(stats.samples, key=lambda s: -s[0])[:5]
    row = {
        "requests": total,
        "completed": stats.completed,
        "shed": stats.shed,
        "rejected": stats.rejected,
        "shed_fraction": round(stats.shed / total, 4) if total else 0.0,
        "achieved_qps": round(stats.completed / wall, 2) if wall else 0.0,
        "latency_ms": {
            "p50": lat["p50_ms"], "p95": lat["p95_ms"],
            "p99": lat["p99_ms"], "mean": lat["mean_ms"],
            "max": lat["max_ms"],
        },
        "version_mix": dict(stats.versions),
        "slowest": [{"latency_ms": round(dt * 1e3, 3),
                     "trace_id": tid, "version": ver}
                    for dt, tid, ver in slowest],
    }
    if stats.label_checked:
        # --check_labels: end-to-end prediction accuracy as the client
        # measured it — over the wire for HTTP targets, so a quantized
        # (or wrong) serving path shows up here, not just in its own
        # publish gate.
        row["label_checked"] = stats.label_checked
        row["accuracy"] = round(
            stats.label_correct / stats.label_checked, 4)
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--mode", choices=["closed", "open"], default="closed")
    ap.add_argument("--mix", type=str, default=None,
                    help="comma-separated traffic mixes to run "
                         "(steady, diurnal, burst, adversarial), one "
                         "report row per mix; open-loop drive, "
                         "--mode is ignored")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop client threads")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop arrival rate (mixes scale it)")
    ap.add_argument("--duration_s", type=float, default=10.0,
                    help="duration per profile (each mix runs this long)")
    ap.add_argument("--deadline_ms", type=float, default=None)
    ap.add_argument("--buckets", type=str, default="1,8,32,128")
    ap.add_argument("--queue_depth", type=int, default=256)
    ap.add_argument("--batch_window_ms", type=float, default=2.0)
    ap.add_argument("--artifact", type=str, default=None,
                    help="serve this export.py artifact instead of a "
                         "fresh-initialized model")
    ap.add_argument("--target", type=str, default=None,
                    help="drive a running --mode serve/fleet HTTP "
                         "endpoint instead of an in-process engine")
    ap.add_argument("--target_cell", type=str, default=None,
                    help="tag every request with this fleet cell "
                         "(X-DML-Cell): the router prefers the cell's "
                         "live replicas and fails over cross-cell "
                         "(cell_route record) when it has none; only "
                         "meaningful with a --target fleet router")
    ap.add_argument("--runtime", type=str, default=None,
                    help="drive the serving head of a live --mode run "
                         "process: a runtime.json path, or the log_dir "
                         "that contains one (the runtime advertises its "
                         "bound serve port there); sets --target")
    ap.add_argument("--model", type=str, default="cnn")
    ap.add_argument("--image_size", type=int, default=32)
    ap.add_argument("--crop_size", type=int, default=24)
    ap.add_argument("--source", choices=["random", "dataset"],
                    default="random")
    ap.add_argument("--check_labels", type=str, default=None,
                    help="npz with images [N,H,W,C] uint8 + labels "
                         "[N]: drive THESE images (replacing --source) "
                         "and verify each response's predicted class "
                         "against its label; the report gains "
                         "accuracy + label_checked")
    ap.add_argument("--dataset", type=str, default="synthetic")
    ap.add_argument("--data_dir", type=str, default="cifar10data")
    ap.add_argument("--metrics_jsonl", type=str, default=None,
                    help="also append JSONL records: client rspan spans "
                         "(both targets) and serve/serve_done windows "
                         "(in-process)")
    ap.add_argument("--trace_sample_rate", type=float, default=0.0,
                    help="head-sample this fraction of requests for "
                         "end-to-end tracing (rspan records; shed or "
                         "retried requests are always captured)")
    ap.add_argument("--report", type=str, default="loadgen_report.json")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    if args.runtime:
        # Discover the in-process serving head of a --mode run process
        # from its advertised runtime.json (runtime/core.py writes it
        # atomically; serve_port is null until the serve job binds).
        if args.target:
            raise SystemExit("--runtime and --target are exclusive")
        state_path = args.runtime
        if os.path.isdir(state_path):
            state_path = os.path.join(state_path, "runtime.json")
        try:
            with open(state_path) as f:
                state = json.load(f)
        except (OSError, ValueError) as e:
            raise SystemExit(f"--runtime: cannot read {state_path}: {e}")
        port = state.get("serve_port")
        if not port:
            raise SystemExit(
                f"--runtime: {state_path} advertises no serve_port yet "
                f"(is the runtime's serve job up? it binds after the "
                f"first checkpoint publish)")
        args.target = f"http://127.0.0.1:{int(port)}"
        print(f"[loadgen] runtime target {args.target} (version "
              f"{state.get('version')}, {state.get('publishes')} "
              f"publish(es))", flush=True)

    import numpy as np

    from dml_cnn_cifar10_tpu.utils import reqtrace
    from dml_cnn_cifar10_tpu.utils.telemetry import latency_summary

    logger = None
    if args.metrics_jsonl:
        from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
        logger = MetricsLogger(jsonl_path=args.metrics_jsonl)

    mixes = None
    if args.mix:
        mixes = [m.strip() for m in args.mix.split(",") if m.strip()]
        unknown = [m for m in mixes if m not in MIX_RATE]
        if unknown:
            raise SystemExit(f"unknown mix(es) {unknown}; choose from "
                             f"{sorted(MIX_RATE)}")

    batcher = None
    metrics = None
    labels_by_digest = None
    if args.target:
        client = _HttpClient(args.target, target_cell=args.target_cell)
        rng = np.random.default_rng(args.seed)
        images = rng.integers(
            0, 256, (256, args.image_size, args.image_size, 3),
            dtype=np.uint8)
        if args.check_labels:
            images, labels_by_digest = load_check_set(args.check_labels)

        def submit(img, stats, oversize):
            # Oversize = wrong byte count on the wire; the server (or
            # any worker behind the router) must answer 400 without
            # disturbing in-flight well-formed requests.
            body = img.tobytes() + (b"\x00" if oversize else b"")
            ctx = reqtrace.mint(args.trace_sample_rate)
            t0 = time.perf_counter()
            correct = None
            if labels_by_digest is None:
                outcome, version = client.predict(
                    body, trace_header=ctx.header())
            else:
                outcome, payload = client.predict(
                    body, trace_header=ctx.header(), full=True)
                version = (payload or {}).get("version")
                label = labels_by_digest.get(
                    hashlib.sha1(body).hexdigest())
                if outcome == "ok" and label is not None:
                    correct = (payload or {}).get("class") == label
            dt = time.perf_counter() - t0
            if outcome == "shed":
                ctx.force()
            reqtrace.emit_span(logger, ctx, "client", dt,
                               reqtrace.wallclock_at(t0),
                               outcome=outcome, version=version)
            stats.record(outcome, dt, version, trace_id=ctx.trace_id,
                         correct=correct)
    else:
        from dml_cnn_cifar10_tpu.serve.batcher import (MicroBatcher,
                                                       ShedError)
        from dml_cnn_cifar10_tpu.serve.metrics import ServeMetrics

        engine = build_engine(args)
        images = load_images(args, engine.image_shape)
        if args.check_labels:
            images, labels_by_digest = load_check_set(args.check_labels)
        metrics = ServeMetrics()
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
        batcher = MicroBatcher(
            engine, buckets=buckets, max_queue_depth=args.queue_depth,
            batch_window_s=args.batch_window_ms / 1e3,
            default_deadline_s=None if args.deadline_ms is None
            else args.deadline_ms / 1e3,
            metrics=metrics, logger=logger)
        print(f"[loadgen] engine ready (compile_s="
              f"{batcher.compile_secs}); driving for "
              f"{args.duration_s}s per profile", flush=True)

        def submit(img, stats, oversize):
            # Oversize = wrong image shape; admission validation
            # rejects it before it can reach the queue.
            if oversize:
                img = np.zeros((img.shape[0] + 1, *img.shape[1:]),
                               np.uint8)
            ctx = reqtrace.mint(args.trace_sample_rate)
            t0 = time.perf_counter()
            try:
                row = batcher.submit(img, trace=ctx).result()
                dt = time.perf_counter() - t0
                version = getattr(row, "version", None)
                correct = None
                if labels_by_digest is not None:
                    label = labels_by_digest.get(
                        hashlib.sha1(img.tobytes()).hexdigest())
                    if label is not None:
                        correct = int(np.asarray(row).argmax()) == label
                reqtrace.emit_span(logger, ctx, "client", dt,
                                   reqtrace.wallclock_at(t0),
                                   outcome="ok", version=version)
                stats.record("ok", dt, version, trace_id=ctx.trace_id,
                             correct=correct)
            except ShedError:
                dt = time.perf_counter() - t0
                ctx.force()
                reqtrace.emit_span(logger, ctx, "client", dt,
                                   reqtrace.wallclock_at(t0),
                                   outcome="shed")
                stats.record("shed", dt, trace_id=ctx.trace_id)
            except ValueError:
                stats.record("rejected")

    def engine_side_stats(reset: bool) -> dict:
        if metrics is None:
            return {}
        return metrics.window(reset=True) if reset \
            else metrics.cumulative()

    loadgen_meta = {
        "mode": args.mode if mixes is None else "mix",
        "engine": "http" if args.target else "inprocess",
        "concurrency": args.concurrency,
        "target_qps": args.qps if (mixes or args.mode == "open")
        else None,
        "duration_s": args.duration_s,
        "deadline_ms": args.deadline_ms,
        "buckets": args.buckets,
        "queue_depth": args.queue_depth,
        "batch_window_ms": args.batch_window_ms,
        "source": args.source,
        "check_labels": args.check_labels,
        "seed": args.seed,
    }

    if mixes is None:
        stats = ClientStats()
        t0 = time.perf_counter()
        if args.mode == "closed":
            run_closed(submit, images, args, stats)
        else:
            run_open(submit, images, args, stats)
        wall = time.perf_counter() - t0
        report = {"loadgen": loadgen_meta,
                  **_row(stats, wall, latency_summary)}
        engine_side = engine_side_stats(reset=False)
        for key in ("batch_fill", "batches", "queue_wait_p50_ms",
                    "device_p50_ms"):
            if key in engine_side:
                report[key] = engine_side[key]
    else:
        rows = []
        for mix in mixes:
            print(f"[loadgen] mix {mix!r}: open loop, base qps "
                  f"{args.qps}, {args.duration_s}s", flush=True)
            stats = ClientStats()
            t0 = time.perf_counter()
            run_open(submit, images, args, stats,
                     rate_fn=MIX_RATE[mix],
                     oversize_frac=ADVERSARIAL_OVERSIZE
                     if mix == "adversarial" else 0.0)
            wall = time.perf_counter() - t0
            row = {"mix": mix, "duration_s": round(wall, 3),
                   **_row(stats, wall, latency_summary)}
            engine_side = engine_side_stats(reset=True)
            for key in ("batch_fill", "batches"):
                if key in engine_side:
                    row[key] = engine_side[key]
            rows.append(row)
        report = {"loadgen": loadgen_meta, "mixes": rows}

    if batcher is not None:
        batcher.close()
        if logger is not None:
            metrics.emit(logger, final=True)
    if logger is not None:
        logger.close()

    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps(report))
    print(f"[loadgen] wrote {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
