#!/usr/bin/env python
"""Load generator for the serving subsystem: closed- or open-loop
traffic against the micro-batching engine, with a BENCH-style report.

Two drive modes (the standard serving-bench dichotomy):

- **closed** (default): ``--concurrency`` client threads each submit
  one request, wait for its result, and immediately submit the next —
  throughput is whatever the engine sustains at that concurrency
  (latency and throughput are coupled).
- **open**: requests arrive on a fixed ``--qps`` schedule regardless of
  completions — the honest overload experiment: when the engine can't
  keep up, the queue grows until admission control sheds, and the
  report's ``shed_fraction`` says so (closed-loop clients would instead
  silently slow down — coordinated omission).

Two targets:

- **in-process** (default): builds a CPU/TPU engine right here —
  ``--artifact PATH`` serves an ``export.py`` artifact, otherwise a
  fresh-initialized CNN (geometry from ``--image_size``) so the tool
  runs on a bare checkout.
- ``--target http://host:port``: drives a running ``--mode serve``
  process over HTTP (raw-bytes POST /predict), measuring end-to-end
  including transport.

Requests replay CIFAR test images (``--source dataset``, raw uint8 from
the on-disk records) or synthetic pixels (``--source random``). The
JSON report (``--report``) carries achieved QPS, latency percentiles,
shed fraction, and batch-fill — the serving analogue of BENCH_*.json.

Usage:
    python tools/loadgen.py --mode closed --concurrency 8 --duration_s 10
    python tools/loadgen.py --mode open --qps 500 --deadline_ms 50 \\
        --artifact /tmp/logs/model.jaxexport --report /tmp/serve_bench.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_engine(args):
    from dml_cnn_cifar10_tpu.serve.engine import ServingEngine

    if args.artifact:
        return ServingEngine.from_artifact(args.artifact)
    import jax

    from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
    from dml_cnn_cifar10_tpu.models.registry import get_model

    model_def = get_model(args.model)
    model_cfg = ModelConfig(name=args.model, logit_relu=False)
    data_cfg = DataConfig(image_height=args.image_size,
                          image_width=args.image_size,
                          crop_height=args.crop_size,
                          crop_width=args.crop_size,
                          normalize="scale")
    params = model_def.init(jax.random.key(args.seed), model_cfg, data_cfg)
    mstate = model_def.init_state(params) if model_def.has_state else None
    return ServingEngine.from_params(model_def, model_cfg, data_cfg,
                                     params, mstate)


def load_images(args, image_shape):
    """[N, H, W, C] uint8 request pool."""
    import numpy as np

    if args.source == "dataset":
        from dml_cnn_cifar10_tpu.config import DataConfig
        from dml_cnn_cifar10_tpu.data import ensure_dataset, test_files
        from dml_cnn_cifar10_tpu.data.pipeline import _load_split

        h, w, c = image_shape
        cfg = DataConfig(dataset=args.dataset, data_dir=args.data_dir,
                         image_height=h, image_width=w, num_channels=c,
                         synthetic_test_records=512,
                         use_native_loader=False)
        ensure_dataset(cfg)
        images, _ = _load_split(test_files(cfg), cfg)
        return images
    rng = np.random.default_rng(args.seed)
    return rng.integers(0, 256, (256, *image_shape), dtype=np.uint8)


class _HttpClient:
    """Minimal stand-in for MicroBatcher.submit over HTTP — blocking
    POST, so it only supports the closed-loop drive."""

    def __init__(self, target: str, image_shape):
        self.target = target.rstrip("/")
        self.image_shape = image_shape

    def predict(self, image) -> bool:
        """True = completed, False = shed (HTTP 503)."""
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"{self.target}/predict", data=image.tobytes(),
            headers={"Content-Type": "application/octet-stream"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
            return True
        except urllib.error.HTTPError as e:
            if e.code == 503:
                return False
            raise


def run_closed(submit, images, args, client_stats):
    """``--concurrency`` threads in submit→wait→repeat lockstep."""
    stop_at = time.perf_counter() + args.duration_s
    counter = {"i": 0}
    lock = threading.Lock()

    def worker():
        while time.perf_counter() < stop_at:
            with lock:
                idx = counter["i"] = (counter["i"] + 1) % len(images)
            submit(images[idx], client_stats)
    threads = [threading.Thread(target=worker)
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def run_open(submit, images, args, client_stats):
    """Fixed-rate arrivals for ``--duration_s``, fire-and-collect: each
    request runs on its own short-lived thread so a slow engine cannot
    slow the arrival schedule (no coordinated omission)."""
    period = 1.0 / args.qps
    t_end = time.perf_counter() + args.duration_s
    pending = []
    i = 0
    next_at = time.perf_counter()
    while next_at < t_end:
        now = time.perf_counter()
        if now < next_at:
            time.sleep(next_at - now)
        img = images[i % len(images)]
        i += 1
        th = threading.Thread(target=submit, args=(img, client_stats))
        th.start()
        pending.append(th)
        next_at += period
    for th in pending:
        th.join(timeout=30)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--mode", choices=["closed", "open"], default="closed")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop client threads")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop arrival rate")
    ap.add_argument("--duration_s", type=float, default=10.0)
    ap.add_argument("--deadline_ms", type=float, default=None)
    ap.add_argument("--buckets", type=str, default="1,8,32,128")
    ap.add_argument("--queue_depth", type=int, default=256)
    ap.add_argument("--batch_window_ms", type=float, default=2.0)
    ap.add_argument("--artifact", type=str, default=None,
                    help="serve this export.py artifact instead of a "
                         "fresh-initialized model")
    ap.add_argument("--target", type=str, default=None,
                    help="drive a running --mode serve HTTP endpoint "
                         "instead of an in-process engine (closed mode "
                         "only)")
    ap.add_argument("--model", type=str, default="cnn")
    ap.add_argument("--image_size", type=int, default=32)
    ap.add_argument("--crop_size", type=int, default=24)
    ap.add_argument("--source", choices=["random", "dataset"],
                    default="random")
    ap.add_argument("--dataset", type=str, default="synthetic")
    ap.add_argument("--data_dir", type=str, default="cifar10data")
    ap.add_argument("--metrics_jsonl", type=str, default=None,
                    help="also append serve/serve_done JSONL records "
                         "(in-process only)")
    ap.add_argument("--report", type=str, default="loadgen_report.json")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from dml_cnn_cifar10_tpu.utils.telemetry import latency_summary

    client_stats = {"completed": 0, "shed": 0, "latencies": [],
                    "lock": threading.Lock()}

    def record(ok: bool, dt: float, stats) -> None:
        with stats["lock"]:
            if ok:
                stats["completed"] += 1
                stats["latencies"].append(dt)
            else:
                stats["shed"] += 1

    if args.target:
        if args.mode != "closed":
            raise SystemExit("--target supports --mode closed only (the "
                             "server's own deadline handles open-loop "
                             "overload)")
        client = _HttpClient(args.target, None)
        import numpy as np
        rng = np.random.default_rng(args.seed)
        images = rng.integers(
            0, 256, (256, args.image_size, args.image_size, 3),
            dtype=np.uint8)

        def submit(img, stats):
            t0 = time.perf_counter()
            ok = client.predict(img)
            record(ok, time.perf_counter() - t0, stats)

        t0 = time.perf_counter()
        run_closed(submit, images, args, client_stats)
        wall = time.perf_counter() - t0
        engine_side = {}
    else:
        from dml_cnn_cifar10_tpu.serve.batcher import (MicroBatcher,
                                                       ShedError)
        from dml_cnn_cifar10_tpu.serve.metrics import ServeMetrics

        engine = build_engine(args)
        images = load_images(args, engine.image_shape)
        metrics = ServeMetrics()
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
        batcher = MicroBatcher(
            engine, buckets=buckets, max_queue_depth=args.queue_depth,
            batch_window_s=args.batch_window_ms / 1e3,
            default_deadline_s=None if args.deadline_ms is None
            else args.deadline_ms / 1e3,
            metrics=metrics)
        print(f"[loadgen] engine ready (compile_s="
              f"{batcher.compile_secs}); driving {args.mode} loop for "
              f"{args.duration_s}s", flush=True)

        def submit(img, stats):
            t0 = time.perf_counter()
            try:
                batcher.submit(img).result()
                record(True, time.perf_counter() - t0, stats)
            except ShedError:
                record(False, time.perf_counter() - t0, stats)

        t0 = time.perf_counter()
        if args.mode == "closed":
            run_closed(submit, images, args, client_stats)
        else:
            run_open(submit, images, args, client_stats)
        wall = time.perf_counter() - t0
        batcher.close()
        engine_side = metrics.cumulative()
        if args.metrics_jsonl:
            from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
            logger = MetricsLogger(jsonl_path=args.metrics_jsonl)
            metrics.emit(logger, final=True)
            logger.close()

    completed = client_stats["completed"]
    shed = client_stats["shed"]
    total = completed + shed
    lat = latency_summary(client_stats["latencies"])
    report = {
        "loadgen": {
            "mode": args.mode,
            "engine": "http" if args.target else "inprocess",
            "concurrency": args.concurrency,
            "target_qps": args.qps if args.mode == "open" else None,
            "duration_s": round(wall, 3),
            "deadline_ms": args.deadline_ms,
            "buckets": args.buckets,
            "queue_depth": args.queue_depth,
            "batch_window_ms": args.batch_window_ms,
            "source": args.source,
            "seed": args.seed,
        },
        "requests": total,
        "completed": completed,
        "shed": shed,
        "shed_fraction": round(shed / total, 4) if total else 0.0,
        "achieved_qps": round(completed / wall, 2) if wall else 0.0,
        "latency_ms": {
            "p50": lat["p50_ms"], "p95": lat["p95_ms"],
            "p99": lat["p99_ms"], "mean": lat["mean_ms"],
            "max": lat["max_ms"],
        },
    }
    for key in ("batch_fill", "batches", "queue_wait_p50_ms",
                "device_p50_ms"):
        if key in engine_side:
            report[key] = engine_side[key]
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps(report))
    print(f"[loadgen] wrote {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
