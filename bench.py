#!/usr/bin/env python
"""Headline benchmark: steady-state training throughput, images/sec/chip.

Runs the faithful reference workload — the 5-layer CIFAR-10 CNN at global
batch 128 (``cifar10cnn.py:13,94-147``) — as one compiled SPMD step over all
available devices, fed by the real input pipeline, and measures steady-state
throughput after compile, in BOTH compute dtypes (fp32 and bf16 — the
MXU-native dtype). The headline value is the faster config; both rows ride
along with TFLOP/s + MFU from XLA's compiled cost analysis.

Round-5 (verdict #4/#5) methodology:

- Every row runs ``reps`` (default 3) INDEPENDENT timed repetitions after
  one shared warmup, and reports min/median/max + spread — the tunneled
  v5e showed run-to-run swings up to ~13% on one row between rounds
  (BENCH_r03 vs r04's K=320), so a single sample cannot adjudicate
  few-percent deltas. The row value is the MEDIAN (robust to a slow
  outlier rep); ``spread_pct`` = (max−min)/median tells you how much to
  trust a comparison.
- The headline config uses the DEVICE index stream
  (``data/device_stream.py``): the training dispatch uploads nothing at
  all. A host-index A/B row rides along.
- Round 8: every row also records a per-dispatch step-time tail
  (``step_ms_p50`` / ``step_ms_p99`` + the raw series) from a separate
  drained sampling pass, so ``tools/bench_gate.py`` can flag tail
  regressions the windowed mean hides.

Baseline note: the reference publishes NO performance numbers
(``README.md``, SURVEY §6 — ``BASELINE.json.published == {}``).
``vs_baseline`` is therefore anchored to the driver's north-star throughput:
≥20,000 steps × batch 128 in <120 s on a v4-8 ⇒ 21,333 images/sec ÷ 8 chips
= 2,666.7 images/sec/chip. vs_baseline = measured / 2666.7.

Compile cost (round 6): every compile seam routes through the
persistent compilation cache (``compilecache/``, default dir
``/tmp/dml_bench_compile_cache``; override with
``BENCH_COMPILE_CACHE_DIR``, empty string disables). Warm re-runs skip
the XLA recompile (jax's native persistent cache armed under the same
dir; raw executable deserialization is opt-in per backend), each row
reports ``compile_s`` + ``cache_hit``, and the FLOPs figure is read
from the SAME cached artifact the timed path executes — the old caveat
(the AOT ``lower().compile()`` probe not sharing the executable cache,
forcing a post-measurement recompile) is gone.

Prints ONE JSON line:
  {"metric": "train_throughput", "value": N, "unit": "images/sec/chip",
   "vs_baseline": N, "fp32": {...}, "bf16": {...}, ...}
"""

from __future__ import annotations

import json
import os
import statistics
import time

NORTH_STAR_IMAGES_PER_SEC_PER_CHIP = 20000 * 128 / 120.0 / 8.0  # 2666.7


def _bench_cache_dir():
    """Cache dir for the bench's compile seams ('' disables)."""
    return os.environ.get("BENCH_COMPILE_CACHE_DIR",
                          "/tmp/dml_bench_compile_cache")

# MXU peak TFLOP/s per chip by device kind (substring match on
# jax.devices()[0].device_kind). One number per part, NOT per dtype:
# under XLA's default precision, float32 matmuls/convs also execute on
# the bf16 MXU (bf16 multiplies, fp32 accumulate) — a run with fp32
# compute_dtype measured 54 TFLOP/s on a v5e, above the 49 "fp32 peak",
# proving the fp32-pass rate is the wrong denominator. MFU here is
# therefore utilization of the MXU the code actually runs on. Override
# with BENCH_PEAK_TFLOPS for other parts.
_PEAKS = {
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v4": 275.0,
    "v5p": 459.0,
}


def _peak_tflops(device_kind: str):
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = device_kind.lower()
    for key, peak in _PEAKS.items():
        if key in kind:
            return peak
    return None


def _optimizer_ms_probe(chunk, prefetch, state, chunk_k: int,
                        dispatches: int = 2):
    """``(state, per_step_optimizer_ms | None)`` — a short
    ``jax.profiler`` capture around ``dispatches`` extra chunk calls,
    parsed host-side (utils/devprof.py) into the per-step device time
    inside the step's ``named_scope("optimizer")``. The row then RECORDS
    the weight-update tail the fused kernel / zero1 sharding attack,
    instead of inferring it from throughput deltas. Fail-open: any
    profiler/parse trouble returns None (the key stays in the row).
    Skipped on the CPU backend entirely (None recorded): tracing a
    bench-sized window there floods the export — the virtual-device
    busy-wait case from PR 8, and measured minutes of stop_trace even
    single-device at bench geometry — and CPU host lanes carry no
    device op scopes to attribute anyway. BENCH_PROFILE_OPT=1 forces
    the capture for debugging."""
    import jax

    if jax.default_backend() == "cpu" \
            and os.environ.get("BENCH_PROFILE_OPT") != "1":
        return state, None
    import shutil
    import tempfile

    from dml_cnn_cifar10_tpu.utils import devprof

    tmp = tempfile.mkdtemp(prefix="bench_opt_ms_")
    try:
        jax.profiler.start_trace(tmp)
        try:
            for _ in range(dispatches):
                state, metrics = chunk(state, *next(prefetch))
            float(jax.device_get(metrics["loss"]))
        finally:
            jax.profiler.stop_trace()
        lanes = devprof.parse_profile_dir(tmp)
        if not lanes:
            return state, None
        per_step = (sum(ln.get("optimizer_ms") or 0.0 for ln in lanes)
                    / len(lanes) / (dispatches * chunk_k))
        return state, round(per_step, 4)
    except Exception:
        return state, None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def measure(compute_dtype: str, chunk_k: int = 100, chunks: int = 60,
            dev_stream: bool = True, reps: int = 3,
            optimizer_sharding: str = "none") -> dict:
    """Steady-state throughput + MFU for one compute dtype —
    ``reps`` independently timed repetitions after one warmup.

    ``dev_stream`` (default ON — the headline config, round-4 verdict
    #5) generates the shuffled index stream on device
    (``data/device_stream.py``): the dispatch carries NO host data at
    all. ``False`` ships host-generated index arrays (the A/B row).
    ``optimizer_sharding="zero1"`` runs the ZeRO-1 sharded weight
    update (reduce-scatter / sharded update / all-gather over the data
    mesh; docs/SHARDING.md) — the ``fp32_zero1`` row."""
    import jax

    from dml_cnn_cifar10_tpu.config import reference_config
    from dml_cnn_cifar10_tpu.data import pipeline as pipe
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from dml_cnn_cifar10_tpu.utils.profiling import (abstractify,
                                                     compiled_flops)

    cfg = reference_config()
    cfg.data.dataset = "synthetic"           # zero-egress box: CIFAR-layout
    cfg.data.data_dir = "/tmp/bench_cifar"   # synthetic records, real pipeline
    cfg.data.synthetic_train_records = 20480
    cfg.data.synthetic_test_records = 1024
    cfg.batch_size = 128
    cfg.log_dir = "/tmp/bench_logs_unused"
    cfg.checkpoint_every = 10**9             # no checkpoint I/O in the loop
    cfg.data.prefetch = 4                    # measured +1.6% over depth 2
    # The raw-chunk path reads the base iterator's in-memory permutation
    # directly; the native loader's C++ shuffle pool would be dead weight.
    cfg.data.use_native_loader = False
    cfg.model.compute_dtype = compute_dtype
    cfg.optim.optimizer_sharding = optimizer_sharding
    # Compile-cache every seam (trainer step fns, the chunk below, the
    # FLOPs probes): warm bench re-runs skip XLA entirely.
    cfg.compile_cache_dir = _bench_cache_dir() or None

    trainer = Trainer(cfg)
    state = trainer.init_or_restore()
    n_chips = len(jax.devices())

    # HBM-resident data path (parallel/step.py:make_train_chunk_resident):
    # the full uint8 dataset lives in HBM, and gather + decode + K training
    # steps run as one compiled dispatch. The reference CNN is ~1 ms of MXU
    # work per step — host-side gather/decode/H2D (measured ~8 ms per
    # 20-step chunk) bounds every host-fed pipeline, so the dataset moves
    # to the device once instead.
    # Steps per dispatch: measured sweep on the v5e tunnel box —
    # 20→435k, 40→532k, 80→574k, 100→614k, 320→643k (plateau) img/s/chip.
    # 100 sits within 5% of the plateau AND divides the reference's
    # 200/500 output/eval cadences, so the benched config is exactly what
    # the Trainer can run with observable-boundary parity.
    train_it = pipe.input_pipeline(cfg.data, cfg.batch_size, train=True)
    repl = mesh_lib.replicated(trainer.mesh)
    ds_images = jax.device_put(train_it.images, repl)
    ds_labels = jax.device_put(train_it.labels.astype("int32"), repl)
    chunk = step_lib.make_train_chunk_resident(
        trainer.model_def, cfg.model, cfg.optim, trainer.mesh,
        ds_images, ds_labels, state_sharding=trainer.state_sharding,
        data_cfg=cfg.data,
        index_stream=((cfg.data.seed, cfg.batch_size, chunk_k)
                      if dev_stream else None),
        compile_cache=trainer.compile_cache)
    if dev_stream:
        def feed():
            return ()
        prefetch = pipe.PrefetchIterator(
            iter(feed, None), depth=1, place=None)
    else:
        idx_sh = mesh_lib.batch_sharding(trainer.mesh, 2, leading_dims=1)

        def next_idx():
            return (jax.device_put(train_it.next_index_chunk(chunk_k),
                                   idx_sh),)
        prefetch = pipe.PrefetchIterator(
            iter(next_idx, None), depth=cfg.data.prefetch, place=None)

    # Warmup: first call compiles (~20-40s), more to fill the pipeline.
    # Drain with device_get, NOT block_until_ready: on the tunneled TPU
    # platform block_until_ready can return before the execution queue
    # drains, which would inflate the measurement ~16x.
    for _ in range(3):
        state, metrics = chunk(state, *next(prefetch))
    float(jax.device_get(metrics["loss"]))

    # Timed steady state: reps independent windows, each drained.
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(chunks):
            state, metrics = chunk(state, *next(prefetch))
        float(jax.device_get(metrics["loss"]))  # full drain
        dt = time.perf_counter() - t0
        rates.append(chunks * chunk_k * cfg.batch_size / dt / n_chips)

    # Step-time tail (round 8): the windowed rates above report the
    # MEAN; a periodic stall (GC, allocator, a slow collective) hides
    # in it completely. A separate sampling pass times individual
    # dispatches, each drained — per-dispatch drains serialize host and
    # device, so these samples are NOT comparable to the throughput
    # windows (each carries one drain round trip); they exist to rank
    # p99 against p50, which tools/bench_gate.py gates on.
    from dml_cnn_cifar10_tpu.utils.telemetry import percentile
    tail_ms = []
    for _ in range(min(chunks, 30)):
        t0 = time.perf_counter()
        state, metrics = chunk(state, *next(prefetch))
        float(jax.device_get(metrics["loss"]))
        tail_ms.append((time.perf_counter() - t0) / chunk_k * 1e3)
    # Measured weight-update tail (docs/OBSERVABILITY.md): a short
    # post-measurement capture attributes the per-step device time in
    # the optimizer named_scope — None when the platform can't trace.
    state, optimizer_ms = _optimizer_ms_probe(chunk, prefetch, state,
                                              chunk_k)
    # One extra (unused) batch before the pipeline closes: its avals let
    # the flops probe below look the TIMED chunk program up in the
    # compile cache without rebuilding shardings by hand.
    probe_batch = () if dev_stream else next(prefetch)
    prefetch.close()

    med = statistics.median(rates)
    row = {
        "images_per_sec_per_chip": round(med, 1),
        "img_s_min": round(min(rates), 1),
        "img_s_max": round(max(rates), 1),
        "spread_pct": round(100.0 * (max(rates) - min(rates)) / med, 2),
        "reps": reps,
        # Per-step time distribution from the drained sampling pass
        # (see above: includes a drain per dispatch — gate on the
        # p99/p50 RATIO trajectory, not on these vs the mean rate).
        "step_ms_p50": round(percentile(tail_ms, 50), 4),
        "step_ms_p99": round(percentile(tail_ms, 99), 4),
        "step_ms_samples": len(tail_ms),
        "step_ms_series": [round(v, 4) for v in tail_ms],
        # Per-step device time in the optimizer named_scope (see the
        # probe above); null when the platform can't capture a trace.
        "optimizer_ms": optimizer_ms,
        "optimizer_sharding": optimizer_sharding,
    }

    # Per-step FLOPs. With the compile cache armed both figures come
    # from CACHED artifacts — zero recompiles after the timed section:
    # the primary source is the cost analysis of the chunk executable
    # the timed loop actually ran (read back through the cache entry),
    # cross-checked against the SCAN-FREE single step (exact for the
    # CNN; also cache-served) to verify the backend counted the K-step
    # scan body once — a chunk/step ratio near K means it was unrolled
    # and the chunk figure scales back by K. XLA cost analysis reports
    # the per-device share of the partitioned program in both cases.
    d = cfg.data
    import numpy as np
    img_abs = jax.ShapeDtypeStruct(
        (cfg.batch_size, d.crop_height, d.crop_width, d.num_channels),
        np.float32)
    lab_abs = jax.ShapeDtypeStruct((cfg.batch_size,), np.int32)
    step_flops = compiled_flops(trainer.train_step,
                                (abstractify(state), img_abs, lab_abs))
    flops = step_flops
    flops_source = "step_probe"
    cached = getattr(chunk, "cached", None)
    if cached is not None:
        ev = cached.last_event or {}
        row["cache_hit"] = bool(ev.get("hit"))
        row["compile_s"] = ev.get("compile_s")
        chunk_f = chunk.cached_flops(abstractify((state, *probe_batch)))
        if chunk_f and step_flops and \
                chunk_f >= (1 + chunk_k) / 2 * step_flops:
            chunk_f /= chunk_k
        if chunk_f:
            flops = chunk_f
            flops_source = "chunk_artifact"
    if flops:
        row["flops_source"] = flops_source
        # Per-DEVICE flop share x GLOBAL steps/sec (matches the verified
        # train/loop.py formula — no extra device_count divide): each
        # step's program runs once per step across the mesh, each chip
        # executing its 1/n flop share, so per-chip TF/s = per-device
        # flops x global steps/sec. MFU from the MEDIAN rep.
        steps_per_sec = med * n_chips / cfg.batch_size
        tflops = flops * steps_per_sec / 1e12
        row["tflops_per_sec_per_chip"] = round(tflops, 2)
        peak = _peak_tflops(jax.devices()[0].device_kind)
        if peak:
            row["mfu"] = round(tflops / peak, 4)
            row["peak_tflops"] = peak
    return row


def measure_int8_serve(batch: int = 128, reps: int = 3,
                       windows: int = 50) -> dict:
    """Serving-path A/B: the int8 quantized forward
    (``quant/convert.py`` — int8 ``dot_general``/``conv`` with
    ``preferred_element_type=int32``, dequant fused into the epilogue)
    vs the SAME weights served through the float program in bf16
    compute. Single device, one jitted dispatch per batch — the shape
    the serving engine's bucket fns execute, without batcher overhead,
    so the row isolates the numeric path. ``speedup_vs_bf16`` is what
    ``tools/bench_gate.py`` floors (TPU rows only — XLA's CPU int8
    lowering has no MXU to win on; the ``backend`` key says which this
    row is)."""
    import dataclasses

    import jax
    import numpy as np

    from dml_cnn_cifar10_tpu.config import reference_config
    from dml_cnn_cifar10_tpu.export import make_variable_serving_fn
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.quant.calibrate import calibrate
    from dml_cnn_cifar10_tpu.quant.convert import (
        make_quantized_serving_fn, quantize_params)
    from dml_cnn_cifar10_tpu.utils.telemetry import percentile

    cfg = reference_config()
    cfg.data.dataset = "synthetic"
    cfg.data.data_dir = "/tmp/bench_cifar"
    cfg.data.synthetic_train_records = 20480
    cfg.data.synthetic_test_records = 1024
    cfg.data.use_native_loader = False

    model_def = get_model(cfg.model.name)
    params = model_def.init(jax.random.key(0), cfg.model, cfg.data)
    d = cfg.data
    rng = np.random.default_rng(0)
    images = rng.integers(
        0, 256, (512, d.image_height, d.image_width, d.num_channels),
        dtype=np.uint8)
    scales = calibrate(params, images[:256], cfg.model, cfg.data,
                       batch_size=64, num_batches=4)
    qtree = quantize_params(params, scales)
    bf16_cfg = dataclasses.replace(cfg.model, compute_dtype="bfloat16")
    quant_fn = jax.jit(make_quantized_serving_fn(cfg.model, cfg.data))
    float_fn = jax.jit(make_variable_serving_fn(model_def, bf16_cfg,
                                                cfg.data))
    batch_imgs = images[:batch]

    def drive(fn, variables):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(variables, batch_imgs))  # compile
        compile_s = time.perf_counter() - t0
        rates, lat_ms = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(windows):
                out = fn(variables, batch_imgs)
            jax.block_until_ready(out)
            rates.append(windows * batch / (time.perf_counter() - t0))
        for _ in range(min(windows, 30)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(variables, batch_imgs))
            lat_ms.append((time.perf_counter() - t0) * 1e3)
        return rates, lat_ms, compile_s

    q_rates, q_lat, q_compile = drive(quant_fn, (qtree, None))
    f_rates, f_lat, _ = drive(float_fn, (params, None))
    q_med, f_med = statistics.median(q_rates), statistics.median(f_rates)
    return {
        "images_per_sec_per_chip": round(q_med, 1),
        "img_s_min": round(min(q_rates), 1),
        "img_s_max": round(max(q_rates), 1),
        "spread_pct": round(
            100.0 * (max(q_rates) - min(q_rates)) / q_med, 2),
        "reps": reps,
        "batch": batch,
        "compile_s": round(q_compile, 4),
        "step_ms_p50": round(percentile(q_lat, 50), 4),
        "step_ms_p99": round(percentile(q_lat, 99), 4),
        "bf16_images_per_sec_per_chip": round(f_med, 1),
        "bf16_step_ms_p50": round(percentile(f_lat, 50), 4),
        "speedup_vs_bf16": round(q_med / f_med, 3),
        "backend": jax.default_backend(),
    }


def main() -> None:
    # Before any jax backend use: the native persistent compilation
    # cache (the warm start when executable swapping is off — the
    # default) is read at client creation; arming later is a no-op.
    from dml_cnn_cifar10_tpu.compilecache import arm_native_cache
    arm_native_cache(_bench_cache_dir() or None)
    rows = {
        # Headline pair: K=100 — the largest dispatch that still lands
        # on the reference's 200/500 observable-boundary cadence, i.e.
        # what the Trainer actually runs with full parity. Device index
        # stream (the default data path since round 5).
        "fp32": measure("float32", chunk_k=100),
        "bf16": measure("bfloat16", chunk_k=100),
        # Plateau: K=320 amortizes dispatch overhead past the cadence
        # constraint (measured sweep plateau) — the ceiling when
        # observable-boundary parity is relaxed.
        "fp32_k320": measure("float32", chunk_k=320, chunks=20),
        # A/B: host-generated index upload (the pre-round-5 default) —
        # pins that the device stream costs nothing.
        "fp32_hostidx": measure("float32", chunk_k=100, dev_stream=False),
        # ZeRO-1 sharded weight update (--optimizer_sharding zero1,
        # docs/SHARDING.md) on the same mesh: reduce-scatter + sharded
        # update + all-gather replacing the grad all-reduce. Joins the
        # perf-regression gate (tools/bench_gate.py row tolerances) so
        # the new path cannot regress silently.
        "fp32_zero1": measure("float32", chunk_k=100,
                              optimizer_sharding="zero1"),
        # Serving A/B: the post-training int8 path (docs/QUANT.md) vs
        # the same weights in bf16 compute. Joins the gate
        # (tools/bench_gate.py) with a speedup floor on TPU backends.
        "int8_serve": measure_int8_serve(),
    }
    # Headline = best PARITY config (K=100): the plateau row is reported
    # as data but may not claim the headline — it relaxes the
    # observable-boundary cadence the Trainer actually honors.
    headline = max((rows["fp32"], rows["bf16"]),
                   key=lambda r: r["images_per_sec_per_chip"])
    per_chip = headline["images_per_sec_per_chip"]
    print(json.dumps({
        "metric": "train_throughput",
        "value": per_chip,
        "unit": "images/sec/chip",
        "vs_baseline": round(
            per_chip / NORTH_STAR_IMAGES_PER_SEC_PER_CHIP, 3),
        **rows,
    }))


if __name__ == "__main__":
    main()
