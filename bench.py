#!/usr/bin/env python
"""Headline benchmark: steady-state training throughput, images/sec/chip.

Runs the faithful reference workload — the 5-layer CIFAR-10 CNN at global
batch 128 (``cifar10cnn.py:13,94-147``) — as one compiled SPMD step over all
available devices, fed by the real input pipeline (shuffle buffer + host→HBM
prefetch), and measures steady-state throughput after compile.

Baseline note: the reference publishes NO performance numbers
(``README.md``, SURVEY §6 — ``BASELINE.json.published == {}``).
``vs_baseline`` is therefore anchored to the driver's north-star throughput:
≥20,000 steps × batch 128 in <120 s on a v4-8 ⇒ 21,333 images/sec ÷ 8 chips
= 2,666.7 images/sec/chip. vs_baseline = measured / 2666.7.

Prints ONE JSON line:
  {"metric": "train_throughput", "value": N, "unit": "images/sec/chip",
   "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import numpy as np

NORTH_STAR_IMAGES_PER_SEC_PER_CHIP = 20000 * 128 / 120.0 / 8.0  # 2666.7


def main() -> None:
    import jax

    from dml_cnn_cifar10_tpu.config import reference_config
    from dml_cnn_cifar10_tpu.data import pipeline as pipe
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    cfg = reference_config()
    cfg.data.dataset = "synthetic"           # zero-egress box: CIFAR-layout
    cfg.data.data_dir = "/tmp/bench_cifar"   # synthetic records, real pipeline
    cfg.data.synthetic_train_records = 20480
    cfg.data.synthetic_test_records = 1024
    cfg.batch_size = 128
    cfg.log_dir = "/tmp/bench_logs_unused"
    cfg.checkpoint_every = 10**9             # no checkpoint I/O in the loop

    trainer = Trainer(cfg)
    state = trainer.init_or_restore()
    n_chips = len(jax.devices())

    train_it = pipe.input_pipeline(cfg.data, cfg.batch_size, train=True)
    prefetch = pipe.PrefetchIterator(train_it, depth=cfg.data.prefetch,
                                     place=trainer._placed)

    # Warmup: first call compiles (~20-40s), a few more to fill the pipeline.
    for _ in range(8):
        state, metrics = trainer.train_step(state, *next(prefetch))
    jax.block_until_ready(metrics["loss"])

    # Timed steady state.
    steps = 300
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.train_step(state, *next(prefetch))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0
    prefetch.close()

    images_per_sec = steps * cfg.batch_size / dt
    per_chip = images_per_sec / n_chips
    print(json.dumps({
        "metric": "train_throughput",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            per_chip / NORTH_STAR_IMAGES_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
