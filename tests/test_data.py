"""Data layer unit tests (SURVEY §4: record parsing vs hand-built records)."""

import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import DataConfig
from dml_cnn_cifar10_tpu.data import pipeline as pipe
from dml_cnn_cifar10_tpu.data import records as rec
from dml_cnn_cifar10_tpu.data.download import generate_synthetic_dataset, train_files


def _handmade_record(label: int, seed: int, cfg: DataConfig) -> np.ndarray:
    rng = np.random.default_rng(seed)
    img = rng.integers(0, 256, size=3072, dtype=np.uint8)
    return np.concatenate([[np.uint8(label)], img]).astype(np.uint8)


def test_decode_matches_handbuilt_record():
    """Byte 0 is the label; bytes 1..3072 are CHW, transposed to HWC
    (reference read_cifar_files, cifar10cnn.py:54-66)."""
    cfg = DataConfig()
    recs = np.stack([_handmade_record(7, 1, cfg), _handmade_record(2, 2, cfg)])
    images, labels = rec.decode_records(recs, cfg)
    assert labels.tolist() == [7, 2]
    assert images.shape == (2, 32, 32, 3) and images.dtype == np.float32
    chw = recs[0, 1:].reshape(3, 32, 32)
    np.testing.assert_array_equal(images[0], chw.transpose(1, 2, 0))


def test_center_crop_is_deterministic_center():
    """Parity with resize_image_with_crop_or_pad (cifar10cnn.py:68):
    TF floors the offset: top = (32-24)//2 = 4."""
    x = np.arange(32 * 32, dtype=np.float32).reshape(1, 32, 32, 1)
    x = np.repeat(x, 3, axis=3)
    out = rec.center_crop(x, 24, 24)
    np.testing.assert_array_equal(out[0, :, :, 0], x[0, 4:28, 4:28, 0])


def test_center_crop_pads_when_smaller():
    x = np.ones((1, 16, 16, 3), dtype=np.float32)
    out = rec.center_crop(x, 24, 24)
    assert out.shape == (1, 24, 24, 3)
    assert out[0, 0, 0, 0] == 0.0 and out[0, 12, 12, 0] == 1.0


def test_random_crop_windows_are_valid(rng):
    x = rng.random((8, 32, 32, 3)).astype(np.float32)
    out = rec.random_crop(x, 24, 24, rng)
    assert out.shape == (8, 24, 24, 3)
    # every crop must be an exact subwindow of its source image
    windows = np.lib.stride_tricks.sliding_window_view(x, (24, 24), axis=(1, 2))
    for i in range(8):
        matches = np.isclose(
            windows[i].transpose(0, 1, 3, 4, 2), out[i], atol=0
        ).all(axis=(2, 3, 4))
        assert matches.any()


def test_synthetic_files_have_cifar_layout(data_cfg):
    path = train_files(data_cfg)[0]
    records = rec.read_record_file(path, data_cfg.record_bytes)
    assert records.shape[1] == 3073
    images, labels = rec.decode_records(records, data_cfg)
    assert labels.min() >= 0 and labels.max() < 10
    assert 0 <= images.min() and images.max() <= 255


def test_shuffle_iterator_covers_epoch_and_repeats(data_cfg):
    it = pipe.ShuffleBatchIterator(
        train_files(data_cfg), data_cfg, batch_size=64, train=True, seed=3)
    n = it.n
    seen = 0
    labels_seen = []
    for _ in range(2 * n // 64):
        b = next(it)
        assert b.images.shape == (64, 24, 24, 3)
        assert b.labels.shape == (64,) and b.labels.dtype == np.int32
        labels_seen.append(b.labels)
        seen += 64
    assert seen == 2 * n  # endless stream, no StopIteration


def test_shuffle_iterator_is_seeded_deterministic(data_cfg):
    a = pipe.ShuffleBatchIterator(train_files(data_cfg), data_cfg, 32, seed=5)
    b = pipe.ShuffleBatchIterator(train_files(data_cfg), data_cfg, 32, seed=5)
    for _ in range(3):
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba.images, bb.images)
        np.testing.assert_array_equal(ba.labels, bb.labels)


def test_sharded_iterators_are_disjoint(data_cfg):
    its = [
        pipe.ShuffleBatchIterator(train_files(data_cfg), data_cfg, 16,
                                  seed=1, shard=s, num_shards=2)
        for s in range(2)
    ]
    assert its[0].n + its[1].n == pipe.ShuffleBatchIterator(
        train_files(data_cfg), data_cfg, 16, seed=1).n


def test_full_sweep_visits_every_record_once(data_cfg):
    it = pipe.ShuffleBatchIterator(
        pipe.download.test_files(data_cfg), data_cfg, 48, train=False, seed=0)
    total = sum(b.images.shape[0] for b in it.full_sweep())
    assert total == it.n


def test_full_sweep_padded_fixed_shapes_and_sentinel_labels(data_cfg):
    it = pipe.ShuffleBatchIterator(
        pipe.download.test_files(data_cfg), data_cfg, 48, train=False, seed=0)
    batches = list(it.full_sweep_padded())
    assert len(batches) == it.num_padded_sweep_batches()
    assert all(b.images.shape == (48, 24, 24, 3) for b in batches)
    real = sum(int((b.labels >= 0).sum()) for b in batches)
    assert real == it.total_records
    # pad rows are exactly the (-1)-labeled rows in the last batch
    assert (batches[-1].labels >= 0).sum() == it.n - (len(batches) - 1) * 48


def test_padded_sweep_equal_batch_count_across_shards(data_cfg):
    """All shards must issue the same number of collective eval steps even
    when strided shard sizes differ (lockstep requirement for multi-host)."""
    its = [pipe.ShuffleBatchIterator(
        pipe.download.test_files(data_cfg), data_cfg, 24, train=False,
        seed=0, shard=s, num_shards=3) for s in range(3)]
    counts = {it.num_padded_sweep_batches() for it in its}
    assert len(counts) == 1
    total_real = sum(
        int((b.labels >= 0).sum()) for it in its for b in it.full_sweep_padded())
    assert total_real == its[0].total_records


def test_clone_shares_arrays_but_streams_independently(data_cfg):
    it = pipe.ShuffleBatchIterator(train_files(data_cfg), data_cfg, 16, seed=1)
    c = it.clone(seed=2)
    assert c.images is it.images        # no second decode / copy
    a, b = next(it), next(c)
    assert not np.array_equal(a.labels, b.labels)  # independent shuffles
    assert c.total_records == it.total_records


def test_prefetch_close_with_depth_one_does_not_hang(data_cfg):
    """Regression: close() while the producer is parked on a full depth-1
    queue must terminate the thread, not leak it blocked mid-put."""
    src = pipe.ShuffleBatchIterator(train_files(data_cfg), data_cfg, 16, seed=0)
    pf = pipe.PrefetchIterator(src, depth=1)
    next(pf)          # ensure producer is active and queue refills
    pf.close()
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()


def test_prefetch_iterator_preserves_order_and_propagates(data_cfg):
    src = pipe.ShuffleBatchIterator(train_files(data_cfg), data_cfg, 16, seed=9)
    ref = pipe.ShuffleBatchIterator(train_files(data_cfg), data_cfg, 16, seed=9)
    direct = [next(ref) for _ in range(4)]
    pf = pipe.PrefetchIterator(src, depth=2)
    for want in direct:
        got = next(pf)
        np.testing.assert_array_equal(got.images, want.images)
    pf.close()


def test_cifar100_record_layout(tmp_path):
    cfg = DataConfig(dataset="cifar100", data_dir=str(tmp_path),
                     num_classes=100, synthetic_train_records=64,
                     synthetic_test_records=16, use_native_loader=False)
    generate_synthetic_dataset(cfg)
    from dml_cnn_cifar10_tpu.data.download import train_files as tf100
    records = rec.read_record_file(tf100(cfg)[0], cfg.record_bytes + 1)
    assert records.shape[1] == 3074  # coarse + fine label bytes
    images, labels = rec.decode_records(records, cfg, label_offset=1)
    assert images.shape[1:] == (32, 32, 3)
    assert labels.max() < 100


def test_imagenet_synth_wide_label_roundtrip(tmp_path):
    """imagenet_synth records: 2-byte big-endian label + CHW image at
    configurable geometry. Class ids past 255 must survive the encode →
    decode round trip (a single CIFAR label byte cannot hold them)."""
    from dml_cnn_cifar10_tpu.data import download

    cfg = DataConfig(dataset="imagenet_synth", data_dir=str(tmp_path),
                     image_height=16, image_width=16, crop_height=12,
                     crop_width=12, num_classes=1000,
                     synthetic_train_records=512,
                     synthetic_test_records=64, use_native_loader=False)
    generate_synthetic_dataset(cfg)
    assert download.label_bytes(cfg) == 2 and download.wide_label(cfg)
    records = rec.read_record_file(download.train_files(cfg)[0],
                                   cfg.record_bytes + 1)
    assert records.shape[1] == 2 + 16 * 16 * 3
    images, labels = rec.decode_records(records, cfg, wide_label=True)
    assert images.shape[1:] == (16, 16, 3)
    assert labels.min() >= 0 and labels.max() < 1000
    assert labels.max() > 255  # wide labels actually exercised
    # The full pipeline decodes the same way.
    it = pipe.input_pipeline(cfg, 32, train=True)
    batch = next(it)
    assert batch.images.shape == (32, 12, 12, 3)
    assert 0 <= batch.labels.min() and batch.labels.max() < 1000


# ---- hardened dataset acquisition (data/download.py) ----

def _fake_targz(path, name="cifar-10-batches-bin/marker.txt"):
    import io
    import tarfile
    with tarfile.open(path, "w:gz") as t:
        data = b"payload"
        info = tarfile.TarInfo(name)
        info.size = len(data)
        t.addfile(info, io.BytesIO(data))


def test_download_retries_transient_network_failure(tmp_path, monkeypatch):
    import os

    from dml_cnn_cifar10_tpu.data import download

    calls = {"n": 0}

    def flaky_fetch(url, dest, timeout):
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("connection reset")
        _fake_targz(dest)

    monkeypatch.setattr(download, "_fetch", flaky_fetch)
    out = download.download_and_extract(
        str(tmp_path), "http://example.invalid/fake.tar.gz",
        retries=3, backoff_s=0.0)
    assert calls["n"] == 3 and out == str(tmp_path)
    assert os.path.isfile(os.path.join(
        str(tmp_path), "cifar-10-batches-bin", "marker.txt"))


def test_download_network_exhaustion_is_classified(tmp_path, monkeypatch):
    from dml_cnn_cifar10_tpu.data import download

    def dead_fetch(url, dest, timeout):
        raise OSError("no route to host")

    monkeypatch.setattr(download, "_fetch", dead_fetch)
    with pytest.raises(download.DownloadError) as ei:
        download.download_and_extract(
            str(tmp_path), "http://example.invalid/f.tar.gz",
            retries=2, backoff_s=0.0)
    assert ei.value.fault == "network"


def test_download_integrity_mismatch_deletes_and_classifies(
        tmp_path, monkeypatch):
    """An archive failing its published size/md5 is deleted and
    re-fetched; persistent mismatch exhausts as an integrity fault."""
    import os

    from dml_cnn_cifar10_tpu.data import download

    url = "http://example.invalid/archive.tar.gz"
    monkeypatch.setattr(download, "KNOWN_ARCHIVES",
                        {url: {"bytes": 3, "md5": "0" * 32}})
    fetches = {"n": 0}

    def fake_fetch(u, dest, timeout):
        fetches["n"] += 1
        _fake_targz(dest)

    monkeypatch.setattr(download, "_fetch", fake_fetch)
    with pytest.raises(download.DownloadError) as ei:
        download.download_and_extract(str(tmp_path), url,
                                      retries=2, backoff_s=0.0)
    assert ei.value.fault == "integrity"
    assert fetches["n"] == 2  # deleted + re-fetched each attempt
    assert not os.path.isfile(os.path.join(str(tmp_path),
                                           "archive.tar.gz"))


def test_corrupt_tarball_refetched_then_integrity_fault(tmp_path,
                                                        monkeypatch):
    from dml_cnn_cifar10_tpu.data import download

    def garbage_fetch(url, dest, timeout):
        with open(dest, "wb") as f:
            f.write(b"definitely not a tar.gz")

    monkeypatch.setattr(download, "_fetch", garbage_fetch)
    with pytest.raises(download.DownloadError) as ei:
        download.download_and_extract(
            str(tmp_path), "http://example.invalid/g.tar.gz",
            retries=2, backoff_s=0.0)
    assert ei.value.fault == "integrity"


def test_ensure_dataset_degrades_only_on_classified_failure(
        tmp_path, monkeypatch):
    import os

    from dml_cnn_cifar10_tpu.data import download

    cfg = DataConfig(dataset="cifar10", data_dir=str(tmp_path / "a"),
                     synthetic_train_records=64,
                     synthetic_test_records=16)

    def down(*a, **k):
        raise download.DownloadError("network", "offline box")

    monkeypatch.setattr(download, "download_and_extract", down)
    download.ensure_dataset(cfg)  # degrades to synthetic, classified
    assert all(os.path.isfile(p) for p in download.train_files(cfg))

    cfg2 = DataConfig(dataset="cifar10", data_dir=str(tmp_path / "b"))

    def boom(*a, **k):
        raise RuntimeError("a genuine bug")

    monkeypatch.setattr(download, "download_and_extract", boom)
    with pytest.raises(RuntimeError, match="genuine bug"):
        download.ensure_dataset(cfg2)  # bugs must NOT degrade silently
