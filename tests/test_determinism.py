"""Determinism: two identical runs produce bitwise-identical parameters.

SURVEY §5 "race detection": the reference's async parameter-server updates
are an *intentional* data race (workers apply gradients on stale weights
with no ordering). The SPMD redesign eliminates the race by construction —
one compiled program, deterministic collective order — and this test is
the enforcement: any nondeterminism (unsynced RNG, host-order leakage,
racing prefetch) breaks bitwise equality."""

import jax
import numpy as np

from dml_cnn_cifar10_tpu.train.loop import Trainer
from tests.conftest import tiny_train_cfg
import pytest


def _run(data_cfg, tmpdir, **kw):
    cfg = tiny_train_cfg(data_cfg, tmpdir, total_steps=20, **kw)
    result = Trainer(cfg).fit()
    return jax.device_get(result.state.params)


@pytest.mark.slow
def test_same_seed_bitwise_identical(data_cfg, tmp_path):
    a = _run(data_cfg, str(tmp_path / "a"))
    b = _run(data_cfg, str(tmp_path / "b"))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_same_seed_bitwise_identical_chunked(data_cfg, tmp_path):
    """The chunked path (background raw-chunk prefetch + device decode) is
    equally deterministic — the prefetch thread changes timing, never
    order."""
    a = _run(data_cfg, str(tmp_path / "a"), steps_per_dispatch=10)
    b = _run(data_cfg, str(tmp_path / "b"), steps_per_dispatch=10)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_different_seed_differs(data_cfg, tmp_path):
    a = _run(data_cfg, str(tmp_path / "a"))
    b = _run(data_cfg, str(tmp_path / "b"), seed=1)
    assert any((np.asarray(x) != np.asarray(y)).any()
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))
