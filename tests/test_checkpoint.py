"""Checkpoint save → restore → bit-identical resume (SURVEY §4)."""

import pytest
import os

import jax
import jax.numpy as jnp
import numpy as np

from dml_cnn_cifar10_tpu import ckpt as ckpt_lib
from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig, OptimConfig
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import step as step_lib


def _state(seed=0):
    return step_lib.init_train_state(
        jax.random.key(seed), get_model("cnn"), ModelConfig(), DataConfig(),
        OptimConfig())


def test_save_restore_roundtrip_bit_identical(tmp_path):
    state = _state()
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=7)
    other = _state(seed=99)  # different values, same structure
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), other)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_without_checkpoint_returns_target(tmp_path):
    state = _state()
    restored = ckpt_lib.restore_checkpoint(str(tmp_path / "empty"), state)
    assert restored is state


def test_latest_and_retention(tmp_path):
    state = _state()
    for s in [1, 2, 3, 4, 5]:
        ckpt_lib.save_checkpoint(str(tmp_path), state, step=s, keep=3)
    assert sorted(ckpt_lib.all_checkpoint_steps(str(tmp_path))) == [3, 4, 5]
    assert ckpt_lib.latest_checkpoint(str(tmp_path)).endswith("ckpt_5.msgpack")
    with open(os.path.join(str(tmp_path), "checkpoint")) as f:
        assert f.read().strip() == "ckpt_5.msgpack"


def test_atomic_write_leaves_no_tmp(tmp_path):
    ckpt_lib.save_checkpoint(str(tmp_path), _state(), step=1)
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))


# ---- integrity sidecars + newest-verifiable fallback restore ----

def test_checksum_sidecar_committed_and_verifies(tmp_path):
    path = ckpt_lib.save_checkpoint(str(tmp_path), _state(), step=7)
    assert os.path.isfile(path + ".sha256")
    ok, reason = ckpt_lib.verify_checkpoint(path)
    assert ok and reason == "verified"


def test_truncated_latest_falls_back_to_older(tmp_path):
    s1, s2 = _state(seed=1), _state(seed=2)
    ckpt_lib.save_checkpoint(str(tmp_path), s1, step=1)
    p2 = ckpt_lib.save_checkpoint(str(tmp_path), s2, step=2)
    with open(p2, "r+b") as f:
        f.truncate(os.path.getsize(p2) // 2)
    notes = []
    restored = ckpt_lib.restore_checkpoint(
        str(tmp_path), _state(seed=9),
        on_fallback=lambda step, path, why, walk_ms: notes.append(
            (step, why)))
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert notes and notes[0][0] == 2 and "mismatch" in notes[0][1]


def test_same_size_bitflip_detected_and_skipped(tmp_path):
    s1 = _state(seed=1)
    ckpt_lib.save_checkpoint(str(tmp_path), s1, step=1)
    p2 = ckpt_lib.save_checkpoint(str(tmp_path), _state(seed=2), step=2)
    size = os.path.getsize(p2)
    with open(p2, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    ok, reason = ckpt_lib.verify_checkpoint(p2)
    assert not ok and "mismatch" in reason
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=9))
    np.testing.assert_array_equal(
        np.asarray(restored.params["conv1"]["kernel"]),
        np.asarray(s1.params["conv1"]["kernel"]))


def test_missing_sidecar_is_back_compat(tmp_path):
    """Pre-integrity checkpoints (no .sha256) still restore; a corrupt
    one without a sidecar is caught by the decode and walked past."""
    s1, s2 = _state(seed=1), _state(seed=2)
    ckpt_lib.save_checkpoint(str(tmp_path), s1, step=1)
    p2 = ckpt_lib.save_checkpoint(str(tmp_path), s2, step=2)
    os.remove(p2 + ".sha256")
    ok, reason = ckpt_lib.verify_checkpoint(p2)
    assert ok and "no checksum sidecar" in reason
    # Still restores the (intact) latest.
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=9))
    np.testing.assert_array_equal(
        np.asarray(restored.params["conv1"]["kernel"]),
        np.asarray(s2.params["conv1"]["kernel"]))
    # Truncate it: no sidecar to catch it, but the msgpack decode fails
    # and the walk still falls back to step 1.
    with open(p2, "r+b") as f:
        f.truncate(os.path.getsize(p2) // 2)
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=9))
    np.testing.assert_array_equal(
        np.asarray(restored.params["conv1"]["kernel"]),
        np.asarray(s1.params["conv1"]["kernel"]))


def test_all_candidates_corrupt_raises(tmp_path):
    path = ckpt_lib.save_checkpoint(str(tmp_path), _state(), step=1)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(ValueError, match="integrity"):
        ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=2))


def test_sharded_member_corruption_falls_back(tmp_path):
    """Directory codec: a damaged manifest-listed shard file fails the
    sidecar (stale EXTRA files stay inert — that contract is pinned by
    test_sharded_stale_shard_files_are_inert) and restore walks back."""
    s1 = _state(seed=1)
    ckpt_lib.save_checkpoint(str(tmp_path), s1, step=1)
    p2 = ckpt_lib.save_checkpoint(str(tmp_path), _state(seed=2), step=2,
                                  fmt="sharded", shard_io_threads=1)
    shard = os.path.join(p2, "shard_0.msgpack")
    with open(shard, "r+b") as f:
        f.truncate(os.path.getsize(shard) // 2)
    ok, reason = ckpt_lib.verify_checkpoint(p2)
    assert not ok
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=9))
    np.testing.assert_array_equal(
        np.asarray(restored.params["conv1"]["kernel"]),
        np.asarray(s1.params["conv1"]["kernel"]))


def test_prune_failure_logged_not_swallowed(tmp_path, monkeypatch):
    """Retention prune hitting an OSError must emit a ckpt_prune_error
    event (and keep going) instead of silently accumulating."""
    events = []

    class FakeLogger:
        def log(self, kind, **fields):
            events.append((kind, fields))

    real_remove = os.remove

    def failing_remove(p):
        if p.endswith(".msgpack"):
            raise OSError("disk on fire")
        real_remove(p)

    mgr = ckpt_lib.CheckpointManager(str(tmp_path), every_steps=1,
                                     keep=1, logger=FakeLogger())
    state = _state()
    mgr.maybe_save(state, 1)
    monkeypatch.setattr(os, "remove", failing_remove)
    mgr.maybe_save(state, 2)
    kinds = [k for k, _ in events]
    assert "ckpt_prune_error" in kinds
    rec = dict(events[kinds.index("ckpt_prune_error")][1])
    assert rec["step"] == 1 and "disk on fire" in rec["error"]


def test_resume_continues_training_identically(tmp_path):
    """Train 4 steps straight vs train 2 + checkpoint + restore + 2 more:
    identical parameters (the MTS restart contract, cifar10cnn.py:222)."""
    model_def = get_model("cnn")
    mc, dc, oc = ModelConfig(), DataConfig(), OptimConfig()
    step_fn = step_lib.make_train_step(model_def, mc, oc, mesh=None)
    rng = np.random.default_rng(0)
    batches = [(jnp.asarray(rng.normal(127, 50, (8, 24, 24, 3)),
                            dtype=jnp.float32),
                jnp.asarray(rng.integers(0, 10, 8), dtype=jnp.int32))
               for _ in range(4)]

    s_straight = _state()
    for im, lb in batches:
        s_straight, _ = step_fn(s_straight, im, lb)

    s_ab = _state()
    for im, lb in batches[:2]:
        s_ab, _ = step_fn(s_ab, im, lb)
    ckpt_lib.save_checkpoint(str(tmp_path), s_ab, step=2)
    s_restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=5))
    assert int(jax.device_get(s_restored.step)) == 2
    for im, lb in batches[2:]:
        s_restored, _ = step_fn(s_restored, im, lb)

    for a, b in zip(jax.tree.leaves(s_straight.params),
                    jax.tree.leaves(s_restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_manager_matches_sync(tmp_path):
    """Async saves produce the same files/retention as sync, stay ordered,
    and flush() drains the writer."""
    import jax
    import jax.numpy as jnp

    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ck

    state = {"w": jnp.arange(8.0), "step": jnp.asarray(3)}
    sync_dir, async_dir = str(tmp_path / "s"), str(tmp_path / "a")
    ms = ck.CheckpointManager(sync_dir, every_steps=1, keep=2)
    ma = ck.CheckpointManager(async_dir, every_steps=1, keep=2,
                              async_save=True)
    for step in (1, 2, 3):
        st = {"w": state["w"] + step, "step": jnp.asarray(step)}
        assert ms.maybe_save(st, step)
        assert ma.maybe_save(st, step)
    ma.close()  # drains (flush) + stops the writer thread

    assert sorted(ck.all_checkpoint_steps(sync_dir)) == [2, 3]  # keep=2
    assert sorted(ck.all_checkpoint_steps(async_dir)) == [2, 3]
    ref = ck.restore_checkpoint(sync_dir, state)
    got = ck.restore_checkpoint(async_dir, state)
    assert jax.numpy.array_equal(ref["w"], got["w"])
    assert int(got["step"]) == 3


def test_async_writer_error_surfaces(tmp_path):
    """A failing background write raises at the next flush/maybe_save."""
    import jax.numpy as jnp

    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ck

    target = tmp_path / "file_not_dir"
    target.write_text("x")  # makedirs inside the writer will fail
    ma = ck.CheckpointManager(str(target / "sub"), every_steps=1,
                              async_save=True)
    assert ma.maybe_save({"w": jnp.zeros(2)}, 1)
    with pytest.raises(Exception):
        ma.flush()
    ma.close()


@pytest.mark.slow
def test_trainer_async_checkpoint(data_cfg, tmp_path):
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ck
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20)
    cfg.async_checkpoint = True
    result = Trainer(cfg).fit()
    assert result.final_step == 20
    assert ck.all_checkpoint_steps(cfg.log_dir)  # final save landed


@pytest.mark.slow
def test_adamw_state_roundtrips(tmp_path, data_cfg):
    """AdamW moments (mu/nu) survive save -> restore -> resume."""
    import dataclasses

    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=10)
    cfg.optim = dataclasses.replace(cfg.optim, optimizer="adamw",
                                    learning_rate=1e-3)
    r1 = Trainer(cfg).fit()
    assert r1.final_step == 10

    cfg2 = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20)
    cfg2.optim = dataclasses.replace(cfg2.optim, optimizer="adamw",
                                     learning_rate=1e-3)
    t2 = Trainer(cfg2)
    state = t2.init_or_restore()
    assert int(np.asarray(state.step)) == 10
    # Restored moments are the trained ones, not zeros.
    assert any(np.abs(np.asarray(x)).max() > 0
               for x in jax.tree.leaves(state.opt["mu"]))
    r2 = t2.fit(state=state)
    assert r2.final_step == 20


@pytest.mark.slow
def test_time_based_cadence(tmp_path, data_cfg):
    """MTS parity: the wall-clock trigger (save_checkpoint_secs analog)
    saves at steps the step cadence would skip, and the clock resets on
    every save."""
    import time

    from dml_cnn_cifar10_tpu.parallel import step as step_lib
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    mgr = ckpt_lib.CheckpointManager(str(tmp_path / "m"),
                                     every_steps=10**9, every_secs=0.05)
    assert not mgr.time_due()
    time.sleep(0.06)
    assert mgr.time_due()
    cfg0 = tiny_train_cfg(data_cfg, str(tmp_path / "m"), total_steps=2)
    st = Trainer(cfg0).init_or_restore()
    assert mgr.maybe_save(st, step=1, force=True)
    assert not mgr.time_due()  # clock reset by the save

    # In the driver: step cadence never fires (every = total), but the
    # elapsed clock writes intermediate checkpoints anyway.
    cfg = tiny_train_cfg(data_cfg, str(tmp_path / "t"), total_steps=8)
    cfg.checkpoint_every = 8
    cfg.checkpoint_every_secs = 1e-3
    Trainer(cfg).fit()
    steps = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
    assert 8 in steps          # final save
    assert any(s < 8 for s in steps)  # a clock-triggered one landed early


@pytest.mark.slow
def test_orbax_format_roundtrip_and_mixed_retention(tmp_path, data_cfg):
    """The orbax directory codec: save/restore round-trip through the
    Trainer, auto-detected restore, and retention that prunes across
    BOTH formats (a run can switch codecs mid-flight)."""
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=4)
    cfg.checkpoint_every = 2
    cfg.ckpt_format = "orbax"
    r1 = Trainer(cfg).fit()
    assert r1.final_step == 4
    assert os.path.isdir(os.path.join(cfg.log_dir, "ckpt_4.orbax"))

    # Resume from the orbax checkpoint with the msgpack codec configured:
    # restore auto-detects, new saves use the new codec, retention spans
    # both.
    cfg2 = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=8)
    cfg2.checkpoint_every = 2
    cfg2.keep_checkpoints = 2
    t2 = Trainer(cfg2)
    state = t2.init_or_restore()
    assert int(np.asarray(state.step)) == 4
    r2 = t2.fit(state=state)
    assert r2.final_step == 8
    steps = sorted(ckpt_lib.all_checkpoint_steps(cfg2.log_dir))
    assert steps == [6, 8]          # orbax 2/4 pruned by retention
    assert os.path.isfile(os.path.join(cfg2.log_dir, "ckpt_8.msgpack"))


@pytest.mark.slow
def test_mismatched_config_restore_error(tmp_path, data_cfg):
    """Restoring with a different model/optimizer names the likely cause
    instead of a bare flax pytree traceback."""

    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=2)
    cfg.checkpoint_every = 2
    Trainer(cfg).fit()

    cfg2 = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=4)
    cfg2.model.name = "resnet18"
    with pytest.raises(ValueError, match="different config"):
        Trainer(cfg2).init_or_restore()


@pytest.mark.slow
def test_sharded_roundtrip_fsdp(tmp_path, rng):
    """Sharded codec on the 8-device fsdp mesh: the single process owns
    every shard, the file set is shard_0 + MANIFEST, and restore
    reassembles bit-identical global arrays that keep training."""
    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            OptimConfig, ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    data = DataConfig(normalize="scale")
    cfg = ModelConfig(logit_relu=False)
    optim = OptimConfig(momentum=0.9)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("cnn")
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, data, optim,
                                        fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, data, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    state, _ = train(state, im, lb)

    path = ckpt_lib.save_checkpoint(str(tmp_path), state, step=1,
                                fmt="sharded", shard_io_threads=1)
    # threads=1 keeps the legacy single-data-file layout; every data
    # file now carries a per-shard sha256 sidecar and the per-process
    # file index the manifest's shard_files is gathered from.
    assert sorted(os.listdir(path)) == [
        "MANIFEST.json", "shard_0.files.json", "shard_0.msgpack",
        "shard_0.msgpack.sha256"]
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == path

    fresh = step_lib.init_train_state(
        jax.random.key(7), model_def, cfg, data, optim, mesh,
        state_sharding=sh)
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), fresh, sharding=sh)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))
    restored, metrics = train(restored, im, lb)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


@pytest.mark.slow
def test_sharded_elastic_restore_to_plain_mesh(tmp_path, rng):
    """Sharded checkpoints are placement-free: written from an fsdp
    layout, restored onto a REPLICATED mesh (different sharding) with
    identical values — the elastic contract."""
    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            OptimConfig, ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    data = DataConfig(normalize="scale")
    cfg = ModelConfig(logit_relu=False)
    optim = OptimConfig()
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("cnn")
    fsdp_sh = step_lib.train_state_shardings(mesh, model_def, cfg, data,
                                             optim, fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(3), model_def, cfg, data, optim, mesh,
        state_sharding=fsdp_sh)
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=2, fmt="sharded")

    repl = mesh_lib.replicated(mesh)
    fresh = step_lib.init_train_state(
        jax.random.key(9), model_def, cfg, data, optim, mesh)
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), fresh, sharding=repl)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))


def test_sharded_manager_cadence_and_retention(tmp_path, rng):
    """CheckpointManager with fmt='sharded': due-cadence respected,
    sidecar written after the manifest commit, retention prunes whole
    .sharded directories."""
    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            OptimConfig, ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    data = DataConfig(normalize="scale")
    cfg = ModelConfig(logit_relu=False)
    optim = OptimConfig()
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("cnn")
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, data, optim, mesh)

    mgr = ckpt_lib.CheckpointManager(str(tmp_path), every_steps=2, keep=2,
                                 fmt="sharded")
    for step in (1, 2, 3, 4, 6):
        saved = mgr.maybe_save(state, step,
                               data_state={"train": step, "acc": 0,
                                           "test": 0})
        assert saved == (step % 2 == 0)
    steps = sorted(ckpt_lib.all_checkpoint_steps(str(tmp_path)))
    assert steps == [4, 6]  # keep=2 pruned the step-2 dir
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           "ckpt_2.sharded"))
    assert ckpt_lib.load_data_state(str(tmp_path), 6) == {"train": 6,
                                                      "acc": 0, "test": 0}


def test_sharded_partial_save_is_invisible(tmp_path):
    """Crash-consistency: a ckpt_<step>.sharded dir WITHOUT its
    MANIFEST.json (SIGKILL mid-save) must be invisible to
    latest_checkpoint/restore — the previous committed checkpoint wins."""
    state = _state()
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=5)  # msgpack, committed
    partial = os.path.join(str(tmp_path), "ckpt_9.sharded")
    os.makedirs(partial)
    with open(os.path.join(partial, "shard_0.msgpack"), "wb") as f:
        f.write(b"not a complete save")
    assert ckpt_lib.all_checkpoint_steps(str(tmp_path)) == [5]
    assert ckpt_lib.latest_checkpoint(str(tmp_path)).endswith(
        "ckpt_5.msgpack")
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=3))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.params["conv1"]["kernel"])),
        np.asarray(jax.device_get(state.params["conv1"]["kernel"])))


def test_sharded_config_mismatch_raises(tmp_path):
    """A sharded checkpoint carrying leaves the resume config lacks
    (momentum buffers here) must fail loudly — the same contract the
    msgpack path enforces via from_bytes."""
    mom_state = step_lib.init_train_state(
        jax.random.key(0), get_model("cnn"), ModelConfig(), DataConfig(),
        OptimConfig(momentum=0.9))
    ckpt_lib.save_checkpoint(str(tmp_path), mom_state, step=1,
                             fmt="sharded")
    with pytest.raises(ValueError, match="different"):
        ckpt_lib.restore_checkpoint(str(tmp_path), _state())


def test_sharded_stale_shard_files_are_inert(tmp_path):
    """ADVICE r2 (medium): a crashed save at a larger process count can
    leave extra shard_*.msgpack next to a later, validly committed save.
    The manifest records the exact shard-file list, so restore must
    ignore the stale file instead of failing the count check."""
    state = _state()
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=3, fmt="sharded")
    ckpt_dir = os.path.join(str(tmp_path), "ckpt_3.sharded")
    # A leftover from a hypothetical crashed 2-process attempt.
    with open(os.path.join(ckpt_dir, "shard_1.msgpack"), "wb") as f:
        f.write(b"stale garbage from a crashed larger-cluster save")
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=9))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_overlapping_entries_raise(tmp_path):
    """ADVICE r2: duplicated shard entries must not mask holes — coverage
    is a boolean mask, and overlap is as fatal as shortfall."""
    from flax import serialization

    from dml_cnn_cifar10_tpu.ckpt import sharded as sharded_lib

    state = _state()
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=1, fmt="sharded",
                             shard_io_threads=1)
    ckpt_dir = os.path.join(str(tmp_path), "ckpt_1.sharded")
    shard_file = os.path.join(ckpt_dir, "shard_0.msgpack")
    with open(shard_file, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    # Duplicate the first leaf's first entry: same index range twice.
    path0 = sorted(payload)[0]
    entries = payload[path0]
    entries = (list(entries.values()) if isinstance(entries, dict)
               else list(entries))
    payload[path0] = entries + [entries[0]]
    with open(shard_file, "wb") as f:
        f.write(serialization.msgpack_serialize(payload))
    # Drop the per-shard sidecar: hand-merged files come without one
    # (legacy pass-through), and this test pins the coverage mask, not
    # the integrity layer (tests/test_sharded_io.py pins that).
    os.remove(shard_file + ".sha256")
    with pytest.raises(ValueError, match="overlap"):
        sharded_lib.restore_sharded(ckpt_dir, _state(seed=4))


def test_sharded_manifest_missing_listed_file_raises(tmp_path):
    """The inverse of stale-file tolerance: a manifest-listed shard file
    that vanished (partial copy between filesystems) must fail loudly."""
    from dml_cnn_cifar10_tpu.ckpt import sharded as sharded_lib

    state = _state()
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=2, fmt="sharded",
                             shard_io_threads=1)
    ckpt_dir = os.path.join(str(tmp_path), "ckpt_2.sharded")
    os.remove(os.path.join(ckpt_dir, "shard_0.msgpack"))
    with pytest.raises(ValueError, match="missing manifest-listed"):
        sharded_lib.restore_sharded(ckpt_dir, _state(seed=4))
