"""SPMD step tests on the 8-virtual-device CPU mesh (SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)  # noqa: F401
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib


def _batch(rng, n=32):
    images = rng.normal(127, 50, (n, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


@pytest.fixture(scope="module")
def setup():
    model_def = get_model("cnn")
    model_cfg, data_cfg, optim_cfg = ModelConfig(), DataConfig(), OptimConfig()
    state = step_lib.init_train_state(jax.random.key(0), model_def, model_cfg,
                                      data_cfg, optim_cfg)
    return model_def, model_cfg, data_cfg, optim_cfg, state


def test_eight_devices_available():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"


def test_mesh_shapes():
    mesh = mesh_lib.build_mesh(ParallelConfig())
    assert mesh.shape == {"data": 8, "model": 1, "seq": 1, "pipe": 1}
    mesh2 = mesh_lib.build_mesh(ParallelConfig(model_axis=2))
    assert mesh2.shape == {"data": 4, "model": 2, "seq": 1, "pipe": 1}
    with pytest.raises(ValueError):
        mesh_lib.build_mesh(ParallelConfig(data_axis=3, model_axis=3))


@pytest.mark.slow
def test_sharded_step_matches_single_device(setup):
    """Sync data parallelism is semantics-preserving: the sharded global
    batch produces the same update as one device computing the full batch."""
    model_def, model_cfg, data_cfg, optim_cfg, state = setup
    rng = np.random.default_rng(0)
    images, labels = _batch(rng)

    single = step_lib.make_train_step(model_def, model_cfg, optim_cfg,
                                      mesh=None)
    s1, m1 = single(jax.tree.map(jnp.copy, state), jnp.asarray(images),
                    jnp.asarray(labels))

    mesh = mesh_lib.build_mesh(ParallelConfig())
    sharded = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh)
    st = jax.device_put(jax.tree.map(jnp.copy, state),
                        mesh_lib.replicated(mesh))
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    s2, m2 = sharded(st, im, lb)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_explicit_collectives_match_auto_sharding(setup):
    """shard_map + lax.pmean == jit auto-partitioning (same math, explicit
    vs compiler-inserted collectives)."""
    model_def, model_cfg, data_cfg, optim_cfg, state = setup
    rng = np.random.default_rng(1)
    images, labels = _batch(rng)
    mesh = mesh_lib.build_mesh(ParallelConfig())

    auto = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh,
                                    explicit_collectives=False)
    expl = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh,
                                    explicit_collectives=True)
    repl = mesh_lib.replicated(mesh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    sa, ma = auto(jax.device_put(jax.tree.map(jnp.copy, state), repl), im, lb)
    se, me = expl(jax.device_put(jax.tree.map(jnp.copy, state), repl), im, lb)

    np.testing.assert_allclose(float(ma["loss"]), float(me["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(ma["accuracy"]), float(me["accuracy"]))
    for a, b in zip(jax.tree.leaves(sa.params), jax.tree.leaves(se.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_loss_decreases_on_separable_data(setup):
    """Integration (SURVEY §4): a short run must learn the synthetic
    class-separable data."""
    model_def, _, data_cfg, _, _ = setup
    # The faithful reference hyperparameters (LR 0.1 on raw 0..255 pixels,
    # ReLU'd logits) are numerically violent — a property of the reference,
    # not the framework. The learning test uses fixed-mode settings.
    model_cfg = ModelConfig(logit_relu=False)
    optim_cfg = OptimConfig(learning_rate=0.05)
    state = step_lib.init_train_state(jax.random.key(0), model_def, model_cfg,
                                      data_cfg, optim_cfg)
    mesh = mesh_lib.build_mesh(ParallelConfig())
    train = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh)
    state = jax.device_put(state, mesh_lib.replicated(mesh))

    rng = np.random.default_rng(2)
    means = rng.integers(30, 226, size=(10, 3)).astype(np.float32)
    def batch():
        labels = rng.integers(0, 10, 32).astype(np.int32)
        base = means[labels][:, None, None, :]
        images = (base + rng.normal(0, 40, (32, 24, 24, 3))).astype(np.float32)
        images = np.clip(images, 0, 255) / 255.0
        return mesh_lib.shard_batch(mesh, images.astype(np.float32), labels)

    losses = []
    for _ in range(40):
        state, metrics = train(state, *batch())
        losses.append(float(metrics["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8
    assert float(metrics["accuracy"]) > 0.2  # well above 10% chance


def test_step_counter_increments(setup):
    model_def, model_cfg, data_cfg, optim_cfg, state = setup
    mesh = mesh_lib.build_mesh(ParallelConfig())
    train = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh)
    state = jax.device_put(jax.tree.map(jnp.copy, state),
                           mesh_lib.replicated(mesh))
    rng = np.random.default_rng(3)
    images, labels = _batch(rng)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    assert int(jax.device_get(state.step)) == 0
    state, _ = train(state, im, lb)
    assert int(jax.device_get(state.step)) == 1


def test_tensor_parallel_mesh_compiles(setup):
    """data=4 x model=2 mesh: the dp step still compiles/runs with a
    nontrivial model axis present (model axis unused by the CNN)."""
    model_def, model_cfg, data_cfg, optim_cfg, state = setup
    mesh = mesh_lib.build_mesh(ParallelConfig(model_axis=2))
    train = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh)
    state = jax.device_put(jax.tree.map(jnp.copy, state),
                           mesh_lib.replicated(mesh))
    rng = np.random.default_rng(4)
    im, lb = mesh_lib.shard_batch(mesh, *_batch(rng))
    state, metrics = train(state, im, lb)
    assert np.isfinite(float(metrics["loss"]))
