"""ViT-Tiny + attention-op tests: geometry, param counts, flash-kernel
numerical parity with the fused XLA path (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig, OptimConfig
from dml_cnn_cifar10_tpu.models import vit
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.ops import flash_attention as fa
from dml_cnn_cifar10_tpu.parallel import step as step_lib


def _vit_cfgs():
    # use_pallas_attention stays True: dispatch must still route the 37-token
    # ViT sequence to the XLA path (short-seq cutoff).
    return (ModelConfig(name="vit_tiny", logit_relu=False),
            DataConfig())


@pytest.mark.slow
def test_vit_shapes_and_param_count():
    cfg, data = _vit_cfgs()
    params = vit.init_params(jax.random.key(0), cfg, data)
    rng = np.random.default_rng(0)
    images = rng.normal(0, 1, (8, 24, 24, 3)).astype(np.float32)
    logits = vit.apply(params, jnp.asarray(images), cfg)
    assert logits.shape == (8, 10)
    # ViT-Ti geometry: 12 blocks x (4*192*192*3 qkv+proj + 8*192*192 mlp)
    # ~= 5.3M + embeddings; well under 6M
    n = vit.param_count(params)
    assert 5_200_000 < n < 6_000_000, n
    # stacked block leaves carry the depth axis
    assert params["blocks"]["qkv"]["kernel"].shape == (12, 192, 3 * 192)


def test_vit_rejects_indivisible_patch():
    cfg, data = _vit_cfgs()
    cfg.patch_size = 5
    with pytest.raises(ValueError):
        vit.init_params(jax.random.key(0), cfg, data)


@pytest.mark.slow
def test_vit_train_step_runs():
    model_def = get_model("vit_tiny")
    cfg, data = _vit_cfgs()
    optim = OptimConfig(learning_rate=0.01)
    st = step_lib.init_train_state(jax.random.key(0), model_def, cfg, data,
                                   optim)
    train = step_lib.make_train_step(model_def, cfg, optim)
    rng = np.random.default_rng(1)
    images = rng.normal(0, 1, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    st, metrics = train(st, jnp.asarray(images), jnp.asarray(labels))
    assert np.isfinite(float(metrics["loss"]))
    assert int(st.step) == 1


@pytest.mark.parametrize("s,d,h", [(128, 64, 2), (200, 64, 3), (384, 32, 1)])
@pytest.mark.slow
def test_flash_matches_xla(s, d, h):
    """Online-softmax kernel == fused XLA attention, including non-multiple
    -of-block sequence lengths (padding + in-kernel masking)."""
    rng = np.random.default_rng(s)
    shape = (2, s, h, d)
    q = rng.normal(0, 1, shape).astype(np.float32)
    k = rng.normal(0, 1, shape).astype(np.float32)
    v = rng.normal(0, 1, shape).astype(np.float32)
    ref = attn.xla_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    out = fa.flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_flash_mixed_block_sizes():
    """block_q != block_k with S not a multiple of either: padding must
    cover BOTH grids (lcm), or trailing keys silently vanish."""
    rng = np.random.default_rng(9)
    shape = (1, 96, 1, 32)
    q = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    out = fa.flash_attention(q, k, v, block_q=128, block_k=64,
                             interpret=True)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_xla_attention_dead_rows_emit_zeros():
    """A row with NO live key (here: disjoint q/kv segment ids) must emit
    exact zeros — matching the flash kernels' _safe_l behavior — not a
    uniform average of V (round-3 advisor finding)."""
    rng = np.random.default_rng(11)
    shape = (1, 8, 1, 16)
    q = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    # Rows 0-3 live in segment 0; keys all live in segment 1 → rows 0-3
    # are fully masked. Rows 4-7 share segment 1 and stay live.
    q_seg = jnp.asarray([[0, 0, 0, 0, 1, 1, 1, 1]], jnp.int32)
    kv_seg = jnp.ones((1, 8), jnp.int32)
    out = attn.xla_attention(q, k, v, segment_ids=(q_seg, kv_seg))
    np.testing.assert_array_equal(np.asarray(out[0, :4]), 0.0)
    assert np.abs(np.asarray(out[0, 4:])).max() > 0


def test_flash_dead_rows_match_xla_zeros():
    """Same dead-row geometry through the flash kernel: segment-masked
    dead rows must ALSO emit zeros (and a large lse so the backward can't
    leak gradient through them) — the cross-engine contract."""
    rng = np.random.default_rng(12)
    s = 256
    shape = (1, s, 1, 32)
    q = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    # First half of queries in segment 0, ALL keys in segment 1:
    # rows 0..127 have no live key anywhere.
    seg_q = jnp.asarray(np.repeat([0, 1], s // 2)[None], jnp.int32)
    seg_kv = jnp.ones((1, s), jnp.int32)
    out, lse = fa.flash_attention_fwd_lse(q, k, v,
                                          segment_ids=(seg_q, seg_kv),
                                          interpret=True)
    np.testing.assert_array_equal(np.asarray(out[0, :s // 2]), 0.0)
    assert np.abs(np.asarray(out[0, s // 2:])).max() > 0
    assert np.asarray(lse[0, :s // 2]).min() >= 1e29
    ref = attn.xla_attention(q, k, v, segment_ids=(seg_q, seg_kv))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_extreme_logits_stable():
    """Large score magnitudes: the running-max rescale must not overflow."""
    rng = np.random.default_rng(7)
    shape = (1, 256, 1, 64)
    q = (50 * rng.normal(0, 1, shape)).astype(np.float32)
    k = (50 * rng.normal(0, 1, shape)).astype(np.float32)
    v = rng.normal(0, 1, shape).astype(np.float32)
    out = fa.flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             interpret=True)
    ref = attn.xla_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


def test_flash_bfloat16_io():
    rng = np.random.default_rng(3)
    shape = (2, 160, 2, 64)
    q = jnp.asarray(rng.normal(0, 1, shape), jnp.bfloat16)
    k = jnp.asarray(rng.normal(0, 1, shape), jnp.bfloat16)
    v = jnp.asarray(rng.normal(0, 1, shape), jnp.bfloat16)
    out = fa.flash_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=0.05,
                               atol=0.05)


def test_dispatch_routes_by_length():
    rng = np.random.default_rng(4)
    short = jnp.asarray(rng.normal(0, 1, (1, 37, 3, 64)), jnp.float32)
    # short path == xla path bitwise (dispatch must not pad/alter)
    np.testing.assert_array_equal(
        np.asarray(attn.dispatch_attention(short, short, short,
                                           use_pallas=True)),
        np.asarray(attn.xla_attention(short, short, short)))
