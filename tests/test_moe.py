"""Expert parallelism: Switch top-1 / GShard top-2 MoE (ops/moe.py) + vit_moe.

Op-level: routing/capacity/aux-loss semantics against a hand-computed
dense-per-expert reference. Step-level: ep (experts over ``model``) matches
the dp-only run; expert shards are real.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.ops import moe
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import shardings
from dml_cnn_cifar10_tpu.parallel import step as step_lib

DATA = DataConfig(normalize="scale")
VIT_MOE = ModelConfig(name="vit_moe", pool="mean", logit_relu=False,
                      vit_depth=2, vit_dim=64, vit_heads=2, patch_size=8,
                      moe_experts=4)


def _moe_params(dim=8, hidden=16, e=4):
    return moe.init_moe_params(jax.random.key(0), dim, hidden, e)


def _dense_expert(params, e_idx, x):
    h = jax.nn.gelu(x @ params["w1"][e_idx] + params["b1"][e_idx])
    return h @ params["w2"][e_idx] + params["b2"][e_idx]


@pytest.mark.slow
def test_moe_routes_to_argmax_expert():
    """Ample capacity: each token's output == its argmax expert's MLP
    scaled by the router prob."""
    params = _moe_params()
    x = jax.random.normal(jax.random.key(1), (2, 3, 8))
    y, stats = moe.moe_mlp(x, params, capacity_factor=4.0)  # capacity >= T
    tokens = x.reshape(-1, 8)
    probs = jax.nn.softmax(
        tokens @ params["gate"]["kernel"], axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    expect = jnp.stack([
        probs[t, idx[t]] * _dense_expert(params, idx[t], tokens[t])
        for t in range(tokens.shape[0])])
    np.testing.assert_allclose(np.asarray(y.reshape(-1, 8)),
                               np.asarray(expect), rtol=1e-5, atol=1e-6)
    assert float(stats["aux_loss"]) > 0
    assert float(stats["dropped_frac"]) == 0.0  # ample capacity


@pytest.mark.slow
def test_moe_capacity_drops_overflow():
    """Capacity 1 with all tokens routed to one expert: only the first
    token gets expert output, the rest emit exactly zero."""
    params = _moe_params()
    # Huge gate bias towards expert 0 via inputs aligned to gate column 0.
    g = np.zeros((8, 4), np.float32)
    g[:, 0] = 10.0
    params = dict(params)
    params["gate"] = {"kernel": jnp.asarray(g)}
    x = jnp.ones((1, 4, 8))
    y, _ = moe.moe_mlp(x, params, capacity_factor=0.25)  # capacity = 1
    out = np.asarray(y.reshape(4, 8))
    assert np.abs(out[0]).sum() > 0
    np.testing.assert_array_equal(out[1:], 0.0)


def test_moe_aux_loss_balanced_vs_collapsed():
    """Aux loss is minimal (≈1) under uniform routing, larger when the
    router collapses onto one expert."""
    params = _moe_params()
    t, e = 64, 4
    # positive inputs so the +10 gate column dominates every token's logits
    x = 0.5 + 0.1 * jnp.abs(jax.random.normal(jax.random.key(2), (1, t, 8)))
    _, stats_learned = moe.moe_mlp(x, params, 1.25)
    collapsed = dict(params)
    g = np.zeros((8, e), np.float32)
    g[:, 0] = 10.0
    collapsed["gate"] = {"kernel": jnp.asarray(g)}
    _, stats_collapsed = moe.moe_mlp(x, collapsed, 1.25)
    assert float(stats_collapsed["aux_loss"]) > \
        float(stats_learned["aux_loss"])
    assert float(stats_collapsed["aux_loss"]) > 3.0  # ~E for full collapse
    # The collapsed router's expert_load stat shows the spike.
    assert float(stats_collapsed["expert_load"][0]) == 1.0


def _run(model_cfg, mesh, images, labels, nsteps=2):
    model_def = get_model(model_cfg.name)
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def _mesh(data, model=1):
    return mesh_lib.build_mesh(
        ParallelConfig(data_axis=data, model_axis=model))


def test_moe_rules_shard_experts():
    model_def = get_model("vit_moe")
    params = jax.eval_shape(
        lambda k: model_def.init(k, VIT_MOE, DATA), jax.random.key(0))
    specs = shardings.param_pspecs("vit_moe", params)
    # stacked [depth, E, D, H] -> expert dim over model
    assert specs["blocks"]["moe"]["w1"] == P(None, "model", None, None)
    assert specs["blocks"]["moe"]["w2"] == P(None, "model", None, None)
    assert specs["blocks"]["moe"]["b1"] == P(None, "model", None)
    assert specs["blocks"]["moe"]["gate"]["kernel"] == P()
    assert specs["blocks"]["qkv"]["kernel"] == P(None, None, "model")


@pytest.mark.slow
def test_ep_train_matches_dp(rng):
    """Experts sharded over model axis == pure layout change."""
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    _, loss_dp = _run(VIT_MOE, _mesh(8), images, labels)
    st_ep, loss_ep = _run(VIT_MOE, _mesh(2, 4), images, labels)
    np.testing.assert_allclose(loss_dp, loss_ep, rtol=2e-5, atol=2e-6)
    w1 = st_ep.params["blocks"]["moe"]["w1"]
    assert w1.shape[1] == 4  # 4 experts
    assert w1.addressable_shards[0].data.shape[1] == 1  # 1 expert per shard
    assert shardings.assert_some_leaf_sharded(st_ep.params)


def test_vit_moe_requires_experts():
    with pytest.raises(ValueError, match="moe_experts"):
        get_model("vit_moe").init(
            jax.random.key(0),
            ModelConfig(name="vit_moe", moe_experts=0), DATA)


@pytest.mark.slow
def test_moe_aux_loss_reaches_training_loss(rng):
    """The train loss must include the aux term: zeroing moe_aux_coef
    changes the loss by exactly coef * aux > 0."""
    import dataclasses
    images = rng.normal(0.5, 0.25, (8, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    mesh = _mesh(8)
    cfg_on = VIT_MOE
    cfg_off = dataclasses.replace(VIT_MOE, moe_aux_coef=0.0)
    _, loss_on = _run(cfg_on, mesh, images, labels, nsteps=1)
    _, loss_off = _run(cfg_off, mesh, images, labels, nsteps=1)
    assert loss_on[0] > loss_off[0]


# ---- top-2 (GShard) routing ----

@pytest.mark.slow
def test_top2_combines_two_experts():
    """Ample capacity: each token's output == renormalized-weighted sum of
    its two highest-prob experts' MLPs."""
    params = _moe_params()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (2, 8, 8)).astype(np.float32))
    y, stats = moe.moe_mlp(x, params, capacity_factor=4.0, top_k=2)

    tokens = np.asarray(x).reshape(-1, 8)
    logits = tokens @ np.asarray(params["gate"]["kernel"])
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    order = np.argsort(-probs, axis=-1)
    got = np.asarray(y).reshape(-1, 8)
    for ti in range(tokens.shape[0]):
        e1, e2 = order[ti, 0], order[ti, 1]
        p1, p2 = probs[ti, e1], probs[ti, e2]
        w1, w2 = p1 / (p1 + p2), p2 / (p1 + p2)
        want = (w1 * np.asarray(_dense_expert(params, e1, tokens[ti]))
                + w2 * np.asarray(_dense_expert(params, e2, tokens[ti])))
        np.testing.assert_allclose(got[ti], want, rtol=2e-4, atol=2e-5)
    assert np.isfinite(float(stats["aux_loss"]))


@pytest.mark.slow
def test_top2_first_choice_priority_under_pressure():
    """Capacity exactly fits the first choices: EVERY rank-0 assignment
    survives and EVERY rank-1 assignment drops — the 'a token loses its
    backup expert before anyone loses their primary' invariant.

    Construction: 32 tokens, 16 route (e0 first, e1 second), 16 route
    (e1 first, e0 second) via a crafted gate; capacity_factor=1.0 with
    top_k=2 gives capacity 16 per expert — exactly the rank-0 load."""
    params = _moe_params()
    s = 8.0
    gate = np.zeros((8, 4), np.float32)
    gate[0, 0] = s   # expert 0 keyed on feature 0
    gate[1, 1] = s   # expert 1 keyed on feature 1
    gate[0, 2] = gate[1, 2] = gate[0, 3] = gate[1, 3] = -s  # never chosen
    params = dict(params, gate={"kernel": jnp.asarray(gate)})

    x = np.zeros((1, 32, 8), np.float32)
    x[0, 0::2, 0], x[0, 0::2, 1] = 2.0, 1.0   # group A: e0 then e1
    x[0, 1::2, 0], x[0, 1::2, 1] = 1.0, 2.0   # group B: e1 then e0
    y, _ = moe.moe_mlp(jnp.asarray(x), params, capacity_factor=1.0,
                       top_k=2)

    tokens = x.reshape(-1, 8)
    logits = tokens @ gate
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    got = np.asarray(y).reshape(-1, 8)
    for ti in range(32):
        e1 = int(np.argsort(-probs[ti])[0])
        e2 = int(np.argsort(-probs[ti])[1])
        w1 = probs[ti, e1] / (probs[ti, e1] + probs[ti, e2])
        # Rank-0 contribution present, rank-1 contribution dropped.
        want = w1 * np.asarray(_dense_expert(params, e1, tokens[ti]))
        np.testing.assert_allclose(got[ti], want, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_top1_unchanged_by_topk_refactor():
    """top_k=1 keeps the Switch semantics: output scaled by raw p1."""
    params = _moe_params()
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(0, 1, (2, 4, 8)).astype(np.float32))
    y, _ = moe.moe_mlp(x, params, capacity_factor=4.0, top_k=1)
    tokens = np.asarray(x).reshape(-1, 8)
    logits = tokens @ np.asarray(params["gate"]["kernel"])
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    got = np.asarray(y).reshape(-1, 8)
    for ti in range(tokens.shape[0]):
        e1 = int(np.argmax(probs[ti]))
        want = probs[ti, e1] * np.asarray(
            _dense_expert(params, e1, tokens[ti]))
        np.testing.assert_allclose(got[ti], want, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_top2_vit_moe_trains(rng):
    import dataclasses

    cfg = dataclasses.replace(VIT_MOE, moe_top_k=2)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=4, model_axis=2))
    model_def = get_model("vit_moe")
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    st, m = train(state, *mesh_lib.shard_batch(mesh, images, labels))
    assert np.isfinite(float(m["loss"]))


# ---- scatter dispatch (round 5) ----

def test_scatter_dispatch_matches_einsum():
    """The O(T·D) scatter/gather dispatch must be bit-comparable to the
    einsum formulation — output, stats, AND gradients — across top-k
    and capacity regimes (ample, exact, starved)."""
    params = _moe_params()
    x = jax.random.normal(jax.random.key(1), (2, 16, 8))
    for topk in (1, 2):
        for cf in (4.0, 1.0, 0.25):
            y1, s1 = moe.moe_mlp(x, params, cf, top_k=topk,
                                 dispatch="einsum")
            y2, s2 = moe.moe_mlp(x, params, cf, top_k=topk,
                                 dispatch="scatter")
            np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                       rtol=1e-5, atol=1e-6)
            assert float(s1["dropped_frac"]) == pytest.approx(
                float(s2["dropped_frac"]), abs=1e-6)
            g1 = jax.grad(lambda p: float(0) + jnp.sum(moe.moe_mlp(
                x, p, cf, top_k=topk, dispatch="einsum")[0] ** 2))(params)
            g2 = jax.grad(lambda p: float(0) + jnp.sum(moe.moe_mlp(
                x, p, cf, top_k=topk, dispatch="scatter")[0] ** 2))(params)
            for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5)


def test_moe_rejects_bad_dispatch():
    params = _moe_params()
    with pytest.raises(ValueError, match="dispatch"):
        moe.moe_mlp(jnp.zeros((1, 2, 8)), params, 1.0, dispatch="nope")


@pytest.mark.slow
def test_ep_train_matches_dp_scatter_dispatch(rng):
    """Expert parallelism composes with the scatter dispatch: experts
    sharded over the model axis give the same losses as dp-only."""
    import dataclasses
    cfg = dataclasses.replace(VIT_MOE, moe_dispatch="scatter")
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    _, loss_dp = _run(cfg, _mesh(8), images, labels)
    st_ep, loss_ep = _run(cfg, _mesh(2, 4), images, labels)
    np.testing.assert_allclose(loss_dp, loss_ep, rtol=2e-5, atol=2e-6)
    assert shardings.assert_some_leaf_sharded(st_ep.params)


# ---- router stats (round-4 verdict #1) ----

def test_moe_stats_match_hand_count():
    """4 tokens forced to expert 0 with capacity 1: dropped_frac is
    exactly 3/4 and expert_load is the [1,0,0,0] spike."""
    params = _moe_params()
    g = np.zeros((8, 4), np.float32)
    g[:, 0] = 10.0
    params = dict(params, gate={"kernel": jnp.asarray(g)})
    x = jnp.ones((1, 4, 8))
    _, stats = moe.moe_mlp(x, params, capacity_factor=0.25)  # capacity = 1
    assert float(stats["dropped_frac"]) == pytest.approx(0.75)
    np.testing.assert_allclose(np.asarray(stats["expert_load"]),
                               [1.0, 0.0, 0.0, 0.0])


def test_drop_table_matches_layer_stats():
    """bench_moe.drop_table must report the layer's own stats — pin one
    cell against a direct moe_mlp call on identical inputs."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    try:
        import bench_moe
    finally:
        sys.path.pop(0)

    rows = bench_moe.drop_table([4], [1.0], tokens=256, dim=16)
    params = moe.init_moe_params(jax.random.PRNGKey(4 * 31 + 1), 16, 64, 4)
    x = jax.random.normal(jax.random.PRNGKey(7), (8, 32, 16), jnp.float32)
    _, stats = moe.moe_mlp(x, params, capacity_factor=1.0, top_k=1)
    assert rows[0]["dropped_frac"] == pytest.approx(
        float(stats["dropped_frac"]), abs=1e-4)
    assert rows[0]["max_expert_load"] == pytest.approx(
        float(jnp.max(stats["expert_load"])), abs=1e-4)


@pytest.mark.slow
def test_moe_stats_reach_step_metrics(rng):
    """A vit_moe train step publishes moe_aux_loss / moe_dropped_frac /
    moe_expert_load in its metrics dict (the Trainer logs them to JSONL
    at the loss cadence — train/loop.py)."""
    images = rng.normal(0.5, 0.25, (8, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    mesh = _mesh(8)
    model_def = get_model("vit_moe")
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, VIT_MOE, DATA,
                                        optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, VIT_MOE, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, VIT_MOE, optim, mesh,
                                     state_sharding=sh)
    _, m = train(state, *mesh_lib.shard_batch(mesh, images, labels))
    assert float(m["moe_aux_loss"]) > 0
    assert 0.0 <= float(m["moe_dropped_frac"]) <= 1.0
    load = np.asarray(m["moe_expert_load"])
    assert load.shape == (4,)
    # First-choice fractions sum to 1 (depth-averaged preserves the sum).
    assert float(load.sum()) == pytest.approx(1.0, abs=1e-5)


def test_topk_rejects_bad_k():
    params = _moe_params(e=4)
    x = jnp.zeros((1, 2, 8))
    with pytest.raises(ValueError):
        moe.moe_mlp(x, params, 1.0, top_k=5)
    with pytest.raises(ValueError):
        moe.moe_mlp(x, params, 1.0, top_k=0)
