"""Real multi-process distributed training on localhost.

SURVEY §4: the reference's only distributed "test" is the README's manual
3-terminal localhost recipe (``README.md:10-14``). The moral equivalent here
is spawning N separate Python processes that bootstrap with
``jax.distributed.initialize`` (Gloo collectives on CPU), form one global
mesh, and train in SPMD lockstep — each process feeding its own shard of the
global batch, exactly like each reference worker feeding its own queue
(``cifar10cnn.py:201``).
"""

import pytest
import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
task_index, n_procs, port, data_dir, log_dir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4], sys.argv[5])
steps_per_dispatch = int(sys.argv[6]) if len(sys.argv) > 6 else 1
fsdp = bool(int(sys.argv[7])) if len(sys.argv) > 7 else False
import jax

from dml_cnn_cifar10_tpu.config import TrainConfig, DataConfig
from dml_cnn_cifar10_tpu.parallel import multihost
from dml_cnn_cifar10_tpu.train.loop import Trainer

total_steps = int(sys.argv[8]) if len(sys.argv) > 8 else 8
ckpt_format = sys.argv[9] if len(sys.argv) > 9 else "msgpack"
resident = bool(int(sys.argv[10])) if len(sys.argv) > 10 else True
dev_stream = bool(int(sys.argv[11])) if len(sys.argv) > 11 else False
# Distinct host:port entries (validate_hosts rejects duplicates — two
# processes on one address hang a real cluster); only hosts[0] is ever
# dialed (the coordinator), the rest just size the process set.
hosts = [f"localhost:{int(port) + i}" for i in range(n_procs)]
multihost.initialize_from_hosts(hosts, task_index)
assert jax.process_count() == n_procs

cfg = TrainConfig(
    batch_size=32, total_steps=total_steps, output_every=4, eval_every=8,
    checkpoint_every=8, log_dir=log_dir,
    steps_per_dispatch=steps_per_dispatch,
    data=DataConfig(dataset="synthetic", data_dir=data_dir,
                    synthetic_train_records=256, synthetic_test_records=64,
                    normalize="scale", use_native_loader=False,
                    device_index_stream=dev_stream),
)
cfg.model.logit_relu = False
cfg.optim.learning_rate = 0.05
cfg.parallel.fsdp = fsdp
cfg.ckpt_format = ckpt_format
cfg.resident_data = resident

trainer = Trainer(cfg, task_index=task_index)
res = trainer.fit()
nonaddr = any(not x.is_fully_addressable
              for x in jax.tree.leaves(res.state.params))
# Multi-host safety of the device stream rests on purity: every process
# must compute the IDENTICAL index sequence. Recompute the first chunks
# locally and publish a digest for the cross-process assert.
idx_digest = None
if dev_stream:
    import numpy as np
    from dml_cnn_cifar10_tpu.data import device_stream
    idx = np.asarray(jax.device_get(device_stream.chunk_shuffle_indices(
        cfg.data.seed, 0, cfg.batch_size, total_steps, 256)))
    idx_digest = int(np.int64(np.sum(idx * (np.arange(idx.size).reshape(
        idx.shape) + 1))))
from dml_cnn_cifar10_tpu.parallel import multihost as mh
print("RESULT " + json.dumps({
    "task": task_index,
    "final_step": res.final_step,
    "loss": res.train_loss[-1],
    "losses": res.train_loss,
    "test_accuracy": res.test_accuracy[-1],
    "is_chief": mh.is_chief(),
    "fsdp_nonaddressable": nonaddr,
    "idx_digest": idx_digest,
}))
"""


# ---------------------------------------------------------------------------
# bootstrap validation + coordinator retry (tier-1: no processes spawned)
# ---------------------------------------------------------------------------

def test_validate_hosts_rejects_bad_inputs():
    """A bad task_index or a malformed/duplicated host list used to
    surface as a late jax.distributed hang; now it is a clear
    ValueError before anything dials anything."""
    from dml_cnn_cifar10_tpu.parallel import multihost

    ok = ["a:2222", "b:2222"]
    multihost.validate_hosts(ok, 0)
    multihost.validate_hosts(ok, 1)
    with pytest.raises(ValueError, match="empty"):
        multihost.validate_hosts([], 0)
    with pytest.raises(ValueError, match="empty"):
        multihost.validate_hosts(["a:2222", ""], 0)
    with pytest.raises(ValueError, match="host:port"):
        multihost.validate_hosts(["a:2222", "b"], 0)
    with pytest.raises(ValueError, match="host:port"):
        multihost.validate_hosts(["a:2222", "b:"], 0)
    with pytest.raises(ValueError, match="duplicated"):
        multihost.validate_hosts(["a:2222", "a:2222"], 0)
    with pytest.raises(ValueError, match="task_index"):
        multihost.validate_hosts(ok, 2)
    with pytest.raises(ValueError, match="task_index"):
        multihost.validate_hosts(ok, -1)
    # initialize_from_hosts validates BEFORE touching jax.distributed.
    with pytest.raises(ValueError, match="task_index"):
        multihost.initialize_from_hosts(ok, 5)


def test_initialize_retries_slow_coordinator(monkeypatch):
    """A refused/slow coordinator is a bounded retry with the shared
    backoff schedule, not a crash; the budget exhausted raises a
    classified RuntimeError naming the coordinator."""
    import jax

    from dml_cnn_cifar10_tpu.config import ParallelConfig
    from dml_cnn_cifar10_tpu.parallel import multihost

    cfg = ParallelConfig(coordinator_address="deadhost:2222",
                         num_processes=2, process_id=1,
                         coordinator_timeout_s=1.0,
                         coordinator_retries=2)
    calls = {"n": 0}
    sleeps = []

    def flaky_init(**kw):
        assert kw["initialization_timeout"] == 1
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("connection refused")

    monkeypatch.setattr(multihost, "_is_initialized", lambda: False)
    monkeypatch.setattr(jax.distributed, "initialize", flaky_init)
    monkeypatch.setattr(multihost.time, "sleep", sleeps.append)
    multihost.initialize(cfg)
    assert calls["n"] == 3            # 2 failures + 1 success
    assert sleeps == [1.0, 2.0]       # utils/backoff.py, base 1s

    calls["n"] = 0
    sleeps.clear()

    def always_down(**kw):
        calls["n"] += 1
        raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", always_down)
    with pytest.raises(RuntimeError, match="deadhost:2222 unreachable"):
        multihost.initialize(cfg)
    assert calls["n"] == 3            # 1 + coordinator_retries attempts


def test_is_chief_prefers_config_world():
    from dml_cnn_cifar10_tpu.config import ParallelConfig
    from dml_cnn_cifar10_tpu.parallel import multihost

    assert multihost.is_chief()  # single-process JAX world
    assert multihost.is_chief(ParallelConfig())  # num_processes=1
    assert multihost.is_chief(
        ParallelConfig(num_processes=2, process_id=0))
    assert not multihost.is_chief(
        ParallelConfig(num_processes=2, process_id=1))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_distributed_training(tmp_path, data_cfg):
    """Two OS processes, one SPMD program: both finish all steps, agree on
    the (replicated) loss, and the chief writes the only checkpoint."""
    _run_n_process(tmp_path, data_cfg, steps_per_dispatch=1)


@pytest.mark.slow
def test_two_process_chunked_dispatch(tmp_path, data_cfg):
    """Same, on the chunked path: each process feeds raw uint8 chunk
    shards via make_array_from_process_local_data with a leading K dim,
    decode runs on device."""
    _run_n_process(tmp_path, data_cfg, steps_per_dispatch=4)


@pytest.mark.slow
def test_two_process_fsdp(tmp_path, data_cfg):
    """ZeRO/FSDP across REAL process boundaries: params shard over the
    2-process data axis (leaves are not fully addressable from either
    process), the collective fetch_to_host reassembles them for the
    chief's checkpoint, and both processes stay in lockstep."""
    results = _run_n_process(tmp_path, data_cfg, steps_per_dispatch=1,
                               fsdp=True)
    assert all(r["fsdp_nonaddressable"] for r in results)


@pytest.mark.slow
def test_two_process_exact_resume(tmp_path, data_cfg):
    """The exact-resume contract across REAL process boundaries: a
    2-process run stopped at 8 and resumed to 16 logs the same losses
    at the same steps as a straight 16-step 2-process run (chief-written
    sidecar, per-process shard streams fast-forwarded)."""
    straight = _run_n_process(tmp_path / "a", data_cfg,
                                steps_per_dispatch=1, total_steps=16,
                                final_step=16)
    _run_n_process(tmp_path / "b", data_cfg, steps_per_dispatch=1,
                     total_steps=8, final_step=8)
    resumed = _run_n_process(tmp_path / "b", data_cfg,
                               steps_per_dispatch=1, total_steps=16,
                               final_step=16)
    # A true resume logs ONLY the post-restore boundaries (train_loss is
    # rebuilt per fit) — a silent from-scratch restart would log four.
    assert len(resumed[0]["losses"]) == 2
    # The straight run's boundary losses at steps 12/16 must reappear
    # exactly in the resumed run (its local boundaries re-align because
    # 8 is a cadence multiple).
    assert straight[0]["losses"][-2:] == resumed[0]["losses"]


def _run_n_process(tmp_path, data_cfg, steps_per_dispatch, fsdp=False,
                     total_steps=8, final_step=8,
                     ckpt_format="msgpack", resident=True, n=2,
                     dev_stream=False):
    port = _free_port()
    data_dir = str(tmp_path / "data")
    log_dir = str(tmp_path / "logs")
    # Pre-generate the shared synthetic dataset so the workers don't race
    # writing the .bin shards.
    import dataclasses
    from dml_cnn_cifar10_tpu.data import ensure_dataset
    ensure_dataset(dataclasses.replace(
        data_cfg, data_dir=data_dir, synthetic_train_records=256,
        synthetic_test_records=64))

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="")  # 1 CPU device per process, n globally
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(n), str(port),
             data_dir, log_dir, str(steps_per_dispatch),
             str(int(fsdp)), str(total_steps), ckpt_format,
             str(int(resident)), str(int(dev_stream))],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for i in range(n)
    ]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:  # a dead coordinator must not leak a hung peer
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"

    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, f"no RESULT line in:\n{out}"
        results.append(json.loads(lines[-1][len("RESULT "):]))

    assert all(r["final_step"] == final_step for r in results)
    # Loss/accuracy come out of the same replicated SPMD computation, so
    # every process must report identical values.
    assert all(r["loss"] == results[0]["loss"] for r in results)
    assert all(r["test_accuracy"] == results[0]["test_accuracy"]
               for r in results)
    import math
    assert math.isfinite(results[0]["loss"])
    # Chief-only checkpointing: exactly one process holds the chief role
    # (the single writer), and the shared dir has the final-step checkpoint.
    assert sorted(r["is_chief"] for r in results) == [False] * (n - 1) + [True]
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt
    # Chief-only single writer, cadence-only steps: [8] for the 8-step
    # runs, [8, 16] after the resumed leg.
    assert sorted(ckpt.all_checkpoint_steps(log_dir)) == list(
        range(8, final_step + 1, 8))
    return results


@pytest.mark.slow
def test_two_process_sharded_checkpoint_and_resume(tmp_path, data_cfg):
    """The pod-scale checkpoint path across REAL process boundaries:
    with fsdp state each process writes ONLY its own shard file (no
    full-state allgather), the chief commits the manifest, and a second
    2-process run restores from the assembled shards and resumes."""
    results = _run_n_process(tmp_path, data_cfg, steps_per_dispatch=1,
                               fsdp=True, ckpt_format="sharded")
    assert all(r["fsdp_nonaddressable"] for r in results)
    ckpt = os.path.join(str(tmp_path / "logs"), "ckpt_8.sharded")
    names = sorted(os.listdir(ckpt))
    assert names == ["MANIFEST.json", "shard_0.msgpack", "shard_1.msgpack"]
    # Resume to 16 from the sharded checkpoint (restore assembles the
    # global arrays from both shard files, re-shards onto the mesh).
    resumed = _run_n_process(tmp_path, data_cfg, steps_per_dispatch=1,
                               fsdp=True, ckpt_format="sharded",
                               total_steps=16, final_step=16)
    import math
    assert math.isfinite(resumed[0]["loss"])


@pytest.mark.slow
def test_two_process_resident_matches_hostfed(tmp_path, data_cfg):
    """Multi-host HBM-resident data: each process replicates the full
    split into device memory and ships only its slice of the global
    index array (local shard rows translated to full-split rows). The
    run must produce EXACTLY the host-fed chunked path's losses — same
    records, same device-side decode — while never gathering images on
    the host."""
    hostfed = _run_n_process(tmp_path / "h", data_cfg,
                               steps_per_dispatch=4, resident=False)
    res = _run_n_process(tmp_path / "r", data_cfg,
                           steps_per_dispatch=4, resident=True)
    assert res[0]["losses"] == hostfed[0]["losses"]
    assert res[0]["test_accuracy"] == hostfed[0]["test_accuracy"]


@pytest.mark.slow
def test_two_process_device_index_stream(tmp_path, data_cfg):
    """Round-4 verdict #5: the device index stream's multi-host story IS
    the point (no per-process index shipping) — prove it across REAL
    process boundaries. Both processes must (a) compute bit-identical
    index streams (purity — the digest is recomputed per process from
    the stateless stream), and (b) train in lockstep to identical
    replicated losses, with the training dispatch taking ONLY the
    donated state."""
    results = _run_n_process(tmp_path, data_cfg, steps_per_dispatch=4,
                               resident=True, dev_stream=True)
    digests = [r["idx_digest"] for r in results]
    assert digests[0] is not None
    assert digests[0] == digests[1], digests
    # (b) is covered by _run_n_process's replicated-loss asserts; the
    # extra teeth here: the run completed all steps on the device stream.
    assert all(r["final_step"] == 8 for r in results)


@pytest.mark.slow
def test_four_process_fsdp_sharded(tmp_path, data_cfg):
    """Beyond the pairwise case: FOUR processes form one mesh, shard
    fsdp state four ways, train in lockstep on the resident path, and
    write a four-file sharded checkpoint the chief commits."""
    results = _run_n_process(tmp_path, data_cfg, steps_per_dispatch=4,
                               fsdp=True, ckpt_format="sharded", n=4)
    assert all(r["fsdp_nonaddressable"] for r in results)
    ckpt = os.path.join(str(tmp_path / "logs"), "ckpt_8.sharded")
    names = sorted(os.listdir(ckpt))
    assert names == ["MANIFEST.json"] + [f"shard_{i}.msgpack"
                                         for i in range(4)]


WORKER_RESIDENT_EVAL = """
import json, sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
task_index, n_procs, port, data_dir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4])
import jax

from dml_cnn_cifar10_tpu.config import TrainConfig, DataConfig
from dml_cnn_cifar10_tpu.data import pipeline as pipe
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import multihost
from dml_cnn_cifar10_tpu.parallel import step as step_lib
from dml_cnn_cifar10_tpu.train.loop import Trainer

hosts = [f"localhost:{int(port) + i}" for i in range(n_procs)]
multihost.initialize_from_hosts(hosts, task_index)

cfg = TrainConfig(
    batch_size=32, total_steps=8, log_dir=data_dir + "/logs",
    eval_full_test_set=True,
    data=DataConfig(dataset="synthetic", data_dir=data_dir,
                    synthetic_train_records=256,
                    synthetic_test_records=72,  # 36/shard: NOT a batch
                    normalize="scale",          # multiple -> padding live
                    use_native_loader=False),
)
cfg.model.logit_relu = False
shard, num_shards = jax.process_index(), jax.process_count()
per_process_batch = cfg.batch_size // num_shards

trainer = Trainer(cfg, task_index=task_index)
state = trainer.init_or_restore()
test_it = pipe.input_pipeline(cfg.data, per_process_batch, train=False,
                              seed=cfg.seed + shard, shard=shard,
                              num_shards=num_shards)

# Resident one-dispatch path (round 3: multi-host included). The
# device_get counter is patched around BUILD + CALL so any library
# fetch reintroduced on this path (e.g. the host-fed fallback's
# per-batch fetches) is counted, not just the worker's own call.
n_gets = 0
_orig_get = jax.device_get
def counting_get(x):
    global n_gets
    n_gets += 1
    return _orig_get(x)
jax.device_get = counting_get
fn, total = step_lib.make_eval_resident(
    trainer.model_def, cfg.model, trainer.mesh, test_it.images,
    test_it.labels, cfg.data, state_sharding=trainer.state_sharding,
    batch_size=per_process_batch, num_shards=num_shards,
    total_records=test_it.total_records,
    expected_batches=test_it.num_padded_sweep_batches())
resident_correct = int(jax.device_get(fn(state)))
jax.device_get = _orig_get

# Host-fed padded sweep (the round-2 fallback), same state.
correct = None
for batch in test_it.full_sweep_padded():
    placed = mesh_lib.shard_batch(trainer.mesh, batch.images, batch.labels)
    c = trainer.eval_step(state, *placed)["correct"]
    correct = c if correct is None else correct + c
hostfed_correct = int(jax.device_get(correct))

print("RESULT " + json.dumps({
    "task": task_index,
    "resident_correct": resident_correct,
    "hostfed_correct": hostfed_correct,
    "total": total,
    "total_records": test_it.total_records,
    "n_gets": n_gets,
}))
"""


@pytest.mark.slow
def test_two_process_resident_full_eval_matches_hostfed(tmp_path, data_cfg):
    """Round-2 verdict missing #2: the multi-host full-split eval gets the
    resident one-dispatch treatment. Each process contributes its padded
    strided shard to the global [M, B, ...] arrays; the replicated scan
    output must equal the host-fed padded sweep BIT-FOR-BIT on every
    process, with exactly one device_get."""
    port = _free_port()
    data_dir = str(tmp_path / "data")
    import dataclasses
    from dml_cnn_cifar10_tpu.data import ensure_dataset
    ensure_dataset(dataclasses.replace(
        data_cfg, data_dir=data_dir, synthetic_train_records=256,
        synthetic_test_records=72))

    script = tmp_path / "worker_eval.py"
    script.write_text(WORKER_RESIDENT_EVAL)
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), "2", str(port), data_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for i in range(2)
    ]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, f"no RESULT line in:\n{out}"
        results.append(json.loads(lines[-1][len("RESULT "):]))

    for r in results:
        assert r["resident_correct"] == r["hostfed_correct"], results
        assert r["total"] == r["total_records"] == 72
        assert r["n_gets"] == 1, r
    # Replicated global count: both processes report the same number.
    assert results[0]["resident_correct"] == results[1]["resident_correct"]
