"""TensorBoard event-file sink (the MTS-wrote-summaries parity knob)."""

import os


def test_tb_event_files_written(tmp_path):
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

    tb_dir = str(tmp_path / "tb")
    logger = MetricsLogger(tensorboard_dir=tb_dir)
    logger.log("train", step=10, loss=1.5, train_accuracy=0.25,
               images_per_sec=1000.0, lr=0.1)
    logger.log("eval", step=10, test_accuracy=0.3)
    logger.log("train", step=20, loss=float("nan"))  # NaN must not crash
    logger.log("done", images_per_sec=1000.0)        # no step: skipped
    logger.close()
    events = [f for f in os.listdir(tb_dir) if "tfevents" in f]
    assert events, os.listdir(tb_dir)
    assert os.path.getsize(os.path.join(tb_dir, events[0])) > 0
