"""Adafactor: factored second moments (Shazeer & Stern 2018).

Pins the three properties that make the optimizer what it is: the
factored estimate is EXACT on rank-1 squared gradients, the state really
is sub-linear in matrix size, and it trains end to end (composing with
the EMA/checkpoint machinery every family shares).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig, OptimConfig
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.train import optim


def _cfg(**kw):
    kw.setdefault("learning_rate", 0.01)
    return OptimConfig(optimizer="adafactor", **kw)


def test_factored_estimate_exact_on_rank1_grads():
    """g^2 = outer(r, c) (rank 1) => vr_i*vc_j/mean(vr) == g^2 exactly,
    so the factored update must equal the full-accumulator RMS update."""
    rng = np.random.default_rng(0)
    r = rng.uniform(0.5, 2.0, (6,)).astype(np.float32)
    c = rng.uniform(0.5, 2.0, (8,)).astype(np.float32)
    g = np.sqrt(np.outer(r, c)).astype(np.float32)
    p = {"w": jnp.asarray(rng.normal(0, 1, (6, 8)), jnp.float32)}
    cfg = _cfg()
    state = optim.sgd_init(p, cfg)
    new_p, new_state = optim.sgd_update({"w": jnp.asarray(g)}, state, p, cfg)

    # Manual full-accumulator reference at step 1: b2 = 1 - 1^-0.8 = 0;
    # relative step alpha = lr * max(RMS(p), 1e-3).
    g2 = g * g + 1e-30
    u = g / np.sqrt(g2)
    u = u / max(1.0, np.sqrt(np.mean(u * u)))
    alpha = 0.01 * max(float(np.sqrt(np.mean(np.square(
        np.asarray(p["w"]))))), 1e-3)
    want = np.asarray(p["w"]) - alpha * u
    np.testing.assert_allclose(np.asarray(new_p["w"]), want,
                               rtol=1e-5, atol=1e-6)
    # Factored stats have the reduced shapes, unfactored slot is a
    # placeholder scalar.
    assert new_state["vr"]["w"].shape == (6,)
    assert new_state["vc"]["w"].shape == (8,)
    assert new_state["v"]["w"].shape == ()


def test_state_is_sublinear_in_matrix_size():
    p = {"big": jnp.zeros((256, 512)), "bias": jnp.zeros((512,))}
    state = optim.sgd_init(p, _cfg())
    # Matrix: O(n+m) stats instead of O(n*m).
    assert state["vr"]["big"].size + state["vc"]["big"].size == 256 + 512
    assert state["v"]["big"].size == 1  # placeholder
    # Vector: full accumulator (factoring a 1-d stat saves nothing).
    assert state["v"]["bias"].shape == (512,)
    assert state["vr"]["bias"].size == state["vc"]["bias"].size == 1


def test_update_rms_clipped_and_parameter_scaled():
    """A huge gradient step is bounded: ||update||_rms <= lr *
    max(RMS(p), eps2) * 1.0 — here p = 0 so the eps2 floor governs."""
    p = {"w": jnp.zeros((4, 4), jnp.float32)}
    g = {"w": jnp.full((4, 4), 1e6, jnp.float32)}
    cfg = _cfg()
    new_p, _ = optim.sgd_update(g, optim.sgd_init(p, cfg), p, cfg)
    step_rms = float(jnp.sqrt(jnp.mean(jnp.square(new_p["w"]))))
    assert step_rms <= cfg.learning_rate * 1e-3 + 1e-9


def test_momentum_rejected():
    with pytest.raises(ValueError, match="momentum"):
        optim.sgd_init({"w": jnp.zeros((2, 2))}, _cfg(momentum=0.9))


@pytest.mark.slow
def test_adafactor_trains_vit(rng):
    """End to end through the jitted step on the optimizer's home turf
    (transformer matrices): loss decreases, state checkpoints and
    restores through the shared pytree machinery."""
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    data = DataConfig(normalize="scale")
    mcfg = ModelConfig(name="vit_tiny", logit_relu=False, vit_depth=2,
                       vit_dim=64, vit_heads=2, patch_size=8)
    ocfg = _cfg(learning_rate=0.05, weight_decay=1e-4)
    mesh = mesh_lib.build_mesh()
    model_def = get_model("vit_tiny")
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, mcfg, data, ocfg, mesh)
    train = step_lib.make_train_step(model_def, mcfg, ocfg, mesh)
    # Class-separable blobs so a real signal exists.
    labels = rng.integers(0, 10, 64).astype(np.int32)
    means = rng.uniform(0.2, 0.8, (10, 3)).astype(np.float32)
    images = (means[labels][:, None, None, :]
              + rng.normal(0, 0.05, (64, 24, 24, 3))).astype(np.float32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(25):
        state, m = train(state, im, lb)
        losses.append(float(jax.device_get(m["loss"])))
    assert losses[-1] < losses[0] * 0.7
    assert int(jax.device_get(state.step)) == 25

    import tempfile

    from dml_cnn_cifar10_tpu import ckpt as ckpt_lib
    with tempfile.TemporaryDirectory() as td:
        ckpt_lib.save_checkpoint(td, state, step=8)
        restored = ckpt_lib.restore_checkpoint(
            td, step_lib.init_train_state(
                jax.random.key(1), model_def, mcfg, data, ocfg, mesh))
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))
