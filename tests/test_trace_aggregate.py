"""Run-wide trace aggregation (tools/trace_aggregate.py): heartbeat-
wallclock clock alignment, the (task, step) timeline, the step-skew /
straggler table, event merging, and the merged Perfetto document. The
2-process cluster-sim integration run lives in tests/test_cluster.py
(the sim already produces real streams there)."""

import json

import pytest

from tools import trace_aggregate as agg_lib


def _rec(kind, t, task, **fields):
    return {"kind": kind, "t": round(t, 4), "task": task, **fields}


def _stream(task, unix0, steps, lag_s=0.0, events=()):
    """A schema-shaped stream for one host whose logger started at unix
    time ``unix0``: heartbeats (with wallclock), train rows, spans."""
    recs = []
    for i, step in enumerate(steps):
        t = 1.0 + i * 2.0 + lag_s
        recs.append(_rec("heartbeat", t, task, step=step,
                         process_id=task, phase="train",
                         wallclock=round(unix0 + t, 3)))
        recs.append(_rec("train", t + 0.5, task, step=step, loss=1.0,
                         train_accuracy=0.5, images_per_sec=100.0,
                         lr=0.1, device_step_ms=12.0,
                         drain_wait_ms=5.0))
        recs.append(_rec("span", t + 0.6, task, step=step,
                         name="dispatch", start_s=t + 0.1, dur_s=0.3,
                         depth=0))
    for kind, t, fields in events:
        recs.append(_rec(kind, t, task, **fields))
    return recs


@pytest.fixture
def two_streams(tmp_path):
    """Host 0's logger started at unix 1000.0; host 1's started 5 s
    EARLIER (995.0) but it reaches each step 0.25 s behind host 0 in
    aligned wall terms — exactly the case raw ``t`` comparison gets
    backwards and wallclock alignment gets right."""
    a = _stream(0, 1000.0, [10, 20, 30],
                events=[("peer_lost", 7.0,
                         {"step": 30, "process_id": 1,
                          "reason": "stale_heartbeat"})])
    # Host 1 wall for step s = 995.0 + t; lag chosen so aligned wall is
    # host0's + 0.25 (t_h1 = t_h0 + 5.0 + 0.25).
    b = _stream(1, 995.0, [10, 20], lag_s=5.25)
    pa, pb = tmp_path / "m0.jsonl", tmp_path / "m1.jsonl"
    for path, recs in ((pa, a), (pb, b)):
        path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(pa), str(pb)


def test_clock_offset_from_heartbeats(two_streams):
    pa, pb = two_streams
    assert agg_lib.clock_offset(agg_lib.load_stream(pa)) == \
        pytest.approx(1000.0, abs=1e-3)
    assert agg_lib.clock_offset(agg_lib.load_stream(pb)) == \
        pytest.approx(995.0, abs=1e-3)
    assert agg_lib.clock_offset([]) is None


def test_aggregate_timeline_counts_and_skew(two_streams):
    pa, pb = two_streams
    agg = agg_lib.aggregate([pa, pb])
    assert agg["aligned_hosts"] == 2

    # Per-host step counts must match the individual streams EXACTLY.
    for host in agg["hosts"]:
        direct = [r["step"] for r in agg_lib.load_stream(host["path"])
                  if r["kind"] == "train"]
        assert host["train_steps"] == direct
        assert host["train_rows"] == len(direct)
    by_task = {h["task"]: h for h in agg["hosts"]}
    assert by_task[0]["train_steps"] == [10, 20, 30]
    assert by_task[1]["train_steps"] == [10, 20]

    # Timeline keyed (task, step): every step each host reported, and
    # only those.
    assert sorted(agg["timeline"][0]) == [10, 20, 30]
    assert sorted(agg["timeline"][1]) == [10, 20]
    assert "train" in agg["timeline"][1][20]["kinds"]

    # Skew: steps 10 and 20 are shared; host 1 arrives 0.25 s later in
    # ALIGNED wall time (its raw t is smaller — alignment is what makes
    # the comparison meaningful).
    skew = agg["skew"]
    assert skew["steps_compared"] == 2
    assert skew["max_spread_s"] == pytest.approx(0.25, abs=1e-3)
    assert skew["laggard_counts"] == {1: 2}

    # The peer_lost event surfaced on the merged event list.
    kinds = [e["kind"] for e in agg["events"]]
    assert "peer_lost" in kinds
    ev = agg["events"][kinds.index("peer_lost")]
    assert ev["task"] == 0 and ev["reason"] == "stale_heartbeat"

    # Text report renders the host table and skew section.
    out = agg_lib.render(agg)
    assert "task 0" in out and "step skew" in out \
        and "peer_lost" in out


def test_aggregate_unaligned_stream_flagged(tmp_path, two_streams):
    pa, _ = two_streams
    # A stream with no heartbeats (single-process run) stays unaligned.
    pc = tmp_path / "m2.jsonl"
    pc.write_text(json.dumps(
        {"kind": "train", "t": 1.0, "task": 2, "step": 10, "loss": 1.0,
         "train_accuracy": 0.5, "images_per_sec": 50.0, "lr": 0.1,
         "device_step_ms": None, "drain_wait_ms": None}) + "\n")
    agg = agg_lib.aggregate([pa, str(pc)])
    by_task = {h["task"]: h for h in agg["hosts"]}
    assert by_task[2]["offset_unix"] is None
    assert agg["aligned_hosts"] == 1
    # Unaligned hosts never enter the skew comparison.
    assert agg["skew"]["steps_compared"] == 0
    assert "UNALIGNED" in agg_lib.render(agg)


def test_merged_trace_document(two_streams, tmp_path):
    pa, pb = two_streams
    doc = agg_lib.build_merged_trace([pa, pb])
    evs = doc["traceEvents"]
    assert evs
    pids = {e.get("pid") for e in evs}
    assert {0, 1} <= pids
    span_x = [e for e in evs if e.get("ph") == "X"]
    counters = [e for e in evs if e.get("ph") == "C"]
    instants = [e for e in evs if e.get("ph") == "i"]
    assert span_x and counters and instants
    # Span lanes land on the SHARED clock: host 1's step-10 dispatch
    # sits ~0.25 s after host 0's, not 5.25 s before.
    def span_ts(pid):
        return min(e["ts"] for e in span_x if e["pid"] == pid)
    assert span_ts(1) - span_ts(0) == pytest.approx(0.25e6, rel=0.05)

    # A real Chrome trace file merges in, shifted by its epoch.
    host_trace = tmp_path / "host0_trace.json"
    host_trace.write_text(json.dumps({
        "traceEvents": [{"ph": "X", "name": "eval", "pid": 0, "tid": 0,
                         "ts": 100.0, "dur": 50.0}],
        "otherData": {"epoch_unix_s": 1001.0}}))
    doc = agg_lib.build_merged_trace([pa, pb], [str(host_trace)])
    merged = [e for e in doc["traceEvents"]
              if e.get("name") == "eval"]
    assert merged and merged[0]["pid"] == 1000
    # wall0 is host 1's 995.0 → the 1001.0 epoch shifts by 6 s.
    assert merged[0]["ts"] == pytest.approx(6.0e6 + 100.0, rel=1e-3)


def test_cli_main(two_streams, tmp_path, capsys):
    pa, pb = two_streams
    out_path = str(tmp_path / "merged.json")
    assert agg_lib.main([pa, pb, "--out", out_path]) == 0
    with open(out_path) as f:
        doc = json.load(f)
    assert doc["traceEvents"]
    captured = capsys.readouterr()
    assert "step skew" in captured.out
    # JSON mode emits the aggregation for tooling.
    assert agg_lib.main([pa, pb, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["skew"]["steps_compared"] == 2
