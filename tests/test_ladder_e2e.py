"""Config-ladder e2e: every model family + CIFAR-100 through the Trainer.

SURVEY §7 rung 6 — CIFAR-100 head swap → ResNet (cross-replica BN) →
ViT/MoE — each driven end-to-end through the real Trainer (jitted SPMD
step, prefetching pipeline, checkpointing) rather than only unit-level.
All runs are tiny and on the 8-virtual-device CPU mesh.
"""

import dataclasses

import numpy as np

from dml_cnn_cifar10_tpu.config import DataConfig, ParallelConfig
from dml_cnn_cifar10_tpu.data import ensure_dataset
from dml_cnn_cifar10_tpu.train.loop import Trainer
from tests.conftest import tiny_train_cfg
import pytest


@pytest.mark.slow
def test_resnet18_trainer_e2e(tmp_path, data_cfg):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=6)
    cfg.output_every = cfg.eval_every = cfg.checkpoint_every = 3
    cfg.model.name = "resnet18"
    cfg.optim.learning_rate = 0.01
    r = Trainer(cfg).fit()
    assert r.final_step == 6
    assert np.isfinite(r.train_loss).all()


@pytest.mark.slow
def test_vit_moe_trainer_e2e(tmp_path, data_cfg):
    """MoE ViT through the Trainer on a dp x tp mesh: expert parallelism,
    aux load-balance loss, and the registry defaults all exercised at the
    driver level."""
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=4)
    cfg.output_every = cfg.eval_every = cfg.checkpoint_every = 2
    cfg.batch_size = 16
    cfg.model = dataclasses.replace(
        cfg.model, name="vit_moe", pool="mean", logit_relu=False,
        vit_depth=2, vit_dim=32, vit_heads=2, patch_size=8,
        moe_experts=2)
    cfg.optim.learning_rate = 1e-3
    cfg.optim.optimizer = "adamw"
    cfg.parallel = ParallelConfig(data_axis=4, model_axis=2)
    r = Trainer(cfg).fit()
    assert r.final_step == 4
    assert np.isfinite(r.train_loss).all()


@pytest.mark.slow
def test_cifar100_trainer_e2e(tmp_path):
    """CIFAR-100: 2 label bytes per record, 100-way head — the first
    ladder rung. Synthetic files are pre-generated so the air-gapped run
    never attempts the download."""
    data = DataConfig(
        dataset="cifar100",
        data_dir=str(tmp_path / "c100"),
        num_classes=100,
        synthetic_train_records=320,
        synthetic_test_records=96,
        use_native_loader=False,
        normalize="scale",
    )
    from dml_cnn_cifar10_tpu.data.download import \
        generate_synthetic_dataset
    generate_synthetic_dataset(data)
    ensure_dataset(data)  # must short-circuit: files exist

    cfg = tiny_train_cfg(data, str(tmp_path), total_steps=4)
    cfg.output_every = cfg.eval_every = cfg.checkpoint_every = 2
    cfg.data = data
    cfg.model.num_classes = 100
    cfg.optim.learning_rate = 0.01
    r = Trainer(cfg).fit()
    assert r.final_step == 4
    assert np.isfinite(r.train_loss).all()
    # The head really is 100-wide (not silently 10).
    head = r.state.params["full3"]["kernel"]
    assert head.shape[-1] == 100


@pytest.mark.slow
def test_resnet50_imagenet_synth_trainer_e2e(tmp_path):
    """The ResNet-50/ImageNet rung (BASELINE.json configs[3]) end-to-end:
    ImageNet-shaped synthetic records (wide 2-byte labels, 1000 classes,
    crop > 64 so the model selects the 7x7/s2 + 3x3/s2 ImageNet stem —
    models/resnet.py) through the real Trainer. Geometry is shrunk (80->72)
    to keep the CPU run tractable; the full 256->224 path is the CLI's
    --dataset imagenet_synth default and differs only in numbers."""
    data = DataConfig(
        dataset="imagenet_synth",
        data_dir=str(tmp_path / "imgnet"),
        image_height=80, image_width=80,
        crop_height=72, crop_width=72,
        num_classes=1000,
        synthetic_train_records=64,
        synthetic_test_records=16,
        use_native_loader=False,
        shuffle_buffer=64,
        normalize="scale",
    )
    ensure_dataset(data)
    cfg = tiny_train_cfg(data, str(tmp_path), total_steps=2)
    cfg.output_every = cfg.eval_every = cfg.checkpoint_every = 2
    cfg.batch_size = 8
    cfg.data = data
    cfg.model.name = "resnet50"
    cfg.model.num_classes = 1000
    cfg.optim.learning_rate = 0.01
    r = Trainer(cfg).fit()
    assert r.final_step == 2
    assert np.isfinite(r.train_loss).all()
    # ImageNet stem (7x7 conv) and 1000-wide head actually selected.
    assert r.state.params["stem"]["conv"].shape[0] == 7
    assert r.state.params["fc"]["kernel"].shape[-1] == 1000
