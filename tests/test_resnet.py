"""ResNet-18/50 model tests: shapes, param counts, BN state semantics,
cross-replica parity between auto-jit and explicit shard_map SPMD."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models import resnet
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib


def _cfgs(name="resnet18", classes=10):
    return (ModelConfig(name=name, num_classes=classes, logit_relu=False),
            DataConfig())


def _batch(rng, n=16, hw=24):
    images = rng.normal(0.0, 1.0, (n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


@pytest.fixture(scope="module")
def r18():
    cfg, data = _cfgs()
    params = resnet.init_params(jax.random.key(0), cfg, data, depth=18)
    state = resnet.init_state(params)
    return cfg, data, params, state


@pytest.mark.slow
def test_resnet18_shapes_and_params(r18):
    cfg, data, params, state = r18
    rng = np.random.default_rng(0)
    images, _ = _batch(rng)
    logits, new_state = resnet.apply(params, state, jnp.asarray(images), cfg,
                                     train=True)
    assert logits.shape == (16, 10)
    assert logits.dtype == jnp.float32
    # torchvision resnet18 is 11.69M with a 7x7 stem; the CIFAR 3x3 stem
    # drops ~9.4k stem weights => ~11.18M
    n = resnet.param_count(params)
    assert 11_000_000 < n < 11_300_000, n
    # state tree must be structurally identical in and out (no silent
    # recompile on step 2)
    assert (jax.tree.structure(state) == jax.tree.structure(new_state))


@pytest.mark.slow
def test_resnet50_bottleneck_shapes():
    cfg, data = _cfgs("resnet50")
    params = resnet.init_params(jax.random.key(0), cfg, data, depth=50)
    state = resnet.init_state(params)
    rng = np.random.default_rng(0)
    images, _ = _batch(rng, n=4)
    logits, _ = resnet.apply(params, state, jnp.asarray(images), cfg,
                             train=True)
    assert logits.shape == (4, 10)
    n = resnet.param_count(params)
    # torchvision resnet50 = 25.56M with a 1000-class head (2048x1000 =
    # 2.05M); the 10-class head drops that to ~23.5M
    assert 23_400_000 < n < 23_700_000, n


@pytest.mark.slow
def test_imagenet_stem_for_large_inputs():
    cfg, _ = _cfgs("resnet50")
    data = DataConfig(image_height=224, image_width=224, crop_height=224,
                      crop_width=224)
    params = resnet.init_params(jax.random.key(0), cfg, data, depth=50)
    assert params["stem"]["conv"].shape == (7, 7, 3, 64)
    state = resnet.init_state(params)
    images = np.random.default_rng(0).normal(
        0, 1, (2, 224, 224, 3)).astype(np.float32)
    logits, _ = resnet.apply(params, state, jnp.asarray(images), cfg,
                             train=False)
    assert logits.shape == (2, 10)
    assert resnet.param_count(params) > 23_400_000


def test_bn_state_updates_in_train_frozen_in_eval(r18):
    cfg, data, params, state = r18
    rng = np.random.default_rng(1)
    images, _ = _batch(rng)
    _, ns_train = resnet.apply(params, state, jnp.asarray(images), cfg,
                               train=True)
    stem0 = state["stem"]["bn"]["mean"]
    stem1 = ns_train["stem"]["bn"]["mean"]
    assert not np.allclose(stem0, stem1), "train must move running stats"
    _, ns_eval = resnet.apply(params, state, jnp.asarray(images), cfg,
                              train=False)
    chex_equal = jax.tree.map(
        lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)),
        state, ns_eval)
    assert all(jax.tree.leaves(chex_equal)), "eval must not touch stats"


def test_eval_deterministic_batch_independent(r18):
    """Eval uses running stats: each example's logits must not depend on the
    rest of the batch."""
    cfg, data, params, state = r18
    rng = np.random.default_rng(2)
    images, _ = _batch(rng, n=8)
    full, _ = resnet.apply(params, state, jnp.asarray(images), cfg,
                           train=False)
    half, _ = resnet.apply(params, state, jnp.asarray(images[:4]), cfg,
                           train=False)
    np.testing.assert_allclose(np.asarray(full)[:4], np.asarray(half),
                               rtol=1e-5, atol=1e-5)


def test_gamma_zero_blocks_start_as_identity(r18):
    """Residual branches are gamma-zero-initialized, so at init the net is
    stem + projections only — logits finite and loss ~= log(10)."""
    cfg, data, params, state = r18
    rng = np.random.default_rng(3)
    images, labels = _batch(rng)
    logits, _ = resnet.apply(params, state, jnp.asarray(images), cfg,
                             train=True)
    assert np.isfinite(np.asarray(logits)).all()
    from dml_cnn_cifar10_tpu.train.loss import softmax_cross_entropy
    loss = softmax_cross_entropy(logits, jnp.asarray(labels))
    assert abs(float(loss) - np.log(10)) < 1.0


@pytest.mark.slow
def test_explicit_shard_map_matches_auto_jit():
    """Cross-replica BN: shard_map with axis_name pmean of (E[x],E[x²]) must
    produce the same update as jit auto-partitioning's global batch stats."""
    model_def = get_model("resnet18")
    cfg, data = _cfgs()
    optim = OptimConfig(learning_rate=0.05, dead_lr_decay=False)
    mesh = mesh_lib.build_mesh(ParallelConfig())
    rng = np.random.default_rng(4)
    images, labels = _batch(rng, n=32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    results = []
    for explicit in (False, True):
        st = step_lib.init_train_state(jax.random.key(0), model_def, cfg,
                                       data, optim, mesh)
        train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                         explicit_collectives=explicit)
        st, metrics = train(st, im, lb)
        results.append((st, metrics))

    (s_auto, m_auto), (s_exp, m_exp) = results
    np.testing.assert_allclose(float(m_auto["loss"]), float(m_exp["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_auto.params),
                    jax.tree.leaves(s_exp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=5e-5)
    # BN running stats must agree too (the pmean'd sufficient statistics)
    for a, b in zip(jax.tree.leaves(s_auto.model_state),
                    jax.tree.leaves(s_exp.model_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=5e-5)


@pytest.mark.slow
def test_two_steps_no_structure_change():
    """Treedef stability: step 2 reuses the compiled step (same structure)."""
    model_def = get_model("resnet18")
    cfg, data = _cfgs()
    optim = OptimConfig(learning_rate=0.05)
    st = step_lib.init_train_state(jax.random.key(0), model_def, cfg, data,
                                   optim)
    train = step_lib.make_train_step(model_def, cfg, optim)
    rng = np.random.default_rng(5)
    for _ in range(2):
        images, labels = _batch(rng)
        st, metrics = train(st, jnp.asarray(images), jnp.asarray(labels))
    assert int(st.step) == 2
    assert np.isfinite(float(metrics["loss"]))


def test_s2d_stem_folded_kernel_equivalence():
    """The space-to-depth stem (--resnet_s2d) computes the SAME function
    as the 7x7/2 stem when the 7x7 kernel is folded into the 4x4x(4C)
    parameterization (zero-pad to 8x8; ws[m,n,(a,b,c)] = w8[2m+a,2n+b,c]
    with the XLA SAME pad lo=2 mapping to folded pad (1,2)) — the MLPerf
    transform is a re-parameterization, not a different model
    (BASELINE.md round-4)."""
    from dml_cnn_cifar10_tpu.models import resnet

    cfg7 = ModelConfig(name="resnet50", logit_relu=False)
    cfgs = ModelConfig(name="resnet50", logit_relu=False, resnet_s2d=True)
    data = DataConfig(crop_height=96, crop_width=96, num_classes=10)
    k = jax.random.key(0)
    p7 = resnet.init_params(k, cfg7, data, depth=50)
    ps = resnet.init_params(k, cfgs, data, depth=50)
    assert ps["stem"]["conv"].shape == (4, 4, 12, 64)

    w7 = np.asarray(p7["stem"]["conv"])
    w8 = np.zeros((8, 8, 3, 64), np.float32)
    w8[:7, :7] = w7
    ws = np.zeros((4, 4, 12, 64), np.float32)
    for m in range(4):
        for n in range(4):
            for a in range(2):
                for b in range(2):
                    ws[m, n, a * 6 + b * 3: a * 6 + b * 3 + 3] = \
                        w8[2 * m + a, 2 * n + b]
    ps["stem"]["conv"] = jnp.asarray(ws)

    x = jnp.asarray(np.random.default_rng(0).normal(0, 1, (2, 96, 96, 3)),
                    jnp.float32)
    o7, _ = resnet.apply(p7, resnet.init_state(p7), x, cfg7, train=True)
    os_, _ = resnet.apply(ps, resnet.init_state(ps), x, cfgs, train=True)
    np.testing.assert_allclose(np.asarray(os_), np.asarray(o7), atol=1e-4)


def test_nf_resnet_init_structure_and_identity_start():
    """--resnet_norm=nf: no BN anywhere (state is all-None), weight-
    standardized convs + SkipInit zero scalar make every residual block
    start as identity + projection — the NF analog of gamma-zero BN."""
    cfg = ModelConfig(name="resnet18", logit_relu=False, resnet_norm="nf")
    data = DataConfig()
    params = resnet.init_params(jax.random.key(0), cfg, data, depth=18)
    state = resnet.init_state(params)
    assert all(leaf is None for leaf in jax.tree.leaves(
        state, is_leaf=lambda x: x is None))
    blk = params["stage1"][0]
    assert "bn1" not in blk and "skip_gain" in blk
    assert float(blk["skip_gain"]) == 0.0
    # Identity start: a non-projection block must pass relu(x) through.
    x = jnp.abs(jax.random.normal(jax.random.key(1), (2, 8, 8, 64))) + 0.1
    out, ns = resnet._nf_basic_block(x, blk, None, 1, cfg, True, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)
    assert set(ns) == set(blk)


@pytest.mark.slow
def test_nf_resnet_trains_and_state_is_stateless():
    """The nf rung trains (loss decreases over a few steps) with the
    standard step machinery; model_state carries no running stats."""
    from dml_cnn_cifar10_tpu.parallel import shardings

    data = DataConfig(normalize="scale")
    cfg = ModelConfig(name="resnet18", logit_relu=False, resnet_norm="nf")
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("resnet18")
    optim = OptimConfig(learning_rate=0.05)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, data, optim)
    state = step_lib.init_train_state(jax.random.key(0), model_def, cfg,
                                      data, optim, mesh, state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    rng = np.random.default_rng(0)
    images, labels = _batch(rng, n=32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(6):
        state, m = train(state, im, lb)
        losses.append(float(jax.device_get(m["loss"])))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    assert not jax.tree.leaves(state.model_state)  # truly stateless


def test_nf_weight_standardization_properties():
    """_ws_conv output has zero mean and 1/fan_in variance per output
    channel (times gain^2) — the scaled-WS contract."""
    w = jax.random.normal(jax.random.key(0), (3, 3, 16, 32)) * 2.0 + 0.5
    g = jnp.full((32,), 1.5)
    ws = resnet._ws_conv(w, g)
    mu = np.asarray(jnp.mean(ws, axis=(0, 1, 2)))
    np.testing.assert_allclose(mu, 0.0, atol=1e-6)
    var = np.asarray(jnp.var(ws, axis=(0, 1, 2)))
    fan_in = 3 * 3 * 16
    np.testing.assert_allclose(var, 1.5**2 / fan_in, rtol=1e-3)
