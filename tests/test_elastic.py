"""Elastic resume: checkpoints restore across mesh shapes and layouts.

The reference's only fault-tolerance story is restart-with-same---log_dir
(MTS restore, ``cifar10cnn.py:222``) on the SAME cluster shape. Here the
checkpoint stores placement-free host arrays, so a job can come back on a
different device count or a different parallelism layout — shrink 8→4
devices, switch dp→fsdp, switch replicated→tensor-parallel — and training
continues from the saved step with identical math.
"""

import jax
import numpy as np

from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
import pytest

DATA = DataConfig(normalize="scale")
CFG = ModelConfig(logit_relu=False)
OPT = OptimConfig(learning_rate=0.01, momentum=0.9)


def _setup(mesh, fsdp=False):
    model_def = get_model("cnn")
    sh = step_lib.train_state_shardings(mesh, model_def, CFG, DATA, OPT,
                                        fsdp=fsdp)
    train = step_lib.make_train_step(model_def, CFG, OPT, mesh,
                                     state_sharding=sh)
    return model_def, sh, train


def _batch(rng, n=16):
    return (rng.normal(0.5, 0.25, (n, 24, 24, 3)).astype(np.float32),
            rng.integers(0, 10, n).astype(np.int32))


@pytest.mark.slow
def test_resume_across_mesh_shapes(tmp_path, rng):
    """Train on an 8-device dp mesh, save; resume on a 4-device dp x tp
    mesh with fsdp — step count, params, and forward math all carry over."""
    images, labels = _batch(rng)

    mesh_a = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def, sh_a, train_a = _setup(mesh_a)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, CFG, DATA, OPT, mesh_a,
        state_sharding=sh_a)
    im, lb = mesh_lib.shard_batch(mesh_a, images, labels)
    for _ in range(3):
        state, _ = train_a(state, im, lb)
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=3)
    want_params = jax.device_get(state.params)

    # "Cluster shrank": 4 devices, different layout (tp=2 + fsdp).
    mesh_b = mesh_lib.build_mesh(
        ParallelConfig(data_axis=2, model_axis=2),
        devices=jax.devices()[:4])
    model_def, sh_b, train_b = _setup(mesh_b, fsdp=True)
    fresh = step_lib.init_train_state(
        jax.random.key(9), model_def, CFG, DATA, OPT, mesh_b,
        state_sharding=sh_b)
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), fresh,
                                           sharding=sh_b)
    assert int(jax.device_get(restored.step)) == 3
    for a, b in zip(jax.tree.leaves(want_params),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # The momentum buffers restored too (same layout rules as params) and
    # training continues: one more step on the new mesh equals the same
    # step taken on the old mesh, to fp32 tolerance.
    im_b, lb_b = mesh_lib.shard_batch(mesh_b, images, labels)
    cont_b, mb = train_b(restored, im_b, lb_b)
    cont_a, ma = train_a(state, im, lb)
    np.testing.assert_allclose(float(jax.device_get(ma["loss"])),
                               float(jax.device_get(mb["loss"])),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(cont_a.params)),
                    jax.tree.leaves(jax.device_get(cont_b.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    assert int(jax.device_get(cont_b.step)) == 4


@pytest.mark.slow
def test_trainer_resume_on_different_parallelism(tmp_path, data_cfg):
    """Driver-level: fit() on dp, resume fit() with fsdp+tp from the same
    log_dir (the restart-with-same---log_dir contract, now elastic)."""
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=4)
    r1 = Trainer(cfg).fit()
    assert r1.final_step == 4

    cfg2 = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=8)
    cfg2.parallel = ParallelConfig(data_axis=4, model_axis=2, fsdp=True)
    r2 = Trainer(cfg2).fit()
    assert r2.final_step == 8
    assert np.isfinite(r2.train_loss).all()
