"""Exact-resume data order: interrupted + resumed == uninterrupted.

The reference's restart contract (MTS, ``cifar10cnn.py:222``) restores
weights but replays the input stream from scratch — a resumed run sees
different data than an uninterrupted one. Here a checkpoint carries a
sidecar of cumulative stream consumption, and a resuming fit
fast-forwards its fresh iterators (``skip_batches``) to that position,
making the whole training trajectory BITWISE identical to a run that
never stopped. Prefetch lookahead regenerates — only consumption counts.
"""

import dataclasses

import jax
import numpy as np

from dml_cnn_cifar10_tpu.data import pipeline as pipe
from dml_cnn_cifar10_tpu.train.loop import Trainer
from tests.conftest import tiny_train_cfg
import pytest


def test_skip_batches_matches_consumed_stream(data_cfg):
    """skip(n) then draw == draw n+1 times, bit-for-bit — including the
    augmentation draws of the host decode path."""
    aug_cfg = dataclasses.replace(
        data_cfg, normalize="scale", random_crop=True, random_flip=True,
        random_brightness=20.0, random_contrast=0.4,
        use_native_loader=False)
    a = pipe.input_pipeline(aug_cfg, 16, train=True, seed=3)
    b = pipe.input_pipeline(aug_cfg, 16, train=True, seed=3)
    for _ in range(5):
        next(a)
    b.skip_batches(5, aug=True)
    for _ in range(3):  # stays aligned across further draws
        ba, bb = next(a), next(b)
        np.testing.assert_array_equal(ba.images, bb.images)
        np.testing.assert_array_equal(ba.labels, bb.labels)

    # Index-chunk streams align too (chunk draws == k single draws).
    c = pipe.input_pipeline(aug_cfg, 16, train=True, seed=9)
    d = pipe.input_pipeline(aug_cfg, 16, train=True, seed=9)
    c.next_index_chunk(4)
    d.skip_batches(4, aug=False)
    np.testing.assert_array_equal(c.next_index_chunk(2),
                                  d.next_index_chunk(2))


def _final_params(result):
    return [np.asarray(x) for x in
            jax.tree.leaves(jax.device_get(result.state.params))]


def _cfg(data_cfg, tmpdir, total_steps, **kw):
    cfg = tiny_train_cfg(data_cfg, tmpdir, total_steps=total_steps)
    cfg.output_every = 2
    cfg.eval_every = 4
    cfg.checkpoint_every = 4
    cfg.data = dataclasses.replace(
        cfg.data, random_crop=True, random_flip=True,
        use_native_loader=False)
    for key, val in kw.items():
        setattr(cfg, key, val)
    return cfg


@pytest.mark.slow
def test_resume_is_bitwise_identical_plain_path(tmp_path, data_cfg):
    """8 straight steps == 4 steps + restart + 4 steps, bit-for-bit, on
    the per-step host path (with host-side augmentation draws)."""
    straight = Trainer(_cfg(data_cfg, str(tmp_path / "a"), 8)).fit()

    Trainer(_cfg(data_cfg, str(tmp_path / "b"), 4)).fit()
    resumed = Trainer(_cfg(data_cfg, str(tmp_path / "b"), 8)).fit()
    assert resumed.final_step == 8
    for x, y in zip(_final_params(straight), _final_params(resumed)):
        np.testing.assert_array_equal(x, y)
    # The eval metrics match too (same shuffled test batches).
    np.testing.assert_array_equal(straight.test_accuracy[-1:],
                                  resumed.test_accuracy[-1:])


@pytest.mark.slow
def test_resume_is_bitwise_identical_resident_path(tmp_path, data_cfg):
    """Same contract on the chunked HBM-resident path (index streams)."""
    kw = dict(steps_per_dispatch=2)
    straight = Trainer(_cfg(data_cfg, str(tmp_path / "a"), 8, **kw)).fit()

    Trainer(_cfg(data_cfg, str(tmp_path / "b"), 4, **kw)).fit()
    resumed = Trainer(_cfg(data_cfg, str(tmp_path / "b"), 8, **kw)).fit()
    assert resumed.final_step == 8
    for x, y in zip(_final_params(straight), _final_params(resumed)):
        np.testing.assert_array_equal(x, y)


@pytest.mark.slow
def test_resume_without_sidecar_still_works(tmp_path, data_cfg):
    """A checkpoint without the sidecar (older run, or native loader)
    resumes fine — weights restore, the stream just restarts."""
    import os

    cfg = _cfg(data_cfg, str(tmp_path), 4)
    Trainer(cfg).fit()
    for name in os.listdir(cfg.log_dir):
        if name.startswith("data_state_"):
            os.remove(os.path.join(cfg.log_dir, name))
    resumed = Trainer(_cfg(data_cfg, str(tmp_path), 8)).fit()
    assert resumed.final_step == 8
    assert np.isfinite(resumed.train_loss).all()
