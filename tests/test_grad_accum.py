"""Gradient accumulation == full-batch math (mean of equal microbatches)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib


def _states_close(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(x)),
                                   np.asarray(jax.device_get(y)),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("model_name", ["cnn", "resnet18"])
@pytest.mark.slow
def test_accum_matches_full_batch(model_name, rng):
    model_def = get_model(model_name)
    model_cfg = ModelConfig(name=model_name, logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    mesh = mesh_lib.build_mesh(ParallelConfig())

    b = 32
    images = rng.normal(0.5, 0.25, (b, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, b).astype(np.int32)

    full = OptimConfig(learning_rate=0.05)
    accum = dataclasses.replace(full, grad_accum=4)

    state0 = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, full, mesh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    step_f = step_lib.make_train_step(model_def, model_cfg, full, mesh)
    st_f, m_f = step_f(jax.tree.map(jnp.copy, state0), im, lb)

    step_a = step_lib.make_train_step(model_def, model_cfg, accum, mesh)
    st_a, m_a = step_a(jax.tree.map(jnp.copy, state0), im, lb)

    # Loss/accuracy are means of equal-sized microbatch means. For BN
    # models the match is approximate BY DESIGN: batch-norm statistics are
    # computed per microbatch (8 samples) instead of the full batch (32),
    # which is standard grad-accumulation semantics, not an error.
    loss_rtol = 1e-4 if model_name == "cnn" else 2e-2
    np.testing.assert_allclose(float(m_f["loss"]), float(m_a["loss"]),
                               rtol=loss_rtol)
    np.testing.assert_allclose(float(m_f["accuracy"]),
                               float(m_a["accuracy"]), rtol=1e-6, atol=0.1)
    if model_name == "cnn":  # no BN: bitwise-comparable math
        _states_close(st_f, st_a)
    assert int(jax.device_get(st_a.step)) == 1  # ONE update for 4 micros


def test_accum_rejects_indivisible_batch(rng):
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    mesh = mesh_lib.build_mesh(ParallelConfig())
    optim = OptimConfig(grad_accum=3)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, optim, mesh)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    step = step_lib.make_train_step(model_def, model_cfg, optim, mesh)
    with pytest.raises(ValueError, match="divisible"):
        step(state, im, lb)
