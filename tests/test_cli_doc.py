"""docs/CLI.md must match the argparse definition (generated doc)."""

import os


def test_cli_doc_is_fresh():
    from tools.gen_cli_doc import render

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "docs", "CLI.md")
    assert os.path.isfile(path), "run: python tools/gen_cli_doc.py"
    with open(path) as f:
        on_disk = f.read()
    assert on_disk == render(), (
        "docs/CLI.md is stale — run: python tools/gen_cli_doc.py")
