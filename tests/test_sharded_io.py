"""Sharded fast-resume (ckpt/sharded.py): concurrent per-shard IO with
per-shard sha256 integrity sidecars — ISSUE-7's checkpoint half.

Pins: split-save/restore roundtrips bit-identical at any thread count
(concurrent == serial), a corrupted shard or sidecar (flip/truncate ×
shard/sidecar) triggers the newest→oldest fallback restore instead of a
crash, new manifests always carry ``shard_files`` while legacy
manifests restore via the loudly-flagged glob path, and the ``shard_io``
JSONL telemetry is schema-clean and summarized by the report CLI."""

import json
import os

import jax
import numpy as np
import pytest

from dml_cnn_cifar10_tpu import ckpt as ckpt_lib
from dml_cnn_cifar10_tpu.ckpt import sharded as sharded_lib
from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig, OptimConfig
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import step as step_lib


def _state(seed=0):
    return step_lib.init_train_state(
        jax.random.key(seed), get_model("cnn"), ModelConfig(), DataConfig(),
        OptimConfig())


class Events:
    def __init__(self):
        self.records = []

    def __call__(self, kind, **fields):
        self.records.append({"kind": kind, **fields})

    def of(self, op):
        return [r for r in self.records if r.get("op") == op]


class FakeLogger:
    """MetricsLogger-shaped sink for the checkpoint.py plumbing."""

    def __init__(self):
        self.records = []

    def log(self, kind, **fields):
        self.records.append({"kind": kind, **fields})


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(x)),
                                      np.asarray(jax.device_get(y)))


# ---------------------------------------------------------------------------
# split save + concurrent restore: bit-identical at every thread count
# ---------------------------------------------------------------------------

def test_split_save_restores_bit_identical_and_emits_shard_io(tmp_path):
    state = _state(seed=1)
    ev = Events()
    path = os.path.join(str(tmp_path), "ckpt_4.sharded")
    sharded_lib.save_sharded(path, state, threads=4, on_event=ev)
    # The payload split into multiple concurrently-written part files,
    # each with its own sha256 sidecar, plus the per-process index.
    names = sorted(n for n in os.listdir(path) if n.endswith(".msgpack"))
    assert len(names) > 1
    for n in names:
        assert os.path.isfile(os.path.join(path, n + ".sha256"))
    with open(os.path.join(path, "shard_0.files.json")) as f:
        assert sorted(json.load(f)["files"]) == names
    # Every data file produced a save-side shard_io record.
    assert sorted(r["shard"] for r in ev.of("save")) == names
    assert all(r["bytes"] > 0 and r["secs"] >= 0 for r in ev.of("save"))

    # Concurrent restore == serial restore == the saved state.
    serial = sharded_lib.restore_sharded(path, _state(seed=9), threads=1)
    conc = sharded_lib.restore_sharded(path, _state(seed=9), threads=4,
                                       on_event=ev)
    _assert_trees_equal(state, serial)
    _assert_trees_equal(serial, conc)
    restores = ev.of("restore")
    assert sorted(r["shard"] for r in restores) == names
    assert all(r["verify"] is True for r in restores)


def test_manifest_always_carries_shard_files(tmp_path):
    """ISSUE-7 satellite: new saves must always commit the exact file
    list — the glob fallback cannot tell stale shards of a crashed
    same-process-count save from a valid set."""
    for threads in (1, 4):
        path = os.path.join(str(tmp_path), f"ckpt_{threads}.sharded")
        sharded_lib.save_sharded(path, _state(), threads=threads)
        with open(os.path.join(path, sharded_lib.MANIFEST)) as f:
            meta = json.load(f)
        assert meta["shard_files"], meta
        for n in meta["shard_files"]:
            assert os.path.isfile(os.path.join(path, n))


def test_legacy_manifest_glob_fallback_warns_loudly(tmp_path, capsys):
    """A manifest WITHOUT shard_files (pre-ISSUE-7 save) still
    restores via the filename glob — with a stderr warning and a
    `legacy_glob` shard_io event, because that path cannot rule out
    stale shards from a crashed save at the SAME process count."""
    state = _state(seed=3)
    path = os.path.join(str(tmp_path), "ckpt_1.sharded")
    sharded_lib.save_sharded(path, state, threads=1)
    mpath = os.path.join(path, sharded_lib.MANIFEST)
    with open(mpath) as f:
        meta = json.load(f)
    del meta["shard_files"]
    with open(mpath, "w") as f:
        json.dump(meta, f)
    ev = Events()
    restored = sharded_lib.restore_sharded(path, _state(seed=8),
                                           on_event=ev)
    _assert_trees_equal(state, restored)
    assert ev.of("legacy_glob"), ev.records
    assert "legacy manifest" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# per-shard integrity: flip/truncate × shard/sidecar → fallback, no crash
# ---------------------------------------------------------------------------

def _corrupt(victim: str, mode: str) -> None:
    if mode == "flip":
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0xFF]))
    else:  # truncate
        with open(victim, "r+b") as f:
            f.truncate(os.path.getsize(victim) // 2)


@pytest.mark.parametrize("target", ["shard", "sidecar"])
@pytest.mark.parametrize("mode", ["flip", "truncate"])
def test_per_shard_corruption_falls_back_to_older(tmp_path, target, mode):
    """Per-shard sha256 verification catches a damaged shard OR sidecar
    even when the TOP-LEVEL sidecar is gone (a pre-integrity-era dir):
    the classified ValueError sends restore_checkpoint's newest→oldest
    walk back to the previous checkpoint instead of crashing."""
    s1 = _state(seed=1)
    ckpt_lib.save_checkpoint(str(tmp_path), s1, step=1, fmt="sharded")
    s2 = _state(seed=2)
    p2 = ckpt_lib.save_checkpoint(str(tmp_path), s2, step=2, fmt="sharded")
    # Remove the whole-checkpoint sidecar so ONLY the per-shard layer
    # stands between the corruption and the restore.
    os.remove(ckpt_lib.checkpoint.checksum_path(p2))
    shard = sorted(n for n in os.listdir(p2)
                   if n.endswith(".msgpack"))[0]
    victim = os.path.join(p2, shard)
    if target == "sidecar":
        victim += ".sha256"
    _corrupt(victim, mode)
    ev = FakeLogger()
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=9),
                                           logger=ev)
    _assert_trees_equal(s1, restored)
    # The damaged shard surfaced as a failed per-shard verify (flip or
    # truncate of the DATA file; a broken sidecar fails before any
    # bytes are trusted) and the walk fell back.
    if target == "shard":
        fails = [r for r in ev.records if r["kind"] == "shard_io"
                 and r.get("verify") is False]
        assert fails and fails[0]["shard"] == shard


def test_missing_per_shard_sidecar_is_back_compat(tmp_path):
    """Pre-per-shard-integrity checkpoints (no .sha256 next to the
    shard file) still restore; verify reports null, not failure."""
    state = _state(seed=5)
    path = os.path.join(str(tmp_path), "ckpt_1.sharded")
    sharded_lib.save_sharded(path, state, threads=1)
    os.remove(os.path.join(path, "shard_0.msgpack.sha256"))
    ev = Events()
    restored = sharded_lib.restore_sharded(path, _state(seed=7),
                                           on_event=ev)
    _assert_trees_equal(state, restored)
    assert [r["verify"] for r in ev.of("restore")] == [None]


# ---------------------------------------------------------------------------
# manager + schema + report plumbing
# ---------------------------------------------------------------------------

def test_manager_threads_shard_io_events_to_logger(tmp_path):
    log = FakeLogger()
    mgr = ckpt_lib.CheckpointManager(str(tmp_path), every_steps=1,
                                     fmt="sharded", logger=log,
                                     shard_io_threads=4)
    assert mgr.maybe_save(_state(), 1)
    saves = [r for r in log.records if r["kind"] == "shard_io"
             and r["op"] == "save"]
    assert len(saves) > 1  # split parts, one record each


def test_shard_io_stream_is_schema_clean_and_reported(tmp_path):
    """End-to-end over the real JSONL writer: save + restore shard_io
    rows pass the schema lint and telemetry_report prints the
    resume-time breakdown."""
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
    from tools import check_jsonl_schema, telemetry_report

    jsonl = os.path.join(str(tmp_path), "m.jsonl")
    log = MetricsLogger(jsonl)
    state = _state(seed=2)
    ckpt_lib.save_checkpoint(str(tmp_path), state, step=1, fmt="sharded",
                             logger=log, shard_io_threads=4)
    ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=6), logger=log)
    log.close()
    assert check_jsonl_schema.check_file(jsonl, strict=True) == []
    out = telemetry_report.summarize(jsonl)
    assert "shard io:" in out
    assert "save:" in out and "restore:" in out
    assert "verify failure" in out


def test_report_world_size_timeline_and_rejoins():
    """The cluster-health section renders shrink AND expand decisions
    as a world-size timeline plus rejoin announcements (fed synthetic
    records — the sim tests produce the real stream)."""
    import tempfile

    from tools import check_jsonl_schema, telemetry_report

    recs = [
        {"kind": "heartbeat", "t": 0.1, "task": 0, "step": 1,
         "process_id": 0, "phase": "train", "wallclock": 1000.1},
        {"kind": "peer_lost", "t": 1.0, "task": 0, "step": 15,
         "process_id": 1, "reason": "stale_heartbeat"},
        {"kind": "elastic_restart", "t": 1.1, "task": 0, "step": 15,
         "restore_step": 10, "world_size": 1, "epoch": 1, "attempt": 1,
         "lost": [1], "source": "disk"},
        {"kind": "host_rejoin", "t": 2.0, "task": 0, "step": 18,
         "process_id": 1, "epoch": 1},
        {"kind": "elastic_expand", "t": 2.1, "task": 0, "step": 19,
         "restore_step": 10, "world_size": 2, "epoch": 2, "attempt": 2,
         "joined": [1], "source": "disk"},
    ]
    assert check_jsonl_schema.check_lines(
        (json.dumps(r) for r in recs), strict=True) == []
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
        path = f.name
    try:
        out = telemetry_report.summarize(path)
    finally:
        os.unlink(path)
    assert "world-size timeline: 1[shrink@15] -> 2[expand@19]" in out
    assert "host_rejoin: process 1 announced at step 18" in out
    assert "elastic expand epoch 2" in out
