"""Resident (one-dispatch) eval == the host-fed padded sweep, exactly."""

import pytest
import jax
import numpy as np

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib


@pytest.mark.slow
def test_resident_full_eval_matches_host_sweep(rng):
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    mesh = mesh_lib.build_mesh(ParallelConfig())

    n = 200  # NOT a multiple of the batch: exercises the -1 padding
    images = rng.integers(0, 256, (n, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)

    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg,
        OptimConfig(), mesh)

    fn, total = step_lib.make_eval_resident(
        model_def, model_cfg, mesh, images, labels, data_cfg, batch_size=64)
    assert total == n
    resident_correct = int(jax.device_get(fn(state)))

    # Host decode + batched eval_step over the same split.
    from dml_cnn_cifar10_tpu.data import records as rec
    ev = step_lib.make_eval_step(model_def, model_cfg, mesh)
    host_correct = 0
    for start in range(0, n, 64):
        ims = rec.normalize(
            rec.center_crop(images[start:start + 64].astype(np.float32),
                            data_cfg.crop_height, data_cfg.crop_width),
            data_cfg.normalize)
        lbs = labels[start:start + 64]
        pad = 64 - ims.shape[0]
        if pad:
            ims = np.concatenate([ims, np.zeros((pad, *ims.shape[1:]),
                                                np.float32)])
            lbs = np.concatenate([lbs, np.full((pad,), -1, np.int32)])
        im, lb = mesh_lib.shard_batch(mesh, ims, lbs)
        host_correct += int(jax.device_get(ev(state, im, lb)["correct"]))

    assert resident_correct == host_correct
    assert 0 <= resident_correct <= n


def test_batch_eval_resident_matches_eval_step(rng):
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    mesh = mesh_lib.build_mesh(ParallelConfig())

    n, b = 256, 32
    images = rng.integers(0, 256, (n, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)
    idx = rng.integers(0, n, b).astype(np.int32)

    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg,
        OptimConfig(), mesh)

    repl = mesh_lib.replicated(mesh)
    fn = step_lib.make_batch_eval_resident(
        model_def, model_cfg, mesh, jax.device_put(images, repl),
        jax.device_put(labels, repl), data_cfg)
    acc_resident = float(jax.device_get(
        fn(state, jax.device_put(idx, mesh_lib.batch_sharding(mesh, 1)))))

    from dml_cnn_cifar10_tpu.data import records as rec
    ims = rec.normalize(
        rec.center_crop(images[idx].astype(np.float32),
                        data_cfg.crop_height, data_cfg.crop_width),
        data_cfg.normalize)
    ev = step_lib.make_eval_step(model_def, model_cfg, mesh)
    im, lb = mesh_lib.shard_batch(mesh, ims, labels[idx])
    acc_host = float(jax.device_get(ev(state, im, lb)["accuracy"]))

    np.testing.assert_allclose(acc_resident, acc_host, atol=1e-6)


def test_hostfed_full_sweep_is_single_fetch(tmp_path, data_cfg, monkeypatch):
    """The host-fed full-split sweep must accumulate its correct-count on
    device and fetch ONCE — a per-batch fetch is M host<->device round
    trips per eval (round-1 verdict weak #5)."""
    from dml_cnn_cifar10_tpu.data import pipeline as pipe
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path))
    cfg.eval_full_test_set = True
    trainer = Trainer(cfg)
    state = trainer.init_or_restore()
    test_it = pipe.input_pipeline(cfg.data, cfg.batch_size, train=False,
                                  seed=0)
    assert test_it.total_records > cfg.batch_size  # multi-batch sweep
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    acc = trainer.evaluate(state, test_it)
    assert 0.0 <= acc <= 1.0
    assert calls["n"] == 1, f"expected one drain fetch, saw {calls['n']}"
