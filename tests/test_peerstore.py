"""Diskless recovery (ISSUE 14, ckpt/peerstore.py): the peer-redundant
replica store — ring assignment, boundary pushes off the step path,
sidecar-verified reads with classified misses, coverage-mask assembly —
plus the acceptance sims: a 2-process lockstep host-loss drill with
``--peer_redundancy`` that recovers with ZERO disk checkpoint reads
(every restore-side ``shard_io`` record says ``source=peer``) and final
params bit-identical to the fault-free reference, and the paired
``replica_corrupt`` double fault that falls back to the untouched disk
walk, still bit-identical."""

import hashlib
import json
import os
import shutil

import numpy as np
import pytest

from dml_cnn_cifar10_tpu.ckpt import peerstore
from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.utils import faults as faults_lib

from tests.test_cluster import (FakeLogger, _ensure_data, _monitor,
                                _read_result, _spawn)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _payload(values, start=0):
    arr = np.asarray(values, dtype=np.float32)
    return {"params/w": [{"index": [[start, start + arr.shape[0]]],
                          "data": arr}]}


# ---------------------------------------------------------------------------
# ring assignment
# ---------------------------------------------------------------------------

def test_ring_assignment_world_sizes_1_to_4():
    # n=1 maps a host to itself: the store degrades to a no-op.
    assert peerstore.ring_successor(0, [0]) == 0
    assert peerstore.ring_predecessor(0, [0]) == 0
    for world in ([0, 1], [0, 1, 2], [3, 0, 2, 1]):
        ring = sorted(world)
        for pid in world:
            succ = peerstore.ring_successor(pid, world)
            assert succ in world and succ != pid
            assert peerstore.ring_predecessor(succ, world) == pid
        # A permutation: every host holds exactly one peer's replica.
        assert sorted(peerstore.ring_successor(p, world)
                      for p in world) == ring
    # Gaps in the id space (a shrunken world) still form a ring.
    assert peerstore.ring_successor(3, [0, 3]) == 0
    assert peerstore.ring_predecessor(0, [0, 3]) == 3


def test_single_host_store_is_a_legal_noop(tmp_path):
    store = peerstore.PeerReplicaStore(str(tmp_path), 0, [0])
    try:
        assert not store.enabled
        assert store.push_async(10, _payload([1.0])) is False
        assert store.push_state_async(10, object()) is False
        store.flush()
        assert store.pushes == 0 and store.replica_step == -1
        assert store.committed_steps(0) == []
    finally:
        store.close()


# ---------------------------------------------------------------------------
# push / retain / prune / idempotence / restart continuity
# ---------------------------------------------------------------------------

def test_push_retain_prune_roundtrip(tmp_path):
    log = FakeLogger()
    store = peerstore.PeerReplicaStore(str(tmp_path), 0, [0, 1], keep=2,
                                       log_fn=log.log)
    try:
        for step in (10, 20, 30):
            assert store.push_async(step, _payload([step, step + 1.0]))
            store.flush()   # one boundary at a time (the bounded
            # queue keeps only the 2 newest under a slow store)
        assert store.pushes == 3
        # Retention: keep=2 pruned the step-10 replica.
        assert store.committed_steps(0) == [20, 30]
        assert store.replica_step == 30
        got = store.read_replica(0, 30)
        np.testing.assert_array_equal(got["params/w"][0]["data"],
                                      [30.0, 31.0])
        pushes = [r for r in log.records if r["kind"] == "peer_replica"
                  and r["op"] == "push"]
        assert len(pushes) == 3 and all(r["ok"] for r in pushes)
        assert all(r["bytes"] > 0 for r in pushes)
        # A replayed boundary (supervisor restart re-saves step 30) is
        # idempotent: no double commit, no double count.
        store.push_async(30, _payload([30.0, 31.0]))
        store.flush()
        assert store.pushes == 3
        assert store.committed_steps(0) == [20, 30]
    finally:
        store.close()
    # Restart continuity: a rebuilt store (the supervisor's next
    # attempt) recovers its advertised replica_step from disk.
    again = peerstore.PeerReplicaStore(str(tmp_path), 0, [0, 1])
    try:
        assert again.replica_step == 30
    finally:
        again.close()


# ---------------------------------------------------------------------------
# read side: every miss is classified, never an unclassified crash
# ---------------------------------------------------------------------------

def test_read_misses_are_classified(tmp_path):
    store = peerstore.PeerReplicaStore(str(tmp_path), 0, [0, 1], keep=4)
    try:
        store.push_async(10, _payload([1.0, 2.0]))
        store.push_async(20, _payload([3.0, 4.0]))
        store.flush()
        # Absent step (stale: pruned or never pushed).
        with pytest.raises(peerstore.ReplicaMiss, match="missing or "
                                                        "stale"):
            store.read_replica(0, 99)
        # Absent owner.
        with pytest.raises(peerstore.ReplicaMiss):
            store.read_replica(7, 10)
        # Truncated payload: the per-shard sha256 sidecar catches it.
        d = store._step_dir(0, 10)
        part = sorted(n for n in os.listdir(d)
                      if n.endswith(".msgpack"))[0]
        victim = os.path.join(d, part)
        with open(victim, "r+b") as f:
            f.truncate(os.path.getsize(victim) // 2)
        with pytest.raises(peerstore.ReplicaMiss, match="verification"):
            store.read_replica(0, 10)
        # Undecodable commit marker.
        with open(os.path.join(store._step_dir(0, 20),
                               peerstore.INDEX), "w") as f:
            f.write("{not json")
        with pytest.raises(peerstore.ReplicaMiss, match="undecodable"):
            store.read_replica(0, 20)
    finally:
        store.close()


def test_legacy_sidecarless_replica_still_reads(tmp_path):
    """A replica without .sha256 sidecars (the sharded codec's legacy
    rule) decodes and restores — back-compat is pinned, not implied."""
    store = peerstore.PeerReplicaStore(str(tmp_path), 0, [0, 1])
    try:
        store.push_async(10, _payload([5.0, 6.0]))
        store.flush()
        d = store._step_dir(0, 10)
        for name in os.listdir(d):
            if name.endswith(".sha256"):
                os.remove(os.path.join(d, name))
        events = []
        got = store.read_replica(
            0, 10, on_event=lambda k, **f: events.append({"kind": k,
                                                          **f}))
        np.testing.assert_array_equal(got["params/w"][0]["data"],
                                      [5.0, 6.0])
        ios = [e for e in events if e["kind"] == "shard_io"]
        assert ios and all(e["verify"] is None and e["source"] == "peer"
                           for e in ios)
    finally:
        store.close()


# ---------------------------------------------------------------------------
# restore: coverage-mask assembly, reconstruct telemetry, zero disk
# ---------------------------------------------------------------------------

def test_restore_assembles_lost_hosts_shards(tmp_path):
    log = FakeLogger()
    s0 = peerstore.PeerReplicaStore(str(tmp_path), 0, [0, 1],
                                    log_fn=log.log)
    s1 = peerstore.PeerReplicaStore(str(tmp_path), 1, [0, 1])
    try:
        # A genuinely partitioned payload: owner 0 holds [0,2), the
        # (about-to-be-lost) owner 1 holds [2,4).
        s0.push_async(10, _payload([1.0, 2.0], start=0))
        s1.push_async(10, _payload([3.0, 4.0], start=2))
        s0.flush()
        s1.flush()
        target = {"params": {"w": np.zeros(4, np.float32)}}
        events = []
        out = s0.restore(target, 10, [0, 1], lost=[1],
                         on_event=lambda k, **f: events.append(
                             {"kind": k, **f}))
        np.testing.assert_array_equal(out["params"]["w"],
                                      [1.0, 2.0, 3.0, 4.0])
        # Own payload came from memory; every shard_io says peer.
        ios = [e for e in events if e["kind"] == "shard_io"]
        assert ios and all(e["source"] == "peer" for e in ios)
        assert any("memory" in e["shard"] for e in ios)
        recon = [r for r in log.records if r["kind"] == "peer_replica"
                 and r["op"] == "reconstruct"]
        assert recon and recon[0]["owner"] == 1 and recon[0]["ok"]
        # A missing replica is a classified miss, and a redundant
        # full-coverage second replica (the CPU-sim layout) dedupes.
        shutil.rmtree(s1._step_dir(1, 10))
        with pytest.raises(peerstore.ReplicaMiss):
            s0.restore(target, 10, [0, 1], lost=[1])
    finally:
        s0.close()
        s1.close()


def test_restore_rejects_partial_overlap_and_holes(tmp_path):
    s0 = peerstore.PeerReplicaStore(str(tmp_path), 0, [0, 1])
    s1 = peerstore.PeerReplicaStore(str(tmp_path), 1, [0, 1])
    try:
        target = {"params": {"w": np.zeros(4, np.float32)}}
        # [1,3) straddles the already-seen [0,2): a partial overlap is
        # ambiguous (which copy wins?) and must be refused, unlike the
        # fully-duplicate ranges redundant replicas legitimately carry.
        s0.push_async(10, _payload([1.0, 2.0], start=0))
        s1.push_async(10, _payload([1.5, 2.5], start=1))
        s0.flush()
        s1.flush()
        with pytest.raises(peerstore.ReplicaMiss,
                           match="partially-overlapping"):
            s0.restore(target, 10, [0, 1], lost=[1])
        shutil.rmtree(s1._step_dir(1, 10))
        s1.push_async(20, _payload([9.9], start=3))
        s1.flush()
        s0.push_async(20, _payload([1.0, 2.0], start=0))
        s0.flush()
        with pytest.raises(peerstore.ReplicaMiss, match="covered"):
            s0.restore(target, 20, [0, 1], lost=[1])
    finally:
        s0.close()
        s1.close()


# ---------------------------------------------------------------------------
# decision-file back-compat: old files have no `source`
# ---------------------------------------------------------------------------

def test_decision_source_roundtrip_and_backcompat(tmp_path):
    c = cluster_lib.RestartCoordinator(str(tmp_path / "new"))
    c.record(cluster_lib.RestartDecision(
        epoch=1, world_size=1, restore_step=10, survivors=[0],
        source="peer"))
    d = c.read()
    assert d is not None and d.source == "peer"
    # A pre-ISSUE-14 decision file (no `source` key, no sidecar) still
    # decodes — and restores from disk, exactly as it always did.
    legacy = cluster_lib.RestartCoordinator(str(tmp_path / "old"))
    with open(legacy.path, "w") as f:
        json.dump({"epoch": 3, "world_size": 2, "restore_step": 20,
                   "survivors": [0, 1]}, f)
    d = legacy.read()
    assert d is not None and d.epoch == 3 and d.source == "disk"


# ---------------------------------------------------------------------------
# replica fault kinds: defer-until-committed, then classified damage
# ---------------------------------------------------------------------------

def test_replica_faults_defer_until_a_replica_is_committed(tmp_path):
    log = FakeLogger()
    # Without a cluster the drill fails loudly, like the other
    # cluster-backed kinds.
    with pytest.raises(faults_lib.InjectedFault, match="cluster"):
        faults_lib.FaultInjector.from_spec(
            "replica_corrupt@1").step_hook(2, None, "/tmp")
    with pytest.raises(faults_lib.InjectedFault, match="cluster"):
        faults_lib.FaultInjector.from_spec(
            "replica_stale@1").step_hook(2, None, "/tmp")
    mon = _monitor(tmp_path, 0)
    store = peerstore.PeerReplicaStore(str(mon.cluster_dir), 0, [0, 1],
                                       keep=4)
    try:
        inj = faults_lib.FaultInjector.from_spec("replica_corrupt@5")
        # Nothing committed yet: the event stays pending (fires later,
        # like ckpt_corrupt before the first save).
        inj.step_hook(5, None, str(tmp_path), logger=log, cluster=mon)
        assert [e.kind for e in inj.pending()] == ["replica_corrupt"]
        store.push_async(10, _payload([1.0, 2.0]))
        store.flush()
        inj.step_hook(11, None, str(tmp_path), logger=log, cluster=mon)
        assert inj.pending() == []
        assert [r["fault"] for r in log.records
                if r["kind"] == "fault"] == ["replica_corrupt"]
        with pytest.raises(peerstore.ReplicaMiss, match="verification"):
            store.read_replica(0, 10)
    finally:
        store.close()
        mon.close()


def test_replica_stale_deletes_newest_but_counters_still_advertise(
        tmp_path):
    log = FakeLogger()
    mon = _monitor(tmp_path, 0)
    store = peerstore.PeerReplicaStore(str(mon.cluster_dir), 0, [0, 1],
                                       keep=4)
    try:
        store.push_async(10, _payload([1.0]))
        store.push_async(20, _payload([2.0]))
        store.flush()
        inj = faults_lib.FaultInjector.from_spec("replica_stale@21")
        inj.step_hook(21, None, str(tmp_path), logger=log, cluster=mon)
        assert inj.pending() == []
        # Newest gone, older kept — but the store's counter (and thus
        # the heartbeat advertisement) still says 20: exactly the
        # decide-peer-then-miss situation the fault exists to drill.
        assert store.committed_steps(0) == [10]
        assert store.replica_step == 20
        with pytest.raises(peerstore.ReplicaMiss):
            store.read_replica(0, 20)
    finally:
        store.close()
        mon.close()


def test_replica_kinds_live_only_in_the_peer_vocabulary():
    """Scheduling a replica fault in a redundancy-OFF scenario would
    guarantee a fault_pairing violation (it could never fire), so the
    kinds exist only in CHAOS_PEER_VOCABULARY."""
    peer_kinds = {t.partition("@")[0]
                  for t in faults_lib.CHAOS_PEER_VOCABULARY}
    assert {"replica_corrupt", "replica_stale"} <= peer_kinds
    # The peer vocabulary extends the cluster drill's.
    assert set(faults_lib.CHAOS_CLUSTER_VOCABULARY) <= set(
        faults_lib.CHAOS_PEER_VOCABULARY)
    for vocab in (faults_lib.CHAOS_VOCABULARY,
                  faults_lib.CHAOS_CLUSTER_VOCABULARY,
                  faults_lib.CHAOS_EXPAND_VOCABULARY):
        assert not any(t.startswith("replica_") for t in vocab)


# ---------------------------------------------------------------------------
# restore walk budget (--restore_deadline_s) + walk_ms telemetry
# ---------------------------------------------------------------------------

def test_restore_walk_reports_walk_ms_and_enforces_deadline(tmp_path):
    from dml_cnn_cifar10_tpu import ckpt as ckpt_lib
    from dml_cnn_cifar10_tpu.train.supervisor import classify_failure
    from tests.test_checkpoint import _state

    s1 = _state(seed=1)
    ckpt_lib.save_checkpoint(str(tmp_path), s1, step=1)
    p2 = ckpt_lib.save_checkpoint(str(tmp_path), _state(seed=2), step=2)
    with open(p2, "r+b") as f:
        f.truncate(os.path.getsize(p2) // 2)
    walks = []
    restored = ckpt_lib.restore_checkpoint(
        str(tmp_path), _state(seed=9),
        on_fallback=lambda step, path, why, walk_ms: walks.append(
            walk_ms))
    np.testing.assert_array_equal(
        np.asarray(restored.params["conv1"]["kernel"]),
        np.asarray(s1.params["conv1"]["kernel"]))
    assert walks and walks[0] >= 0.0
    # An impossible budget raises the CLASSIFIED ckpt_restore error
    # (the supervisor's bounded-retry policy takes over) instead of
    # walking a slow store forever.
    with pytest.raises(ValueError, match="deadline") as ei:
        ckpt_lib.restore_checkpoint(str(tmp_path), _state(seed=9),
                                    deadline_s=1e-9)
    assert classify_failure(ei.value) == "ckpt_restore"
    # deadline_s=0 (the default) is off: the walk above succeeded.


# ---------------------------------------------------------------------------
# the pin: replication rides checkpoint boundaries, never the step path
# ---------------------------------------------------------------------------

def test_pushes_ride_checkpoint_boundaries_not_steps(data_cfg,
                                                     tmp_path):
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=40)
    cfg.checkpoint_every = 10
    cfg.keep_checkpoints = 20     # retention must not eat the count
    cfg.metrics_jsonl = os.path.join(str(tmp_path), "m.jsonl")
    cfg.parallel.cluster_dir = str(tmp_path / "cluster")
    cfg.parallel.num_processes = 2
    cfg.parallel.process_id = 0
    cfg.parallel.peer_redundancy = True
    # The lone peer never beats in this test; don't declare it dead.
    cfg.parallel.straggler_after_s = 60.0
    cfg.parallel.peer_dead_after_s = 600.0
    trainer = Trainer(cfg)
    result = trainer.fit()
    assert result.final_step == 40
    store = trainer.cluster.peer_store
    assert store is not None and store.enabled
    saved = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
    # One push per committed checkpoint boundary — NOT one per step.
    assert saved and store.pushes == len(saved) < 40
    assert store.committed_steps(0)[-1] == max(saved)
    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    pushed = [r for r in recs if r["kind"] == "peer_replica"
              and r["op"] == "push" and r["ok"]]
    assert len(pushed) == store.pushes
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl,
                                         strict=True) == []


# ---------------------------------------------------------------------------
# the acceptance sims: 2-process lockstep host loss under
# --peer_redundancy with the SHARDED codec (so any disk read would be
# visible as a shard_io source=disk record)
# ---------------------------------------------------------------------------

WORKER = """
import json, sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
task, n, data_dir, log_dir, cluster_dir, fault_spec, total_steps = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6], int(sys.argv[7]))
import hashlib
import numpy as np
import jax
from dml_cnn_cifar10_tpu.config import TrainConfig, DataConfig
from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised

cfg = TrainConfig(
    batch_size=32, total_steps=total_steps, output_every=10,
    eval_every=20, checkpoint_every=10, log_dir=log_dir,
    metrics_jsonl=f"{log_dir}/metrics.jsonl",
    data=DataConfig(dataset="synthetic", data_dir=data_dir,
                    synthetic_train_records=256, synthetic_test_records=64,
                    normalize="scale", use_native_loader=False),
)
cfg.model.logit_relu = False
cfg.optim.learning_rate = 0.05
cfg.ckpt_format = "sharded"
cfg.keep_checkpoints = 20   # retention must not prune the restore point
cfg.recovery_backoff_s = 0.05
cfg.recovery_backoff_max_s = 0.2
cfg.fault_spec = fault_spec or None
cfg.parallel.process_id = task
cfg.parallel.num_processes = n
if cluster_dir:
    cfg.parallel.cluster_dir = cluster_dir
    cfg.parallel.cluster_lockstep = True
    cfg.parallel.peer_redundancy = True
    cfg.parallel.heartbeat_interval_s = 0.1
    cfg.parallel.straggler_after_s = 0.4
    cfg.parallel.peer_dead_after_s = 2.5
    cfg.parallel.collective_timeout_s = 300.0

res = fit_supervised(cfg, task_index=task)
if res is None:
    print("RESULT " + json.dumps({"task": task, "fenced": True}))
    sys.exit(0)
h = hashlib.sha256()
for leaf in jax.tree.leaves(jax.device_get(res.state.params)):
    h.update(np.ascontiguousarray(leaf).tobytes())
print("RESULT " + json.dumps({
    "task": task, "fenced": False, "final_step": res.final_step,
    "digest": h.hexdigest()}))
"""

_REF_DIGEST_CACHE = {}


def _sharded_ckpt_key(ckpt_dir):
    h = hashlib.sha256()
    for name in sorted(os.listdir(ckpt_dir)):
        h.update(name.encode())
        with open(os.path.join(ckpt_dir, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _reference_digest(tmp_path, data_dir, survivor_logs, restore_step,
                      script):
    """Digest of a fault-free single-process run restored from the same
    SHARDED checkpoint the survivor restarted from. Cached on the
    checkpoint bytes: both peer scenarios restart from an identical
    step-10 checkpoint, so one reference run serves both."""
    ckpt = os.path.join(survivor_logs, f"ckpt_{restore_step}.sharded")
    key = _sharded_ckpt_key(ckpt)
    if key in _REF_DIGEST_CACHE:
        return _REF_DIGEST_CACHE[key]
    ref_logs = str(tmp_path / "ref_logs")
    os.makedirs(ref_logs)
    shutil.copytree(ckpt, os.path.join(
        ref_logs, f"ckpt_{restore_step}.sharded"))
    for name in (f"ckpt_{restore_step}.sharded.sha256",
                 f"data_state_{restore_step}.json"):
        src = os.path.join(survivor_logs, name)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(ref_logs, name))
    proc = _spawn(script, [0, 1, data_dir, ref_logs, "", "", 40],
                  tmp_path)
    out = proc.communicate(timeout=300)[0]
    assert proc.returncode == 0, f"reference run failed:\n{out}"
    res = _read_result(out)
    assert res["final_step"] == 40
    _REF_DIGEST_CACHE[key] = res["digest"]
    return res["digest"]


def _run_peer_scenario(tmp_path, data_cfg, survivor_spec):
    """Two lockstep sim hosts on the sharded codec with peer redundancy
    ON; task 1 dies abruptly at 15 (one boundary past the step-10 save
    and push), task 0 optionally carries a replica fault. Returns
    (survivor result, survivor records, reference digest)."""
    from dml_cnn_cifar10_tpu.utils.faults import EXIT_HOST_LOST

    data_dir = _ensure_data(tmp_path, data_cfg)
    cluster_dir = str(tmp_path / "cluster")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    logs = [str(tmp_path / f"logs_{t}") for t in (0, 1)]
    specs = [survivor_spec, "host_lost@15"]
    procs = [
        _spawn(script, [t, 2, data_dir, logs[t], cluster_dir, specs[t],
                        40], tmp_path)
        for t in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert procs[0].returncode == 0, f"survivor failed:\n{outs[0]}"
    assert procs[1].returncode == EXIT_HOST_LOST, \
        f"lost host exit {procs[1].returncode}:\n{outs[1]}"
    survivor = _read_result(outs[0])
    assert not survivor["fenced"] and survivor["final_step"] == 40

    with open(os.path.join(logs[0], "metrics.jsonl")) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_lines(
        (json.dumps(r) for r in recs), strict=True) == []
    er = [r for r in recs if r["kind"] == "elastic_restart"]
    assert er and er[0]["world_size"] == 1 and er[0]["restore_step"] == 10
    # The chief chose the peer source (every old-world host had pushed
    # its step-10 replica and advertised it over the heartbeats).
    assert er[0]["source"] == "peer"
    decides = [r for r in recs if r["kind"] == "peer_replica"
               and r["op"] == "decide"]
    assert decides and decides[0]["ok"] and decides[0]["step"] == 10

    ref = _reference_digest(tmp_path, data_dir, logs[0], 10, script)
    return survivor, recs, ref


def test_sim_diskless_recovery_zero_disk_reads_bit_identical(
        tmp_path, data_cfg):
    """ISSUE-14 acceptance: host_lost@15 under --peer_redundancy — the
    survivor restores its own live shards from memory, reconstructs the
    lost host's from its pushed replica, re-enters with ZERO disk
    checkpoint reads (every restore-side shard_io says source=peer),
    and finishes bit-identical to the fault-free reference."""
    survivor, recs, ref = _run_peer_scenario(tmp_path, data_cfg, "")
    # The lost host's shards were rebuilt from its replica.
    recon = [r for r in recs if r["kind"] == "peer_replica"
             and r["op"] == "reconstruct"]
    assert recon and recon[0]["owner"] == 1 and recon[0]["ok"]
    # ZERO checkpoint reads: every restore-side shard_io record came
    # from the peer store; saves (and only saves) touched disk.
    restores = [r for r in recs if r["kind"] == "shard_io"
                and r["op"] != "save"]
    assert restores and all(r["source"] == "peer" for r in restores)
    assert any(r["kind"] == "shard_io" and r["op"] == "save"
               and r["source"] == "disk" for r in recs)
    # No disk fallback was needed, and the walk never skipped anything.
    assert not [r for r in recs if r["kind"] == "peer_replica"
                and r["op"] == "fallback"]
    assert not [r for r in recs if r["kind"] == "ckpt_fallback"]
    assert survivor["digest"] == ref
    # The report surfaces the restore source.
    from tools import telemetry_report
    out = telemetry_report.summarize(
        os.path.join(str(tmp_path), "logs_0", "metrics.jsonl"))
    assert "restore source" in out
    data = telemetry_report.summarize_json(
        os.path.join(str(tmp_path), "logs_0", "metrics.jsonl"))
    src = data["resilience"]["restore_source"]
    assert src["peer_restores"] == 1 and src["disk_restores"] == 0
    assert src["reconstructs"] == 1


def test_sim_replica_corrupt_falls_back_to_disk_bit_identical(
        tmp_path, data_cfg):
    """ISSUE-14 acceptance (double fault): the replica set is corrupted
    before the host dies. The decision still says peer (beats advertise
    the pushed step), the restore's sidecar verify classifies the miss,
    an explicit peer_replica fallback record lands, and the UNTOUCHED
    disk walk completes the recovery — still bit-identical."""
    survivor, recs, ref = _run_peer_scenario(tmp_path, data_cfg,
                                             "replica_corrupt@14")
    inj = [r for r in recs if r["kind"] == "fault"
           and r["fault"] == "replica_corrupt" and r["injected"]]
    assert inj
    fallbacks = [r for r in recs if r["kind"] == "peer_replica"
                 and r["op"] == "fallback"]
    assert fallbacks and fallbacks[0]["ok"] is False
    assert "verification" in fallbacks[0]["error"]
    # The disk restore actually ran — visible as source=disk shard_io.
    assert [r for r in recs if r["kind"] == "shard_io"
            and r["op"] == "restore" and r["source"] == "disk"]
    assert survivor["digest"] == ref
