"""Rematerialization: same math (bitwise grads), less activation memory."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
import pytest

DATA = DataConfig(crop_height=32, crop_width=32, normalize="scale")
VIT = ModelConfig(name="vit_tiny", pool="mean", logit_relu=False,
                  vit_depth=3, vit_dim=64, vit_heads=2, patch_size=4)


@pytest.mark.slow
def test_remat_same_training_math(rng):
    images = rng.normal(0.5, 0.25, (8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    mesh = mesh_lib.build_mesh(ParallelConfig())
    model_def = get_model("vit_tiny")
    optim = OptimConfig(learning_rate=0.01)

    def run(cfg):
        state = step_lib.init_train_state(
            jax.random.key(0), model_def, cfg, DATA, optim, mesh)
        train = step_lib.make_train_step(model_def, cfg, optim, mesh)
        im, lb = mesh_lib.shard_batch(mesh, images, labels)
        st, m = train(state, im, lb)
        return jax.device_get(st.params), float(m["loss"])

    p_plain, l_plain = run(VIT)
    p_remat, l_remat = run(dataclasses.replace(VIT, remat=True))
    assert l_plain == l_remat
    for a, b in zip(jax.tree.leaves(p_plain), jax.tree.leaves(p_remat)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_remat_composes_with_sp(rng):
    images = rng.normal(0.5, 0.25, (8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=2, seq_axis=4))
    cfg = dataclasses.replace(VIT, remat=True)
    model_def = get_model("vit_tiny")
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    st, m = train(state, *mesh_lib.shard_batch(mesh, images, labels))
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_remat_composes_with_pp(rng):
    """remat wraps the pipeline stage body too (not silently ignored)."""
    images = rng.normal(0.5, 0.25, (8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=4, pipe_axis=2))
    cfg = dataclasses.replace(VIT, remat=True, vit_depth=2)
    model_def = get_model("vit_tiny")
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    st, m = train(state, *mesh_lib.shard_batch(mesh, images, labels))
    assert np.isfinite(float(m["loss"]))

    # Same math as without remat.
    cfg0 = dataclasses.replace(cfg, remat=False)
    state0 = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg0, DATA, optim, mesh,
        state_sharding=sh)
    train0 = step_lib.make_train_step(model_def, cfg0, optim, mesh,
                                      state_sharding=sh)
    st0, m0 = train0(state0, *mesh_lib.shard_batch(mesh, images, labels))
    assert float(m0["loss"]) == float(m["loss"])


@pytest.mark.slow
def test_remat_resnet_same_training_math(rng):
    """--remat on the ResNet family (per-residual-block jax.checkpoint):
    bitwise-identical step to the plain path, BN state included."""
    images = rng.normal(0.5, 0.25, (8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    mesh = mesh_lib.build_mesh(ParallelConfig())
    model_def = get_model("resnet18")
    optim = OptimConfig(learning_rate=0.01)
    base = ModelConfig(name="resnet18", logit_relu=False)

    def run(cfg):
        state = step_lib.init_train_state(
            jax.random.key(0), model_def, cfg, DATA, optim, mesh)
        train = step_lib.make_train_step(model_def, cfg, optim, mesh)
        im, lb = mesh_lib.shard_batch(mesh, images, labels)
        st, m = train(state, im, lb)
        return jax.device_get((st.params, st.model_state)), float(m["loss"])

    s_plain, l_plain = run(base)
    s_remat, l_remat = run(dataclasses.replace(base, remat=True))
    assert l_plain == l_remat
    for a, b in zip(jax.tree.leaves(s_plain), jax.tree.leaves(s_remat)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
