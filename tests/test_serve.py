"""Serving subsystem: dynamic micro-batching engine (``serve/``).

Covers the batcher's contract from three angles: pure queueing behavior
against a stub engine (bucket selection, coalescing, padding isolation,
deadline/queue shedding — no jax in the loop), exactness against the
real jitted forward on CPU (serve == direct, padding stripped), and the
``tools/loadgen.py`` closed-loop smoke that exercises the whole stack
including the JSONL ``serve`` schema.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.serve import (MicroBatcher, ServeMetrics,
                                       ServingEngine, ShedError)


class StubEngine:
    """Deterministic fake device: logits row i = [sum(image i), lane i].

    Row values depend ONLY on that row's image (plus its lane index, to
    catch scatter misalignment), so any cross-lane leak or misrouting
    shows up as a wrong sum. Records every dispatched batch shape.
    """

    image_shape = (2, 2, 1)

    def __init__(self, forward_s: float = 0.0, gate: threading.Event = None):
        self.batch_sizes = []
        self.forward_s = forward_s
        self.gate = gate

    def warmup(self, buckets):
        return {}

    def forward_timed(self, batch):
        if self.gate is not None:
            self.gate.wait(timeout=10)
        if self.forward_s:
            time.sleep(self.forward_s)
        self.batch_sizes.append(batch.shape[0])
        logits = np.stack(
            [np.array([float(batch[i].sum()), float(i)], np.float32)
             for i in range(batch.shape[0])])
        return logits, self.forward_s


def _images(n, shape=(2, 2, 1), seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (n, *shape), dtype=np.uint8)


def test_bucket_selection_and_padding_isolation():
    """Requests coalesce into the smallest fitting bucket; every result
    is a function of its own image only (padded lanes never leak)."""
    eng = StubEngine()
    with MicroBatcher(eng, buckets=(1, 4, 16), batch_window_s=0.2,
                      warmup=False) as b:
        imgs = _images(6)
        futs = [b.submit(im) for im in imgs]
        res = [f.result(timeout=10) for f in futs]
    # 6 requests submitted well inside one 200 ms window -> one batch,
    # padded up to the smallest bucket that fits (16, not 4).
    assert eng.batch_sizes == [16]
    for i, (im, r) in enumerate(zip(imgs, res)):
        assert r[0] == float(im.sum())    # own image's payload
        assert r[1] == float(i)           # own lane (order preserved)
    snap = b.metrics.cumulative()
    assert snap["completed"] == 6
    assert snap["batches"] == 1
    assert snap["batch_fill"] == pytest.approx(6 / 16)


def test_oversized_burst_splits_at_max_bucket():
    eng = StubEngine()
    with MicroBatcher(eng, buckets=(1, 4), batch_window_s=0.2,
                      warmup=False) as b:
        futs = [b.submit(im) for im in _images(6, seed=1)]
        for f in futs:
            f.result(timeout=10)
    # Max bucket is 4: a 6-burst is two dispatches (4 real + 2 real
    # padded to 4) — every device shape is a pre-compiled bucket, never
    # a fresh size-6 compile.
    assert eng.batch_sizes == [4, 4]


def test_bad_submit_and_bad_buckets_rejected():
    eng = StubEngine()
    with MicroBatcher(eng, buckets=(1,), warmup=False) as b:
        with pytest.raises(ValueError, match="shape"):
            b.submit(np.zeros((3, 3, 1), np.uint8))
        with pytest.raises(ValueError, match="shape"):
            b.submit(np.zeros((2, 2, 1), np.int32))
    with pytest.raises(ValueError, match="buckets"):
        MicroBatcher(eng, buckets=(4, 1), warmup=False)
    with pytest.raises(ValueError, match="buckets"):
        MicroBatcher(eng, buckets=(), warmup=False)


def test_queue_full_sheds_at_admission():
    """Bounded queue: with the worker wedged in a dispatch and the
    queue at depth, submit fails immediately — load is shed at the
    door, not buffered into unbounded latency."""
    gate = threading.Event()
    eng = StubEngine(gate=gate)
    metrics = ServeMetrics()
    b = MicroBatcher(eng, buckets=(1,), max_queue_depth=1,
                     batch_window_s=0.0, metrics=metrics, warmup=False)
    try:
        f1 = b.submit(_images(1)[0])          # dequeued, wedged on gate
        time.sleep(0.1)                       # let the worker pick it up
        b.submit(_images(1)[0])               # fills the 1-deep queue
        with pytest.raises(ShedError) as exc:
            b.submit(_images(1)[0])
        assert exc.value.reason == "queue_full"
        assert metrics.cumulative()["shed_queue"] == 1
    finally:
        gate.set()
        b.close()
    assert f1.result(timeout=10) is not None


def test_deadline_expired_requests_shed_at_dispatch():
    """A request whose deadline passes while queued fails with
    ShedError instead of occupying device lanes."""
    gate = threading.Event()
    eng = StubEngine(gate=gate)
    metrics = ServeMetrics()
    b = MicroBatcher(eng, buckets=(1,), max_queue_depth=8,
                     batch_window_s=0.0, metrics=metrics, warmup=False)
    try:
        b.submit(_images(1)[0])               # wedges the worker
        time.sleep(0.05)
        doomed = b.submit(_images(1)[0], deadline_s=0.01)
        time.sleep(0.05)                      # deadline passes in queue
    finally:
        gate.set()
        b.close()
    with pytest.raises(ShedError, match="deadline"):
        doomed.result(timeout=10)
    snap = metrics.cumulative()
    assert snap["shed_deadline"] == 1
    assert snap["completed"] == 1             # the wedged one finished


@pytest.fixture(scope="module")
def cnn_engine():
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    params = model_def.init(jax.random.key(0), model_cfg, data_cfg)
    return ServingEngine.from_params(model_def, model_cfg, data_cfg,
                                     params)


def test_serve_equals_direct_forward(cnn_engine, rng):
    """Acceptance: batcher output is EXACTLY the direct jitted forward
    on the same inputs — same bucket, padding stripped."""
    imgs = rng.integers(0, 256, (5, 32, 32, 3)).astype(np.uint8)
    with MicroBatcher(cnn_engine, buckets=(1, 8),
                      batch_window_s=0.25) as b:
        futs = [b.submit(im) for im in imgs]
        served = [f.result(timeout=60) for f in futs]
    assert b.metrics.cumulative()["batches"] == 1  # coalesced: bucket 8

    padded = np.zeros((8, 32, 32, 3), np.uint8)
    padded[:5] = imgs
    direct, _ = cnn_engine.forward_timed(padded)
    for i in range(5):
        assert np.array_equal(served[i], direct[i])


def test_padding_content_cannot_leak(cnn_engine, rng):
    """Same real rows, different pad garbage -> same real outputs (rows
    are independent through the eval forward)."""
    imgs = rng.integers(0, 256, (3, 32, 32, 3)).astype(np.uint8)
    zeros_pad = np.zeros((8, 32, 32, 3), np.uint8)
    zeros_pad[:3] = imgs
    full_pad = np.full((8, 32, 32, 3), 255, np.uint8)
    full_pad[:3] = imgs
    a, _ = cnn_engine.forward_timed(zeros_pad)
    c, _ = cnn_engine.forward_timed(full_pad)
    np.testing.assert_allclose(a[:3], c[:3], rtol=1e-6, atol=1e-6)


def test_serve_from_artifact_matches_live(cnn_engine, rng):
    """The artifact path of the engine serves the same numbers as the
    live-params path, through the batcher."""
    from dml_cnn_cifar10_tpu import export as export_lib

    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    params = model_def.init(jax.random.key(0), model_cfg, data_cfg)
    blob = export_lib.export_forward(model_def, model_cfg, data_cfg,
                                     params, platforms=["cpu"])
    art = ServingEngine.from_artifact(blob=blob)
    assert art.image_shape == (32, 32, 3)

    img = rng.integers(0, 256, (32, 32, 3)).astype(np.uint8)
    with MicroBatcher(art, buckets=(1,)) as b:
        got = b.submit(img).result(timeout=60)
    want, _ = cnn_engine.forward_timed(img[None])
    np.testing.assert_allclose(got, want[0], rtol=1e-5, atol=1e-6)


def test_serve_metrics_jsonl_schema(tmp_path):
    """serve / serve_done records pass the tier-1 schema lint."""
    from tools import check_jsonl_schema

    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

    eng = StubEngine()
    metrics = ServeMetrics()
    with MicroBatcher(eng, buckets=(1, 4), batch_window_s=0.05,
                      metrics=metrics, warmup=False) as b:
        for f in [b.submit(im) for im in _images(3, seed=2)]:
            f.result(timeout=10)
    path = str(tmp_path / "serve.jsonl")
    logger = MetricsLogger(jsonl_path=path)
    metrics.emit(logger)            # window record mid-run
    metrics.emit(logger, final=True)
    logger.close()
    assert check_jsonl_schema.check_file(path, strict=True) == []
    kinds = [json.loads(l)["kind"] for l in open(path)]
    assert kinds == ["serve", "serve", "serve_done"]


def test_cli_serve_flags_plumb_into_config():
    from dml_cnn_cifar10_tpu.cli.main import build_parser, config_from_args

    args, _ = build_parser().parse_known_args([
        "--mode", "serve", "--serve_buckets", "2,16",
        "--serve_queue_depth", "7", "--serve_batch_window_ms", "3.5",
        "--serve_deadline_ms", "40", "--serve_port", "0",
        "--serve_artifact", "/x/model.jaxexport"])
    cfg = config_from_args(args)
    assert cfg.serve.buckets == (2, 16)
    assert cfg.serve.max_queue_depth == 7
    assert cfg.serve.batch_window_ms == 3.5
    assert cfg.serve.deadline_ms == 40
    assert cfg.serve.port == 0
    assert cfg.serve.artifact_path == "/x/model.jaxexport"


def test_loadgen_closed_loop_smoke(tmp_path):
    """Acceptance: a closed-loop loadgen run on the CPU engine writes a
    report with latency percentiles and shed fraction (~2 s)."""
    import tools.loadgen as loadgen

    report_path = str(tmp_path / "report.json")
    jsonl_path = str(tmp_path / "serve.jsonl")
    assert loadgen.main([
        "--mode", "closed", "--concurrency", "2",
        "--duration_s", "1.0", "--buckets", "1,8",
        "--report", report_path, "--metrics_jsonl", jsonl_path]) == 0

    with open(report_path) as f:
        report = json.load(f)
    assert report["completed"] > 0
    assert report["requests"] == report["completed"] + report["shed"]
    assert 0.0 <= report["shed_fraction"] <= 1.0
    assert report["achieved_qps"] > 0
    for q in ("p50", "p95", "p99"):
        assert report["latency_ms"][q] > 0
    assert 0.0 < report["batch_fill"] <= 1.0

    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(jsonl_path, strict=True) == []


# ---- graceful SIGTERM/stop drain (serve/server.py) ----

def test_batcher_drain_completes_queued_work():
    eng = StubEngine()
    b = MicroBatcher(eng, buckets=(1, 4), max_queue_depth=64,
                     batch_window_s=0.001)
    futs = [b.submit(img) for img in _images(8)]
    assert b.drain(timeout=5.0) is True
    assert all(f.done() and f.exception() is None for f in futs)


def test_batcher_drain_deadline_sheds_backlog():
    """A backlog slower than the drain deadline: whatever completes in
    time completes, the rest is shed with ShedError — never a future
    left unresolved."""
    eng = StubEngine(forward_s=0.25)
    b = MicroBatcher(eng, buckets=(1,), max_queue_depth=64,
                     batch_window_s=0.0)
    futs = [b.submit(img) for img in _images(6)]
    assert b.drain(timeout=0.3) is False
    done_ok = sum(1 for f in futs if f.exception() is None)
    shed = sum(1 for f in futs
               if isinstance(f.exception(), ShedError))
    assert done_ok >= 1 and shed >= 1
    assert done_ok + shed == len(futs)


def test_main_serve_graceful_stop_drains_and_flushes(tmp_path):
    """The full --mode serve runtime shut down via its stop hook (the
    same path a SIGTERM takes through PreemptionGuard): in-flight work
    answered, final serve_done record flushed, exit code 0."""
    import socket
    import urllib.request

    from dml_cnn_cifar10_tpu.config import TrainConfig
    from dml_cnn_cifar10_tpu.serve.server import main_serve

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    cfg = TrainConfig(log_dir=str(tmp_path / "logs"),
                      metrics_jsonl=str(tmp_path / "m.jsonl"))
    cfg.model.logit_relu = False
    cfg.serve.port = port
    cfg.serve.buckets = (1, 4)
    cfg.serve.metrics_every_s = 0.2
    cfg.serve.drain_deadline_s = 5.0

    ready, stop = threading.Event(), threading.Event()
    rc = {}
    t = threading.Thread(
        target=lambda: rc.setdefault("rc", main_serve(
            cfg, ready_event=ready, stop_event=stop)),
        daemon=True)
    t.start()
    assert ready.wait(180), "server never became ready"

    health = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=60).read())
    # The probe-without-traffic contract the fleet router relies on.
    assert health["replica_id"] == 0
    assert health["version"] == "0"          # fresh init, no checkpoint
    assert health["queue_depth"] == 0
    assert health["uptime_s"] >= 0
    img = np.zeros(tuple(health["image_shape"]), np.uint8).tobytes()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=img, method="POST")
    resp = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert "class" in resp
    assert resp["version"] == "0"            # responses carry the tag

    stop.set()
    t.join(120)
    assert not t.is_alive(), "serve loop did not exit on stop"
    assert rc["rc"] == 0

    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    finals = [r for r in recs if r["kind"] == "serve_done"]
    assert finals and finals[-1]["completed"] >= 1
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl, strict=True) == []
