"""Elastic scale-UP (ISSUE 7): host rejoin + coordinated expand.

Unit tests for the rejoin/expand protocol seams in parallel/cluster.py
and the ISSUE-7 acceptance sim: a 2-process CPU lockstep run loses host
1 (`host_lost@15`), shrinks to world size 1, the host RETURNS (the
harness respawns it, the survivor's `host_return@18` injection pins the
step), the chief records a monotone-epoch EXPAND decision, both
processes re-enter restore at world size 2, and the final params are
BIT-IDENTICAL to an uninterrupted 2-process run — with `host_rejoin` /
`elastic_expand` events in schema-clean JSONL streams."""

import json
import os
import time

import pytest

from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.utils import faults as faults_lib

from tests.test_cluster import (FakeLogger, _monitor, _read_result,
                                _spawn, _ensure_data)


# ---------------------------------------------------------------------------
# protocol units
# ---------------------------------------------------------------------------

def test_rejoin_candidates_sees_fresh_rejoin_beats_only(tmp_path):
    mon = _monitor(tmp_path, 0, n=1)
    try:
        outsider = cluster_lib.HeartbeatStore(str(tmp_path), 7)
        outsider.publish(0, "train")          # wrong phase
        assert mon.rejoin_candidates() == []
        outsider.publish(0, "rejoin")         # fresh + rejoin
        assert mon.rejoin_candidates() == [7]
        # A survivor's beat never counts as a rejoin candidate.
        member = cluster_lib.HeartbeatStore(str(tmp_path), 0)
        member.publish(3, "rejoin")
        assert mon.rejoin_candidates() == [7]
    finally:
        mon.close()


def test_decide_expand_grows_world_with_monotone_epoch(tmp_path):
    mon = _monitor(tmp_path, 0, n=2)
    try:
        shrink = mon.decide_restart([1], restore_step=10)
        mon.adopt(shrink)
        assert mon.world_size() == 1 and shrink.kind == "shrink"
        d = mon.decide_expand([1], restore_step=10)
        assert d.kind == "expand" and d.epoch == 2
        assert d.survivors == [0, 1] and d.world_size == 2
        mon.adopt(d)
        assert mon.world_size() == 2 and mon.epoch == 2
        # The decision file stays monotone across kinds.
        with pytest.raises(ValueError, match="monotone"):
            mon.coordinator.record(cluster_lib.RestartDecision(
                epoch=2, world_size=2, restore_step=10, survivors=[0, 1]))
    finally:
        mon.close()


def test_begin_step_raises_peer_rejoin_for_chief_with_expand_on(tmp_path):
    log = FakeLogger()
    mon = _monitor(tmp_path, 0, n=1, logger=log, elastic_expand=True)
    try:
        joiner = cluster_lib.HeartbeatStore(str(tmp_path), 1)
        joiner.publish(10, "rejoin")
        mon._last_rejoin_scan = 0.0
        with pytest.raises(cluster_lib.PeerRejoinError) as ei:
            mon.begin_step(11)
        assert ei.value.process_ids == [1]
        assert any(r["kind"] == "host_rejoin" and r["process_id"] == 1
                   for r in log.records)
    finally:
        mon.close()


def test_rejoin_scan_is_off_by_default(tmp_path):
    """Without --elastic_expand the PR-4 contract holds: a rejoin
    announcement is ignored and the world stays shrunk."""
    mon = _monitor(tmp_path, 0, n=1)
    try:
        cluster_lib.HeartbeatStore(str(tmp_path), 1).publish(5, "rejoin")
        mon._last_rejoin_scan = 0.0
        mon.begin_step(6)  # no raise
        mon.end_step(7)
    finally:
        mon.close()


def test_request_rejoin_and_await_inclusion(tmp_path):
    """Returning-host seat: adopt the excluding world as current truth,
    announce with a rejoin-phase beat, then block until a NEWER epoch
    includes us."""
    mon = _monitor(tmp_path, 1, n=2)
    try:
        mon.coordinator.record(cluster_lib.RestartDecision(
            epoch=1, world_size=1, restore_step=10, survivors=[0]))
        mon.stall_heartbeats()
        mon.request_rejoin()
        assert mon.epoch == 1 and not mon._stalled
        beat = mon.store.read(1)
        assert beat.phase == "rejoin"
        # Not yet included → bounded wait raises.
        with pytest.raises(cluster_lib.PeerLostError, match="rejoin"):
            mon.await_inclusion(timeout_s=0.2, poll_s=0.02)
        # The chief's expand decision lets us in.
        mon.coordinator.record(cluster_lib.RestartDecision(
            epoch=2, world_size=2, restore_step=10, survivors=[0, 1],
            kind="expand"))
        d = mon.await_inclusion(timeout_s=1.0)
        assert d.epoch == 2 and d.kind == "expand"
        mon.adopt(d)
        assert mon.world_size() == 2
    finally:
        mon.close()


def test_stale_epoch_mid_step_exits_via_clean_peer_lost(tmp_path):
    """ISSUE-7 satellite: a non-chief that observes a NEWER coordinator
    epoch that still includes it must not race the decision file — it
    exits through the peer_lost path (empty process_ids) after a
    bounded re-read, and the supervisor adopts the pending decision."""
    log = FakeLogger()
    mon = _monitor(tmp_path, 1, n=2, logger=log)
    try:
        mon.coordinator.record(cluster_lib.RestartDecision(
            epoch=1, world_size=2, restore_step=20, survivors=[0, 1],
            kind="expand"))
        with pytest.raises(cluster_lib.PeerLostError) as ei:
            mon.check_evicted(25)
        assert ei.value.process_ids == []
        assert any(r["kind"] == "peer_lost"
                   and r["reason"] == "stale_epoch" for r in log.records)
        # The supervisor seat adopts the pending decision instead of
        # deciding its own (no epoch race).
        from dml_cnn_cifar10_tpu.config import TrainConfig
        from dml_cnn_cifar10_tpu.train import supervisor as sup
        cfg = TrainConfig()
        cfg.parallel.num_processes = 2
        d = sup._coordinate_restart(cfg, mon, ei.value, FakeLogger(), 1)
        assert d.epoch == 1 and mon.epoch == 1
        assert cfg.parallel.num_processes == 2
    finally:
        mon.close()


def test_classify_and_fault_spec_cover_rejoin_kinds():
    from dml_cnn_cifar10_tpu.train.supervisor import classify_failure
    assert classify_failure(
        cluster_lib.PeerRejoinError([2], "x")) == "peer_rejoin"
    events = faults_lib.parse_fault_spec("host_lost@15,host_return@18")
    assert [(e.kind, e.step) for e in events] == [("host_lost", 15),
                                                 ("host_return", 18)]
    inj = faults_lib.FaultInjector(
        faults_lib.parse_fault_spec("host_return@0"))
    with pytest.raises(faults_lib.InjectedFault, match="cluster_dir"):
        inj.step_hook(0, None, "/tmp", cluster=None)


def test_host_return_unblocks_on_rejoin_beat(tmp_path):
    """The drill injection holds the step until a rejoin announcement
    is visible, then returns (the chief's scan drives the expand)."""
    mon = _monitor(tmp_path, 0, n=1)
    try:
        inj = faults_lib.FaultInjector(
            faults_lib.parse_fault_spec("host_return@5"))
        import threading
        done = threading.Event()

        def run():
            inj.step_hook(5, "state", str(tmp_path), cluster=mon)
            done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(0.15)
        assert not done.is_set()          # still holding the seam
        cluster_lib.HeartbeatStore(str(tmp_path), 1).publish(0, "rejoin")
        assert done.wait(5.0)
        assert inj.pending() == []
    finally:
        mon.close()


# ---------------------------------------------------------------------------
# the ISSUE-7 acceptance sim: 2 → 1 → 2, bit-identical to uninterrupted
# ---------------------------------------------------------------------------

WORKER = """
import json, sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
task, n, data_dir, log_dir, cluster_dir, fault_spec, total_steps = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6], int(sys.argv[7]))
import hashlib
import numpy as np
import jax
from dml_cnn_cifar10_tpu.config import TrainConfig, DataConfig
from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised

cfg = TrainConfig(
    batch_size=32, total_steps=total_steps, output_every=10,
    eval_every=20, checkpoint_every=10, log_dir=log_dir,
    metrics_jsonl=f"{log_dir}/metrics.jsonl",
    data=DataConfig(dataset="synthetic", data_dir=data_dir,
                    synthetic_train_records=256, synthetic_test_records=64,
                    normalize="scale", use_native_loader=False),
)
cfg.model.logit_relu = False
cfg.optim.learning_rate = 0.05
cfg.keep_checkpoints = 20   # retention must not prune the restore point
cfg.recovery_backoff_s = 0.05
cfg.recovery_backoff_max_s = 0.2
cfg.fault_spec = fault_spec or None
cfg.parallel.process_id = task
cfg.parallel.num_processes = n
if cluster_dir:
    cfg.parallel.cluster_dir = cluster_dir
    cfg.parallel.cluster_lockstep = True
    cfg.parallel.elastic_expand = True
    cfg.parallel.heartbeat_interval_s = 0.1
    cfg.parallel.straggler_after_s = 0.4
    cfg.parallel.peer_dead_after_s = 2.5
    cfg.parallel.collective_timeout_s = 300.0

res = fit_supervised(cfg, task_index=task)
if res is None:
    print("RESULT " + json.dumps({"task": task, "fenced": True}))
    sys.exit(0)
h = hashlib.sha256()
for leaf in jax.tree.leaves(jax.device_get(res.state.params)):
    h.update(np.ascontiguousarray(leaf).tobytes())
print("RESULT " + json.dumps({
    "task": task, "fenced": False, "final_step": res.final_step,
    "digest": h.hexdigest()}))
"""


def test_sim_2_1_2_expand_bit_identical_to_uninterrupted(tmp_path,
                                                         data_cfg):
    """host_lost@15 on task 1, host_return@18 on task 0: the survivor
    shrinks to world 1 from ckpt_10, holds step 18 until the respawned
    host announces rejoin, expands back to world 2 (epoch 2) restoring
    ckpt_10, and BOTH processes finish step 40 with params
    bit-identical to an uninterrupted 2-process reference run."""
    data_dir = _ensure_data(tmp_path, data_cfg)
    script = tmp_path / "worker.py"
    script.write_text(WORKER)

    # The uninterrupted 2-process lockstep reference (fresh dirs).
    ref_cluster = str(tmp_path / "ref_cluster")
    ref_logs = [str(tmp_path / f"ref_logs_{t}") for t in (0, 1)]
    ref_procs = [_spawn(script, [t, 2, data_dir, ref_logs[t],
                                 ref_cluster, "", 40], tmp_path)
                 for t in (0, 1)]
    ref_outs = [p.communicate(timeout=300)[0] for p in ref_procs]
    for p, out in zip(ref_procs, ref_outs):
        assert p.returncode == 0, f"reference run failed:\n{out}"
    ref = [_read_result(o) for o in ref_outs]
    assert all(r["final_step"] == 40 for r in ref)

    # The elastic run: task 1 dies at 15; task 0 pins the return at 18.
    cluster_dir = str(tmp_path / "cluster")
    logs = [str(tmp_path / f"logs_{t}") for t in (0, 1)]
    procs = [
        _spawn(script, [0, 2, data_dir, logs[0], cluster_dir,
                        "host_return@18", 40], tmp_path),
        _spawn(script, [1, 2, data_dir, logs[1], cluster_dir,
                        "host_lost@15", 40], tmp_path),
    ]
    rejoined = None
    try:
        # The scheduler seat: respawn task 1 once its first life exits
        # with the abrupt-death code AND the survivor has committed the
        # shrink decision — a host that returns before the world even
        # noticed it was gone just keeps beating and nothing shrank
        # (there is no death to recover from, and no drill).
        assert procs[1].wait(timeout=300) == faults_lib.EXIT_HOST_LOST, \
            procs[1].communicate()[0]
        coord = cluster_lib.RestartCoordinator(cluster_dir)
        deadline = time.time() + 240
        while True:
            d = coord.read()
            if d is not None and d.epoch >= 1:
                break
            assert time.time() < deadline, "survivor never shrank"
            assert procs[0].poll() is None, \
                f"survivor died early:\n{procs[0].communicate()[0]}"
            time.sleep(0.1)
        rejoined = _spawn(script, [1, 2, data_dir, logs[1], cluster_dir,
                                   "", 40], tmp_path)
        outs = [procs[0].communicate(timeout=300)[0],
                rejoined.communicate(timeout=300)[0]]
    finally:
        for p in procs + ([rejoined] if rejoined else []):
            if p.poll() is None:
                p.kill()
    assert procs[0].returncode == 0, f"survivor failed:\n{outs[0]}"
    assert rejoined.returncode == 0, f"rejoined host failed:\n{outs[1]}"

    survivor = _read_result(outs[0])
    joiner = _read_result(outs[1])
    assert not survivor["fenced"] and not joiner["fenced"]
    assert survivor["final_step"] == 40 and joiner["final_step"] == 40

    # Bit-identical to the uninterrupted 2-process run, on BOTH seats.
    assert survivor["digest"] == ref[0]["digest"]
    assert joiner["digest"] == ref[1]["digest"]

    # Stream contract: the survivor classified the loss, shrank, saw
    # the rejoin, expanded; the joiner announced and adopted the
    # expand. Both streams pass the schema lint.
    from tools import check_jsonl_schema, telemetry_report
    streams = []
    for d in logs:
        with open(os.path.join(d, "metrics.jsonl")) as f:
            streams.append([json.loads(ln) for ln in f if ln.strip()])
    for recs in streams:
        assert check_jsonl_schema.check_lines(
            (json.dumps(r) for r in recs), strict=True) == []
    s_kinds = {r["kind"] for r in streams[0]}
    assert {"peer_lost", "elastic_restart", "host_rejoin",
            "elastic_expand"} <= s_kinds
    shrink = [r for r in streams[0] if r["kind"] == "elastic_restart"]
    assert shrink[0]["world_size"] == 1 and shrink[0]["epoch"] == 1
    expand = [r for r in streams[0] if r["kind"] == "elastic_expand"]
    assert expand[0]["world_size"] == 2 and expand[0]["epoch"] == 2
    assert expand[0]["restore_step"] == 10
    assert expand[0]["joined"] == [1]
    j_kinds = {r["kind"] for r in streams[1]}
    assert {"host_rejoin", "elastic_expand"} <= j_kinds
    j_expand = [r for r in streams[1] if r["kind"] == "elastic_expand"]
    assert j_expand[0]["world_size"] == 2
    assert j_expand[0]["restore_step"] == 10

    # The report CLI renders the full shrink→expand arc.
    out = telemetry_report.summarize(os.path.join(logs[0],
                                                  "metrics.jsonl"))
    assert "elastic expand epoch 2" in out
    assert "world-size timeline: 1[shrink@" in out
    assert "2[expand@" in out
