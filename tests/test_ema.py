"""Parameter EMA: train on raw params, evaluate the moving average.

No reference counterpart — the standard ViT/ResNet recipe stabilizer.
The EMA lives in the optimizer state (key "ema", so the fsdp/tp sharding
rules cover it like any moment buffer), updates every step across every
optimizer family, and the eval paths pick it automatically.
"""

import pytest
import jax
import numpy as np

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import shardings
from dml_cnn_cifar10_tpu.parallel import step as step_lib
from dml_cnn_cifar10_tpu.train import optim

DATA = DataConfig(normalize="scale")


def test_ema_math_one_step(rng):
    """ema_1 = d*params_0 + (1-d)*params_1, across optimizer families."""
    for name in ("sgd", "adamw"):
        cfg = OptimConfig(optimizer=name, learning_rate=0.05,
                          schedule="constant", ema_decay=0.9)
        params = {"w": np.asarray(rng.normal(0, 1, (4, 3)), np.float32)}
        grads = {"w": np.asarray(rng.normal(0, 1, (4, 3)), np.float32)}
        state = optim.sgd_init(params, cfg)
        np.testing.assert_array_equal(np.asarray(state["ema"]["w"]),
                                      params["w"])
        new_params, new_state = optim.sgd_update(grads, state, params, cfg)
        # Warmup-ramped decay: at t=1 the effective decay is
        # min(d, (1+1)/(10+1)) = 2/11, so early EMAs track the live
        # params instead of random init.
        d = min(0.9, 2.0 / 11.0)
        want = d * params["w"] + (1 - d) * np.asarray(new_params["w"])
        np.testing.assert_allclose(np.asarray(new_state["ema"]["w"]), want,
                                   rtol=1e-6)


@pytest.mark.slow
def test_eval_uses_ema_params(rng):
    """After a violent step, raw-params eval and EMA eval must differ —
    and the eval step must be the EMA one (equal to logits computed with
    the EMA weights by hand)."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("cnn")
    mcfg = ModelConfig(logit_relu=False)
    ocfg = OptimConfig(learning_rate=0.5, schedule="constant",
                       ema_decay=0.99)
    sh = step_lib.train_state_shardings(mesh, model_def, mcfg, DATA, ocfg)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, mcfg, DATA, ocfg, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, mcfg, ocfg, mesh,
                                     state_sharding=sh)
    ev = step_lib.make_eval_step(model_def, mcfg, mesh, state_sharding=sh)

    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    for _ in range(3):
        state, _ = train(state, im, lb)

    got = ev(state, im, lb)["accuracy"]
    ema_params = jax.device_get(state.opt["ema"])
    raw_params = jax.device_get(state.params)
    ema_logits = model_def.apply(ema_params, images, mcfg, train=False)
    raw_logits = model_def.apply(raw_params, images, mcfg, train=False)
    assert not np.allclose(np.asarray(ema_logits), np.asarray(raw_logits))
    want = float(np.mean(np.argmax(np.asarray(ema_logits), -1) == labels))
    np.testing.assert_allclose(float(jax.device_get(got)), want, atol=1e-6)


@pytest.mark.slow
def test_ema_shards_and_checkpoints(tmp_path, rng):
    """EMA buffers shard over data under fsdp and survive a checkpoint
    round-trip."""
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("cnn")
    mcfg = ModelConfig(logit_relu=False)
    ocfg = OptimConfig(learning_rate=0.05, schedule="constant",
                       ema_decay=0.999)
    sh = step_lib.train_state_shardings(mesh, model_def, mcfg, DATA, ocfg,
                                        fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, mcfg, DATA, ocfg, mesh,
        state_sharding=sh)
    assert shardings.assert_some_leaf_sharded(state.opt["ema"], axis="data")

    train = step_lib.make_train_step(model_def, mcfg, ocfg, mesh,
                                     state_sharding=sh)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    state, _ = train(state, *mesh_lib.shard_batch(mesh, images, labels))

    ckpt_lib.save_checkpoint(str(tmp_path), state, step=1)
    fresh = step_lib.init_train_state(
        jax.random.key(5), model_def, mcfg, DATA, ocfg, mesh,
        state_sharding=sh)
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), fresh, sharding=sh)
    for a, b in zip(jax.tree.leaves(jax.device_get(state.opt["ema"])),
                    jax.tree.leaves(jax.device_get(restored.opt["ema"]))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ema_decay_validation():

    with pytest.raises(ValueError, match="ema_decay"):
        optim.sgd_init({"w": np.ones(2, np.float32)},
                       OptimConfig(ema_decay=1.0))


@pytest.mark.slow
def test_ema_covers_bn_state(rng):
    """BatchNorm models track an EMA of the running stats too
    ("ema_mstate"), and eval pairs it with the EMA params."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("resnet18")
    mcfg = ModelConfig(name="resnet18", logit_relu=False)
    ocfg = OptimConfig(learning_rate=0.05, schedule="constant",
                      ema_decay=0.99)
    sh = step_lib.train_state_shardings(mesh, model_def, mcfg, DATA, ocfg)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, mcfg, DATA, ocfg, mesh,
        state_sharding=sh)
    assert "ema_mstate" in state.opt
    train = step_lib.make_train_step(model_def, mcfg, ocfg, mesh,
                                     state_sharding=sh)
    ev = step_lib.make_eval_step(model_def, mcfg, mesh, state_sharding=sh)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    for _ in range(2):
        state, _ = train(state, im, lb)
    # The stats EMA moved off the live stats and off init.
    live = jax.device_get(state.model_state)
    ema = jax.device_get(state.opt["ema_mstate"])
    diffs = [not np.allclose(np.asarray(a), np.asarray(b))
             for a, b in zip(jax.tree.leaves(live), jax.tree.leaves(ema))]
    assert any(diffs)
    acc = ev(state, im, lb)["accuracy"]
    assert np.isfinite(float(jax.device_get(acc)))
