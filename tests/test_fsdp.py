"""ZeRO/FSDP: param + optimizer-moment sharding over the ``data`` axis.

The reference's PS placed variables round-robin over PS tasks
(``cifar10cnn.py:195-196``) — the only "state sharding" it had. The SPMD
form is ZeRO-3: every param/moment leaf partitioned over ``data``, GSPMD
all-gathering weights before compute and reduce-scattering gradients.
These tests prove it is *real* (leaves actually partitioned 1/N on device)
and *pure layout* (same math as replicated dp to fp32 tolerance), on the
8-virtual-device CPU mesh (SURVEY §4's no-pod distributed recipe).
"""

import dataclasses

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import shardings
from dml_cnn_cifar10_tpu.parallel import step as step_lib
import pytest

DATA = DataConfig(normalize="scale")


def _mesh(data=8, model=1):
    return mesh_lib.build_mesh(
        ParallelConfig(data_axis=data, model_axis=model))


def _batch(rng, n=16, hw=24):
    images = rng.normal(0.5, 0.25, (n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


def _run_steps(model_cfg, mesh, images, labels, fsdp, nsteps=3, optim=None):
    model_def = get_model(model_cfg.name)
    optim = optim or OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim, fsdp=fsdp)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    losses = []
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def test_fsdp_spec_picks_largest_free_dim():
    # conv kernel [5,5,3,64]: only 64 divides 8 -> trailing dim sharded.
    assert shardings._add_fsdp(P(), (5, 5, 3, 64), 8) == P(
        None, None, None, "data")
    # fc kernel [2304,384]: both divide, 2304 is larger -> dim 0.
    assert shardings._add_fsdp(P(), (2304, 384), 8) == P("data", None)
    # model-sharded col kernel: the tp dim is taken, fsdp takes the other.
    assert shardings._add_fsdp(P(None, "model"), (2304, 384), 8) == P(
        "data", "model")
    # no divisible free dim -> unchanged (bias of the 10-way head).
    assert shardings._add_fsdp(P(), (10,), 8) == P()
    # scalars / data_size 1 -> unchanged.
    assert shardings._add_fsdp(P(), (), 8) == P()
    assert shardings._add_fsdp(P(), (64,), 1) == P()


@pytest.mark.slow
def test_fsdp_state_actually_sharded():
    mesh = _mesh()
    model_def = get_model("cnn")
    cfg = ModelConfig(logit_relu=False)
    optim = OptimConfig(momentum=0.9)  # momentum buffers shard like params
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim,
                                        fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    k = state.params["full1"]["kernel"]          # [2304, 384] col-parallel:
    # the tp rule claims the trailing dim (size-1 model axis here), fsdp
    # takes the free leading dim.
    assert k.sharding.spec == P("data", "model")
    assert k.addressable_shards[0].data.shape == (2304 // 8, 384)
    m = state.opt["momentum"]["full1"]["kernel"]
    assert m.sharding.spec == P("data", "model")
    assert shardings.assert_some_leaf_sharded(state.params, axis="data")
    # scalar step and the tiny head bias stay replicated
    assert state.opt["step"].sharding.spec == P()
    assert state.params["full3"]["bias"].sharding.spec == P()


@pytest.mark.slow
def test_fsdp_matches_dp(rng):
    """fsdp must be a pure layout change: same losses, same final params
    as replicated dp, to fp32 tolerance (reduce-scatter vs all-reduce can
    reorder the sum)."""
    cfg = ModelConfig(logit_relu=False)
    images, labels = _batch(rng)
    st_dp, loss_dp = _run_steps(cfg, _mesh(), images, labels, fsdp=False)
    st_fs, loss_fs = _run_steps(cfg, _mesh(), images, labels, fsdp=True)
    np.testing.assert_allclose(loss_dp, loss_fs, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(st_dp.params),
                    jax.tree.leaves(st_fs.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_fsdp_composes_with_tp(rng):
    """data=4 (fsdp) x model=2 (tp): the col-parallel kernel carries BOTH
    axes and the step still matches pure dp."""
    cfg = ModelConfig(logit_relu=False)
    images, labels = _batch(rng)
    mesh = _mesh(data=4, model=2)
    model_def = get_model("cnn")
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim,
                                        fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    k = state.params["full1"]["kernel"]          # [2304, 384] col-parallel
    assert k.sharding.spec == P("data", "model")
    assert k.addressable_shards[0].data.shape == (2304 // 4, 384 // 2)

    _, loss_dp = _run_steps(cfg, _mesh(), images, labels, fsdp=False)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(3):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    np.testing.assert_allclose(loss_dp, losses, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_fsdp_adamw_vit(rng):
    """AdamW mu/nu shard over ``data`` and train finitely on a ViT."""
    cfg = ModelConfig(name="vit_tiny", vit_depth=2, vit_dim=64, vit_heads=2,
                      patch_size=8, logit_relu=False)
    images, labels = _batch(rng)
    optim = OptimConfig(optimizer="adamw", learning_rate=1e-3)
    st, losses = _run_steps(cfg, _mesh(), images, labels, fsdp=True,
                            nsteps=2, optim=optim)
    assert np.isfinite(losses).all()
    assert shardings.assert_some_leaf_sharded(st.opt["mu"], axis="data")
    assert int(jax.device_get(st.step)) == 2


@pytest.mark.slow
def test_fsdp_tp_compiles_without_involuntary_remat(rng, capfd):
    """Regression for the 8-device dryrun artifact (round 1): the fsdp x tp
    CNN step used to compile with an SPMD "Involuntary full
    rematerialization" warning — the data-axis storage sharding of
    full1/kernel leaked into the backward flatten reshape. The ZeRO-3
    gather-before-compute constraint (step._fsdp_gather_wrap) must keep the
    partitioned program free of that fallback. capfd sees the C++ absl log
    on fd 2."""
    cfg = ModelConfig(logit_relu=False)
    mesh = _mesh(data=4, model=2)
    model_def = get_model("cnn")
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim,
                                        fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    # Distinct batch size => fresh XLA compile (a cache hit would not
    # re-emit the warning and the assert would pass vacuously).
    images, labels = _batch(rng, n=32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    capfd.readouterr()  # drain anything prior
    state, metrics = train(state, im, lb)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err, err


@pytest.mark.slow
def test_fsdp_checkpoint_roundtrip(tmp_path, rng):
    """Save from fsdp-sharded state, restore into the same layout: the
    host fetch assembles the global arrays, restore re-sharding matches."""
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    cfg = ModelConfig(logit_relu=False)
    images, labels = _batch(rng)
    mesh = _mesh()
    model_def = get_model("cnn")
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim,
                                        fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim, mesh,
                                     state_sharding=sh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    state, _ = train(state, im, lb)

    ckpt_lib.save_checkpoint(str(tmp_path), state, step=1)
    fresh = step_lib.init_train_state(
        jax.random.key(7), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    restored = ckpt_lib.restore_checkpoint(str(tmp_path), fresh, sharding=sh)
    assert restored.params["full1"]["kernel"].sharding.spec == P(
        "data", "model")
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))
    # restored state trains on (the donated-buffer layouts line up)
    restored, metrics = train(restored, im, lb)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
