"""Ring attention (sequence parallelism) on the 8-virtual-device CPU mesh:
numerical parity with single-device attention, dp×sp composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import ParallelConfig
from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import ring_attention as ra


def _qkv(rng, b=2, s=64, h=2, d=16, scale=1.0):
    mk = lambda: (scale * rng.normal(0, 1, (b, s, h, d))).astype(np.float32)
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


def test_ring_matches_dense_seq_only():
    """All 8 devices on the seq axis."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    out = ra.ring_attention(q, k, v, mesh)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_ring_composes_with_data_parallel():
    """2-way dp × 4-way sp on the same mesh."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=2, seq_axis=4))
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, b=4, s=32)
    sharded = jax.device_put((q, k, v), ra.sequence_sharding(mesh))
    out = ra.ring_attention(*sharded, mesh)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_ring_large_logits_stable():
    """The cross-shard online-softmax merge must survive big score
    magnitudes (each shard's local max differs wildly). Scores are driven
    large through Q/K only; V stays unit-scale so a saturation near-tie
    (both answers valid in f32) can't dominate the comparison."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(2)
    shape = (2, 64, 2, 16)
    q = jnp.asarray((8.0 * rng.normal(0, 1, shape)).astype(np.float32))
    k = jnp.asarray((8.0 * rng.normal(0, 1, shape)).astype(np.float32))
    v = jnp.asarray(rng.normal(0, 1, shape).astype(np.float32))
    out = ra.ring_attention(q, k, v, mesh)
    ref = attn.xla_attention(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-3,
                               atol=1e-3)


def test_ring_rejects_indivisible_seq():
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, s=60)
    with pytest.raises(ValueError):
        ra.ring_attention(q, k, v, mesh)


def test_ring_under_jit_compiles_once():
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng)

    @jax.jit
    def f(q, k, v):
        return ra.ring_attention(q, k, v, mesh)

    out1 = f(q, k, v)
    out2 = f(q * 0.5, k, v)
    assert out1.shape == q.shape and out2.shape == q.shape
