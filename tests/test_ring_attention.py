"""Ring attention (sequence parallelism) on the 8-virtual-device CPU mesh:
numerical parity with single-device attention, dp×sp composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import ParallelConfig
from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import ring_attention as ra


def _qkv(rng, b=2, s=64, h=2, d=16, scale=1.0):
    mk = lambda: (scale * rng.normal(0, 1, (b, s, h, d))).astype(np.float32)
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


def test_ring_matches_dense_seq_only():
    """All 8 devices on the seq axis."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    out = ra.ring_attention(q, k, v, mesh)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


@pytest.mark.slow
def test_ring_composes_with_data_parallel():
    """2-way dp × 4-way sp on the same mesh."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=2, seq_axis=4))
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, b=4, s=32)
    sharded = jax.device_put((q, k, v), ra.sequence_sharding(mesh))
    out = ra.ring_attention(*sharded, mesh)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_ring_large_logits_stable():
    """The cross-shard online-softmax merge must survive big score
    magnitudes (each shard's local max differs wildly). Scores are driven
    large through Q/K only; V stays unit-scale so a saturation near-tie
    (both answers valid in f32) can't dominate the comparison."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(2)
    shape = (2, 64, 2, 16)
    q = jnp.asarray((8.0 * rng.normal(0, 1, shape)).astype(np.float32))
    k = jnp.asarray((8.0 * rng.normal(0, 1, shape)).astype(np.float32))
    v = jnp.asarray(rng.normal(0, 1, shape).astype(np.float32))
    out = ra.ring_attention(q, k, v, mesh)
    ref = attn.xla_attention(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-3,
                               atol=1e-3)


def test_ring_rejects_indivisible_seq():
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, s=60)
    with pytest.raises(ValueError):
        ra.ring_attention(q, k, v, mesh)


def test_ring_under_jit_compiles_once():
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=1, seq_axis=8))
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng)

    @jax.jit
    def f(q, k, v):
        return ra.ring_attention(q, k, v, mesh)

    out1 = f(q, k, v)
    out2 = f(q * 0.5, k, v)
    assert out1.shape == q.shape and out2.shape == q.shape


@pytest.mark.slow
def test_flash_stats_interface():
    """flash_attention_stats returns (acc, m, l) with acc f32
    unnormalized (the ring merge currency) and acc/l == dense attention."""
    from dml_cnn_cifar10_tpu.ops import flash_attention as fa

    rng = np.random.default_rng(7)
    q, k, v = _qkv(rng, b=2, s=200, h=2, d=16)
    acc, m, l = fa.flash_attention_stats(q, k, v)
    assert acc.dtype == jnp.float32
    out = acc / l[..., None]     # l [B,S,H] broadcasts over D
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)
    # Recompute the softmax stats densely and compare.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (16 ** -0.5)
    m_ref = jnp.transpose(jnp.max(s, -1), (0, 2, 1))
    l_ref = jnp.transpose(
        jnp.sum(jnp.exp(s - jnp.max(s, -1, keepdims=True)), -1), (0, 2, 1))
    np.testing.assert_allclose(np.asarray(m), np.asarray(m_ref), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(l), np.asarray(l_ref), rtol=1e-4,
                               atol=1e-4)


@pytest.mark.slow
def test_ring_pallas_local_block_matches_dense():
    """Ring attention with the local block on the Pallas flash kernel
    (long shards: S_local = 256 >= 128) == dense attention."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=2, seq_axis=4))
    rng = np.random.default_rng(8)
    q, k, v = _qkv(rng, b=2, s=1024, h=2, d=16)
    out = ra.ring_attention(q, k, v, mesh, use_pallas=True)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow
def test_ring_pallas_bf16_partials_stay_f32():
    """bf16 inputs: the stats interface keeps partials f32, so the ring
    merge matches dense attention at bf16-input tolerance."""
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=2, seq_axis=4))
    rng = np.random.default_rng(9)
    shape = (2, 512, 2, 16)
    qf = rng.normal(0, 1, shape).astype(np.float32)
    kf = rng.normal(0, 1, shape).astype(np.float32)
    vf = rng.normal(0, 1, shape).astype(np.float32)
    q, k, v = (jnp.asarray(a, jnp.bfloat16) for a in (qf, kf, vf))
    out = ra.ring_attention(q, k, v, mesh, use_pallas=True)
    assert out.dtype == jnp.bfloat16
    ref = attn.xla_attention(jnp.asarray(qf), jnp.asarray(kf),
                             jnp.asarray(vf))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)
