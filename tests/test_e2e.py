"""End-to-end Trainer tests on synthetic data (SURVEY §4: short training run
asserting loss decreases and accuracy beats chance; checkpoint-resume)."""

import pytest
import os

import numpy as np

from dml_cnn_cifar10_tpu.train.loop import Trainer
from tests.conftest import tiny_train_cfg


@pytest.mark.slow
def test_trainer_end_to_end(data_cfg, tmp_path, capsys):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60)
    cfg.metrics_jsonl = os.path.join(str(tmp_path), "metrics.jsonl")
    result = Trainer(cfg).fit()

    assert result.final_step == 60
    assert len(result.train_loss) == 6       # every 10 of 60 local steps
    assert len(result.test_accuracy) == 3    # every 20
    # learns the separable synthetic data
    assert result.train_loss[-1] < result.train_loss[0]
    assert result.test_accuracy[-1] > 0.15   # > 10% chance

    out = capsys.readouterr().out
    assert "Starting Training" in out                       # cifar10cnn.py:225
    assert "task:0_step" in out                             # :234-235 format
    assert " --- Test Accuracy = " in out                   # :240-241 format
    assert os.path.isfile(cfg.metrics_jsonl)
    # checkpoints written at the cadence + final
    assert os.path.isfile(os.path.join(cfg.log_dir, "checkpoint"))


@pytest.mark.slow
def test_trainer_resume_from_checkpoint(data_cfg, tmp_path):
    """Stop at 30, build a fresh Trainer on the same log_dir, resume to 60 —
    the StopAtStepHook-on-global-step contract (cifar10cnn.py:219,222)."""
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=30)
    r1 = Trainer(cfg).fit()
    assert r1.final_step == 30

    cfg2 = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60)
    t2 = Trainer(cfg2)
    state = t2.init_or_restore()
    assert int(np.asarray(state.step)) == 30  # restored, not fresh
    r2 = t2.fit(state=state)
    assert r2.final_step == 60


def test_trainer_full_test_set_eval(data_cfg, tmp_path):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20)
    cfg.eval_full_test_set = True
    t = Trainer(cfg)
    state = t.init_or_restore()
    from dml_cnn_cifar10_tpu.data import pipeline as pipe
    test_it = pipe.input_pipeline(cfg.data, cfg.batch_size, train=False)
    acc = t.evaluate(state, test_it)
    assert 0.0 <= acc <= 1.0


@pytest.mark.slow
def test_trainer_explicit_collectives_mode(data_cfg, tmp_path):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=12)
    cfg.parallel.explicit_collectives = True
    result = Trainer(cfg).fit()
    assert result.final_step == 12
    assert np.isfinite(result.train_loss[0])


@pytest.mark.slow
def test_trainer_chunked_dispatch(data_cfg, tmp_path, capsys):
    """steps_per_dispatch > 1: the chunked (raw-uint8 + device-decode)
    path drives the same loop with identical observable cadence."""

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60,
                         steps_per_dispatch=10)
    result = Trainer(cfg).fit()
    assert result.final_step == 60
    assert len(result.train_loss) == 6       # cadence preserved (every 10)
    assert len(result.test_accuracy) == 3    # every 20
    # Learns the separable data (single-batch losses are noisy at this LR,
    # so judge by the trend and the test accuracy, not one batch).
    assert np.mean(result.train_loss[-2:]) < result.train_loss[0]
    assert result.test_accuracy[-1] > 0.15
    out = capsys.readouterr().out
    assert "task:0_step 9," in out           # local-step numbering preserved
    assert os.path.isfile(os.path.join(cfg.log_dir, "checkpoint"))

    # Misaligned cadence must be rejected up front.
    bad = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60,
                         steps_per_dispatch=7)
    with pytest.raises(ValueError, match="multiple"):
        Trainer(bad)


@pytest.mark.slow
def test_trainer_chunked_dispatch_native_loader(data_cfg, tmp_path):
    """Chunk mode + the C++ loader: raw chunks stream from the native
    bounded shuffle pool."""
    import dataclasses

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20,
                         steps_per_dispatch=10)
    cfg.data = dataclasses.replace(cfg.data, use_native_loader=True)
    result = Trainer(cfg).fit()
    assert result.final_step == 20
    assert np.isfinite(result.train_loss).all()


@pytest.mark.slow
def test_trainer_bfloat16_compute(data_cfg, tmp_path):
    """compute_dtype=bfloat16 (the TPU-native activations dtype, exposed
    as --compute_dtype) trains end-to-end and learns."""
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=30)
    cfg.model.compute_dtype = "bfloat16"
    result = Trainer(cfg).fit()
    assert result.final_step == 30
    assert np.isfinite(result.train_loss).all()
    assert result.test_accuracy[-1] > 0.15


@pytest.mark.slow
def test_profile_trace_writes_files(data_cfg, tmp_path):
    """--profile_dir captures a jax.profiler trace during fit."""
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=10)
    cfg.profile_dir = os.path.join(str(tmp_path), "trace")
    result = Trainer(cfg).fit()
    assert result.final_step == 10
    files = []
    for root, _, names in os.walk(cfg.profile_dir):
        files += [os.path.join(root, n) for n in names]
    assert files, "profiler produced no trace files"
