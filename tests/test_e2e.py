"""End-to-end Trainer tests on synthetic data (SURVEY §4: short training run
asserting loss decreases and accuracy beats chance; checkpoint-resume)."""

import pytest
import os

import numpy as np

from dml_cnn_cifar10_tpu.train.loop import Trainer
from tests.conftest import tiny_train_cfg


@pytest.mark.slow
def test_trainer_end_to_end(data_cfg, tmp_path, capsys):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60)
    cfg.metrics_jsonl = os.path.join(str(tmp_path), "metrics.jsonl")
    result = Trainer(cfg).fit()

    assert result.final_step == 60
    assert len(result.train_loss) == 6       # every 10 of 60 local steps
    assert len(result.test_accuracy) == 3    # every 20
    # learns the separable synthetic data
    assert result.train_loss[-1] < result.train_loss[0]
    assert result.test_accuracy[-1] > 0.15   # > 10% chance

    out = capsys.readouterr().out
    assert "Starting Training" in out                       # cifar10cnn.py:225
    assert "task:0_step" in out                             # :234-235 format
    assert " --- Test Accuracy = " in out                   # :240-241 format
    assert os.path.isfile(cfg.metrics_jsonl)
    # checkpoints written at the cadence + final
    assert os.path.isfile(os.path.join(cfg.log_dir, "checkpoint"))


@pytest.mark.slow
def test_trainer_resume_from_checkpoint(data_cfg, tmp_path):
    """Stop at 30, build a fresh Trainer on the same log_dir, resume to 60 —
    the StopAtStepHook-on-global-step contract (cifar10cnn.py:219,222)."""
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=30)
    r1 = Trainer(cfg).fit()
    assert r1.final_step == 30

    cfg2 = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60)
    t2 = Trainer(cfg2)
    state = t2.init_or_restore()
    assert int(np.asarray(state.step)) == 30  # restored, not fresh
    r2 = t2.fit(state=state)
    assert r2.final_step == 60


def test_trainer_full_test_set_eval(data_cfg, tmp_path):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20)
    cfg.eval_full_test_set = True
    t = Trainer(cfg)
    state = t.init_or_restore()
    from dml_cnn_cifar10_tpu.data import pipeline as pipe
    test_it = pipe.input_pipeline(cfg.data, cfg.batch_size, train=False)
    acc = t.evaluate(state, test_it)
    assert 0.0 <= acc <= 1.0


@pytest.mark.slow
def test_trainer_explicit_collectives_mode(data_cfg, tmp_path):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=12)
    cfg.parallel.explicit_collectives = True
    result = Trainer(cfg).fit()
    assert result.final_step == 12
    assert np.isfinite(result.train_loss[0])


@pytest.mark.slow
def test_trainer_chunked_dispatch(data_cfg, tmp_path, capsys):
    """steps_per_dispatch > 1: the chunked (raw-uint8 + device-decode)
    path drives the same loop with identical observable cadence."""

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60,
                         steps_per_dispatch=10)
    result = Trainer(cfg).fit()
    assert result.final_step == 60
    assert len(result.train_loss) == 6       # cadence preserved (every 10)
    assert len(result.test_accuracy) == 3    # every 20
    # Learns the separable data (single-batch losses are noisy at this LR,
    # so judge by the trend and the test accuracy, not one batch).
    assert np.mean(result.train_loss[-2:]) < result.train_loss[0]
    assert result.test_accuracy[-1] > 0.15
    out = capsys.readouterr().out
    assert "task:0_step 9," in out           # local-step numbering preserved
    assert os.path.isfile(os.path.join(cfg.log_dir, "checkpoint"))

    # Misaligned cadence must be rejected up front.
    bad = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=60,
                         steps_per_dispatch=7)
    with pytest.raises(ValueError, match="multiple"):
        Trainer(bad)


@pytest.mark.slow
def test_trainer_chunked_dispatch_native_loader(data_cfg, tmp_path):
    """Chunk mode + the C++ loader: raw chunks stream from the native
    bounded shuffle pool."""
    import dataclasses

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20,
                         steps_per_dispatch=10)
    cfg.data = dataclasses.replace(cfg.data, use_native_loader=True)
    result = Trainer(cfg).fit()
    assert result.final_step == 20
    assert np.isfinite(result.train_loss).all()


@pytest.mark.slow
def test_trainer_bfloat16_compute(data_cfg, tmp_path):
    """compute_dtype=bfloat16 (the TPU-native activations dtype, exposed
    as --compute_dtype) trains end-to-end and learns."""
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=30)
    cfg.model.compute_dtype = "bfloat16"
    result = Trainer(cfg).fit()
    assert result.final_step == 30
    assert np.isfinite(result.train_loss).all()
    assert result.test_accuracy[-1] > 0.15


@pytest.mark.slow
def test_profile_trace_writes_files(data_cfg, tmp_path):
    """--profile_dir captures a jax.profiler trace during fit."""
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=10)
    cfg.profile_dir = os.path.join(str(tmp_path), "trace")
    result = Trainer(cfg).fit()
    assert result.final_step == 10
    files = []
    for root, _, names in os.walk(cfg.profile_dir):
        files += [os.path.join(root, n) for n in names]
    assert files, "profiler produced no trace files"


@pytest.mark.slow
def test_vit_tflops_corrected_for_scanned_stack(data_cfg, tmp_path):
    """Round-2 verdict weak #4: XLA cost analysis counts the ViT's
    depth-scanned block once, so the TFLOP/s metric undercounted ~depth×.
    The stack_probe correction must land in the metrics with its label,
    and the corrected per-step FLOPs must be ≥ (depth/2) × the raw scan-
    once count (i.e. actually corrected, not a no-op)."""
    import dataclasses
    import json
    import time

    depth = 4
    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20,
                         output_every=10, eval_every=20)
    cfg.model = dataclasses.replace(
        cfg.model, name="vit_tiny", vit_depth=depth, vit_dim=96,
        vit_heads=2, logit_relu=False)
    cfg.metrics_jsonl = os.path.join(str(tmp_path), "metrics.jsonl")
    trainer = Trainer(cfg)
    trainer.fit()

    # The flops probe runs on a daemon thread and may post after fit()
    # returns (metrics rows only exist at output boundaries, so a short
    # run can miss it). Poll the trainer's cell — the probe's actual
    # output — then cross-check the magnitude against the probe's own
    # per-block measurement.
    deadline = time.time() + 120
    cell = trainer._flops_cell
    while time.time() < deadline and "flops" not in cell:
        time.sleep(0.5)
    assert cell.get("flops"), cell
    # "stack" may already have been popped into a metrics row by a late
    # output boundary; when still present it must name the correction.
    assert cell.get("stack", f"scan_once_x{depth}") == \
        f"scan_once_x{depth}", cell
    from dml_cnn_cifar10_tpu.models import vit
    # Match the loop's per-chip accounting: it probes at
    # batch / grad_accum / data-axis (8 virtual devices here).
    import jax
    micro = cfg.batch_size // jax.device_count()
    d, bfc, bft = vit.block_flops_probe(cfg.model, cfg.data, micro)
    assert d == depth and bft and bft > 0
    # Corrected per-step FLOPs must carry the full stack: at least
    # (depth-1) x one block (the correction added (depth-1)*bft to a
    # scan-once count that held ~one block + embed/head).
    assert cell["flops"] >= (depth - 1) * bft, (cell, bft)

    # When a boundary DID land after the probe, the labels flow to the
    # metrics stream too.
    with open(cfg.metrics_jsonl) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    labels = [r["flops_stack"] for r in rows if "flops_stack" in r]
    assert all(lb == f"scan_once_x{depth}" for lb in labels)


def test_correct_stack_flops_cases():
    """The pure correction rule (utils/profiling.py): scan-once swaps one
    counted block for depth x true blocks; per-iteration fixes only the
    pallas-vs-dense gap; unusable probe numbers -> probe_failed and the
    figure comes back unchanged (the loop then withholds TFLOP/s)."""
    from dml_cnn_cifar10_tpu.utils.profiling import correct_stack_flops

    f, lb = correct_stack_flops(10.0, 12, 8.0, 9.0)
    assert (f, lb) == (10.0 - 8.0 + 12 * 9.0, "scan_once_x12")
    f, lb = correct_stack_flops(100.0, 12, 8.0, 9.0)
    assert (f, lb) == (100.0 + 12 * 1.0, "per_iteration")
    # Round-3 advisor case: scan-once step whose non-stack FLOPs exceed
    # one block (f = overhead 20 + one body 8) must NOT flip to
    # per_iteration under the depth-aware threshold.
    f, lb = correct_stack_flops(28.0, 12, 8.0, 9.0)
    assert lb == "scan_once_x12"
    for bad in [(0, 8.0, 9.0), (12, None, 9.0), (12, 8.0, None),
                (1, 8.0, 9.0)]:
        f, lb = correct_stack_flops(10.0, *bad)
        assert (f, lb) == (10.0, "probe_failed")
