"""Flash-attention backward + causal masking (ops/flash_attention.py).

The reference trains every op it exposes (``minimize`` builds the backward
for the whole graph, cifar10cnn.py:163); round 2's verdict confirmed the
flash path was forward-only — ``jax.grad`` through it crashed, taking any
≥128-token ViT train config down with it. These tests pin the custom_vjp
contract: values AND gradients match the dense XLA reference (fp32
tolerance), causal and non-divisible sequence lengths included, through
the bare kernel, dispatch, ring, Ulysses, and a full ViT train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.ops import flash_attention as fa


def _qkv(shape, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _grads(f, q, k, v):
    # sin() keeps the cotangent non-trivial (varied sign/magnitude).
    return jax.grad(lambda *a: jnp.sum(jnp.sin(f(*a))), argnums=(0, 1, 2))(
        q, k, v)


def _assert_close(got, want, atol):
    for name, g, w in zip("qkv", got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=atol,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_xla_s512(causal):
    """VERDICT round-2 done-condition (a): S=512 gradient parity."""
    q, k, v = _qkv((1, 512, 2, 32), seed=1)
    g_flash = _grads(
        lambda q, k, v: fa.flash_attention(q, k, v, causal=causal), q, k, v)
    g_ref = _grads(
        lambda q, k, v: attn.xla_attention(q, k, v, causal=causal), q, k, v)
    _assert_close(g_flash, g_ref, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_ragged_seq(causal):
    """S=300 is not a multiple of any block size: the zero-padded rows and
    masked columns must contribute exactly nothing to every gradient."""
    q, k, v = _qkv((2, 300, 2, 16), seed=2)
    g_flash = _grads(
        lambda q, k, v: fa.flash_attention(q, k, v, causal=causal), q, k, v)
    g_ref = _grads(
        lambda q, k, v: attn.xla_attention(q, k, v, causal=causal), q, k, v)
    _assert_close(g_flash, g_ref, atol=2e-5)


def test_flash_causal_forward_parity():
    q, k, v = _qkv((2, 256, 2, 32), seed=3)
    out = fa.flash_attention(q, k, v, causal=True)
    ref = attn.xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


@pytest.mark.slow
def test_flash_bf16_trains():
    """bf16 inputs: grads come back bf16 and finite, close to the f32 ref."""
    q, k, v = _qkv((1, 256, 2, 32), seed=4, dtype=jnp.bfloat16)
    g = _grads(lambda q, k, v: fa.flash_attention(q, k, v), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(q, k, v),
                   *(t.astype(jnp.float32) for t in (q, k, v)))
    for got, want in zip(g, g_ref):
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), atol=0.05)


def test_fwd_lse_matches_dense_logsumexp():
    """The saved residual itself: lse == logsumexp(scores) per row."""
    q, k, v = _qkv((1, 256, 2, 16), seed=5)
    _, lse = fa.flash_attention_fwd_lse(q, k, v)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    want = jnp.transpose(jax.nn.logsumexp(scores, axis=-1), (0, 2, 1))
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want), atol=1e-5)


@pytest.mark.slow
def test_dispatch_attention_differentiates_long_seq():
    """The user-facing face of round 2's confirmed crash: dispatch routes
    ≥128 tokens through the flash kernel, which must now differentiate."""
    q, k, v = _qkv((2, 128, 2, 16), seed=6)
    g = _grads(lambda q, k, v: attn.dispatch_attention(
        q, k, v, use_pallas=True), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(q, k, v), q, k, v)
    _assert_close(g, g_ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_standalone_blockwise(causal):
    """flash_attention_bwd (the ring building block) against autodiff of
    the dense reference, driven with an arbitrary upstream cotangent."""
    q, k, v = _qkv((1, 256, 2, 16), seed=7)
    do = jax.random.normal(jax.random.PRNGKey(99), q.shape)
    out, lse = fa.flash_attention_fwd_lse(q, k, v, causal=causal)
    delta = fa.attention_delta(out, do)
    dq, dk, dv = fa.flash_attention_bwd(q, k, v, do, lse, delta,
                                        causal=causal)
    _, vjp = jax.vjp(
        lambda q, k, v: attn.xla_attention(q, k, v, causal=causal), q, k, v)
    _assert_close((dq, dk, dv), vjp(do), atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp_attention_pallas_grads(sp_mode, causal):
    """VERDICT round-2 done-condition (d): ring and Ulysses with
    use_pallas=True differentiate, causal included, on a data×seq mesh."""
    from dml_cnn_cifar10_tpu.parallel import ring_attention as ring
    from dml_cnn_cifar10_tpu.parallel import ulysses

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "seq"))
    # S_local = 128 ≥ the pallas threshold, so the kernels really engage.
    q, k, v = _qkv((2, 256, 4, 16), seed=8)
    sp_fn = ring.ring_attention if sp_mode == "ring" \
        else ulysses.ulysses_attention
    g = _grads(lambda q, k, v: sp_fn(q, k, v, mesh, use_pallas=True,
                                     causal=causal), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(q, k, v,
                                                      causal=causal),
                   q, k, v)
    _assert_close(g, g_ref, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segment_ids_fwd_and_grads(causal):
    """Packed sequences: segment_ids restrict attention to same-segment
    pairs in both directions (packed-causal = the LM batching layout).
    Ragged S=300 on purpose — padded Q rows are segment-mask-exempt so
    their lse stays finite; their grads must still be exactly absent."""
    q, k, v = _qkv((2, 300, 2, 16), seed=10)
    seg = jnp.concatenate(
        [jnp.zeros((2, 100), jnp.int32), jnp.ones((2, 120), jnp.int32),
         jnp.full((2, 80), 2, jnp.int32)], axis=1)
    out = fa.flash_attention(q, k, v, causal=causal, segment_ids=seg)
    ref = attn.xla_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)
    g = _grads(lambda q, k, v: fa.flash_attention(
        q, k, v, causal=causal, segment_ids=seg), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(
        q, k, v, causal=causal, segment_ids=seg), q, k, v)
    _assert_close(g, g_ref, atol=2e-5)


def test_segment_isolation_is_exact():
    """Tokens in one segment must see zero influence from another: compare
    a packed two-segment batch against the two segments attended alone."""
    q, k, v = _qkv((1, 256, 2, 16), seed=11)
    seg = jnp.concatenate([jnp.zeros((1, 128), jnp.int32),
                           jnp.ones((1, 128), jnp.int32)], axis=1)
    packed = fa.flash_attention(q, k, v, segment_ids=seg)
    alone_a = fa.flash_attention(q[:, :128], k[:, :128], v[:, :128])
    alone_b = fa.flash_attention(q[:, 128:], k[:, 128:], v[:, 128:])
    np.testing.assert_allclose(np.asarray(packed[:, :128]),
                               np.asarray(alone_a), atol=5e-6)
    np.testing.assert_allclose(np.asarray(packed[:, 128:]),
                               np.asarray(alone_b), atol=5e-6)


@pytest.mark.slow
def test_ring_pallas_causal_bf16_grads():
    """bf16 is the realistic long-context training dtype: the causal ring
    backward's lax.switch once crashed on mismatched branch dtypes (f32
    skip zeros vs bf16 kernel partials). Per-step partials now stay f32
    through the accumulation on both engines."""
    from dml_cnn_cifar10_tpu.parallel import ring_attention as ring

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "seq"))
    q, k, v = _qkv((2, 256, 4, 16), seed=9, dtype=jnp.bfloat16)
    g = _grads(lambda q, k, v: ring.ring_attention(
        q, k, v, mesh, use_pallas=True, causal=True), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(q, k, v, causal=True),
                   *(t.astype(jnp.float32) for t in (q, k, v)))
    for got, want in zip(g, g_ref):
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), atol=0.05)


@pytest.mark.slow
def test_vit_256_tokens_trains_end_to_end():
    """VERDICT round-2 done-condition (c): the exact crashing config —
    vit_tiny at crop 64 (16×16 patches + cls = 257 tokens ≥128 → pallas
    path) — runs a jitted value_and_grad step with finite loss and
    non-trivial grads."""
    from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
    from dml_cnn_cifar10_tpu.models import vit

    mc = ModelConfig(name="vit_tiny", use_pallas_attention=True,
                     logit_relu=False)
    dc = DataConfig(crop_height=64, crop_width=64)
    params = vit.init_params(jax.random.PRNGKey(0), mc, dc)
    imgs = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 64, 3))
    labels = jnp.arange(4) % 10

    def loss_fn(p):
        logits = vit.apply(p, imgs, mc)
        return jnp.mean(-jax.nn.log_softmax(logits)[jnp.arange(4), labels])

    val, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert jnp.isfinite(val)
    gsum = jax.tree.reduce(
        lambda a, b: a + b,
        jax.tree.map(lambda g: float(jnp.sum(jnp.abs(g))), grads))
    assert gsum > 0.0


@pytest.mark.slow
@pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp_attention_segment_ids(sp_mode, causal):
    """Packed sequences through sequence parallelism: ring carries each
    K/V shard's segment ids around the ring with it; Ulysses all-gathers
    the ids for its full-sequence local kernel. Segment boundaries
    (96/64/96) intentionally straddle the 128-token shard boundary, so
    cross-shard spans are real. Values and grads vs the masked dense
    reference."""
    from dml_cnn_cifar10_tpu.parallel import ring_attention as ring
    from dml_cnn_cifar10_tpu.parallel import ulysses

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "seq"))
    q, k, v = _qkv((2, 256, 4, 16), seed=12)
    seg = jnp.concatenate(
        [jnp.zeros((2, 96), jnp.int32), jnp.ones((2, 64), jnp.int32),
         jnp.full((2, 96), 2, jnp.int32)], axis=1)
    sp_fn = ring.ring_attention if sp_mode == "ring" \
        else ulysses.ulysses_attention
    out = sp_fn(q, k, v, mesh, use_pallas=True, causal=causal,
                segment_ids=seg)
    ref = attn.xla_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)
    g = _grads(lambda q, k, v: sp_fn(q, k, v, mesh, use_pallas=True,
                                     causal=causal, segment_ids=seg),
               q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(
        q, k, v, causal=causal, segment_ids=seg), q, k, v)
    _assert_close(g, g_ref, atol=5e-5)


def test_cross_length_causal_bwd():
    """kv_len > q_len with causal: trailing K rows have no live Q block,
    and the dK/dV q-side index clamp must stay in range on those
    fully-dead grid rows (review r3 edge case)."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (2, 200, 2, 32))
    k = jax.random.normal(ks[1], (2, 512, 2, 32))
    v = jax.random.normal(ks[2], (2, 512, 2, 32))
    out, lse = fa.flash_attention_fwd_lse(q, k, v, causal=True)
    ref = attn.xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)
    do = jax.random.normal(jax.random.PRNGKey(9), q.shape)
    delta = fa.attention_delta(out, do)
    grads = fa.flash_attention_bwd(q, k, v, do, lse, delta, causal=True)
    _, vjp = jax.vjp(
        lambda q, k, v: attn.xla_attention(q, k, v, causal=True), q, k, v)
    _assert_close(grads, vjp(do), atol=5e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_sliding_window(causal):
    """Sliding-window/local attention: the band |row-col| < W (lower half
    only under causal) in both directions, vs the banded dense
    reference; ragged S so padded rows (window-mask-exempt) stay
    finite."""
    q, k, v = _qkv((1, 300, 2, 16), seed=13)
    for W in (64, 200):
        out = fa.flash_attention(q, k, v, causal=causal, window=W)
        ref = attn.xla_attention(q, k, v, causal=causal, window=W)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-6)
        g = _grads(lambda q, k, v: fa.flash_attention(
            q, k, v, causal=causal, window=W), q, k, v)
        g_ref = _grads(lambda q, k, v: attn.xla_attention(
            q, k, v, causal=causal, window=W), q, k, v)
        _assert_close(g, g_ref, atol=2e-5)


@pytest.mark.slow
def test_flash_window_composes_with_segments():
    """window x segment_ids x causal in one kernel call — the packed
    local-attention LM layout."""
    q, k, v = _qkv((2, 256, 2, 16), seed=14)
    seg = jnp.concatenate([jnp.zeros((2, 120), jnp.int32),
                           jnp.ones((2, 136), jnp.int32)], axis=1)
    kw = dict(causal=True, window=48, segment_ids=seg)
    out = fa.flash_attention(q, k, v, **kw)
    ref = attn.xla_attention(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)
    g = _grads(lambda q, k, v: fa.flash_attention(q, k, v, **kw), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(q, k, v, **kw),
                   q, k, v)
    _assert_close(g, g_ref, atol=2e-5)


@pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [True, False])
def test_sp_attention_window(sp_mode, causal):
    """Sliding-window attention through sequence parallelism (round-4):
    the ring only visits the diagonal and adjacent shards (W <= S_local,
    static kv_start offsets in the block masks); Ulysses passes the
    window to its full-sequence local kernel. S_local=128 clears the
    ring's >=128 Pallas gate, so the PALLAS kv_start path really runs
    (a 64-token shard silently fell back to the jnp engine — round-4
    review finding); W=100 < S_local straddles every shard boundary.
    Values and grads vs the global dense reference."""
    from dml_cnn_cifar10_tpu.parallel import ring_attention as ring
    from dml_cnn_cifar10_tpu.parallel import ulysses

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("data", "seq"))
    q, k, v = _qkv((1, 512, 4, 16), seed=21)   # 4 heads: ulysses needs
    W = 100                                    # heads % seq_axis == 0
    sp_fn = ring.ring_attention if sp_mode == "ring" \
        else ulysses.ulysses_attention
    out = sp_fn(q, k, v, mesh, use_pallas=True, causal=causal, window=W)
    ref = attn.xla_attention(q, k, v, causal=causal, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)
    g = _grads(lambda q, k, v: sp_fn(q, k, v, mesh, use_pallas=True,
                                     causal=causal, window=W), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(
        q, k, v, causal=causal, window=W), q, k, v)
    _assert_close(g, g_ref, atol=5e-5)


@pytest.mark.parametrize("kv_start", [-192, 0, 192])
def test_flash_kv_start_unaligned_parity(kv_start):
    """kv_start (ring neighbor offsets) with an UNALIGNED kv length
    (192, not a block multiple): the padded-column bound must key on the
    LOCAL column while the window band sees the SHIFTED global column —
    conflating them attends zero-padding (kv_start<0) or masks the whole
    shard (kv_start>0) (round-4 review finding, reproduced both ways)."""
    q, k, v = _qkv((1, 192, 1, 16), seed=31)
    W = 64
    out, lse = fa.flash_attention_fwd_lse(q, k, v, window=W, causal=False,
                                          kv_start=kv_start, block_q=128,
                                          block_k=128)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (16 ** -0.5)
    s = attn.mask_scores(s, 192, 192, window=W, kv_start=kv_start)
    probs = jax.nn.softmax(s, axis=-1)
    live = jnp.max(s, axis=-1, keepdims=True) > -5e29
    probs = jnp.where(live, probs, 0.0)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)


def test_ring_window_composes_with_segments():
    """window x segment_ids through the ring: the packed local-attention
    LM layout at sequence-parallel scale. Segment boundary (100) and the
    W=40 band both straddle the 64-token shard boundaries."""
    from dml_cnn_cifar10_tpu.parallel import ring_attention as ring

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("data", "seq"))
    q, k, v = _qkv((2, 256, 2, 16), seed=22)
    seg = jnp.concatenate([jnp.zeros((2, 100), jnp.int32),
                           jnp.ones((2, 156), jnp.int32)], axis=1)
    kw = dict(causal=True, window=40, segment_ids=seg)
    out = ring.ring_attention(q, k, v, mesh, use_pallas=True, **kw)
    ref = attn.xla_attention(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)
    g = _grads(lambda q, k, v: ring.ring_attention(
        q, k, v, mesh, use_pallas=True, **kw), q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(q, k, v, **kw),
                   q, k, v)
    _assert_close(g, g_ref, atol=5e-5)


def test_ring_window_rejects_oversized_window():
    """W > S_local cannot be dispatched by the adjacent-shard ring switch
    and must fail loudly, not return silently wrong attention."""
    from dml_cnn_cifar10_tpu.parallel import ring_attention as ring

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("data", "seq"))
    q, k, v = _qkv((1, 256, 2, 16), seed=23)
    with pytest.raises(ValueError, match="exceeds the local shard"):
        ring.ring_attention(q, k, v, mesh, window=65)


def test_window_fully_dead_rows_are_finite_and_inert():
    """A cross-length window geometry can leave Q rows with NO keys at
    all (row - window + 1 >= kv_len). Those rows must emit zeros, not
    NaN, and their (arbitrary) cotangents must not leak into other rows'
    dK/dV — the forward publishes a large lse so the backward's
    p = exp(s - lse) is exactly zero there."""
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (1, 512, 1, 64))
    k = jax.random.normal(ks[1], (1, 128, 1, 64))
    v = jax.random.normal(ks[2], (1, 128, 1, 64))
    out = fa.flash_attention(q, k, v, window=64)
    assert bool(jnp.all(jnp.isfinite(out)))
    assert bool(jnp.all(out[:, 256:] == 0))       # rows past kv+window
    g = _grads(lambda q, k, v: fa.flash_attention(q, k, v, window=64),
               q, k, v)
    for t in g:
        assert bool(jnp.all(jnp.isfinite(t)))
    # Live-region gradients still match the dense reference exactly
    # (no contamination from the dead rows).
    g_live = _grads(lambda q, k, v: fa.flash_attention(
        q, k, v, window=64)[:, :190], q, k, v)
    g_ref = _grads(lambda q, k, v: attn.xla_attention(
        q, k, v, window=64)[:, :190], q, k, v)
    _assert_close(g_live, g_ref, atol=5e-6)
