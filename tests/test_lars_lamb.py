"""LARS / LAMB: per-layer trust-ratio optimizers for large-batch scaling.

No reference counterpart (plain SGD, ``cifar10cnn.py:162``) — these are
the standard companions of wide ``data``-axis scaling. LAMB is pinned
numerically against optax.lamb; LARS against a NumPy hand-computation of
You et al.'s local-LR formula.
"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
from dml_cnn_cifar10_tpu.train import optim


def _tree(rng):
    return {
        "layer": {"kernel": rng.normal(0, 0.5, (6, 4)).astype(np.float32),
                  "bias": rng.normal(0, 0.1, (4,)).astype(np.float32)},
    }


@pytest.mark.slow
def test_lamb_matches_optax(rng):
    import optax

    cfg = OptimConfig(optimizer="lamb", learning_rate=0.01,
                      weight_decay=0.01, schedule="constant")
    params = _tree(rng)
    grads = jax.tree.map(lambda p: np.asarray(
        rng.normal(0, 0.2, p.shape), np.float32), params)

    state = optim.sgd_init(params, cfg)
    ours = params
    ref = optax.lamb(0.01, b1=cfg.adam_b1, b2=cfg.adam_b2,
                     eps=cfg.adam_eps, weight_decay=0.01)
    ref_state = ref.init(params)
    theirs = params
    for _ in range(3):
        ours, state = optim.sgd_update(grads, state, ours, cfg)
        updates, ref_state = ref.update(grads, ref_state, theirs)
        theirs = optax.apply_updates(theirs, updates)
    for a, b in zip(jax.tree.leaves(ours), jax.tree.leaves(theirs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_lars_local_lr_formula(rng):
    """One LARS step against NumPy: adapted kernel gets
    eta*||w||/(||g+wd*w||-ish) scaling, 1-D bias skips adaptation,
    momentum buffer accumulates the adapted gradient."""
    cfg = OptimConfig(optimizer="lars", learning_rate=0.1,
                      weight_decay=0.01, momentum=0.9,
                      schedule="constant", lars_trust_coef=0.001)
    params = _tree(rng)
    grads = jax.tree.map(lambda p: np.asarray(
        rng.normal(0, 0.2, p.shape), np.float32), params)

    state = optim.sgd_init(params, cfg)
    new_params, new_state = optim.sgd_update(grads, state, params, cfg)

    w = params["layer"]["kernel"]
    g = grads["layer"]["kernel"] + 0.01 * w
    local = 0.001 * np.linalg.norm(w) / (np.linalg.norm(g) + cfg.lars_eps)
    want_kernel = w - 0.1 * (local * g)          # m0 = 0 -> m1 = adapted g
    np.testing.assert_allclose(
        np.asarray(new_params["layer"]["kernel"]), want_kernel,
        rtol=1e-5, atol=1e-7)

    b = params["layer"]["bias"]
    gb = grads["layer"]["bias"] + 0.01 * b       # no trust adaptation
    np.testing.assert_allclose(
        np.asarray(new_params["layer"]["bias"]), b - 0.1 * gb,
        rtol=1e-5, atol=1e-7)
    assert int(new_state["step"]) == 1


def test_lars_zero_norm_guard():
    """Zero weights / zero grads take the ratio-1 path, no NaN."""
    cfg = OptimConfig(optimizer="lars", learning_rate=0.1,
                      schedule="constant")
    params = {"k": jnp.zeros((3, 3)), "b": jnp.zeros((3,))}
    grads = {"k": jnp.zeros((3, 3)), "b": jnp.zeros((3,))}
    state = optim.sgd_init(params, cfg)
    new_params, _ = optim.sgd_update(grads, state, params, cfg)
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree.leaves(new_params))


@pytest.mark.slow
def test_lars_trains_under_fsdp(rng):
    """LARS momentum buffers shard like params (same 'momentum' key the
    sharding rules already map) and a large-batch step runs on the
    dp x fsdp mesh."""
    data = DataConfig(normalize="scale")
    cfg = ModelConfig(logit_relu=False)
    optim_cfg = OptimConfig(optimizer="lars", learning_rate=0.1,
                            weight_decay=1e-4, schedule="constant",
                            warmup_steps=2)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("cnn")
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, data,
                                        optim_cfg, fsdp=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, data, optim_cfg, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, cfg, optim_cfg, mesh,
                                     state_sharding=sh)
    images = rng.normal(0.5, 0.25, (64, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 64).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(3):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert np.isfinite(losses).all()
    from dml_cnn_cifar10_tpu.parallel import shardings
    assert shardings.assert_some_leaf_sharded(state.opt["momentum"],
                                              axis="data")


def test_lamb_rejects_momentum():

    with pytest.raises(ValueError, match="momentum"):
        optim.sgd_init({"w": jnp.ones(2)},
                       OptimConfig(optimizer="lamb", momentum=0.9))
