"""Spatial partitioning: conv models shard the image H dim over ``seq``.

The vision analog of sequence parallelism — activations for large images
split spatially across devices, GSPMD inserting the conv/pool halo
exchanges. The reference has nothing like it (fixed 24x24 inputs,
``cifar10cnn.py:17-18``); it is a pure TPU-scale capability. Tests prove
the input really lands H-sharded and the math is identical to plain dp,
on the 8-virtual-device CPU mesh.
"""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
import pytest

DATA = DataConfig(normalize="scale")


def _mesh(data, seq):
    return mesh_lib.build_mesh(ParallelConfig(data_axis=data, seq_axis=seq))


def _run(model_cfg, mesh, images, labels, nsteps=3, fsdp=False):
    model_def = get_model(model_cfg.name)
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim, fsdp=fsdp)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    spatial = model_def.spatial and mesh.shape["seq"] > 1
    im, lb = mesh_lib.shard_batch(mesh, images, labels, spatial=spatial)
    losses = []
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses, im


def test_images_land_h_sharded(rng):
    mesh = _mesh(4, 2)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    im, _ = mesh_lib.shard_batch(mesh, images, labels, spatial=True)
    assert im.sharding.spec == P("data", "seq", None, None)
    assert im.addressable_shards[0].data.shape == (16 // 4, 24 // 2, 24, 3)


@pytest.mark.slow
def test_cnn_spatial_matches_dp(rng):
    """data=4 x seq=2 (H halved per shard) must equal pure dp: the halo
    exchange reconstructs exactly the rows SAME conv/pool padding needs."""
    cfg = ModelConfig(logit_relu=False)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    _, loss_dp, _ = _run(cfg, _mesh(8, 1), images, labels)
    st, loss_sp, im = _run(cfg, _mesh(4, 2), images, labels)
    np.testing.assert_allclose(loss_dp, loss_sp, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_resnet_spatial_matches_dp(rng):
    """BatchNorm under spatial sharding: the batch statistics reduce over
    (B, H, W) — GSPMD turns the partial spatial sums into a cross-device
    reduction, so stats (and therefore training) match plain dp."""
    cfg = ModelConfig(name="resnet18", logit_relu=False)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    _, loss_dp, _ = _run(cfg, _mesh(8, 1), images, labels, nsteps=2)
    _, loss_sp, _ = _run(cfg, _mesh(4, 2), images, labels, nsteps=2)
    np.testing.assert_allclose(loss_dp, loss_sp, rtol=2e-5, atol=2e-6)


def test_vit_does_not_claim_spatial():
    """ViTs use ``seq`` for token parallelism — ModelDef.spatial stays off
    so the input sharding never puts image H on the seq axis."""
    assert not get_model("vit_tiny").spatial
    assert not get_model("vit_moe").spatial
    assert get_model("cnn").spatial
    assert get_model("resnet18").spatial
    assert get_model("resnet50").spatial


@pytest.mark.slow
def test_spatial_resident_matches_hostfed(rng):
    """The HBM-resident gather path pins the same spatial layout the
    host-fed chunk uses: identical math on identical indices."""
    mesh = _mesh(4, 2)
    cfg = ModelConfig(logit_relu=False)
    model_def = get_model("cnn")
    optim = OptimConfig(learning_rate=0.01)
    data_cfg = DataConfig(normalize="scale")
    ds_images = rng.integers(0, 256, (64, 32, 32, 3)).astype(np.uint8)
    ds_labels = rng.integers(0, 10, 64).astype(np.int32)
    idx = rng.integers(0, 64, (2, 16)).astype(np.int32)

    def fresh_state(sh):
        return step_lib.init_train_state(
            jax.random.key(0), model_def, cfg, data_cfg, optim, mesh,
            state_sharding=sh)

    sh = step_lib.train_state_shardings(mesh, model_def, cfg, data_cfg,
                                        optim)
    resident = step_lib.make_train_chunk_resident(
        model_def, cfg, optim, mesh,
        jax.device_put(ds_images, mesh_lib.replicated(mesh)),
        jax.device_put(ds_labels, mesh_lib.replicated(mesh)),
        state_sharding=sh, data_cfg=data_cfg)
    st_r, m_r = resident(fresh_state(sh),
                         jax.device_put(idx, mesh_lib.batch_sharding(
                             mesh, 2, leading_dims=1)))

    hostfed = step_lib.make_train_chunk(model_def, cfg, optim, mesh,
                                        state_sharding=sh,
                                        data_cfg=data_cfg)
    im, lb = mesh_lib.shard_batch(mesh, ds_images[idx], ds_labels[idx],
                                  leading_dims=1, spatial=True)
    st_h, m_h = hostfed(fresh_state(sh), im, lb)
    np.testing.assert_allclose(float(jax.device_get(m_r["loss"])),
                               float(jax.device_get(m_h["loss"])),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(st_r.params),
                    jax.tree.leaves(st_h.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_spatial_chunked_step(rng):
    """The K-step raw-uint8 chunk path under spatial sharding: device-side
    decode (crop from 32 to 24) composes with the H-sharded layout."""
    mesh = _mesh(4, 2)
    cfg = ModelConfig(logit_relu=False)
    model_def = get_model("cnn")
    optim = OptimConfig(learning_rate=0.01)
    data_cfg = DataConfig(normalize="scale")
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, data_cfg,
                                        optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, data_cfg, optim, mesh,
        state_sharding=sh)
    chunk = step_lib.make_train_chunk(model_def, cfg, optim, mesh,
                                      state_sharding=sh, data_cfg=data_cfg)
    raw = rng.integers(0, 256, (2, 16, 32, 32, 3)).astype(np.uint8)
    rlb = rng.integers(0, 10, (2, 16)).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, raw, rlb, leading_dims=1,
                                  spatial=True)
    state, metrics = chunk(state, im, lb)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    assert int(jax.device_get(state.step)) == 2


@pytest.mark.slow
def test_spatial_composes_with_fsdp(rng):
    """Input H over seq + state over data in one step: the two shardings
    are orthogonal (activations vs weights) and must compose — same math
    as plain dp, state really partitioned."""
    cfg = ModelConfig(logit_relu=False)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    _, loss_dp, _ = _run(cfg, _mesh(8, 1), images, labels)
    st, losses, im = _run(cfg, _mesh(4, 2), images, labels, fsdp=True)
    assert im.sharding.spec == P("data", "seq", None, None)
    from dml_cnn_cifar10_tpu.parallel import shardings
    assert shardings.assert_some_leaf_sharded(st.params, axis="data")
    np.testing.assert_allclose(loss_dp, losses, rtol=1e-5, atol=1e-6)
