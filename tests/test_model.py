"""Model unit tests: shapes, param counts, init statistics, quirk switches
(SURVEY §4)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.models import cnn
from dml_cnn_cifar10_tpu.ops import layers as L


def test_param_shapes_and_count():
    """24x24 input → two 3x3/2 SAME pools → 6x6x64 = 2304 flatten, exactly
    the reference's hardcoded reshaped_dim (cifar10cnn.py:126-131)."""
    params = cnn.init_params(jax.random.key(0), ModelConfig(), DataConfig())
    assert params["conv1"]["kernel"].shape == (5, 5, 3, 64)
    assert params["conv2"]["kernel"].shape == (5, 5, 64, 64)
    assert params["full1"]["kernel"].shape == (2304, 384)
    assert params["full2"]["kernel"].shape == (384, 192)
    assert params["full3"]["kernel"].shape == (192, 10)
    want = (5*5*3*64 + 64) + (5*5*64*64 + 64) + (2304*384 + 384) \
        + (384*192 + 192) + (192*10 + 10)
    assert cnn.param_count(params) == want


def test_init_statistics():
    """Truncated normal sigma=0.05 within ±2 sigma (cifar10cnn.py:97-98),
    biases constant 0.1 (cifar10cnn.py:100-101)."""
    params = cnn.init_params(jax.random.key(1), ModelConfig(), DataConfig())
    w = np.asarray(params["full1"]["kernel"]).ravel()
    assert np.abs(w).max() <= 0.1 + 1e-6          # hard truncation at 2 sigma
    assert abs(w.mean()) < 2e-3
    assert 0.03 < w.std() < 0.05                  # truncated std ≈ 0.88*sigma
    assert np.allclose(params["conv1"]["bias"], 0.1)


def test_forward_shape_and_faithful_logit_relu():
    data, model = DataConfig(), ModelConfig(logit_relu=True)
    params = cnn.init_params(jax.random.key(0), model, data)
    x = jnp.asarray(np.random.default_rng(0).normal(
        127, 50, (4, 24, 24, 3)).astype(np.float32))
    logits = cnn.apply(params, x, model)
    assert logits.shape == (4, 10)
    assert (logits >= 0).all()                    # faithful: ReLU'd logits

    fixed = ModelConfig(logit_relu=False)
    raw = cnn.apply(params, x, fixed)
    assert (raw < 0).any()                        # fixed mode exposes negatives
    np.testing.assert_allclose(jax.nn.relu(raw), logits, rtol=1e-5)


def test_full_resolution_input_changes_flatten_dim():
    """Config-driven flatten (no hardcoded 2304): 32x32 input → 8x8x64."""
    data = DataConfig(crop_height=32, crop_width=32)
    params = cnn.init_params(jax.random.key(0), ModelConfig(), data)
    assert params["full1"]["kernel"].shape == (4096, 384)
    x = jnp.zeros((2, 32, 32, 3))
    assert cnn.apply(params, x, ModelConfig()).shape == (2, 10)


def test_cifar100_head_swap():
    model = ModelConfig(num_classes=100)
    params = cnn.init_params(jax.random.key(0), model, DataConfig())
    assert params["full3"]["kernel"].shape == (192, 100)
    x = jnp.zeros((2, 24, 24, 3))
    assert cnn.apply(params, x, model).shape == (2, 100)


def test_max_pool_matches_reference_semantics():
    """3x3 window stride 2 SAME (cifar10cnn.py:113): 24→12, overlapping max."""
    x = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
    out = L.max_pool(x)
    assert out.shape == (1, 2, 2, 1)
    # windows centered per SAME/stride2: max over x[0:3,0:3] = 10
    assert float(out[0, 0, 0, 0]) == 10.0
    assert float(out[0, 1, 1, 0]) == 15.0


def test_conv2d_matches_manual_nhwc():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, 5, 5, 2)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(3, 3, 2, 4)).astype(np.float32))
    out = L.conv2d(x, k)
    assert out.shape == (1, 5, 5, 4)
    # centre output pixel = full 3x3 valid correlation at that location
    want = np.einsum("hwc,hwco->o", np.asarray(x)[0, 1:4, 1:4], np.asarray(k))
    np.testing.assert_allclose(np.asarray(out)[0, 2, 2], want,
                               rtol=1e-2, atol=1e-2)


@pytest.mark.slow
def test_bfloat16_compute_path():
    model = ModelConfig(compute_dtype="bfloat16")
    params = cnn.init_params(jax.random.key(0), model, DataConfig())
    x = jnp.ones((2, 24, 24, 3))
    logits = cnn.apply(params, x, model)
    assert logits.dtype == jnp.float32             # outputs upcast for loss
    ref = cnn.apply(params, x, ModelConfig())
    np.testing.assert_allclose(logits, ref, rtol=0.1, atol=2.0)
