"""Device-side preprocess == the host decode path (crop/pad/normalize)."""

import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import DataConfig
from dml_cnn_cifar10_tpu.data import records as rec
from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess


def _host(images_u8: np.ndarray, cfg: DataConfig) -> np.ndarray:
    """The deterministic host path (pipeline._finish without augmentation)."""
    x = images_u8.astype(np.float32)
    x = rec.center_crop(x, cfg.crop_height, cfg.crop_width)
    return rec.normalize(x, cfg.normalize)


@pytest.mark.parametrize("normalize", ["none", "scale", "standardize"])
def test_matches_host_path(rng, normalize):
    cfg = DataConfig(normalize=normalize)  # 32x32 -> 24x24 center crop
    images = rng.integers(0, 256, (16, 32, 32, 3)).astype(np.uint8)
    np.testing.assert_allclose(
        np.asarray(device_preprocess(images, cfg)), _host(images, cfg),
        rtol=1e-5, atol=1e-5)


def test_pad_if_smaller_matches_host(rng):
    cfg = DataConfig(crop_height=40, crop_width=36, normalize="scale")
    images = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    out = np.asarray(device_preprocess(images, cfg))
    assert out.shape == (4, 40, 36, 3)
    np.testing.assert_allclose(out, _host(images, cfg), rtol=1e-5, atol=1e-5)


def test_chunked_leading_dims(rng):
    cfg = DataConfig(normalize="standardize")
    chunk = rng.integers(0, 256, (3, 8, 32, 32, 3)).astype(np.uint8)
    out = np.asarray(device_preprocess(chunk, cfg))
    assert out.shape == (3, 8, 24, 24, 3)
    flat = _host(chunk.reshape(-1, 32, 32, 3), cfg)
    np.testing.assert_allclose(out.reshape(-1, 24, 24, 3), flat,
                               rtol=1e-5, atol=1e-5)


def test_rejects_augmented_config():
    with pytest.raises(ValueError):
        device_preprocess(np.zeros((1, 32, 32, 3), np.uint8),
                          DataConfig(random_crop=True))
