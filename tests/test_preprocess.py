"""Device-side preprocess == the host decode path (crop/pad/normalize)."""

import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import DataConfig
from dml_cnn_cifar10_tpu.data import records as rec
from dml_cnn_cifar10_tpu.ops.preprocess import device_preprocess


def _host(images_u8: np.ndarray, cfg: DataConfig) -> np.ndarray:
    """The deterministic host path (pipeline._finish without augmentation)."""
    x = images_u8.astype(np.float32)
    x = rec.center_crop(x, cfg.crop_height, cfg.crop_width)
    return rec.normalize(x, cfg.normalize)


@pytest.mark.parametrize("normalize", ["none", "scale", "standardize"])
def test_matches_host_path(rng, normalize):
    cfg = DataConfig(normalize=normalize)  # 32x32 -> 24x24 center crop
    images = rng.integers(0, 256, (16, 32, 32, 3)).astype(np.uint8)
    np.testing.assert_allclose(
        np.asarray(device_preprocess(images, cfg)), _host(images, cfg),
        rtol=1e-5, atol=1e-5)


def test_pad_if_smaller_matches_host(rng):
    cfg = DataConfig(crop_height=40, crop_width=36, normalize="scale")
    images = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    out = np.asarray(device_preprocess(images, cfg))
    assert out.shape == (4, 40, 36, 3)
    np.testing.assert_allclose(out, _host(images, cfg), rtol=1e-5, atol=1e-5)


def test_chunked_leading_dims(rng):
    cfg = DataConfig(normalize="standardize")
    chunk = rng.integers(0, 256, (3, 8, 32, 32, 3)).astype(np.uint8)
    out = np.asarray(device_preprocess(chunk, cfg))
    assert out.shape == (3, 8, 24, 24, 3)
    flat = _host(chunk.reshape(-1, 32, 32, 3), cfg)
    np.testing.assert_allclose(out.reshape(-1, 24, 24, 3), flat,
                               rtol=1e-5, atol=1e-5)


def test_augmented_requires_key():
    with pytest.raises(ValueError):
        device_preprocess(np.zeros((1, 32, 32, 3), np.uint8),
                          DataConfig(random_crop=True))


def test_device_random_crop(rng):
    import jax

    cfg = DataConfig(random_crop=True, normalize="none")
    images = rng.integers(0, 256, (64, 32, 32, 3)).astype(np.uint8)
    k = jax.random.key(0)
    out = np.asarray(device_preprocess(images, cfg, k))
    assert out.shape == (64, 24, 24, 3)
    # Deterministic per key; different keys give different windows.
    again = np.asarray(device_preprocess(images, cfg, k))
    np.testing.assert_array_equal(out, again)
    other = np.asarray(device_preprocess(images, cfg, jax.random.key(1)))
    assert (out != other).any()
    # Every crop is a contiguous window: check via a coordinate image whose
    # value encodes (row, col) — the window must be row/col-translates.
    coord = (np.arange(32)[:, None] * 32 + np.arange(32)[None, :])
    coord_img = np.repeat(coord[None, :, :, None], 3, axis=3).astype(np.uint8)
    w = np.asarray(device_preprocess(
        np.broadcast_to(coord_img, (4, 32, 32, 3)), cfg, k))
    for i in range(4):
        d = w[i, :, :, 0]
        assert (np.diff(d, axis=1) % 256 == 1).all()  # contiguous cols


def test_device_random_crop_with_fused_flip(rng):
    """Crop+flip fused into the column-selection matmul: every output is
    a contiguous window read forward or backward."""
    import jax

    cfg = DataConfig(random_crop=True, random_flip=True, normalize="none")
    coord = (np.arange(32)[:, None] * 32 + np.arange(32)[None, :])
    imgs = np.broadcast_to(
        np.repeat(coord[None, :, :, None], 3, axis=3), (64, 32, 32, 3)
    ).astype(np.uint8)
    out = np.asarray(device_preprocess(imgs, cfg, jax.random.key(0)))
    assert out.shape == (64, 24, 24, 3)
    dirs = set()
    for i in range(64):
        d = np.diff(out[i, :, :, 0], axis=1) % 256
        assert (d == 1).all() or (d == 255).all()  # forward or mirrored
        dirs.add(int(d[0, 0]))
    assert dirs == {1, 255}  # both orientations occur across 64 images


def test_device_random_flip(rng):
    import jax

    cfg = DataConfig(random_crop=False, random_flip=True, normalize="none")
    images = rng.integers(0, 256, (512, 32, 32, 3)).astype(np.uint8)
    out = np.asarray(device_preprocess(images, cfg, jax.random.key(0)))
    center = _host(images, cfg)  # flip disabled on host path here
    flipped = (out != center).any(axis=(1, 2, 3))
    # ~half flipped, and flipped images are exact mirrors.
    assert 0.3 < flipped.mean() < 0.7
    np.testing.assert_array_equal(out[flipped], center[flipped][:, :, ::-1])


@pytest.mark.slow
def test_augmented_chunk_trains(rng):
    """make_train_chunk with an augmented data config: fresh crops per
    chunk, deterministic per (seed, step)."""
    import jax
    import jax.numpy as jnp

    from dml_cnn_cifar10_tpu.config import (ModelConfig, OptimConfig,
                                            ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    cfg = DataConfig(random_crop=True, random_flip=True, normalize="scale")
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    optim_cfg = OptimConfig(learning_rate=0.02)
    mesh = mesh_lib.build_mesh(ParallelConfig())
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, cfg, optim_cfg, mesh)
    chunk = step_lib.make_train_chunk(model_def, model_cfg, optim_cfg, mesh,
                                      data_cfg=cfg)
    raw = rng.integers(0, 256, (2, 16, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (2, 16)).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, raw, labels, leading_dims=1)
    state, m = chunk(state, im, lb)
    assert np.isfinite(float(m["loss"]))
    assert int(jax.device_get(state.step)) == 2


def test_device_brightness_contrast(rng):
    import jax

    images = rng.integers(0, 256, (32, 32, 32, 3)).astype(np.uint8)
    # Brightness only: out - center_crop(in) is a per-image constant.
    cfg_b = DataConfig(random_brightness=40.0, normalize="none")
    out = np.asarray(device_preprocess(images, cfg_b, jax.random.key(0)))
    base = _host(images, cfg_b)
    diff = out - base
    per_image = diff.reshape(32, -1)
    assert np.allclose(per_image, per_image[:, :1], atol=1e-4)
    assert (np.abs(per_image[:, 0]) <= 40.0 + 1e-4).all()
    assert np.unique(np.round(per_image[:, 0], 3)).size > 8  # varies

    # Contrast only: per-image per-channel means preserved.
    cfg_c = DataConfig(random_contrast=0.5, normalize="none")
    out = np.asarray(device_preprocess(images, cfg_c, jax.random.key(1)))
    np.testing.assert_allclose(out.mean(axis=(1, 2)), base.mean(axis=(1, 2)),
                               rtol=1e-4, atol=1e-3)
    assert (out != base).any()


def test_host_brightness_contrast_semantics(rng):
    images = rng.normal(128, 40, (16, 24, 24, 3)).astype(np.float32)
    g = np.random.default_rng(0)
    out = rec.random_brightness(images, 30.0, g)
    d = (out - images).reshape(16, -1)
    assert np.allclose(d, d[:, :1])
    out = rec.random_contrast(images, 0.5, np.random.default_rng(1))
    np.testing.assert_allclose(out.mean(axis=(1, 2)),
                               images.mean(axis=(1, 2)), rtol=1e-5)
