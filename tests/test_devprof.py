"""Device-time attribution (utils/devprof.py): capture-spec parsing, op
bucketing, trace parsing, the boundary step-time estimator — and the
ISSUE-8 acceptance smoke: a CPU run with --profile_at_steps whose
stream carries schema-clean `devtime` records and train rows with
`device_step_ms`, rendered by telemetry_report in both formats."""

import json
import os
import subprocess
import sys

import pytest

from dml_cnn_cifar10_tpu.utils import devprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# spec parsing and op bucketing
# ---------------------------------------------------------------------------

def test_parse_profile_at_steps():
    assert devprof.parse_profile_at_steps(None) is None
    assert devprof.parse_profile_at_steps("") is None
    assert devprof.parse_profile_at_steps("100:20") == (100, 20)
    assert devprof.parse_profile_at_steps("0:1") == (0, 1)
    for bad in ("100", "a:b", "5:0", "-1:5", "1:2:3"):
        with pytest.raises(ValueError, match="profile_at_steps"):
            devprof.parse_profile_at_steps(bad)


def test_classify_op_buckets():
    for name in ("all-reduce.1", "all-gather-start",
                 "reduce-scatter.3", "all-to-all",
                 "collective-permute-done", "fusion.all_reduce"):
        assert devprof.classify_op(name) == "collective", name
    for name in ("infeed.2", "outfeed", "copy-start.1", "copy.3",
                 "MemcpyD2H", "transfer"):
        assert devprof.classify_op(name) == "infeed", name
    for name in ("fusion.123", "convolution.2", "dot_general",
                 "fwd_bwd/conv2d", "optimizer/add.4"):
        assert devprof.classify_op(name) == "compute", name


# ---------------------------------------------------------------------------
# trace parsing (synthetic Chrome docs — no profiler involved)
# ---------------------------------------------------------------------------

def _doc(lane_name, pid=7):
    """One device lane: 2 compute ops, 1 collective, 1 infeed."""
    return {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": lane_name}},
        {"ph": "X", "name": "fusion.1", "pid": pid, "tid": 0,
         "ts": 0.0, "dur": 600.0},
        {"ph": "X", "name": "fusion.1", "pid": pid, "tid": 0,
         "ts": 700.0, "dur": 400.0},
        {"ph": "X", "name": "all-reduce.2", "pid": pid, "tid": 0,
         "ts": 1200.0, "dur": 300.0},
        {"ph": "X", "name": "infeed.3", "pid": pid, "tid": 0,
         "ts": 1600.0, "dur": 100.0},
    ]}


def test_parse_trace_doc_buckets_and_topk():
    lanes = devprof.parse_trace_doc(_doc("/device:TPU:0"), top_k=2)
    assert len(lanes) == 1
    lane = lanes[0]
    assert lane["device"] == "/device:TPU:0"
    assert lane["compute_ms"] == pytest.approx(1.0)
    assert lane["collective_ms"] == pytest.approx(0.3)
    assert lane["infeed_ms"] == pytest.approx(0.1)
    assert lane["total_ms"] == pytest.approx(1.4)
    assert lane["window_ms"] == pytest.approx(1.7)   # 0 .. 1700 us
    # top_k=2 keeps the two largest ops, fracs against the lane total.
    assert [op["name"] for op in lane["top_ops"]] == ["fusion.1",
                                                      "all-reduce.2"]
    assert lane["top_ops"][0]["calls"] == 2
    assert lane["top_ops"][0]["frac"] == pytest.approx(1.0 / 1.4,
                                                       abs=1e-3)
    assert lane["top_ops"][1]["bucket"] == "collective"


def test_parse_trace_doc_prefers_device_lanes_with_host_fallback():
    # Device + host lanes present: host lane excluded.
    doc = _doc("/device:TPU:0", pid=7)
    doc["traceEvents"] += _doc("/host:CPU", pid=9)["traceEvents"]
    lanes = devprof.parse_trace_doc(doc)
    assert [ln["device"] for ln in lanes] == ["/device:TPU:0"]
    # Host lanes only (the CPU backend): fall back so the record shape
    # survives on every platform.
    lanes = devprof.parse_trace_doc(_doc("/host:CPU", pid=9))
    assert [ln["device"] for ln in lanes] == ["/host:CPU"]
    assert devprof.parse_trace_doc({"traceEvents": []}) == []


# ---------------------------------------------------------------------------
# boundary step-time estimator
# ---------------------------------------------------------------------------

def test_device_step_estimator_math():
    est = devprof.DeviceStepEstimator()
    # No mark yet: device_step unknown, drain wait still reported.
    dev, drain = est.boundary(10, drain_start=1.0, drain_end=1.25)
    assert dev is None and drain == pytest.approx(250.0)
    est.mark(10, now=100.0)
    # 10 steps between mark and boundary; drain ends 2 s after mark.
    dev, drain = est.boundary(20, drain_start=101.5, drain_end=102.0)
    assert dev == pytest.approx(200.0)       # 2 s / 10 steps
    assert drain == pytest.approx(500.0)
    # Zero-step window (mark at the boundary step) degrades to None.
    est.mark(20, now=200.0)
    dev, _ = est.boundary(20, drain_start=200.1, drain_end=200.2)
    assert dev is None


# ---------------------------------------------------------------------------
# acceptance smoke: real Trainer run on CPU with a capture window
# ---------------------------------------------------------------------------

def test_profile_at_steps_trainer_run(tmp_path):
    """Acceptance smoke, via the real CLI in a SINGLE-device
    subprocess: the in-process test mesh simulates 8 CPU devices whose
    executor threads busy-wait — profiling that floods the trace with
    millions of spin events and the profiler's stop/export takes
    minutes. One real CPU device keeps the same code path (window arm →
    drained-boundary stop → parse → devtime emit) at test speed, and
    covers the --profile_at_steps flag end-to-end."""
    log_dir = str(tmp_path / "logs")
    jsonl = str(tmp_path / "m.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "dml_cnn_cifar10_tpu",
         "--dataset", "synthetic", "--data_dir", str(tmp_path / "d"),
         "--synthetic_train_records", "256",
         "--log_dir", log_dir, "--metrics_jsonl", jsonl,
         "--batch_size", "32", "--total_steps", "10",
         "--output_every", "2", "--eval_every", "10",
         "--checkpoint_every", "10", "--learning_rate", "0.01",
         "--use_native_loader", "false",
         "--profile_at_steps", "4:2"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[devprof]" in proc.stdout     # the attribution narrator line

    with open(jsonl) as f:
        recs = [json.loads(line) for line in f]
    devs = [r for r in recs if r["kind"] == "devtime"]
    assert devs, "capture window must emit devtime records"
    for r in devs:
        assert r["step"] >= 6                # stopped at/after 4 + 2
        assert isinstance(r["top_ops"], list) and r["top_ops"]
        total = (r["compute_ms"] + r["collective_ms"]
                 + r["infeed_ms"])
        assert total == pytest.approx(r["total_ms"], abs=0.01)
    # The trace itself landed under the default <log_dir>/devprof.
    assert os.path.isdir(os.path.join(log_dir, "devprof"))

    # Always-on estimator: every train row carries the keys; after the
    # first window they are real numbers.
    trains = [r for r in recs if r["kind"] == "train"]
    assert trains
    for r in trains:
        assert "device_step_ms" in r and "drain_wait_ms" in r
    assert any(isinstance(r["device_step_ms"], (int, float))
               for r in trains)

    # Schema-clean (devtime + the new train keys are registered kinds).
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(jsonl, strict=True) == []

    # Both report renderers cover the new sections.
    from tools import telemetry_report
    out = telemetry_report.summarize(jsonl)
    assert "device-time attribution" in out
    assert "device step time" in out
    doc = telemetry_report.summarize_json(jsonl)
    assert doc["devtime"] and doc["device_split"]["boundaries"] > 0
    assert doc["device_split"]["device_step_ms_p50"] > 0


def test_profile_window_fail_open(tmp_path, capsys, monkeypatch):
    """Attribution must never kill a training run: a profiler that
    fails to start, and a capture that leaves no parseable trace, both
    degrade to a warning."""
    import jax

    # Start failure → window done, loop continues.
    def boom(_dir):
        raise RuntimeError("no profiler here")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    win = devprof.ProfileWindow(0, 1, str(tmp_path / "a"))
    win.maybe_start(0)
    assert win.state == "done"
    assert "start failed" in capsys.readouterr().err

    # Clean start/stop but nothing written → "no parseable trace".
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    win = devprof.ProfileWindow(0, 1, str(tmp_path / "b"))
    win.maybe_start(0)
    assert win.state == "active"
    # Not drained / before the stop step: no-op.
    win.maybe_stop(5, drained=False)
    win.maybe_stop(0, drained=True)
    assert win.state == "active"
    win.maybe_stop(5, drained=True)
    assert win.state == "done"
    assert "no parseable trace" in capsys.readouterr().err
