"""Tensor parallelism: param sharding rules over the ``model`` mesh axis.

Proves tp is *real* — weights actually partitioned on device, training
math identical to pure dp — on the 8-virtual-device CPU mesh (SURVEY §4's
no-pod distributed test recipe).
"""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import shardings
from dml_cnn_cifar10_tpu.parallel import step as step_lib

DATA = DataConfig(normalize="scale")


def _mesh(data=4, model=2, seq=1):
    return mesh_lib.build_mesh(
        ParallelConfig(data_axis=data, model_axis=model, seq_axis=seq))


def _batch(rng, n=16, hw=24):
    images = rng.normal(0.5, 0.25, (n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


def _run_steps(model_cfg, mesh, images, labels, nsteps=3, momentum=0.0,
               optim=None):
    model_def = get_model(model_cfg.name)
    optim = optim or OptimConfig(learning_rate=0.01, momentum=momentum)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    losses = []
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def test_cnn_pspec_rules():
    model_def = get_model("cnn")
    params = jax.eval_shape(
        lambda k: model_def.init(k, ModelConfig(), DATA), jax.random.key(0))
    specs = shardings.param_pspecs("cnn", params)
    assert specs["full1"]["kernel"] == P(None, "model")
    assert specs["full1"]["bias"] == P("model")
    assert specs["full2"]["kernel"] == P("model", None)
    assert specs["full2"]["bias"] == P()
    assert specs["conv1"]["kernel"] == P()


def test_vit_pspec_rules_stacked_blocks():
    cfg = ModelConfig(name="vit_tiny")
    model_def = get_model("vit_tiny")
    params = jax.eval_shape(
        lambda k: model_def.init(k, cfg, DATA), jax.random.key(0))
    specs = shardings.param_pspecs("vit_tiny", params)
    # stacked leaves carry the leading [depth] axis -> extra None
    assert specs["blocks"]["qkv"]["kernel"] == P(None, None, "model")
    assert specs["blocks"]["qkv"]["bias"] == P(None, "model")
    assert specs["blocks"]["proj"]["kernel"] == P(None, "model", None)
    assert specs["blocks"]["mlp1"]["kernel"] == P(None, None, "model")
    assert specs["blocks"]["mlp2"]["kernel"] == P(None, "model", None)
    assert specs["blocks"]["proj"]["bias"] == P()
    assert specs["head"]["kernel"] == P()


def test_cnn_params_actually_sharded():
    mesh = _mesh()
    model_def = get_model("cnn")
    cfg = ModelConfig(logit_relu=False)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA,
                                        OptimConfig())
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, OptimConfig(), mesh,
        state_sharding=sh)
    k = state.params["full1"]["kernel"]
    assert k.sharding.spec == P(None, "model")
    # each model-shard holds half of the 384 output features
    local = k.addressable_shards[0].data.shape
    assert local == (k.shape[0], k.shape[1] // 2), local
    assert shardings.assert_some_leaf_sharded(state)


@pytest.mark.parametrize("name,momentum", [("cnn", 0.0), ("cnn", 0.9),
                                           ("vit_tiny", 0.0)])
@pytest.mark.slow
def test_tp_matches_dp(name, momentum, rng):
    """model_axis=2 must be a pure layout change: same losses, same final
    params as the dp-only mesh, to fp32 tolerance."""
    cfg = ModelConfig(name=name, logit_relu=False)
    if name == "vit_tiny":
        cfg = dataclasses.replace(cfg, vit_depth=2, vit_dim=64, vit_heads=2,
                                  patch_size=8)
    images, labels = _batch(rng)
    st_dp, loss_dp = _run_steps(cfg, _mesh(8, 1), images, labels,
                                momentum=momentum)
    st_tp, loss_tp = _run_steps(cfg, _mesh(4, 2), images, labels,
                                momentum=momentum)
    np.testing.assert_allclose(loss_dp, loss_tp, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(st_dp.params),
                    jax.tree.leaves(st_tp.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=2e-5, atol=2e-6)


def test_tp_heads_sharded_vit():
    """With model | heads, the qkv kernel is head-sharded: each shard holds
    whole heads (heads-major layout in models/vit.py)."""
    mesh = _mesh(4, 2)
    cfg = ModelConfig(name="vit_tiny", vit_depth=2, vit_dim=64, vit_heads=2,
                      patch_size=8, logit_relu=False)
    model_def = get_model("vit_tiny")
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA,
                                        OptimConfig())
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, OptimConfig(), mesh,
        state_sharding=sh)
    k = state.params["blocks"]["qkv"]["kernel"]
    assert k.shape == (2, 64, 3 * 64)
    assert k.addressable_shards[0].data.shape == (2, 64, 3 * 32)


def test_explicit_collectives_rejects_tp():
    with pytest.raises(ValueError):
        step_lib.make_train_step(get_model("cnn"), ModelConfig(),
                                 OptimConfig(), _mesh(4, 2),
                                 explicit_collectives=True)


@pytest.mark.slow
def test_adamw_under_tp(rng):
    """AdamW's sharded mu/nu moments flow through a real tensor-parallel
    train step (spec-level coverage lives in test_train_math)."""
    cfg = ModelConfig(name="vit_tiny", vit_depth=2, vit_dim=64, vit_heads=2,
                      patch_size=4, pool="mean", logit_relu=False)
    images, labels = _batch(rng)
    st, losses = _run_steps(
        cfg, _mesh(), images, labels, nsteps=2,
        optim=OptimConfig(optimizer="adamw", learning_rate=1e-3))
    assert np.isfinite(losses).all()
    assert int(jax.device_get(st.step)) == 2
