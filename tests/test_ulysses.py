"""Ulysses (all-to-all) sequence parallelism on the 8-virtual-device CPU
mesh: op-level parity with dense attention, dp/tp composition, and the
full ViT training step with ``sp_mode='ulysses'`` matching dp-only."""

import dataclasses

import jax
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.ops import attention as attn
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
from dml_cnn_cifar10_tpu.parallel import ulysses


def _qkv(rng, b=2, s=64, h=8, d=16):
    mk = lambda: rng.normal(0, 1, (b, s, h, d)).astype(np.float32)
    return (jax.numpy.asarray(mk()), jax.numpy.asarray(mk()),
            jax.numpy.asarray(mk()))


def _mesh(data, model=1, seq=1):
    return mesh_lib.build_mesh(
        ParallelConfig(data_axis=data, model_axis=model, seq_axis=seq))


def test_ulysses_matches_dense_seq_only():
    """All 8 devices on the seq axis (8 heads, one per device slice)."""
    mesh = _mesh(1, 1, 8)
    q, k, v = _qkv(np.random.default_rng(0))
    out = ulysses.ulysses_attention(q, k, v, mesh)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_ulysses_composes_with_data_parallel():
    mesh = _mesh(2, 1, 4)
    q, k, v = _qkv(np.random.default_rng(1), b=4, s=32, h=4)
    sharded = jax.device_put((q, k, v), ulysses.sequence_sharding(mesh))
    out = ulysses.ulysses_attention(*sharded, mesh)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_ulysses_composes_with_tensor_parallel():
    """dp=2 x tp=2 x sp=2: heads shard over model, each slice splits
    over seq."""
    mesh = _mesh(2, 2, 2)
    q, k, v = _qkv(np.random.default_rng(2), b=4, s=32, h=4)
    out = ulysses.ulysses_attention(q, k, v, mesh)
    ref = attn.xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = _mesh(1, 1, 8)
    q, k, v = _qkv(np.random.default_rng(3), h=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="heads"):
        ulysses.ulysses_attention(q, k, v, mesh)


def test_ulysses_rejects_indivisible_seq():
    mesh = _mesh(1, 1, 8)
    q, k, v = _qkv(np.random.default_rng(4), s=60)
    with pytest.raises(ValueError, match="sequence"):
        ulysses.ulysses_attention(q, k, v, mesh)


# ---- full training step with sp_mode="ulysses" ----

DATA = DataConfig(crop_height=32, crop_width=32, normalize="scale")
VIT = ModelConfig(name="vit_tiny", pool="mean", logit_relu=False,
                  vit_depth=2, vit_dim=64, vit_heads=4, patch_size=4)


def _run(model_cfg, mesh, images, labels, nsteps=2):
    model_def = get_model(model_cfg.name)
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return losses


@pytest.mark.parametrize("axes", [(2, 1, 4), (4, 1, 2), (2, 2, 2)])
@pytest.mark.slow
def test_ulysses_train_matches_dp(axes, rng):
    images = rng.normal(0.5, 0.25, (8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    uly = dataclasses.replace(VIT, sp_mode="ulysses")
    loss_dp = _run(VIT, _mesh(8), images, labels)
    loss_sp = _run(uly, _mesh(*axes), images, labels)
    np.testing.assert_allclose(loss_dp, loss_sp, rtol=2e-5, atol=2e-6)
    assert np.isfinite(loss_sp).all()
