"""Loss / metrics / optimizer vs NumPy references (SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import OptimConfig
from dml_cnn_cifar10_tpu.train import loss as loss_lib
from dml_cnn_cifar10_tpu.train import metrics as metrics_lib
from dml_cnn_cifar10_tpu.train import optim as optim_lib


def _np_softmax_ce(logits, labels):
    z = logits - logits.max(axis=1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
    return -logp[np.arange(len(labels)), labels].mean()


def test_loss_matches_numpy(rng):
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    got = float(loss_lib.softmax_cross_entropy(jnp.asarray(logits),
                                               jnp.asarray(labels)))
    np.testing.assert_allclose(got, _np_softmax_ce(logits, labels), rtol=1e-5)


def test_accuracy_matches_numpy(rng):
    logits = rng.normal(size=(32, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 32).astype(np.int32)
    got = float(metrics_lib.batch_accuracy(jnp.asarray(logits),
                                           jnp.asarray(labels)))
    want = (logits.argmax(1) == labels).mean()
    np.testing.assert_allclose(got, want)


def test_faithful_lr_is_constant():
    """Reference quirk: decay keyed on a never-incremented variable →
    constant LR 0.1 (cifar10cnn.py:161,216)."""
    cfg = OptimConfig(dead_lr_decay=True)
    for step in [0, 100, 250, 5000, 19999]:
        np.testing.assert_allclose(
            float(optim_lib.learning_rate(cfg, jnp.asarray(step))), 0.1,
            rtol=1e-6)


def test_fixed_lr_staircase_decay():
    """tf.train.exponential_decay(0.1, step, 250, 0.9, staircase=True)."""
    cfg = OptimConfig(dead_lr_decay=False)
    lr = lambda s: float(optim_lib.learning_rate(cfg, jnp.asarray(s)))
    np.testing.assert_allclose(lr(0), 0.1)
    np.testing.assert_allclose(lr(249), 0.1)
    np.testing.assert_allclose(lr(250), 0.1 * 0.9, rtol=1e-6)
    np.testing.assert_allclose(lr(999), 0.1 * 0.9**3, rtol=1e-5)


def test_sgd_update_matches_formula(rng):
    params = {"w": jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))}
    grads = {"w": jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))}
    cfg = OptimConfig()
    st = optim_lib.sgd_init(params, cfg)
    new_params, new_st = optim_lib.sgd_update(grads, st, params, cfg)
    np.testing.assert_allclose(
        np.asarray(new_params["w"]),
        np.asarray(params["w"]) - 0.1 * np.asarray(grads["w"]), rtol=1e-6)
    assert int(new_st["step"]) == 1


def test_sgd_momentum_and_weight_decay(rng):
    params = {"w": jnp.ones((3,), jnp.float32)}
    grads = {"w": jnp.full((3,), 2.0)}
    cfg = OptimConfig(momentum=0.9, weight_decay=0.01, dead_lr_decay=True)
    st = optim_lib.sgd_init(params, cfg)
    p1, st = optim_lib.sgd_update(grads, st, params, cfg)
    # g' = g + wd*p = 2.01; m = g'; p1 = 1 - 0.1*2.01
    np.testing.assert_allclose(np.asarray(p1["w"]), 1 - 0.1 * 2.01, rtol=1e-6)
    p2, st = optim_lib.sgd_update(grads, st, p1, cfg)
    g2 = 2.0 + 0.01 * np.asarray(p1["w"])
    m2 = 0.9 * 2.01 + g2
    np.testing.assert_allclose(np.asarray(p2["w"]),
                               np.asarray(p1["w"]) - 0.1 * m2, rtol=1e-6)


def test_grad_clipping():
    params = {"w": jnp.zeros((2,), jnp.float32)}
    grads = {"w": jnp.asarray([3.0, 4.0])}  # norm 5
    cfg = OptimConfig(grad_clip_norm=1.0)
    st = optim_lib.sgd_init(params, cfg)
    p1, _ = optim_lib.sgd_update(grads, st, params, cfg)
    np.testing.assert_allclose(np.asarray(p1["w"]),
                               -0.1 * np.asarray([0.6, 0.8]), rtol=1e-5)


def test_optax_equivalence(rng):
    """as_optax() applies the same update as the hand-rolled SGD."""
    import optax
    params = {"w": jnp.asarray(rng.normal(size=(5,)).astype(np.float32))}
    grads = {"w": jnp.asarray(rng.normal(size=(5,)).astype(np.float32))}
    cfg = OptimConfig(dead_lr_decay=False)
    tx = optim_lib.as_optax(cfg)
    ost = tx.init(params)
    updates, _ = tx.update(grads, ost, params)
    via_optax = optax.apply_updates(params, updates)
    ours, _ = optim_lib.sgd_update(grads, optim_lib.sgd_init(params, cfg),
                                   params, cfg)
    np.testing.assert_allclose(np.asarray(via_optax["w"]),
                               np.asarray(ours["w"]), rtol=1e-6)


def test_cosine_schedule_with_warmup():
    cfg = OptimConfig(learning_rate=1.0, schedule="cosine",
                      warmup_steps=10, cosine_decay_steps=110)
    lr = lambda s: float(optim_lib.learning_rate(cfg, jnp.asarray(s)))
    assert lr(0) == pytest.approx(0.1)            # ramp: (0+1)/10
    assert lr(9) == pytest.approx(1.0)            # warmup done
    assert lr(10) == pytest.approx(1.0)           # cosine start
    assert lr(60) == pytest.approx(0.5, abs=0.02) # halfway
    assert lr(110) == pytest.approx(0.0, abs=1e-6)
    assert lr(200) == pytest.approx(0.0, abs=1e-6)  # clamps past horizon


def test_constant_and_exponential_schedules_unchanged():
    const = OptimConfig(learning_rate=0.3, schedule="constant",
                        warmup_steps=0)
    assert float(optim_lib.learning_rate(const, jnp.asarray(999))) == \
        pytest.approx(0.3)
    # Reference faithful mode: dead decay -> constant 0.1 at any step.
    ref = OptimConfig()
    assert float(optim_lib.learning_rate(ref, jnp.asarray(5000))) == \
        pytest.approx(0.1)
    # Fixed mode: staircase decay really decays.
    fixed = OptimConfig(dead_lr_decay=False)
    assert float(optim_lib.learning_rate(fixed, jnp.asarray(250))) == \
        pytest.approx(0.09)
    with pytest.raises(ValueError, match="cosine_decay_steps"):
        bad = OptimConfig(schedule="cosine")
        optim_lib.learning_rate(bad, jnp.asarray(0))
    with pytest.raises(ValueError, match="warmup"):
        bad = OptimConfig(schedule="cosine", warmup_steps=500,
                          cosine_decay_steps=400)
        optim_lib.learning_rate(bad, jnp.asarray(0))


def test_host_lr_mirror_matches_device():
    """train/loop._current_lr (host math, logging) == optim.learning_rate
    (device math) across schedules and steps."""
    from dml_cnn_cifar10_tpu.config import TrainConfig
    from dml_cnn_cifar10_tpu.train.loop import _current_lr

    cfgs = [
        OptimConfig(),
        OptimConfig(dead_lr_decay=False),
        OptimConfig(schedule="constant", learning_rate=0.02),
        OptimConfig(schedule="cosine", warmup_steps=10,
                    cosine_decay_steps=110, learning_rate=0.5),
        OptimConfig(dead_lr_decay=False, staircase=False, warmup_steps=5),
    ]
    for o in cfgs:
        t = TrainConfig()
        t.optim = o
        for step in (0, 1, 9, 10, 60, 249, 250, 251, 1000):
            host = _current_lr(t, step)
            dev = float(optim_lib.learning_rate(o, jnp.asarray(step)))
            assert host == pytest.approx(dev, rel=1e-6), (o.schedule, step)


def test_adamw_matches_optax():
    """Native AdamW == optax.adamw over several steps (same clip/wd/LR)."""
    import optax

    cfg = OptimConfig(optimizer="adamw", learning_rate=0.01,
                      weight_decay=0.05, grad_clip_norm=1.0,
                      schedule="constant")
    params = {"w": jnp.arange(6.0).reshape(2, 3) / 10, "b": jnp.ones((3,))}
    rng = np.random.default_rng(0)

    state = optim_lib.sgd_init(params, cfg)
    tx = optim_lib.as_optax(cfg)
    opt_state = tx.init(params)
    p_mine, p_ox = params, params
    for _ in range(5):
        grads = jax.tree.map(
            lambda p: jnp.asarray(rng.normal(0, 1, p.shape), jnp.float32),
            params)
        p_mine, state = optim_lib.sgd_update(grads, state, p_mine, cfg)
        updates, opt_state = tx.update(grads, opt_state, p_ox)
        p_ox = optax.apply_updates(p_ox, updates)
    for a, b in zip(jax.tree.leaves(p_mine), jax.tree.leaves(p_ox)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert int(state["step"]) == 5


@pytest.mark.slow
def test_adamw_trains_vit(rng):
    """AdamW through the full train step (the transformer-ladder recipe)."""
    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    data = DataConfig(crop_height=32, crop_width=32, normalize="scale")
    vit = ModelConfig(name="vit_tiny", pool="mean", logit_relu=False,
                      vit_depth=2, vit_dim=64, vit_heads=2, patch_size=4)
    cfg = OptimConfig(optimizer="adamw", learning_rate=1e-3,
                      weight_decay=0.01, schedule="cosine",
                      warmup_steps=2, cosine_decay_steps=100)
    mesh = mesh_lib.build_mesh(ParallelConfig())
    model_def = get_model("vit_tiny")
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, vit, data, cfg, mesh)
    train = step_lib.make_train_step(model_def, vit, cfg, mesh)
    images = rng.normal(0.5, 0.25, (16, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    losses = []
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    for _ in range(5):
        state, m = train(state, im, lb)
        losses.append(float(jax.device_get(m["loss"])))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # overfits the fixed batch


def test_adamw_moments_shard_with_params():
    """Under tensor parallelism mu/nu mirror the param shardings (not
    replicated) — optimizer memory scales with TP like the params do."""
    from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig, ParallelConfig
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    data = DataConfig(crop_height=32, crop_width=32, normalize="scale")
    vit = ModelConfig(name="vit_tiny", pool="mean", logit_relu=False,
                      vit_depth=2, vit_dim=64, vit_heads=2, patch_size=4)
    cfg = OptimConfig(optimizer="adamw", learning_rate=1e-3)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=4, model_axis=2))
    sh = step_lib.train_state_shardings(mesh, get_model("vit_tiny"), vit,
                                        data, cfg)
    p_specs = [s.spec for s in jax.tree.leaves(sh.params)]
    mu_specs = [s.spec for s in jax.tree.leaves(sh.opt["mu"])]
    nu_specs = [s.spec for s in jax.tree.leaves(sh.opt["nu"])]
    assert mu_specs == p_specs and nu_specs == p_specs
    assert any(spec != jax.sharding.PartitionSpec() for spec in mu_specs)

    with pytest.raises(ValueError, match="momentum"):
        optim_lib.sgd_init({"w": jnp.zeros(2)},
                           OptimConfig(optimizer="adamw", momentum=0.9))


def test_label_smoothing_loss():
    """ε-smoothed CE == (1-ε)*CE + ε*uniform-CE, computed densely."""
    from dml_cnn_cifar10_tpu.train import loss as loss_lib

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 2, (8, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, 8).astype(np.int32))
    eps = 0.1
    got = float(loss_lib.softmax_cross_entropy(logits, labels,
                                               label_smoothing=eps))
    logp = np.asarray(jax.nn.log_softmax(logits, -1))
    onehot = np.eye(10)[np.asarray(labels)]
    target = (1 - eps) * onehot + eps / 10
    want = float(np.mean(-np.sum(target * logp, -1)))
    assert got == pytest.approx(want, rel=1e-6)
    # eps=0 is exactly the parity loss.
    assert float(loss_lib.softmax_cross_entropy(logits, labels)) == \
        pytest.approx(float(loss_lib.softmax_cross_entropy(
            logits, labels, label_smoothing=0.0)))


@pytest.mark.slow
def test_label_smoothing_through_train_step(rng):
    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    data = DataConfig(normalize="scale")
    model_cfg = ModelConfig(logit_relu=False)
    mesh = mesh_lib.build_mesh(ParallelConfig())
    model_def = get_model("cnn")
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    def loss_at(eps):
        cfg = OptimConfig(learning_rate=0.01, label_smoothing=eps)
        state = step_lib.init_train_state(
            jax.random.key(0), model_def, model_cfg, data, cfg, mesh)
        train = step_lib.make_train_step(model_def, model_cfg, cfg, mesh)
        _, m = train(state, im, lb)
        return float(jax.device_get(m["loss"]))

    # Smoothing changes the loss value (and at init, raises it toward
    # the uniform target's entropy floor).
    assert loss_at(0.1) != loss_at(0.0)
