"""Alert-triggered flight recorder (utils/flightrec.py): ring bounds,
the one-bundle-per-emitted-firing contract against the alert engine's
rate limit (suppressed re-fires capture nothing, ``alert_resolved``
never captures), bundle atomicity/contents — and the acceptance drill:
a supervised train sim with ``nan@15`` where the ``nonfinite_burst``
firing auto-captures exactly one bundle, ``tools/postmortem.py``
renders it, the ring holds the records leading to the fault, and
arming the recorder adds ZERO device fetches."""

import json
import os

import pytest

from dml_cnn_cifar10_tpu.utils.alerts import AlertEngine, built_in_rules
from dml_cnn_cifar10_tpu.utils.flightrec import FlightRecorder
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger


def _serve(shed=0, p99=10.0):
    return {"requests": 100, "completed": 100 - shed,
            "shed_queue": shed, "shed_deadline": 0, "cache_hit": 0,
            "qps": 50.0, "p50_ms": 2.0, "p95_ms": 5.0, "p99_ms": p99,
            "batch_fill": 0.5, "window_s": 2.0}


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------

def test_ring_is_bounded_and_ordered():
    fr = FlightRecorder(size=8, postmortem_dir=None)
    for i in range(20):
        fr.observe("train", {"step": i})
    snap = fr.snapshot()
    assert [r["step"] for r in snap] == list(range(12, 20))
    assert all(r["kind"] == "train" and "wallclock" in r for r in snap)


def test_ring_coerces_unjsonable_fields(tmp_path):
    fr = FlightRecorder(size=4, postmortem_dir=str(tmp_path))
    fr.observe("train", {"step": 1, "weird": object()})
    (rec,) = fr.snapshot()
    json.dumps(rec)                        # ring stays JSON-ready
    assert rec["step"] == 1


def test_from_config_armed_only_by_postmortem_dir(tmp_path):
    class Cfg:
        postmortem_dir = None
        flightrec_size = 16

    assert FlightRecorder.from_config(Cfg()) is None
    Cfg.postmortem_dir = str(tmp_path / "pm")
    fr = FlightRecorder.from_config(Cfg())
    assert fr is not None and fr.size == 16


# ---------------------------------------------------------------------------
# alert → capture contract (rate limit, resolution, atomicity)
# ---------------------------------------------------------------------------

def _recorder_with_engine(tmp_path, min_interval_s=30.0):
    """Production wiring with an injectable clock: logger → flight
    recorder observer (FIRST) → alert engine observer."""
    pm_dir = str(tmp_path / "pm")
    logger = MetricsLogger(jsonl_path=str(tmp_path / "m.jsonl"))
    fr = FlightRecorder(size=32, postmortem_dir=pm_dir, logger=logger)
    logger.add_observer(fr.observer())
    eng = AlertEngine(built_in_rules(), min_interval_s=min_interval_s)
    clock = {"now": 100.0}
    logger.add_observer(
        lambda kind, fields: eng.observe(kind, fields, emit=logger.log,
                                         now=clock["now"]))
    return logger, fr, pm_dir, clock


def test_one_bundle_per_emitted_firing_rate_limited(tmp_path):
    logger, fr, pm_dir, clock = _recorder_with_engine(tmp_path)
    # Four shed/recover flaps inside the 30 s rate-limit window: ONE
    # emitted alert (+ its resolution), so exactly one bundle — the
    # suppressed re-fires emit no record and capture nothing, and the
    # alert_resolved records never capture.
    for _ in range(4):
        logger.log("serve", **_serve(shed=5))
        clock["now"] += 1.0
        logger.log("serve", **_serve(shed=0))
        clock["now"] += 1.0
    assert len(fr.bundles) == 1
    # Past the window the next breach fires — and captures — again.
    clock["now"] = 200.0
    logger.log("serve", **_serve(shed=5))
    assert len(fr.bundles) == 2
    logger.close()

    assert sorted(os.listdir(pm_dir)) == [os.path.basename(b)
                                          for b in fr.bundles]
    assert all("serve_shed" in b for b in fr.bundles)
    # The stream says both captures happened (and passes strict lint).
    recs = _read_jsonl(str(tmp_path / "m.jsonl"))
    pms = [r for r in recs if r["kind"] == "postmortem"]
    assert [r["dir"] for r in pms] == fr.bundles
    assert sum(1 for r in recs if r["kind"] == "alert") == 2
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(str(tmp_path / "m.jsonl"),
                                         strict=True) == []


def test_bundle_contents_and_atomicity(tmp_path):
    logger, fr, pm_dir, clock = _recorder_with_engine(tmp_path)
    logger.log("train", step=10, loss=1.0)
    logger.log("serve", **_serve(shed=5))
    (bundle,) = fr.bundles
    # Atomic publish: no temp dirs left behind, all files present.
    assert all(".tmp" not in n for n in os.listdir(pm_dir))
    names = set(os.listdir(bundle))
    assert {"ring.jsonl", "alert.json", "env.json",
            "context.json"} <= names
    with open(os.path.join(bundle, "alert.json")) as f:
        alert = json.load(f)
    assert alert["rule"] == "serve_shed" and "captured_wallclock" in alert
    # The ring holds the causal prefix: the records BEFORE the firing,
    # then the alert record itself (the observer attach order contract).
    ring = _read_jsonl(os.path.join(bundle, "ring.jsonl"))
    assert [r["kind"] for r in ring] == ["train", "serve", "alert"]
    logger.close()


def test_capture_failure_is_fail_open(tmp_path):
    target = tmp_path / "pm"
    target.write_text("not a directory")   # capture will fail
    logger, fr, _, _ = _recorder_with_engine(tmp_path)
    logger.log("serve", **_serve(shed=5))  # must not raise
    assert fr.bundles == []
    logger.close()


def test_devprof_window_pops_once(tmp_path):
    logger, fr, _, _ = _recorder_with_engine(tmp_path)
    assert fr.pop_devprof_window(5) is None
    logger.log("serve", **_serve(shed=5))
    win = fr.pop_devprof_window(7)
    assert win is not None and win.start_step == 7
    assert win.out_dir == os.path.join(fr.bundles[0], "devprof")
    assert fr.pop_devprof_window(8) is None      # one-shot
    logger.close()


# ---------------------------------------------------------------------------
# acceptance drill: supervised nan@15, one bundle, rendered post-mortem
# ---------------------------------------------------------------------------

def test_flight_recorder_drill_supervised_nan(data_cfg, tmp_path,
                                              monkeypatch):
    import jax

    from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised
    from tests.conftest import tiny_train_cfg

    counts = {"n": 0}
    real_get = jax.device_get

    def counting_get(x):
        counts["n"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    def run(sub, postmortem_dir):
        cfg = tiny_train_cfg(data_cfg, str(tmp_path / sub),
                             total_steps=30)
        cfg.checkpoint_every = 10
        cfg.output_every = 10
        cfg.eval_every = 30
        cfg.check_numerics = True
        cfg.on_nonfinite = "rollback"
        cfg.recovery_backoff_s = 0.01
        cfg.fault_spec = "nan@15"
        cfg.metrics_jsonl = os.path.join(str(tmp_path / sub), "m.jsonl")
        cfg.postmortem_dir = postmortem_dir
        counts["n"] = 0
        result = fit_supervised(cfg)
        assert result.final_step == 30
        return counts["n"], cfg

    pm_dir = str(tmp_path / "pm")
    fetches_armed, cfg = run("armed", pm_dir)

    # Exactly one bundle: nonfinite_burst fired once for the one fault.
    bundles = [os.path.join(pm_dir, n) for n in sorted(os.listdir(pm_dir))]
    assert len(bundles) == 1 and "nonfinite_burst" in bundles[0]

    # The ring holds the run leading to the fault: training boundaries
    # before it, the fault record itself, then the firing that tripped
    # the capture.
    ring = _read_jsonl(os.path.join(bundles[0], "ring.jsonl"))
    kinds = [r["kind"] for r in ring]
    assert kinds[-1] == "alert"
    assert "fault" in kinds and "train" in kinds
    faults = [r for r in ring if r["kind"] == "fault"]
    # Both the injected poison and its boundary detection are ringed,
    # in causal order, before the firing.
    assert [r["fault"] for r in faults] == ["nan", "nonfinite"]
    assert faults[0].get("injected") and not faults[1].get("injected")

    # The capture armed a one-shot devprof window; the restarted
    # attempt's loop popped it and wrote under the bundle.
    assert os.path.isdir(os.path.join(bundles[0], "devprof"))

    # The stream records the capture and still lints strictly.
    recs = _read_jsonl(cfg.metrics_jsonl)
    pms = [r for r in recs if r["kind"] == "postmortem"]
    assert len(pms) == 1 and pms[0]["dir"] == bundles[0]
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl,
                                         strict=True) == []

    # tools/postmortem.py renders the bundle (text + scan + merged
    # Perfetto of the ring).
    from tools import postmortem
    assert postmortem.scan(pm_dir) == bundles
    text = postmortem.render_bundle(postmortem.load_bundle(bundles[0]))
    assert "nonfinite_burst" in text and "fault" in text
    out = str(tmp_path / "pm_trace.json")
    assert postmortem.main([bundles[0], "--out", out]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert doc["otherData"]["bundles"] == [bundles[0]]
    assert any(str(e.get("name", "")).startswith("fault")
               for e in doc["traceEvents"])

    # Zero extra device fetches: the armed run's fetch count equals an
    # identical unarmed run's (the recorder rides the observer hook).
    fetches_off, _ = run("unarmed", None)
    assert fetches_armed == fetches_off, \
        "flight recorder must not add device fetches"
