"""Live operational observability, end to end: the JsonlTail shared
tailing helper, the cluster-wide live monitor (tools/live_monitor.py),
telemetry_report --follow, and the tier-1 acceptance smoke — a
supervised CPU training sim with an injected non-finite fault serving
valid Prometheus text on --stats_port WHILE it runs, with the
nonfinite-burst alert firing and resolving as paired alert/
alert_resolved records in a schema-clean stream."""

import io
import json
import os
import socket
import threading
import time
import urllib.request

from dml_cnn_cifar10_tpu.utils.metrics_registry import (
    MetricsRegistry, StatsServer, parse_prometheus_text)
from tests.conftest import tiny_train_cfg
from tools.live_monitor import (JsonlTail, active_alerts, build_state,
                                render_view, run_monitor,
                                scrape_endpoint)


def _write(path, recs, mode="a"):
    with open(path, mode) as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_jsonl_tail_incremental_and_partial_lines(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tail = JsonlTail(path)
    assert tail.poll() == []                  # not created yet
    _write(path, [{"kind": "train", "t": 1.0, "task": 0, "step": 10}])
    assert [r["step"] for r in tail.poll()] == [10]
    assert tail.poll() == []                  # nothing new
    _write(path, [{"kind": "train", "t": 2.0, "task": 0, "step": 20},
                  {"kind": "train", "t": 3.0, "task": 0, "step": 30}])
    assert [r["step"] for r in tail.poll()] == [20, 30]
    # A writer mid-append: the partial line waits for its newline.
    with open(path, "a") as f:
        f.write('{"kind": "train", "t": 4.0, "ta')
    assert tail.poll() == []
    with open(path, "a") as f:
        f.write('sk": 0, "step": 40}\n')
    assert [r["step"] for r in tail.poll()] == [40]


def test_active_alert_pairing_order():
    recs = [
        {"kind": "alert", "rule": "a", "severity": "warn"},
        {"kind": "alert_resolved", "rule": "a", "severity": "warn"},
        {"kind": "alert", "rule": "a", "severity": "warn"},
        {"kind": "alert", "rule": "b", "severity": "page"},
        {"kind": "alert_resolved", "rule": "b", "severity": "page"},
    ]
    # fire/resolve/REFIRE = still active; b ended resolved.
    assert [a["rule"] for a in active_alerts(recs)] == ["a"]


def test_build_state_and_render_multi_stream(tmp_path):
    train_stream = [
        {"kind": "heartbeat", "t": 1.0, "task": 0, "step": 10,
         "process_id": 0, "phase": "train", "wallclock": 1001.0},
        {"kind": "train", "t": 2.0, "task": 0, "step": 20, "loss": 0.5,
         "images_per_sec": 500.0, "device_step_ms": 2.0,
         "drain_wait_ms": 1.0},
        {"kind": "goodput", "t": 2.1, "task": 0, "step": 20,
         "total_s": 2.0, "train_frac": 0.7, "compile_frac": 0.3},
        {"kind": "elastic_restart", "t": 2.5, "task": 0, "step": 20,
         "restore_step": 10, "world_size": 2, "epoch": 3,
         "attempt": 1},
        {"kind": "alert", "t": 3.0, "task": 0, "rule": "x",
         "severity": "page", "window": "50 steps", "value": 1.0},
    ]
    serve_stream = [
        {"kind": "serve", "t": 1.0, "task": 1, "qps": 42.0,
         "p50_ms": 1.0, "p99_ms": 9.0, "completed": 100,
         "shed_queue": 1, "shed_deadline": 0, "batch_fill": 0.8},
        {"kind": "serve_done", "t": 2.0, "task": 1, "qps": 42.0},
    ]
    state = build_state({"train.jsonl": train_stream,
                         "serve.jsonl": serve_stream},
                        now=1005.0)
    assert state["world_size"] == 2 and state["epoch"] == 3
    t0, t1 = state["tasks"]
    assert t0["train"]["step"] == 20 and not t0["finished"]
    # Aligned age: offset = 1001 - 1 = 1000; last t = 3.0 → age 2.0.
    assert t0["age_s"] == 2.0
    assert t1["serve"]["qps"] == 42.0 and t1["finished"]
    assert t1["age_s"] is None            # no heartbeat: unaligned
    assert [a["rule"] for a in state["alerts"]] == ["x"]
    assert not state["finished"]          # one stream still running
    view = render_view(state)
    assert "world size 2" in view and "epoch 3" in view
    assert "step 20" in view and "42.0 qps" in view
    assert "ACTIVE ALERTS (1)" in view and "[page] x" in view
    assert "goodput: train 70% compile 30%" in view


def test_monitor_scrapes_endpoint_and_renders(tmp_path):
    reg = MetricsRegistry()
    reg.gauge("dml_train_step", "s").set(120)
    reg.gauge("dml_serve_qps", "q").set(33.5)
    reg.gauge("dml_alert_active", "a",
              labelnames=("rule", "severity")
              ).set(1, rule="hbm_headroom", severity="warn")
    srv = StatsServer(reg, port=0)
    try:
        url = f"http://127.0.0.1:{srv.port}"
        scrape = scrape_endpoint(url)
        assert scrape["ok"]
        state = build_state({}, [scrape])
        e = state["endpoints"][0]
        assert e["step"] == 120.0 and e["qps"] == 33.5
        assert [a["rule"] for a in state["alerts"]] == ["hbm_headroom"]
        view = render_view(state)
        assert "step 120" in view and "hbm_headroom" in view
        # A dead endpoint is a finding, not a crash.
        dead = scrape_endpoint("http://127.0.0.1:1")
        assert not dead["ok"]
        assert "UNREACHABLE" in render_view(build_state({}, [dead]))
    finally:
        srv.close()


def test_monitor_one_shot_on_finished_run(tmp_path):
    path = str(tmp_path / "m.jsonl")
    _write(path, [
        {"kind": "train", "t": 1.0, "task": 0, "step": 10,
         "loss": 0.1, "images_per_sec": 100.0},
        {"kind": "done", "t": 2.0, "task": 0, "step": 10,
         "images_per_sec": 90.0},
    ])
    buf = io.StringIO()
    # No --once flag: the finished stream itself degrades the monitor
    # to a single snapshot (no refresh loop to kill).
    assert run_monitor([path], [], refresh_s=0.0, out=buf) == 0
    v = buf.getvalue()
    assert "RUN FINISHED" in v and v.count("live run monitor") == 1
    # --format json emits the state dict verbatim.
    buf2 = io.StringIO()
    assert run_monitor([path], [], once=True, fmt="json",
                       out=buf2) == 0
    state = json.loads(buf2.getvalue())
    assert state["finished"] and state["tasks"][0]["train"]["step"] == 10


def test_live_monitor_cli_requires_input():
    import pytest

    from tools import live_monitor
    with pytest.raises(SystemExit):
        live_monitor.main([])


def test_telemetry_report_follow_tails_growing_stream(tmp_path):
    """--follow re-renders as the stream grows and exits when the
    final record lands (shared JsonlTail helper)."""
    from tools import telemetry_report

    path = str(tmp_path / "m.jsonl")
    _write(path, [{"kind": "train", "t": 1.0, "task": 0, "step": 10,
                   "loss": 0.5, "train_accuracy": 0.5,
                   "images_per_sec": 100.0, "lr": 0.1,
                   "device_step_ms": None, "drain_wait_ms": None,
                   "optimizer_ms": None}])
    buf = io.StringIO()
    done = threading.Event()

    def grow():
        time.sleep(0.2)
        _write(path, [{"kind": "train", "t": 2.0, "task": 0,
                       "step": 20, "loss": 0.4, "train_accuracy": 0.6,
                       "images_per_sec": 110.0, "lr": 0.1,
                       "device_step_ms": None, "drain_wait_ms": None,
                       "optimizer_ms": None},
                      {"kind": "done", "t": 3.0, "task": 0, "step": 20,
                       "images_per_sec": 105.0}])
        done.set()

    t = threading.Thread(target=grow)
    t.start()
    rc = telemetry_report.follow([path], refresh_s=0.1,
                                 max_refreshes=100, clear=False,
                                 out=buf)
    t.join()
    assert rc == 0 and done.is_set()
    out = buf.getvalue()
    # First render saw step 10; a later one saw the grown stream's
    # final record (which also ended the loop).
    assert "steps: 10" in out and "steps: 20" in out
    assert "run-average throughput: 105.0" in out


def test_fleet_record_device_ms_from_beats(tmp_path):
    """ReplicaView carries the beats' device_ms and the router's fleet
    window records expose it (the PR-8 field, now rendered)."""
    from dml_cnn_cifar10_tpu.fleet.router import Router
    from dml_cnn_cifar10_tpu.parallel.cluster import HeartbeatStore
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

    fleet_dir = str(tmp_path / "fleet")
    for rid, dev_ms in ((0, 1.2), (1, 9.8)):
        HeartbeatStore(fleet_dir, process_id=rid).publish(
            5, "serve", extra={"replica_id": rid, "version": "1",
                               "queue_depth": 0, "port": 9000 + rid,
                               "device_ms": dev_ms})
    jsonl = str(tmp_path / "router.jsonl")
    logger = MetricsLogger(jsonl)
    router = Router(fleet_dir, dead_after_s=60.0, logger=logger)
    views = {v.replica_id: v for v in router.views()}
    assert views[0].device_ms == 1.2 and views[1].device_ms == 9.8
    assert router.healthz()["replicas"]["1"]["device_ms"] == 9.8
    router.emit(final=True)
    logger.close()
    with open(jsonl) as f:
        recs = [json.loads(line) for line in f]
    fleet = [r for r in recs if r["kind"] == "fleet"][-1]
    assert fleet["device_ms"] == {"0": 1.2, "1": 9.8}
    from tools import check_jsonl_schema, telemetry_report
    assert check_jsonl_schema.check_file(jsonl, strict=True) == []
    out = telemetry_report.summarize(jsonl)
    assert "per-replica device_ms" in out and "r1: 9.8 ms" in out


# ---------------------------------------------------------------------------
# the ISSUE-11 acceptance smoke (tier-1)
# ---------------------------------------------------------------------------

def test_supervised_run_serves_live_metrics_and_pairs_alerts(
        data_cfg, tmp_path):
    """Supervised CPU sim with an injected non-finite fault and
    --stats_port: GET /metrics serves valid Prometheus text exposition
    (step counter, goodput fractions, drain-wait gauge) WHILE the run
    is live; the nonfinite-burst alert fires and later resolves as
    paired alert/alert_resolved records; the whole stream passes the
    schema lint. (The zero-extra-device-fetch contract is pinned
    separately by test_telemetry's fetch-parity assert.)"""
    from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised
    from dml_cnn_cifar10_tpu.utils import metrics_registry

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=80)
    cfg.output_every = 10
    cfg.eval_every = 20
    cfg.checkpoint_every = 10
    cfg.check_numerics = True
    cfg.on_nonfinite = "rollback"
    cfg.fault_spec = "nan@15"
    cfg.telemetry = True
    cfg.stats_port = port
    cfg.metrics_jsonl = os.path.join(str(tmp_path), "m.jsonl")

    result_box = {}

    def run():
        result_box["result"] = fit_supervised(cfg)

    worker = threading.Thread(target=run)
    worker.start()
    live_scrapes = []
    try:
        deadline = time.time() + 240
        while time.time() < deadline and worker.is_alive():
            alive_before = worker.is_alive()
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=2) as resp:
                    text = resp.read().decode()
            except OSError:
                time.sleep(0.1)
                continue
            # Only scrapes bracketed by a live worker count as
            # MID-RUN evidence.
            if alive_before and worker.is_alive():
                doc = parse_prometheus_text(text)   # must be valid
                if "dml_train_step" in doc:
                    live_scrapes.append(doc)
            time.sleep(0.2)
        worker.join(timeout=240)
    finally:
        metrics_registry.stop_stats_server()
    assert not worker.is_alive(), "supervised run never finished"
    assert result_box["result"].final_step == 80

    # (a) live export: at least one mid-run scrape served the step
    # counter, the goodput fractions, and the drain-wait gauge.
    assert live_scrapes, "never scraped /metrics while the run was live"
    best = live_scrapes[-1]
    step = best["dml_train_step"]["samples"][()]
    assert 0 < step <= 80
    assert best["dml_train_step"]["type"] == "gauge"
    gp = {labels[0][1]: v for labels, v in
          best["dml_goodput_fraction"]["samples"].items()}
    assert "train" in gp and 0.0 <= gp["train"] <= 1.0
    assert () in best["dml_drain_wait_ms"]["samples"]
    # The injected fault is live too (counter fed by the stream).
    assert best["dml_faults_total"]["samples"][
        (("fault", "nonfinite"),)] >= 1.0

    # (b) the stream is schema-clean with the new kinds present...
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl, strict=True) == []
    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(line) for line in f]
    nf_alerts = [r for r in recs if r.get("kind") == "alert"
                 and r.get("rule") == "nonfinite_burst"]
    nf_resolved = [r for r in recs if r.get("kind") == "alert_resolved"
                   and r.get("rule") == "nonfinite_burst"]
    # ...with the nonfinite-burst alert fired at the fault and
    # resolved once training progressed a clean window past it.
    assert len(nf_alerts) == 1 and len(nf_resolved) == 1
    assert recs.index(nf_alerts[0]) < recs.index(nf_resolved[0])
    assert nf_alerts[0]["severity"] == "page"

    # (c) the reports surface the alert lifecycle.
    from tools import telemetry_report
    out = telemetry_report.summarize(cfg.metrics_jsonl)
    assert "nonfinite_burst" in out and "resolved" in out
    j = telemetry_report.summarize_json(cfg.metrics_jsonl)
    assert j["alerts"]["fired"] >= 1
    assert all(a["rule"] != "nonfinite_burst"
               for a in j["alerts"]["active"])
    # And the live monitor's one-shot degradation renders the run.
    buf = io.StringIO()
    assert run_monitor([cfg.metrics_jsonl], [], once=True,
                       out=buf) == 0
    assert "FINISHED" in buf.getvalue()
