"""Perf-regression gate (tools/bench_gate.py): the synthetic decision
table, the real BENCH_r* trajectory acceptance (r05 must pass against
r01-r05), and the regressions the gate exists to flag (10% throughput,
3x compile_s, tail blowup)."""

import copy
import json
import os

from tools import bench_gate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checks_by(checks, name):
    return [c for c in checks if c["check"] == name]


# ---------------------------------------------------------------------------
# decision table on synthetic reports (the tier-1 self-check wire)
# ---------------------------------------------------------------------------

def test_self_check_decision_table(capsys):
    assert bench_gate.self_check() == 0
    out = capsys.readouterr().out
    assert "0 wrong verdict(s)" in out
    # And via the CLI entry point (the CI wire).
    assert bench_gate.main(["--self-check"]) == 0


def test_gate_flags_throughput_and_compile_regressions():
    baselines = [bench_gate._synth(990.0), bench_gate._synth(1000.0),
                 bench_gate._synth(1010.0)]
    # 10% throughput regression → the throughput checks fail.
    checks = bench_gate.gate(bench_gate._synth(ips=900.0), baselines)
    bad = [c for c in checks if not c["ok"]]
    assert bad and all(c["check"] == "throughput" for c in bad)
    # 3x compile_s → only the compile check fails.
    checks = bench_gate.gate(bench_gate._synth(compile_s=60.0),
                             baselines)
    bad = [c for c in checks if not c["ok"]]
    assert [c["check"] for c in bad] == ["compile_s"]
    # Tail regression the mean hides: p99 alone blows up.
    checks = bench_gate.gate(bench_gate._synth(p99=2.4), baselines)
    bad = [c for c in checks if not c["ok"]]
    assert [c["check"] for c in bad] == ["step_tail_p99"]
    # Tolerances are honored: a wide-open throughput tolerance passes
    # the same 10% regression.
    checks = bench_gate.gate(bench_gate._synth(ips=900.0), baselines,
                             tol_throughput=0.5)
    assert all(c["ok"] for c in _checks_by(checks, "throughput"))
    # Metrics absent from the baselines are skipped, never failed.
    bare = [{"metric": "train_throughput", "value": 1000.0}]
    checks = bench_gate.gate(bench_gate._synth(), bare)
    assert all(c["ok"] for c in checks)
    assert not _checks_by(checks, "compile_s")


# ---------------------------------------------------------------------------
# the real trajectory: r05 vs r01-r05 (ISSUE-8 acceptance)
# ---------------------------------------------------------------------------

def test_r05_passes_the_recorded_trajectory(capsys):
    rc = bench_gate.main([os.path.join(REPO, "BENCH_r05.json")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "PASS" in out and "REGRESSION" not in out


def test_synthetic_10pct_regression_of_r05_fails(tmp_path, capsys):
    report = bench_gate.load_report(os.path.join(REPO,
                                                 "BENCH_r05.json"))
    slow = copy.deepcopy(report)
    slow["value"] *= 0.9
    for row in bench_gate.ROW_KEYS:
        if isinstance(slow.get(row), dict):
            slow[row]["images_per_sec_per_chip"] *= 0.9
            if "mfu" in slow[row]:
                slow[row]["mfu"] *= 0.9
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(slow))
    rc = bench_gate.main([str(cand), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["pass"] is False
    bad = [c for c in doc["checks"] if not c["ok"]]
    assert any(c["check"] == "throughput" for c in bad)


def test_load_report_shapes(tmp_path):
    # BENCH_r wrapper and raw bench stdout both load to the same doc.
    wrapped = bench_gate.load_report(os.path.join(REPO,
                                                  "BENCH_r05.json"))
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(wrapped))
    assert bench_gate.load_report(str(raw)) == wrapped
    bogus = tmp_path / "bogus.json"
    bogus.write_text(json.dumps({"metric": "other"}))
    try:
        bench_gate.load_report(str(bogus))
        assert False, "non-bench report must be rejected"
    except ValueError:
        pass
