"""Test env: simulate an 8-device mesh on CPU (SURVEY §4) before jax loads."""

import os

# Force CPU even when the environment pins a TPU platform (JAX_PLATFORMS=axon
# on the bench box): the test suite runs on the 8-virtual-device CPU mesh.
from dml_cnn_cifar10_tpu.utils.platform import force_cpu

force_cpu(virtual_devices=8)

import jax  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from dml_cnn_cifar10_tpu.config import DataConfig, TrainConfig  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy integration test (multi-process runs, long compiles, "
        "full Trainer e2e). The smoke pass excludes them: "
        "pytest -m 'not slow' finishes in ~1-2 min; the full suite runs "
        "everything (ARCHITECTURE §7).")


@pytest.fixture(scope="session")
def synth_data_dir(tmp_path_factory) -> str:
    return str(tmp_path_factory.mktemp("cifar_synth"))


@pytest.fixture(scope="session")
def data_cfg(synth_data_dir) -> DataConfig:
    """Small synthetic CIFAR-format dataset, generated once per session."""
    cfg = DataConfig(
        dataset="synthetic",
        data_dir=synth_data_dir,
        synthetic_train_records=640,
        synthetic_test_records=160,
        shuffle_buffer=256,
        use_native_loader=False,
    )
    from dml_cnn_cifar10_tpu.data import ensure_dataset
    ensure_dataset(cfg)
    return cfg


def tiny_train_cfg(data_cfg: DataConfig, tmpdir: str, **kw) -> TrainConfig:
    """Small, numerically tame config: the faithful LR-0.1-on-raw-pixels
    combination NaNs within steps (a reference property), so integration
    tests normalize inputs and drop the LR."""
    import dataclasses
    cfg = TrainConfig(
        batch_size=32,
        total_steps=40,
        output_every=10,
        eval_every=20,
        checkpoint_every=20,
        log_dir=os.path.join(tmpdir, "logs"),
        data=dataclasses.replace(data_cfg, normalize="scale"),
    )
    cfg.optim.learning_rate = 0.05
    cfg.model.logit_relu = False
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


@pytest.fixture
def rng():
    return np.random.default_rng(0)
