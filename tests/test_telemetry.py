"""Run-health telemetry layer (utils/telemetry.py + parallel/step.py
health metrics + the JSONL schema lint): span nesting/export, goodput
accounting, health-metric fusion into the fused boundary fetch, and the
zero-extra-device-fetches contract when telemetry is off."""

import json
import os
import time

import numpy as np
import pytest

from dml_cnn_cifar10_tpu.utils.telemetry import (GOODPUT_CATEGORIES,
                                                 SpanTracer,
                                                 flush_boundary, hbm_stats)
from tests.conftest import tiny_train_cfg


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_span_nesting_and_drain(monkeypatch):
    clock = _FakeClock()
    monkeypatch.setattr(time, "perf_counter", clock)
    tr = SpanTracer(enabled=True)
    tr.start()
    with tr.span("outer", cat="eval"):
        clock.t += 1.0
        with tr.span("inner"):
            clock.t += 0.5
        clock.t += 0.5
    spans = tr.drain()
    # Inner finishes first; depth recorded at entry.
    assert [(s[0], s[4]) for s in spans] == [("inner", 1), ("outer", 0)]
    name, cat, start, dur, depth = spans[1]
    assert cat == "eval" and start == 0.0 and dur == pytest.approx(2.0)
    # drain() forgets — a second drain is empty; the ring retains.
    assert tr.drain() == []
    assert len(tr._ring) == 2


def test_disabled_tracer_is_noop():
    tr = SpanTracer(enabled=False)
    # The fast path returns one shared no-op context manager: no
    # allocation, no clock read, nothing recorded.
    cm = tr.span("anything", cat="eval")
    assert tr.span("other") is cm
    with cm:
        pass
    assert tr.drain() == []


def test_goodput_fractions_sum_to_one(monkeypatch):
    """Synthetic timeline: categorized spans attribute their seconds,
    productive training is the remainder, fractions sum to 1.0."""
    clock = _FakeClock()
    monkeypatch.setattr(time, "perf_counter", clock)
    tr = SpanTracer(enabled=True)
    tr.start()
    for cat, dur in (("compile", 2.0), ("data", 1.0), ("eval", 0.5),
                     ("checkpoint", 0.4), ("sync", 0.1)):
        with tr.span(cat, cat=cat):
            clock.t += dur
    clock.t = 10.0
    gp = tr.goodput()
    assert gp["total_s"] == pytest.approx(10.0)
    assert gp["compile_frac"] == pytest.approx(0.2)
    assert gp["data_frac"] == pytest.approx(0.1)
    assert gp["eval_frac"] == pytest.approx(0.05)
    assert gp["checkpoint_frac"] == pytest.approx(0.04)
    assert gp["sync_frac"] == pytest.approx(0.01)
    assert gp["train_frac"] == pytest.approx(0.6)
    total_frac = gp["train_frac"] + sum(
        gp[f"{c}_frac"] for c in GOODPUT_CATEGORIES)
    assert total_frac == pytest.approx(1.0, abs=1e-5)
    # Nested spans with a category must NOT double-count their parent.
    with tr.span("eval", cat="eval"):
        with tr.span("inner", cat="eval"):
            clock.t += 1.0
    assert tr._cat_secs["eval"] == pytest.approx(0.5 + 1.0)


def test_chrome_trace_export_and_ring_overflow(tmp_path):
    tr = SpanTracer(enabled=True, max_spans=4)
    for i in range(6):
        with tr.span(f"s{i}", cat="data"):
            pass
    assert tr.dropped == 2 and len(tr._ring) == 4
    path = str(tmp_path / "trace.json")
    tr.export_chrome_trace(path, pid=3)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert [e["name"] for e in events] == ["s2", "s3", "s4", "s5"]
    for e in events:
        assert e["ph"] == "X" and e["pid"] == 3
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
    assert doc["otherData"]["dropped_spans"] == 2


def test_hbm_stats_shape():
    """Emitted unconditionally: on backends without memory stats (CPU)
    the record still carries the full schema with available=False."""
    s = hbm_stats()
    assert set(s) == {"available", "devices", "bytes_in_use",
                      "peak_bytes", "bytes_limit"}
    assert isinstance(s["available"], bool)


def test_flush_boundary_logs_span_goodput_hbm(tmp_path):
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    logger = MetricsLogger(path)
    tr = SpanTracer(enabled=True)
    with tr.span("eval", cat="eval"):
        pass
    flush_boundary(tr, logger, step=7, final=True)
    logger.close()
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    kinds = [r["kind"] for r in recs]
    assert kinds == ["span", "goodput", "hbm"]
    assert recs[0]["name"] == "eval" and recs[0]["step"] == 7
    assert recs[1]["final"] == 1
    # Disabled tracer: flush is a no-op (no records, no fetches).
    flush_boundary(SpanTracer(enabled=False), logger, step=8)


def test_health_stats_in_step_metrics(data_cfg):
    """health_metrics=True compiles the scalars into the step's metrics
    dict (the fused-fetch payload); off means the keys don't exist."""
    import jax

    from dml_cnn_cifar10_tpu.config import ModelConfig, OptimConfig
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    model_cfg = ModelConfig(logit_relu=False)
    optim_cfg = OptimConfig(learning_rate=0.05)
    model_def = get_model("cnn")
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, optim_cfg)
    images = np.random.default_rng(0).normal(
        size=(8, data_cfg.crop_height, data_cfg.crop_width, 3)
    ).astype(np.float32)
    labels = np.arange(8, dtype=np.int32) % 10

    plain = step_lib.make_train_step(model_def, model_cfg, optim_cfg)
    _, metrics = plain(state, images, labels)
    assert not any(k.startswith("health_") for k in metrics)

    state2 = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, optim_cfg)
    healthy = step_lib.make_train_step(model_def, model_cfg, optim_cfg,
                                       health_metrics=True)
    _, metrics = healthy(state2, images, labels)
    gn = float(metrics["health_grad_norm"])
    pn = float(metrics["health_param_norm"])
    ur = float(metrics["health_update_ratio"])
    assert gn > 0 and pn > 0 and 0 < ur < 1
    # SGD: ||Δθ|| = lr·||g|| exactly, so the ratio is checkable.
    assert ur == pytest.approx(optim_cfg.learning_rate * gn / pn,
                               rel=1e-4)


def test_telemetry_run_and_fetch_parity(data_cfg, tmp_path, monkeypatch):
    """One telemetry-off and one telemetry+health-on run of the real
    Trainer: (a) telemetry must add ZERO jax.device_get calls (spans,
    goodput, and hbm are host-side; health rides the fused fetch);
    (b) the on-run emits span/goodput/hbm records whose goodput
    categories sum to within 2% of the recorded wall-clock, a valid
    Chrome trace, health keys in the train records, a schema-clean JSONL
    stream, and a telemetry_report summary."""
    import jax

    from dml_cnn_cifar10_tpu.train.loop import Trainer

    counts = {"n": 0}
    real_get = jax.device_get

    def counting_get(x):
        counts["n"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    def run(sub, telemetry, health, trace=None):
        cfg = tiny_train_cfg(data_cfg, str(tmp_path / sub), total_steps=20,
                             output_every=5, eval_every=10,
                             checkpoint_every=10)
        cfg.telemetry = telemetry
        cfg.health_metrics = health
        cfg.metrics_jsonl = os.path.join(str(tmp_path / sub), "m.jsonl")
        cfg.trace_events_path = trace
        counts["n"] = 0
        t0 = time.perf_counter()
        result = Trainer(cfg).fit()
        wall = time.perf_counter() - t0
        assert result.final_step == 20
        return counts["n"], cfg, wall

    fetches_off, _, _ = run("off", telemetry=False, health=False)
    trace_path = str(tmp_path / "on" / "host_trace.json")
    fetches_on, cfg, wall = run("on", telemetry=True, health=True,
                                trace=trace_path)
    assert fetches_on == fetches_off, \
        "telemetry/health must not add device fetches"

    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(line) for line in f]
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    assert {"train", "eval", "span", "goodput", "hbm"} <= set(by_kind)

    # Health scalars fused into every train record.
    for r in by_kind["train"]:
        assert {"health_grad_norm", "health_param_norm",
                "health_update_ratio"} <= set(r)
        assert np.isfinite(r["health_grad_norm"])

    # The always-on device step-time estimator (utils/devprof.py)
    # rides the same fused fetch: every train row carries the keys,
    # real numbers once the first window completes — and it added zero
    # fetches (the assertion above already proved it).
    for r in by_kind["train"]:
        assert {"device_step_ms", "drain_wait_ms"} <= set(r)
    assert any(isinstance(r["device_step_ms"], (int, float)) and
               r["device_step_ms"] > 0 for r in by_kind["train"])

    # Span phases cover the loop; depth-0 categories feed goodput.
    names = {r["name"] for r in by_kind["span"]}
    assert {"data_wait", "compile_first_dispatch", "dispatch",
            "boundary_drain", "eval", "checkpoint"} <= names

    # Final goodput record: categories + train remainder sum to the
    # wall-clock total (within 2%), and the tracer's total is within
    # the fit() call's measured wall time.
    final = [r for r in by_kind["goodput"] if r.get("final")]
    assert final, "run end must flush a final goodput record"
    gp = final[-1]
    cat_s = sum(gp[f"{c}_frac"] for c in GOODPUT_CATEGORIES) \
        + gp["train_frac"]
    assert cat_s == pytest.approx(1.0, abs=0.02)
    assert 0 < gp["total_s"] <= wall * 1.02
    assert gp["compile_frac"] > 0      # first dispatch compiled

    # hbm records carry the full schema even on CPU.
    assert by_kind["hbm"][-1]["available"] in (True, False)

    # Chrome trace-event file: valid JSON, Perfetto-loadable shape,
    # and WELL-FORMED spans — complete events with non-negative
    # durations that, within one lane (pid, tid=depth), are monotone
    # and non-overlapping (the host loop's same-depth spans are
    # sequential context managers; an overlap would mean the exporter
    # scrambled ts/dur and Perfetto would render garbage).
    with open(trace_path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert events and all(e["ph"] == "X" for e in events)
    lanes = {}
    for e in events:
        assert e["dur"] >= 0 and e["ts"] >= 0
        lanes.setdefault((e["pid"], e["tid"]), []).append(e)
    for lane in lanes.values():
        lane.sort(key=lambda e: e["ts"])
        for a, b in zip(lane, lane[1:]):
            # 0.2 us slack: ts/dur round to 0.1 us on export.
            assert b["ts"] >= a["ts"] + a["dur"] - 0.2, \
                (a, b, "same-depth spans must not overlap")

    # The stream passes the documented-schema lint (wired into tier 1).
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl, strict=True) == []

    # And the report CLI summarizes it.
    from tools import telemetry_report
    out = telemetry_report.summarize(cfg.metrics_jsonl)
    assert "goodput over" in out and "train" in out
    assert "grad norm" in out
    assert telemetry_report.main([cfg.metrics_jsonl]) == 0


def test_schema_kinds_match_observability_doc():
    """Doc-drift gate: every kind the lint knows appears in the
    docs/OBSERVABILITY.md kinds table, and vice versa — the exact drift
    KIND_KEYS' comment says the lint exists to catch, now enforced in
    BOTH directions (--list-kinds is the machine-readable side)."""
    import re

    from tools import check_jsonl_schema as lint

    doc_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "OBSERVABILITY.md")
    with open(doc_path) as f:
        doc = f.read()
    # Table rows look like: | `kind` | `required keys` | emitted |
    doc_kinds = set(re.findall(r"^\| `(\w+)` \|", doc, re.MULTILINE))
    lint_kinds = set(lint.list_kinds())
    assert lint_kinds - doc_kinds == set(), \
        "kinds missing from the docs/OBSERVABILITY.md table"
    assert doc_kinds - lint_kinds == set(), \
        "documented kinds missing from tools/check_jsonl_schema.py"


def test_list_kinds_cli(capsys):
    from tools import check_jsonl_schema as lint

    assert lint.main(["--list-kinds"]) == 0
    out = capsys.readouterr().out.split()
    assert out == sorted(lint.KIND_KEYS)
    assert "devtime" in out and "train" in out


def test_check_jsonl_schema_catches_violations(tmp_path):
    from tools import check_jsonl_schema as lint

    good = {"kind": "eval", "t": 1.0, "task": 0, "step": 10,
            "test_accuracy": 0.5}
    assert lint.check_lines([json.dumps(good)]) == []
    # NaN token → non-strict JSON.
    errs = lint.check_lines(['{"kind": "eval", "t": NaN, "task": 0, '
                             '"step": 1, "test_accuracy": 0.1}'])
    assert errs and "strict JSON" in errs[0]
    # Missing required key for the kind.
    errs = lint.check_lines(['{"kind": "eval", "t": 1.0, "task": 0, '
                             '"step": 1}'])
    assert errs and "test_accuracy" in errs[0]
    # Unknown kind: tolerated by default (an old checkout reading a
    # newer stream), rejected under strict — the drift guard the repo's
    # own tests run with.
    mystery = '{"kind": "mystery", "t": 1.0, "task": 0}'
    assert lint.check_lines([mystery]) == []
    errs = lint.check_lines([mystery], strict=True)
    assert errs and "unknown kind" in errs[0]
    # Garbage line.
    assert lint.check_lines(["not json"])
    # File-level entry point.
    p = tmp_path / "m.jsonl"
    p.write_text(json.dumps(good) + "\n")
    assert lint.check_file(str(p), strict=True) == []
    assert lint.main(["--strict", str(p)]) == 0
