"""Partition-tolerant coordination transport (parallel/net.py), the
deterministic network-fault proxy (utils/netfaults.py), and the cell
layer it feeds (router cell routing, --target_cell loadgen): the
transport must degrade CLASSIFIED — timeout / unreachable / http_<code>
/ proto, never a hang — and every store contract over it must read as
*absence*, not error, so the existing liveness machinery (stale beats,
missing decisions) handles a partition without new failure modes."""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.parallel import net as net_lib
from dml_cnn_cifar10_tpu.utils import backoff
from dml_cnn_cifar10_tpu.utils import netfaults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeLogger:
    def __init__(self):
        self.records = []

    def log(self, kind, **fields):
        self.records.append({"kind": kind, **fields})

    def flush(self):
        pass

    def kinds(self):
        return [r["kind"] for r in self.records]


@pytest.fixture(autouse=True)
def _clean_netfaults():
    netfaults.clear()
    yield
    netfaults.clear()


@pytest.fixture
def served(tmp_path):
    """A CoordServer over a tmp dir + a loopback client for pid 0."""
    server = net_lib.CoordServer(str(tmp_path))
    client = net_lib.CoordClient(str(tmp_path), 0, timeout_s=2.0,
                                 retries=1)
    yield str(tmp_path), server, client
    server.stop()


# ---------------------------------------------------------------------------
# transport KV contract
# ---------------------------------------------------------------------------

def test_transport_kv_roundtrip_lands_on_serving_disk(served):
    root, server, client = served
    assert client.healthz()
    assert client.get("a/b.json") is None          # 404 → absent
    client.put("a/b.json", b'{"x": 1}')
    assert client.get("a/b.json") == b'{"x": 1}'
    # Same artifacts: the bytes land in the server's directory layout,
    # so tools/-side consumers stay transport-blind.
    with open(os.path.join(root, "a", "b.json"), "rb") as f:
        assert f.read() == b'{"x": 1}'
    client.put("a/c.json", b"2")
    assert sorted(client.list_dir("a")) == ["b.json", "c.json"]
    assert client.scan("a") == {"b.json": '{"x": 1}', "c.json": "2"}
    client.rename("a/c.json", "a/d.json")
    assert client.get("a/c.json") is None
    assert client.get("a/d.json") == b"2"
    client.delete("a/d.json")
    assert client.get("a/d.json") is None
    client.delete_tree("a")
    assert client.list_dir("a") == []


def test_transport_rejects_path_escape(served):
    _, _, client = served
    with pytest.raises(net_lib.TransportError) as e:
        client.get("../outside.txt")
    assert e.value.reason.startswith("http_")


def test_unreachable_classifies_and_stores_read_absent(tmp_path):
    """No server at the advertised address: every request classifies
    `unreachable` after its bounded budget; the store contracts map
    that to silence (None / {}), never an exception."""
    with open(os.path.join(str(tmp_path), net_lib.ADDR_FILENAME),
              "w") as f:
        json.dump({"host": "127.0.0.1", "port": 1}, f)
    client = net_lib.CoordClient(str(tmp_path), 0, timeout_s=0.3,
                                 retries=0, resolve_grace_s=0.0)
    with pytest.raises(net_lib.TransportError) as e:
        client.put("x", b"1")
    assert e.value.reason == "unreachable"
    store = net_lib.NetHeartbeatStore(str(tmp_path), 0, client)
    assert store.publish(1, "train") is not None   # swallowed, silent
    assert store.read(1) is None
    assert store.read_all() == {}
    assert not client.healthz()


def test_partition_classifies_timeout_within_bound(served):
    """An armed partition HOLDS the isolated pid's connections; the
    client-side socket timeout is the only thing that unsticks it —
    classified `timeout`, inside the budget, not a hang."""
    root, _, _ = served
    iso = net_lib.CoordClient(root, 7, timeout_s=0.3, retries=1)
    netfaults.arm("net_partition", [7], duration_s=30.0)
    t0 = time.time()
    with pytest.raises(net_lib.TransportError) as e:
        iso.get("anything")
    elapsed = time.time() - t0
    assert e.value.reason == "timeout"
    # 2 attempts x 0.3s + one bounded backoff sleep, with slack.
    assert elapsed < 3.0
    # Other pids sail through the same server.
    ok = net_lib.CoordClient(root, 3, timeout_s=2.0, retries=0)
    ok.put("fine", b"1")
    assert ok.get("fine") == b"1"


def test_partition_auto_heals(served):
    root, _, client = served
    netfaults.arm("net_partition", [0], duration_s=0.3)
    fast = net_lib.CoordClient(root, 0, timeout_s=0.2, retries=0)
    with pytest.raises(net_lib.TransportError):
        fast.get("x")
    time.sleep(0.4)
    assert netfaults.active() == []                # expired + pruned
    client.put("x", b"1")                          # healed: works again
    assert client.get("x") == b"1"


def test_net_telemetry_records_are_classified_and_rate_limited(served):
    root, _, _ = served
    log = FakeLogger()
    client = net_lib.CoordClient(root, 0, timeout_s=2.0, retries=0,
                                 log_fn=log.log)
    client.put("k", b"v")
    client.get("k")
    with open(os.path.join(root, net_lib.ADDR_FILENAME), "w") as f:
        json.dump({"host": "127.0.0.1", "port": 1}, f)
    bad = net_lib.CoordClient(root, 0, timeout_s=0.2, retries=0,
                              log_fn=log.log, resolve_grace_s=0.0)
    for _ in range(5):                             # rate-limited to 1
        with pytest.raises(net_lib.TransportError):
            bad.get("k")
    nets = [r for r in log.records if r["kind"] == "net"]
    assert all(set(("op", "ok", "ms", "attempts")) <= set(r)
               for r in nets)
    oks = [r for r in nets if r["ok"]]
    fails = [r for r in nets if not r["ok"]]
    assert oks and oks[0]["status"] == 200 and oks[0]["error"] is None
    assert len(fails) == 1                         # 5 failures, 1 record
    assert fails[0]["error"] == "unreachable"


# ---------------------------------------------------------------------------
# degraded-network drills: delay / drop / dup
# ---------------------------------------------------------------------------

def test_net_delay_adds_latency_inside_the_budget(served):
    root, _, client = served
    client.put("k", b"v")
    t0 = time.time()
    assert client.get("k") == b"v"
    base = time.time() - t0
    netfaults.arm("net_delay", [0], duration_s=5.0)
    t0 = time.time()
    assert client.get("k") == b"v"                 # slower, still fine
    assert time.time() - t0 >= base + 0.2


def test_net_drop_is_absorbed_by_the_retry_budget(served):
    """Drop 503s every 2nd request inside its window — the bounded
    retry budget absorbs it, so coordination completes unchanged."""
    assert netfaults.server_action(2) == ("ok",)
    netfaults.arm("net_drop", [2], duration_s=60.0)
    acts = [netfaults.server_action(2) for _ in range(6)]
    assert acts.count(("drop",)) == 3              # deterministic: 2nd
    root, _, _ = served
    client = net_lib.CoordClient(root, 2, timeout_s=2.0, retries=2)
    for i in range(6):
        client.put(f"k{i}", b"v")
        assert client.get(f"k{i}") == b"v"


def test_net_dup_is_harmless_under_atomic_commit(served):
    root, _, _ = served
    netfaults.arm("net_dup", [4], duration_s=60.0)
    client = net_lib.CoordClient(root, 4, timeout_s=2.0, retries=0)
    client.put("dup.json", b"payload")
    assert client.get("dup.json") == b"payload"
    with open(os.path.join(root, "dup.json"), "rb") as f:
        assert f.read() == b"payload"


def test_netfaults_unknown_kind_fails_loudly():
    with pytest.raises(ValueError):
        netfaults.arm("net_typo", [0])


# ---------------------------------------------------------------------------
# store contracts over the transport
# ---------------------------------------------------------------------------

def test_net_heartbeat_store_matches_file_store(served):
    root, _, client = served
    net_store = net_lib.NetHeartbeatStore(root, 0, client)
    net_store.publish(5, "train", extra={"port": 9, "cell": "cella"})
    # The file store over the SAME dir sees the beat — same artifacts.
    file_store = cluster_lib.HeartbeatStore(root, 1)
    file_store.publish(3, "serve")
    beats = net_store.read_all()
    assert set(beats) == {0, 1}
    assert beats[0].step == 5 and beats[0].phase == "train"
    assert beats[0].extra == {"port": 9, "cell": "cella"}
    assert net_store.read(1).step == 3
    file_beats = file_store.read_all()
    assert set(file_beats) == {0, 1} and file_beats[0].step == 5
    assert net_store.read_peers([0, 1]).keys() == {1}


def test_beat_decode_error_classified_on_both_transports(served):
    """A torn/corrupt beat file reads as ABSENT for that poll with a
    classified beat_decode_error record — on the file store and on the
    net store — so a flaky writer degrades to a stale heartbeat, never
    a monitor crash."""
    root, _, client = served
    log = FakeLogger()
    client.put("heartbeats/proc_2.json", b'{"torn')
    good = net_lib.NetHeartbeatStore(root, 0, client, log_fn=log.log)
    good.publish(1, "train")
    beats = good.read_all()
    assert set(beats) == {0}                       # torn one skipped
    nerrs = [r for r in log.records
             if r["kind"] == "beat_decode_error"]
    assert nerrs and "proc_2" in nerrs[0]["path"] and nerrs[0]["error"]

    flog = FakeLogger()
    fstore = cluster_lib.HeartbeatStore(root, 1, log_fn=flog.log)
    assert set(fstore.read_all()) == {0}
    ferrs = [r for r in flog.records
             if r["kind"] == "beat_decode_error"]
    assert ferrs and "proc_2" in ferrs[0]["path"]


def _decision(epoch, survivors=(0,)):
    return cluster_lib.RestartDecision(
        epoch=epoch, world_size=len(survivors), restore_step=10,
        survivors=list(survivors), kind="shrink", source="disk")


def test_net_coordinator_sidecar_monotone_and_corruption(served):
    root, _, client = served
    log = FakeLogger()
    coord = net_lib.NetRestartCoordinator(root, client, log_fn=log.log)
    assert coord.read() is None
    coord.record(_decision(1, (0, 1)))
    d = coord.read()
    assert d.epoch == 1 and d.survivors == [0, 1]
    # The decision + sidecar land in the file coordinator's layout.
    assert os.path.exists(os.path.join(root, "restart_decision.json"))
    # Decision race, included seat: a re-record at a stale epoch ADOPTS
    # the committed decision instead of racing (or crashing on) it.
    adopted = coord.record(_decision(1, (0,)))
    assert adopted.epoch == 1 and adopted.survivors == [0, 1]
    # Decision race, excluded seat (the healed partition minority):
    # the committed file wins — classified eviction, fence/rejoin.
    loser = net_lib.NetRestartCoordinator(
        root, net_lib.CoordClient(root, 9, timeout_s=2.0, retries=0))
    with pytest.raises(cluster_lib.EvictedError) as race:
        loser.record(_decision(1, (9,)))
    assert "decision race lost" in str(race.value)
    # Corrupt the payload under a stale sidecar: the digest check
    # classifies it and the decision reads as ABSENT, never adopted.
    client.put("restart_decision.json", b'{"epoch": 99}')
    assert coord.read() is None
    assert "decision_corrupt" in log.kinds()
    # await_decision's bounded poll degrades to the classified
    # coordinator-lost failure on absence — same contract as the file
    # coordinator, never a hang.
    with pytest.raises(cluster_lib.PeerLostError):
        coord.await_decision(2, timeout_s=0.2)


def test_record_under_partition_raises_evicted(served):
    """A host that cannot reach coordination must not believe its own
    restart decision: record() maps the classified transport failure to
    EvictedError — the fence (or, under --elastic_expand, the rejoin
    request) the supervisor already knows how to run."""
    root, _, _ = served
    client = net_lib.CoordClient(root, 3, timeout_s=0.2, retries=0)
    coord = net_lib.NetRestartCoordinator(root, client)
    netfaults.arm("net_partition", [3], duration_s=30.0)
    with pytest.raises(cluster_lib.EvictedError) as e:
        coord.record(_decision(1, (3,)))
    assert "fencing" in str(e.value)
    assert coord.read() is None                    # reads: silence


# ---------------------------------------------------------------------------
# decision adoption under a slow store (satellite: bounded re-read)
# ---------------------------------------------------------------------------

class _SlowChasingCoordinator:
    """read() is slow AND returns an ever-newer epoch each call — the
    worst case for the seam check: a chief writing again while we read."""

    def __init__(self, start_epoch, survivors, sleep_s=0.05,
                 chase=True):
        self.epoch = start_epoch
        self.survivors = survivors
        self.sleep_s = sleep_s
        self.chase = chase
        self.reads = 0

    def read(self):
        self.reads += 1
        time.sleep(self.sleep_s)
        d = _decision(self.epoch, self.survivors)
        if self.chase:
            self.epoch += 1
        return d


class _Disarmable:
    def disarm(self):
        pass


def test_check_evicted_bounded_rereads_under_slow_chasing_store():
    """The included-at-a-newer-epoch seam path re-reads the decision
    with BOUNDED backoff (3 re-reads, utils/backoff.py) and then acts —
    a store that is slow and perpetually newer must not turn the seam
    check into a hang."""
    log = FakeLogger()
    stub = type("Stub", (), {})()
    stub.coordinator = _SlowChasingCoordinator(5, [0, 1])
    stub.epoch = 1
    stub.process_id = 0
    stub.log = log.log
    stub.watchdog = _Disarmable()
    t0 = time.time()
    with pytest.raises(cluster_lib.PeerLostError):
        cluster_lib.ClusterMonitor.check_evicted(stub, step=20)
    elapsed = time.time() - t0
    # Initial read + exactly 3 bounded re-reads, never more.
    assert stub.coordinator.reads == 1 + 3
    # Sleeps are the pinned plan: delay_s(0.02, 0.2, 1..3) + 4 slow
    # reads — comfortably under a second, nowhere near a poll loop.
    budget = sum(backoff.delay_s(0.02, 0.2, a) for a in (1, 2, 3))
    assert elapsed < budget + 4 * 0.05 + 1.0
    assert log.records[-1]["reason"] == "stale_epoch"


def test_check_evicted_settles_early_when_epoch_stabilizes():
    log = FakeLogger()
    stub = type("Stub", (), {})()
    stub.coordinator = _SlowChasingCoordinator(5, [1], chase=False)
    stub.epoch = 1
    stub.process_id = 0                            # excluded → fence
    stub.log = log.log
    stub.watchdog = _Disarmable()
    with pytest.raises(cluster_lib.EvictedError):
        cluster_lib.ClusterMonitor.check_evicted(stub, step=20)
    assert stub.coordinator.reads == 1             # no re-read churn
    assert log.records[-1]["reason"] == "evicted"


# ---------------------------------------------------------------------------
# cells: router preference, crossing records, data-plane partition
# ---------------------------------------------------------------------------

class _FakeWorker:
    """A real HTTP /predict endpoint so the router's socket path runs."""

    def __init__(self, version="7"):
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                outer.hits += 1
                outer.headers.append(dict(self.headers))
                body = json.dumps({"version": version,
                                   "class": 0}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.hits = 0
        self.headers = []
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


def _beat(store, port, cell, step=0):
    store.publish(step, "serve", extra={"port": port, "version": "7",
                                        "queue_depth": 0, "cell": cell})


def test_router_prefers_cell_and_logs_crossings(tmp_path):
    from dml_cnn_cifar10_tpu.fleet import router as router_lib
    wa, wb = _FakeWorker(), _FakeWorker()
    try:
        log = FakeLogger()
        _beat(cluster_lib.HeartbeatStore(str(tmp_path), 0), wa.port,
              "cella")
        _beat(cluster_lib.HeartbeatStore(str(tmp_path), 1), wb.port,
              "cellb")
        r = router_lib.Router(str(tmp_path), dead_after_s=5.0,
                              logger=log, route_backoff_s=0.0)
        views = {v.replica_id: v for v in r.live()}
        assert views[0].cell == "cella" and views[1].cell == "cellb"
        # In-cell requests stay in-cell: no crossing records.
        for _ in range(4):
            status, payload = r.proxy_predict(b"x", target_cell="cellb")
            assert status == 200 and payload["replica_id"] == 1
        assert "cell_route" not in log.kinds()
        # healthz advertises the placement.
        assert r.healthz()["replicas"]["0"]["cell"] == "cella"
        # No target_cell: the pre-cell routing, both replicas in play.
        hit = {r.proxy_predict(b"x")[1]["replica_id"]
               for _ in range(6)}
        assert hit == {0, 1}
        # Cell with no live replica: fail over out of it, log the
        # crossing, answer the request anyway.
        status, payload = r.proxy_predict(b"x", target_cell="cellz")
        assert status == 200
        routes = [x for x in log.records if x["kind"] == "cell_route"]
        assert routes and routes[0]["from_cell"] == "cellz"
        assert routes[0]["to_cell"] in ("cella", "cellb")
        assert routes[0]["replica_id"] == payload["replica_id"]
    finally:
        wa.stop()
        wb.stop()


def test_router_partition_evicts_instantly_with_spaced_retries(
        tmp_path):
    """A replica the armed partition isolates is failed WITHOUT dialing
    the socket that would hang, evicted with its own classified reason,
    and consecutive failed attempts are spaced by the bounded
    route_backoff_s exponential."""
    from dml_cnn_cifar10_tpu.fleet import router as router_lib
    log = FakeLogger()
    _beat(cluster_lib.HeartbeatStore(str(tmp_path), 0), 1111, "cella")
    _beat(cluster_lib.HeartbeatStore(str(tmp_path), 1), 2222, "cellb")
    r = router_lib.Router(str(tmp_path), dead_after_s=5.0, logger=log,
                          route_retries=2, route_backoff_s=0.1)
    netfaults.arm("net_partition", [0, 1], duration_s=30.0)
    t0 = time.time()
    status, payload = r.proxy_predict(b"x")
    elapsed = time.time() - t0
    assert status == 503 and payload == {"shed": "no_live_replicas"}
    reasons = [x["reason"] for x in log.records
               if x["kind"] == "peer_lost"]
    assert reasons == ["replica_evicted_partitioned"] * 2
    # Two failed attempts → two backoff sleeps (0.1, 0.2); instant
    # otherwise — nowhere near a route_timeout_s socket burn.
    assert 0.25 <= elapsed < 5.0


def test_loadgen_target_cell_header(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(REPO, "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)
    w = _FakeWorker()
    try:
        c = loadgen._HttpClient(f"http://127.0.0.1:{w.port}",
                                target_cell="cellb")
        assert c.predict(b"x") == ("ok", "7")
        assert w.headers[-1].get("X-Dml-Cell") == "cellb"
        plain = loadgen._HttpClient(f"http://127.0.0.1:{w.port}")
        assert plain.predict(b"x") == ("ok", "7")
        assert "X-Dml-Cell" not in w.headers[-1]
    finally:
        w.stop()


# ---------------------------------------------------------------------------
# CLI plumbing, schema lint, report section
# ---------------------------------------------------------------------------

def test_cli_transport_and_cell_flags_plumb_to_config():
    from dml_cnn_cifar10_tpu.cli.main import build_parser, \
        config_from_args
    p = build_parser()
    cfg = config_from_args(p.parse_args([]))
    assert cfg.parallel.cluster_transport == "file"   # default intact
    assert cfg.fleet.cell == "default"
    cfg = config_from_args(p.parse_args(
        ["--cluster_transport", "net", "--net_timeout_s", "1.5",
         "--net_retries", "7", "--cell", "cella,cellb"]))
    assert cfg.parallel.cluster_transport == "net"
    assert cfg.parallel.net_timeout_s == 1.5
    assert cfg.parallel.net_retries == 7
    assert cfg.fleet.cell == "cella,cellb"


def _net_stream():
    return [
        {"kind": "net", "t": 0.1, "task": 0, "op": "put", "ok": True,
         "ms": 1.2, "attempts": 1, "status": 200, "error": None,
         "wallclock": 1.0},
        {"kind": "net", "t": 0.2, "task": 1, "op": "get", "ok": False,
         "ms": 600.0, "attempts": 3, "status": None,
         "error": "timeout", "wallclock": 2.0},
        {"kind": "fault", "t": 0.3, "task": 1, "step": 15,
         "fault": "net_partition", "injected": True, "isolate": [1],
         "duration_s": 6.0},
        {"kind": "cell_route", "t": 0.4, "task": -1,
         "from_cell": "cellb", "to_cell": "cella", "replica_id": 0,
         "attempt": 1},
        {"kind": "beat_decode_error", "t": 0.5, "task": 0,
         "path": "heartbeats/proc_2.json", "error": "torn"},
    ]


def test_new_kinds_pass_schema_lint(tmp_path):
    from tools import check_jsonl_schema as lint
    good = tmp_path / "good.jsonl"
    good.write_text("\n".join(json.dumps(r) for r in _net_stream())
                    + "\n")
    assert lint.check_file(str(good), strict=True) == []
    for kind in ("net", "cell_route", "beat_decode_error"):
        assert kind in lint.list_kinds()
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"kind": "net", "t": 0.1, "task": 0,
                               "op": "put"}) + "\n")   # missing `ok`
    assert lint.check_file(str(bad), strict=True) != []


def test_report_network_health_section_text_and_json(tmp_path):
    from tools import telemetry_report
    path = tmp_path / "run.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in _net_stream())
                    + "\n")
    out = telemetry_report.summarize(str(path))
    assert "network health:" in out
    assert "timeout" in out and "net_partition" in out
    doc = telemetry_report.summarize_json(str(path))
    net = doc["network"]
    assert net["ops"]["put"]["ok"] == 1
    assert net["ops"]["get"]["failed"] == 1
    assert net["errors"] == {"timeout": 1}
    assert net["partitions"][0]["fault"] == "net_partition"
    assert net["cell_routes"]["count"] == 1
    assert net["cell_routes"]["crossings"] == {"cellb->cella": 1}
    assert net["beat_decode_errors"] == 1
    # A pre-transport stream renders byte-identical: no section, no key.
    plain = tmp_path / "plain.jsonl"
    plain.write_text(json.dumps(
        {"kind": "done", "t": 1.0, "task": 0, "step": 10,
         "images_per_sec": 1.0}) + "\n")
    assert "network health" not in telemetry_report.summarize(
        str(plain))
    assert "network" not in telemetry_report.summarize_json(str(plain))


# ---------------------------------------------------------------------------
# the 2-process lockstep sim over the net transport (acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_net_partition_sim_heals_and_ends_bit_identical(tmp_path):
    """The chaos net_partition drill as one pinned schedule: a 2-seat
    lockstep run over --cluster_transport net, seat 1 partitioned at
    step 15 (plus a degraded-network fault on the survivor), the split
    classified, the world shrunk, the heal rejoined via the expand
    path — both seats exit 0 and end bit-identical to the fault-free
    reference."""
    from tools import chaos as chaos_lib

    from dml_cnn_cifar10_tpu.utils import faults as faults_lib
    harness = chaos_lib.ChaosHarness(str(tmp_path / "chaos"))
    r = harness.run_schedule(
        faults_lib.parse_fault_spec("net_delay@20"), "net_partition",
        tag="netsim")
    assert r.ok, r.invariant
    assert r.injected.get("net_partition") == 1
    assert r.injected.get("net_delay") == 1
