"""Unified multi-job runtime (runtime/ package, ``--mode run``,
docs/RUNTIME.md): the ``--jobs`` grammar, the config dict round-trip
every worker ships through, the single-registry ``/metrics`` endpoint,
``tools/loadgen.py --runtime`` discovery, and the tier-1 acceptance
smoke — one process trains while serving and evaluating on the shared
mesh, every committed checkpoint hot-swaps the in-process engine from
live device buffers (zero checkpoint reads), an injected accuracy
alert triggers a FineTuneJob whose alert→job→publish lineage is on the
stream, and the served outputs exactly equal the standalone ``--mode
serve`` restore path. A separate run pins the fetch-parity invariant:
publishing into the engine adds ZERO ``jax.device_get`` calls over a
serve-less training run."""

import dataclasses
import json
import os
import socket
import time
import urllib.request

import numpy as np
import pytest

from tests.conftest import tiny_train_cfg


# ---------------------------------------------------------------------------
# --jobs grammar
# ---------------------------------------------------------------------------

def test_parse_jobs_grammar():
    from dml_cnn_cifar10_tpu.runtime import parse_jobs

    jobs = parse_jobs("train,serve,eval")
    assert [j.jtype for j in jobs] == ["train", "serve", "eval"]
    # train is a task job; serve/eval are services that outlive it.
    assert [j.service for j in jobs] == [False, True, True]
    assert parse_jobs(" train , serve ")[1].jtype == "serve"
    with pytest.raises(ValueError, match="twice"):
        parse_jobs("train,train")
    with pytest.raises(ValueError, match="finetune"):
        parse_jobs("train,finetune")
    with pytest.raises(ValueError, match="unknown job"):
        parse_jobs("train,bogus")
    with pytest.raises(ValueError, match="no jobs"):
        parse_jobs(" , ")


# ---------------------------------------------------------------------------
# config round-trip (the dict every mode ships through)
# ---------------------------------------------------------------------------

def test_config_round_trip_covers_every_dataclass():
    """config_to_dict → JSON → config_from_dict is the identity over
    the FULL config tree — with a drift gate: every nested dataclass
    field of TrainConfig must be registered in _SUBCONFIGS, so adding a
    subsystem config without wiring its reconstruction fails here."""
    from dml_cnn_cifar10_tpu import config as config_lib

    cfg = config_lib.TrainConfig()
    nested = {f.name for f in dataclasses.fields(config_lib.TrainConfig)
              if dataclasses.is_dataclass(getattr(cfg, f.name))}
    assert nested == set(config_lib._SUBCONFIGS), \
        "new subconfig not registered for config_from_dict reconstruction"

    # Perturb one JSON-representable field in EVERY subconfig plus some
    # top-level scalars, so the equality below proves each subtree
    # actually round-trips (not just defaults comparing to defaults).
    def perturb(obj):
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if isinstance(v, bool):
                setattr(obj, f.name, not v)
            elif isinstance(v, int):
                setattr(obj, f.name, v + 7)
            elif isinstance(v, float):
                setattr(obj, f.name, v + 0.25)
            elif isinstance(v, str):
                setattr(obj, f.name, v + "_x")
            else:
                continue
            return f.name
        raise AssertionError(f"no perturbable field on {obj}")

    for name in config_lib._SUBCONFIGS:
        assert perturb(getattr(cfg, name))
    cfg.total_steps = 1234
    cfg.metrics_jsonl = "/tmp/m.jsonl"
    cfg.alert_rules = "x=eval.test_accuracy<0.5"
    cfg.runtime.jobs = "train,serve,eval"
    cfg.runtime.finetune_steps = 50

    wire = json.loads(json.dumps(config_lib.config_to_dict(cfg)))
    back = config_lib.config_from_dict(wire)
    assert back == cfg
    # JSON has no tuples; the typed field comes back as one.
    assert isinstance(back.serve.buckets, tuple)

    # Unknown keys fail loudly — top level and nested.
    with pytest.raises(TypeError):
        config_lib.config_from_dict({**wire, "bogus": 1})
    bad = json.loads(json.dumps(wire))
    bad["runtime"]["bogus"] = 1
    with pytest.raises(TypeError):
        config_lib.config_from_dict(bad)


# ---------------------------------------------------------------------------
# one /metrics endpoint, both job families, no double-bind
# ---------------------------------------------------------------------------

def test_metrics_registry_one_endpoint_both_families():
    from dml_cnn_cifar10_tpu.utils.metrics_registry import (
        MetricsRegistry, ensure_stats_server, observe_record,
        parse_prometheus_text, stop_stats_server)

    reg = MetricsRegistry()
    observe_record("train", {"step": 10, "loss": 1.2,
                             "images_per_sec": 100.0,
                             "device_step_ms": 2.0,
                             "drain_wait_ms": 0.5}, reg)
    observe_record("serve", {"requests": 10, "completed": 10,
                             "shed_queue": 0, "shed_deadline": 0,
                             "qps": 5.0, "p50_ms": 4.0, "p95_ms": 6.0,
                             "p99_ms": 8.0, "batch_fill": 0.9,
                             "window_s": 5.0}, reg)
    observe_record("job", {"job": "train", "jtype": "train",
                           "state": "running"}, reg)
    observe_record("job_done", {"job": "train", "jtype": "train",
                                "ok": True, "secs": 1.5}, reg)
    observe_record("publish", {"step": 20, "version": "20",
                               "source": "live_params",
                               "latency_ms": 3.0, "swapped": True}, reg)
    doc = parse_prometheus_text(reg.render())
    # Both families and the runtime series on ONE registry render.
    assert doc["dml_train_step"]["samples"][()] == 10.0
    assert doc["dml_serve_qps"]["samples"][()] == 5.0
    assert doc["dml_job_transitions_total"]["samples"][
        (("jtype", "train"), ("state", "running"))] == 1.0
    assert doc["dml_jobs_done_total"]["samples"][
        (("jtype", "train"), ("ok", "true"))] == 1.0
    assert doc["dml_publishes_total"]["samples"][
        (("swapped", "true"),)] == 1.0
    assert doc["dml_publish_latency_ms"]["samples"][()] == 3.0
    assert doc["dml_published_step"]["samples"][()] == 20.0

    # ensure_stats_server is one bind per process: a second call (even
    # with a different port) returns the SAME server — the runtime and
    # every Trainer it hosts share the endpoint instead of fighting.
    stop_stats_server()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    try:
        s1 = ensure_stats_server(port)
        assert s1 is not None and s1.port == port
        assert ensure_stats_server(port) is s1
        assert ensure_stats_server(port + 1) is s1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{s1.port}/metrics", timeout=5) as r:
            parse_prometheus_text(r.read().decode())
    finally:
        stop_stats_server()


# ---------------------------------------------------------------------------
# loadgen --runtime discovery
# ---------------------------------------------------------------------------

def test_loadgen_runtime_discovery(tmp_path):
    from tools import loadgen

    with pytest.raises(SystemExit, match="cannot read"):
        loadgen.main(["--runtime", str(tmp_path / "missing.json")])
    with pytest.raises(SystemExit, match="exclusive"):
        loadgen.main(["--runtime", str(tmp_path),
                      "--target", "http://localhost:1"])
    # A runtime that has not published yet advertises no port — the
    # error says why instead of hammering a null target. Passing the
    # log_dir (not the file) exercises the directory resolution.
    (tmp_path / "runtime.json").write_text(json.dumps(
        {"pid": 1, "serve_port": None, "version": None, "publishes": 0}))
    with pytest.raises(SystemExit, match="serve_port"):
        loadgen.main(["--runtime", str(tmp_path)])


# ---------------------------------------------------------------------------
# the acceptance smoke: train + serve + eval on one mesh, closed into
# an alert-triggered fine-tune, zero checkpoint reads on the hot path
# ---------------------------------------------------------------------------

def test_runtime_unified_smoke(data_cfg, tmp_path, monkeypatch):
    import jax

    from dml_cnn_cifar10_tpu import ckpt as ckpt_lib
    from dml_cnn_cifar10_tpu.data import download
    from dml_cnn_cifar10_tpu.data.pipeline import _load_split
    from dml_cnn_cifar10_tpu.runtime import Runtime

    restores = {"n": 0}
    real_restore = ckpt_lib.restore_checkpoint

    def counting_restore(*a, **kw):
        restores["n"] += 1
        return real_restore(*a, **kw)

    monkeypatch.setattr(ckpt_lib, "restore_checkpoint", counting_restore)

    cfg = tiny_train_cfg(data_cfg, str(tmp_path / "run"), total_steps=20,
                         output_every=5, eval_every=10,
                         checkpoint_every=10)
    cfg.metrics_jsonl = os.path.join(cfg.log_dir, "metrics.jsonl")
    cfg.serve.port = 0                       # ephemeral: no collisions
    cfg.runtime.jobs = "train,serve,eval"
    cfg.runtime.eval_every_s = 0.2
    # The injected drift signal: accuracy is always < 1.5, so the rule
    # fires (once — it never resolves) on the first eval record and the
    # control loop must turn it into exactly one FineTuneJob.
    cfg.alert_rules = "acc_drop=eval.test_accuracy<1.5"
    cfg.runtime.finetune_steps = 10
    cfg.runtime.finetune_rules = "acc_drop"
    cfg.runtime.max_finetunes = 1

    rt = Runtime(cfg, task_index=0)
    try:
        rt.start()
        # The serve job binds after the FIRST publish (step-10 commit);
        # probe the live HTTP surface while training is still running.
        deadline = time.time() + 600
        while rt.serve_port is None and time.time() < deadline:
            time.sleep(0.05)
        assert rt.serve_port, "serve job never bound (no publish?)"
        base = f"http://127.0.0.1:{rt.serve_port}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=60) as r:
            health = json.load(r)
        assert health["ok"] and health["version"] is not None
        download.ensure_dataset(cfg.data)
        images, _ = _load_split(download.test_files(cfg.data), cfg.data)
        req = urllib.request.Request(f"{base}/predict",
                                     data=images[0].tobytes(),
                                     method="POST")
        with urllib.request.urlopen(req, timeout=300) as r:
            assert r.status == 200
            body = json.load(r)
        assert 0 <= body["class"] < 10 and len(body["logits"]) == 10
        rt.wait()

        # Train ran 20 steps, the fine-tune continued 20 → 30; the final
        # commit's publish leaves the engine at version "30". The whole
        # run made exactly ONE restore call — TrainJob's initial
        # (empty-dir) restore; publishes and the fine-tune state
        # hand-off read no checkpoints.
        assert restores["n"] == 1
        batch = images[:32]
        live_logits, _, live_version = \
            rt.engine.forward_timed_versioned(batch)
        assert live_version == "30"
    finally:
        rt.close()

    # --- the stream tells the whole story, and lints clean -------------
    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(line) for line in f]
    by = {}
    for r in recs:
        by.setdefault(r["kind"], []).append(r)

    pubs = by["publish"]
    assert len(pubs) >= 3                    # steps 10, 20, 30
    assert all(p["source"] == "live_params" and p["swapped"]
               for p in pubs)
    assert pubs[-1]["step"] == 30
    assert any(p["job"] == "finetune-1" and p["step"] == 30
               for p in pubs)

    fired = [r for r in by["alert"] if r["rule"] == "acc_drop"]
    assert len(fired) == 1                   # fires once, never resolves

    names = {r["job"] for r in by["job"]}
    assert names == {"train", "serve", "eval", "finetune-1"}
    ft = [r for r in by["job"] if r["job"] == "finetune-1"]
    assert ft and all(r["trigger"] == "acc_drop" for r in ft)
    assert [r["state"] for r in ft] == ["pending", "running", "done"]
    dones = by["job_done"]
    assert {r["job"] for r in dones} == names
    assert all(r["ok"] for r in dones)

    # The eval job measured published weights on the shared engine.
    rt_evals = [r for r in by["eval"]
                if r.get("source") == "runtime_eval"]
    assert rt_evals and all(0.0 <= r["test_accuracy"] <= 1.0
                            for r in rt_evals)

    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl,
                                         strict=True) == []

    # telemetry_report renders the lifecycle + lineage, text and JSON.
    from tools import telemetry_report
    js = telemetry_report.summarize_json(cfg.metrics_jsonl)
    assert js["jobs"]["publish"]["publishes"] == len(pubs)
    assert js["jobs"]["publish"]["last_version"] == "30"
    assert any(ln["rule"] == "acc_drop" and ln["job"] == "finetune-1"
               and "30" in ln["versions"]
               for ln in js["jobs"]["lineage"])
    txt = telemetry_report.summarize(cfg.metrics_jsonl)
    assert "runtime jobs:" in txt and "finetune-1" in txt
    assert "lineage" in txt

    # runtime.json advertises what loadgen --runtime needs.
    with open(os.path.join(cfg.log_dir, "runtime.json")) as f:
        state = json.load(f)
    assert state["serve_port"] and state["version"] == "30"
    assert state["publishes"] == len(pubs)

    # --- served outputs == the standalone --mode serve path ------------
    # resolve_engine restores the newest checkpoint (step 30) from disk
    # — the restore count proves it reads what the runtime never did —
    # and must produce bitwise-identical logits for the same uint8
    # batch.
    from dml_cnn_cifar10_tpu.serve.server import resolve_engine
    scfg = dataclasses.replace(cfg, metrics_jsonl=None)
    eng2 = resolve_engine(scfg)
    assert restores["n"] == 2
    ref_logits, _, ref_version = eng2.forward_timed_versioned(batch)
    assert ref_version == "30"
    assert np.array_equal(live_logits, ref_logits)


# ---------------------------------------------------------------------------
# fetch parity: publishing into the in-process engine is free
# ---------------------------------------------------------------------------

def test_runtime_train_fetch_parity(data_cfg, tmp_path, monkeypatch):
    """A --mode run process (train + serve, no traffic) must issue
    EXACTLY the device fetches of a bare serve-less Trainer run: the
    publish protocol parks device-side copies and pointer-swaps them —
    any jax.device_get it added would stall the train step."""
    import jax

    from dml_cnn_cifar10_tpu.runtime import Runtime
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    counts = {"n": 0}
    real_get = jax.device_get

    def counting_get(x):
        counts["n"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    def mk(sub):
        cfg = tiny_train_cfg(data_cfg, str(tmp_path / sub),
                             total_steps=20, output_every=5,
                             eval_every=10, checkpoint_every=10)
        cfg.metrics_jsonl = os.path.join(cfg.log_dir, "m.jsonl")
        return cfg

    cfg_bare = mk("bare")
    counts["n"] = 0
    assert Trainer(cfg_bare).fit().final_step == 20
    bare_fetches = counts["n"]

    cfg_run = mk("run")
    cfg_run.serve.port = 0
    cfg_run.runtime.jobs = "train,serve"
    rt = Runtime(cfg_run)
    counts["n"] = 0
    try:
        rt.start()
        rt.wait()
    finally:
        rt.close()
    assert rt.engine is not None and rt.engine.version == "20"
    assert counts["n"] == bare_fetches, \
        "publishing into the serving engine must add zero device fetches"
