"""End-to-end request tracing (utils/reqtrace.py + the rspan seams in
serve/ and fleet/): context mint/parse/force mechanics, the batcher's
causally-linked batch/member spans against a stub engine, the merged
Perfetto hop lanes + flow events, the per-hop report section — and the
acceptance smoke: a 2-worker fleet under forced sampling where a
worker kill mid-load leaves a retried trace showing BOTH placements,
every sampled trace's hops causally linked client→router→worker→
batcher→engine, per-hop durations nesting inside the measured
end-to-end latency, and every stream passing the strict schema lint."""

import copy
import dataclasses
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax

from dml_cnn_cifar10_tpu.config import TrainConfig
from dml_cnn_cifar10_tpu.serve import MicroBatcher, ServeMetrics, ShedError
from dml_cnn_cifar10_tpu.utils import reqtrace
from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
from tests.test_fleet import (FakeLogger, _fleet_cfg, _free_port,
                              _healthz, _save_ckpt, _worker_log_tails)
from tests.test_serve import StubEngine, _images


# ---------------------------------------------------------------------------
# trace-context mechanics (pure)
# ---------------------------------------------------------------------------

def test_mint_parse_header_round_trip():
    ctx = reqtrace.mint(1.0)
    assert len(ctx.trace_id) == 16 and ctx.sampled and ctx.emit
    assert ctx.header() == f"{ctx.trace_id};s=1"
    back = reqtrace.parse(ctx.header(), 0.0)
    assert back.trace_id == ctx.trace_id and back.sampled
    # Rate 0: minted but not sampled; the id still propagates.
    cold = reqtrace.mint(0.0)
    assert not cold.sampled and not cold.emit
    assert cold.header().endswith(";s=0")
    assert reqtrace.parse(cold.header(), 1.0).sampled is False


def test_parse_mints_on_absent_or_malformed():
    for bad in (None, "", ";s=1", "  ;s=1"):
        ctx = reqtrace.parse(bad, 1.0)
        assert len(ctx.trace_id) == 16 and ctx.sampled
    # A foreign id is adopted as-is; an unparsable s bit reads unsampled
    # (tracing never fails a request).
    ctx = reqtrace.parse("abc;s=2", 1.0)
    assert ctx.trace_id == "abc" and not ctx.sampled


def test_force_upgrades_emit_and_header():
    ctx = reqtrace.mint(0.0)
    assert not ctx.emit
    ctx.force()
    # Sampling decision unchanged; emission (and the downstream header)
    # upgraded — shed/retried requests become fully traced.
    assert not ctx.sampled and ctx.emit and ctx.forced
    assert ctx.header().endswith(";s=1")


def test_emit_span_respects_decision_and_clamps():
    log = FakeLogger()
    reqtrace.emit_span(log, reqtrace.mint(0.0), "client", 0.01, 100.0)
    reqtrace.emit_span(None, reqtrace.mint(1.0), "client", 0.01, 100.0)
    reqtrace.emit_span(log, None, "client", 0.01, 100.0)
    assert log.records == []
    ctx = reqtrace.mint(1.0)
    reqtrace.emit_span(log, ctx, "engine", -0.5, 100.0, batch_id="ab")
    (r,) = log.records
    assert r["kind"] == "rspan" and r["trace_id"] == ctx.trace_id
    assert r["hop"] == "engine" and r["dur_ms"] == 0.0
    assert r["wallclock"] == 100.0 and r["batch_id"] == "ab"


# ---------------------------------------------------------------------------
# batcher spans against the stub engine
# ---------------------------------------------------------------------------

def test_batcher_emits_linked_batch_and_member_spans():
    eng = StubEngine(forward_s=0.01)
    log = FakeLogger()
    traces = [reqtrace.mint(1.0) for _ in range(3)]
    with MicroBatcher(eng, buckets=(1, 4), batch_window_s=0.2,
                      warmup=False, logger=log) as b:
        futs = [b.submit(im, trace=t)
                for im, t in zip(_images(3), traces)]
        for f in futs:
            f.result(timeout=10)
    spans = [r for r in log.records if r["kind"] == "rspan"]
    by_hop = {}
    for s in spans:
        by_hop.setdefault(s["hop"], []).append(s)
    # One batch span; its batch_id links every member's queue wait
    # (batcher) and device share (engine).
    (batch,) = by_hop["batch"]
    assert batch["n"] == 3
    assert len(by_hop["batcher"]) == 3 and len(by_hop["engine"]) == 3
    ids = {t.trace_id for t in traces}
    for s in by_hop["batcher"] + by_hop["engine"]:
        assert s["batch_id"] == batch["trace_id"]
        assert s["trace_id"] in ids
    # The engine span carries the batch's device time, not queue time.
    for s in by_hop["engine"]:
        assert s["dur_ms"] >= 10.0 - 1e-6


def test_batcher_unsampled_requests_emit_nothing():
    eng = StubEngine()
    log = FakeLogger()
    with MicroBatcher(eng, buckets=(1, 4), batch_window_s=0.1,
                      warmup=False, logger=log) as b:
        futs = [b.submit(im, trace=reqtrace.mint(0.0))
                for im in _images(3)]
        futs.append(b.submit(_images(1)[0]))     # untraced caller
        for f in futs:
            f.result(timeout=10)
    assert [r for r in log.records if r["kind"] == "rspan"] == []


def test_batcher_sheds_force_sampling():
    gate = threading.Event()
    eng = StubEngine(gate=gate)
    log = FakeLogger()
    b = MicroBatcher(eng, buckets=(1,), max_queue_depth=1,
                     batch_window_s=0.0, metrics=ServeMetrics(),
                     warmup=False, logger=log)
    try:
        b.submit(_images(1)[0], trace=reqtrace.mint(0.0))  # wedged
        time.sleep(0.1)
        doomed = b.submit(_images(1)[0], deadline_s=0.01,
                          trace=reqtrace.mint(0.0))        # queued
        shed_ctx = reqtrace.mint(0.0)
        with pytest.raises(ShedError):
            b.submit(_images(1)[0], trace=shed_ctx)        # queue full
        assert shed_ctx.emit                               # forced
        time.sleep(0.05)
    finally:
        gate.set()
        b.close()
    with pytest.raises(ShedError):
        doomed.result(timeout=10)
    sheds = {r.get("shed") for r in log.records
             if r["kind"] == "rspan" and r["hop"] == "batcher"}
    assert sheds == {"queue_full", "deadline"}


# ---------------------------------------------------------------------------
# merged Perfetto lanes + clock-anchor fallback
# ---------------------------------------------------------------------------

def _span_rec(t, trace_id, hop, dur_ms, wallclock, **extra):
    return {"kind": "rspan", "t": t, "task": 0, "trace_id": trace_id,
            "hop": hop, "dur_ms": dur_ms, "wallclock": wallclock,
            **extra}


def test_merged_trace_links_hops_with_flow_events(tmp_path):
    from tools.trace_aggregate import build_merged_trace

    w0 = 1_700_000_000.0
    client = [_span_rec(0.01, "aa" * 8, "client", 30.0, w0)]
    serve = [
        {"kind": "serve", "t": 0.5, "task": 1, "requests": 2,
         "completed": 2, "shed_queue": 0, "shed_deadline": 0,
         "qps": 4.0, "p50_ms": 5.0, "p95_ms": 9.0, "p99_ms": 9.0,
         "batch_fill": 1.0, "window_s": 0.5, "wallclock": w0 + 0.49},
        _span_rec(0.011, "aa" * 8, "server", 25.0, w0 + 0.001),
        _span_rec(0.012, "aa" * 8, "batcher", 5.0, w0 + 0.002,
                  batch_id="bb" * 4),
        _span_rec(0.013, "aa" * 8, "engine", 15.0, w0 + 0.007,
                  batch_id="bb" * 4),
        _span_rec(0.013, "cc" * 8, "batch", 15.0, w0 + 0.007, n=1),
    ]
    p1, p2 = tmp_path / "client.jsonl", tmp_path / "serve.jsonl"
    p1.write_text("".join(json.dumps(r) + "\n" for r in client))
    p2.write_text("".join(json.dumps(r) + "\n" for r in serve))
    doc = build_merged_trace([str(p1), str(p2)])
    events = doc["traceEvents"]
    xs = [e for e in events if e.get("cat") == "rspan"
          and e["ph"] == "X"]
    assert {e["args"]["hop"] for e in xs} == \
        {"client", "server", "batcher", "engine", "batch"}
    # Hop lanes: each hop gets its own tid so lanes nest visually.
    assert len({(e["pid"], e["tid"]) for e in xs}) == len(xs)
    # One flow thread for the multi-span trace: start → steps → finish
    # in wallclock order, client first.
    flows = sorted((e for e in events if e.get("cat") == "rspan"
                    and e["ph"] in ("s", "t", "f")),
                   key=lambda e: e["ts"])
    assert [e["ph"] for e in flows] == ["s", "t", "t", "f"]
    assert len({e["id"] for e in flows}) == 1
    # The serve stream aligned via its window-record wallclock anchor
    # (no heartbeats anywhere in it).
    client_x = next(e for e in xs if e["args"]["hop"] == "client")
    server_x = next(e for e in xs if e["args"]["hop"] == "server")
    assert server_x["ts"] - client_x["ts"] == pytest.approx(1e3, abs=50)


def test_clock_offset_falls_back_to_serve_anchor():
    from tools.trace_aggregate import clock_offset

    recs = [{"kind": "fleet", "t": 2.0, "task": 0, "replicas": 2,
             "live": 2, "routed": 10, "rerouted": 0, "evictions": 0,
             "shed": 0, "version_mix": {"1": 2}, "window_s": 2.0,
             "wallclock": 1002.0}]
    assert clock_offset(recs) == pytest.approx(1000.0)
    assert clock_offset([{"kind": "train", "t": 1.0, "task": 0}]) is None


def test_report_renders_per_hop_breakdown(tmp_path):
    from tools import telemetry_report

    recs = []
    for i in range(4):
        tid = f"{i + 1:016x}"
        recs.append(_span_rec(0.1 * i, tid, "client", 20.0 + i, 100.0))
        recs.append(_span_rec(0.1 * i, tid, "engine", 5.0, 100.0,
                              version="7"))
    recs.append(_span_rec(0.9, "dd" * 8, "batch", 9.0, 100.0, n=4))
    path = tmp_path / "m.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    out = telemetry_report.summarize(str(path))
    assert "request tracing" in out and "client" in out
    js = telemetry_report.summarize_json(str(path))
    hop = js["request_tracing"]
    assert hop["traces"] == 4
    by_hop = {h["hop"]: h for h in hop["hops"]}
    assert by_hop["client"]["spans"] == 4
    # Text/JSON parity on the slowest-trace exemplars: the batch span
    # is infrastructure, not a request — excluded from totals.
    slowest = hop["slowest"][0]
    assert slowest["total_ms"] == pytest.approx(23.0 + 5.0)
    assert slowest["trace_id"] in out and slowest["version"] == "7"


# ---------------------------------------------------------------------------
# acceptance smoke: traced 2-worker fleet surviving a worker kill
# ---------------------------------------------------------------------------

def _traced_predict(port, img, logger, sample_rate=1.0):
    """One client request with a minted trace context: send the header,
    emit the client span (forced on shed/failure like loadgen)."""
    ctx = reqtrace.mint(sample_rate)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=img.tobytes(),
        headers={"Content-Type": "application/octet-stream",
                 reqtrace.TRACE_HEADER: ctx.header()})
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            body = json.loads(resp.read())
    except Exception:
        ctx.force()
        reqtrace.emit_span(logger, ctx,
                           "client", time.perf_counter() - t0,
                           reqtrace.wallclock_at(t0), status=0)
        raise
    reqtrace.emit_span(logger, ctx, "client",
                       time.perf_counter() - t0,
                       reqtrace.wallclock_at(t0), status=200,
                       version=body.get("version"))
    return ctx.trace_id, body


def test_fleet_tracing_smoke_kill_retry_and_causal_chain(
        tmp_path, data_cfg, monkeypatch, rng):
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
    from dml_cnn_cifar10_tpu.fleet.controller import main_fleet
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tools import check_jsonl_schema
    from tools.trace_aggregate import build_merged_trace

    monkeypatch.setenv("XLA_FLAGS", "")
    cfg = _fleet_cfg(tmp_path, data_cfg)
    cfg.serve.trace_sample_rate = 1.0      # sampling forced on
    cfg.fleet.worker_fault = "1:host_lost@15"

    seed_cfg = copy.deepcopy(cfg)
    seed_cfg.metrics_jsonl = None
    trainer = Trainer(seed_cfg)
    host_state = ckpt_lib.fetch_to_host(trainer.init_or_restore())
    _save_ckpt(cfg, host_state, 1)

    images = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    client_jsonl = str(tmp_path / "client.jsonl")
    client_log = MetricsLogger(jsonl_path=client_jsonl)

    ready, stop = threading.Event(), threading.Event()
    rc = {}
    t = threading.Thread(
        target=lambda: rc.setdefault("rc", main_fleet(
            cfg, ready_event=ready, stop_event=stop)),
        daemon=True)
    t.start()
    port = cfg.fleet.port
    trace_ids = []
    e2e = {}     # trace_id -> client-measured latency (s)
    try:
        assert ready.wait(60), "router never became ready"
        deadline = time.time() + 240
        while time.time() < deadline:
            if _healthz(port)["live"] >= 2:
                break
            time.sleep(0.5)
        else:
            pytest.fail("fleet never reached 2 live replicas\n"
                        + _worker_log_tails(cfg.fleet.dir))
        for i in range(60):
            t0 = time.perf_counter()
            tid, resp = _traced_predict(port, images[i % 4], client_log)
            e2e[tid] = time.perf_counter() - t0
            assert "class" in resp, f"request {i} failed: {resp}"
            trace_ids.append(tid)
            time.sleep(0.01)
        hz = _healthz(port)
        assert hz["replicas"]["1"]["live"] is False, \
            "replica 1 was never killed/evicted\n" \
            + _worker_log_tails(cfg.fleet.dir)
    finally:
        stop.set()
        t.join(120)
        client_log.close()
    assert not t.is_alive() and rc.get("rc") == 0

    tele = os.path.join(cfg.fleet.dir, "telemetry")
    streams = [client_jsonl, cfg.metrics_jsonl] + sorted(
        os.path.join(tele, n) for n in os.listdir(tele)
        if n.endswith(".jsonl"))
    spans = []
    for path in streams:
        # Every stream — client, router, every replica — passes the
        # strict schema lint (unknown kinds rejected).
        assert check_jsonl_schema.check_file(path, strict=True) == [], \
            path
        with open(path) as f:
            spans.extend(r for r in (json.loads(ln) for ln in f
                                     if ln.strip())
                         if r["kind"] == "rspan")
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)

    # Every client trace is causally complete: the request is visible
    # at every hop of the path that served it.
    chain = ("client", "router", "worker", "batcher", "engine")
    for tid in trace_ids:
        hops = {s["hop"] for s in by_trace[tid]}
        assert set(chain) <= hops, (tid, hops)
        # ... and the batcher/engine spans link to a real batch span.
        links = {s.get("batch_id") for s in by_trace[tid]
                 if s["hop"] in ("batcher", "engine")} - {None}
        assert links and links <= set(by_trace), (tid, links)

    # The kill left at least one retried request whose trace shows BOTH
    # placements: router attempt spans naming two distinct replicas.
    retried = [tid for tid in trace_ids
               if len({s.get("replica_id")
                       for s in by_trace[tid]
                       if s["hop"] == "router"
                       and s.get("replica_id") is not None}) >= 2]
    assert retried, "no trace recorded a failover across replicas"

    # Per-hop durations nest inside the measured end-to-end latency:
    # queue wait + device share fit in the worker's handler span, which
    # fits in the client's wall time (generous slack for scheduling).
    for tid in trace_ids:
        by_hop = {}
        for s in by_trace[tid]:
            by_hop.setdefault(s["hop"], []).append(s["dur_ms"])
        interior = max(by_hop["batcher"]) + max(by_hop["engine"])
        assert interior <= max(by_hop["worker"]) + 100.0, (tid, by_hop)
        assert max(by_hop["worker"]) <= e2e[tid] * 1e3 + 150.0, \
            (tid, by_hop, e2e[tid])

    # The merged Perfetto file causally links the hops: one flow id per
    # multi-span trace, threading start → finish.
    doc = build_merged_trace(streams)
    flow = [e for e in doc["traceEvents"]
            if e.get("cat") == "rspan" and e["ph"] in ("s", "t", "f")]
    starts = sum(1 for e in flow if e["ph"] == "s")
    finishes = sum(1 for e in flow if e["ph"] == "f")
    assert starts == finishes and starts >= len(set(trace_ids))
    lanes = {(e["pid"], e["tid"]) for e in doc["traceEvents"]
             if e.get("cat") == "rspan" and e["ph"] == "X"}
    assert len(lanes) >= len(chain)
