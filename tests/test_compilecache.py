"""Persistent compilation cache (compilecache/; docs/COMPILECACHE.md):
keying determinism, hit/miss + store/load mechanics, corruption
fail-open, LRU eviction, the CLI, and the ISSUE-5 acceptance smoke —
train under --compile_cache_dir, kill via sigterm@N, supervisor-restart
in the same cache dir, and require `compile` hit events plus final
params bit-identical to an uninterrupted run."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dml_cnn_cifar10_tpu.compilecache import CompileCache, wrap
from dml_cnn_cifar10_tpu.compilecache import cache as cc_lib
from dml_cnn_cifar10_tpu.train.loop import Trainer
from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised
from tests.conftest import tiny_train_cfg
from tools import check_jsonl_schema, compile_cache_cli


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _compile_events(path):
    return [r for r in _read_jsonl(path) if r["kind"] == "compile"]


class _EventSink:
    """MetricsLogger-shaped collector for cache events."""

    def __init__(self):
        self.events = []

    def log(self, kind, **fields):
        self.events.append({"kind": kind, **fields})


# ---------------------------------------------------------------------------
# keying: determinism + sensitivity
# ---------------------------------------------------------------------------

def test_fingerprint_deterministic_and_sensitive(tmp_path):
    cache = CompileCache(str(tmp_path))
    f = jax.jit(lambda x: x * 2 + 1)
    aval32 = jax.ShapeDtypeStruct((16,), jnp.float32)
    aval16 = jax.ShapeDtypeStruct((16,), jnp.bfloat16)
    hlo32 = f.lower(aval32).as_text()
    ctx = {"donate": [], "mesh_axes": ["data"], "mesh_shape": [8]}
    # Same program + context twice -> identical key (lowering is
    # deterministic; the whole warm-start contract rests on this).
    assert cache.fingerprint(hlo32, ctx) == cache.fingerprint(hlo32, ctx)
    assert cache.fingerprint(f.lower(aval32).as_text(), ctx) \
        == cache.fingerprint(hlo32, ctx)
    # dtype changes the lowered module -> different key.
    assert cache.fingerprint(f.lower(aval16).as_text(), ctx) \
        != cache.fingerprint(hlo32, ctx)
    # mesh / donation changes re-key via the explicit context even when
    # the module text were equal.
    assert cache.fingerprint(hlo32, {**ctx, "mesh_shape": [4, 2]}) \
        != cache.fingerprint(hlo32, ctx)
    assert cache.fingerprint(hlo32, {**ctx, "donate": [0]}) \
        != cache.fingerprint(hlo32, ctx)


def test_train_step_key_determinism_across_builders(data_cfg, tmp_path):
    """The same TrainConfig builds the same train-step key twice; a
    compute-dtype flip builds a different one."""
    from dml_cnn_cifar10_tpu.config import ModelConfig, OptimConfig
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import step as step_lib
    from dml_cnn_cifar10_tpu.parallel.mesh import build_mesh, shard_batch
    from dml_cnn_cifar10_tpu.config import ParallelConfig

    mesh = build_mesh(ParallelConfig())
    md = get_model("cnn")
    oc = OptimConfig()
    cache = CompileCache(str(tmp_path))
    rng = np.random.default_rng(0)
    batch = shard_batch(mesh, rng.random((32, 24, 24, 3), np.float32),
                        rng.integers(0, 10, (32,)).astype(np.int32))

    def key_for(mc):
        sh = step_lib.train_state_shardings(mesh, md, mc, data_cfg, oc)
        fn = step_lib.make_train_step(md, mc, oc, mesh,
                                      state_sharding=sh,
                                      compile_cache=cache)
        state = step_lib.init_train_state(
            jax.random.key(0), md, mc, data_cfg, oc, mesh,
            state_sharding=sh)
        fn(state, *batch)
        return fn.last_event["key"]

    k1 = key_for(ModelConfig(logit_relu=False))
    k2 = key_for(ModelConfig(logit_relu=False))
    k3 = key_for(ModelConfig(logit_relu=False,
                             compute_dtype="bfloat16"))
    assert k1 == k2 and k1 is not None
    assert k3 != k1


def test_optimizer_sharding_and_partition_rules_change_key(data_cfg,
                                                           tmp_path):
    """--optimizer_sharding and --partition_rules alter the lowered
    StableHLO (sharding constraints / in-sharding annotations), so they
    MUST re-key the compile cache — a stale hit here silently serves an
    executable with the wrong update schedule or state layout."""
    from dml_cnn_cifar10_tpu.config import (ModelConfig, OptimConfig,
                                            ParallelConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import shardings
    from dml_cnn_cifar10_tpu.parallel import step as step_lib
    from dml_cnn_cifar10_tpu.parallel.mesh import build_mesh, shard_batch

    mesh = build_mesh(ParallelConfig())
    md = get_model("cnn")
    mc = ModelConfig(logit_relu=False)
    cache = CompileCache(str(tmp_path))
    rng = np.random.default_rng(0)
    batch = shard_batch(mesh, rng.random((32, 24, 24, 3), np.float32),
                        rng.integers(0, 10, (32,)).astype(np.int32))

    def key_for(oc, rules=None, zero1=False):
        sh = step_lib.train_state_shardings(mesh, md, mc, data_cfg, oc,
                                            zero1=zero1, rules=rules)
        fn = step_lib.make_train_step(md, mc, oc, mesh,
                                      state_sharding=sh, rules=rules,
                                      compile_cache=cache)
        state = step_lib.init_train_state(
            jax.random.key(0), md, mc, data_cfg, oc, mesh,
            state_sharding=sh)
        fn(state, *batch)
        return fn.last_event["key"]

    base = key_for(OptimConfig(momentum=0.9))
    zero1 = key_for(OptimConfig(momentum=0.9,
                                optimizer_sharding="zero1"), zero1=True)
    rules = shardings.parse_partition_rules(
        "full1/kernel$=data,-; .*=")     # storage layout change
    ruled = key_for(OptimConfig(momentum=0.9), rules=rules)
    assert base is not None
    assert zero1 != base
    assert ruled != base and ruled != zero1


# ---------------------------------------------------------------------------
# hit/miss mechanics + entry layout
# ---------------------------------------------------------------------------

def test_miss_stores_committed_entry_then_hits(tmp_path):
    # Executable swapping is OPT-IN (default allowlist is empty — see
    # EXECUTABLE_BACKENDS); small donation-free programs exercise the
    # serialize/store/verify machinery safely on CPU.
    sink = _EventSink()
    cache = CompileCache(str(tmp_path), logger=sink,
                         executable_backends=("cpu",))
    f = jax.jit(lambda x: jnp.sin(x) * 3)
    x = jnp.arange(8, dtype=jnp.float32)
    w1 = wrap(f, cache, "train_step")
    out1 = w1(x)
    assert w1.last_event["hit"] is False
    assert w1.last_event["source"] == "miss"
    assert w1.last_event["compile_s"] > 0
    key = w1.last_event["key"]
    # Entry committed with the full file set and a verifying sidecar.
    for suffix in (".meta.json", ".exec", ".exec.sha256", ".hlo.z"):
        assert os.path.isfile(os.path.join(str(tmp_path), key + suffix))
    ok, reason = cache.verify_entry(key)
    assert ok, reason
    # Second wrapper, same program: in-process registry hit, identical
    # numerics, hit-count bumped in the meta.
    w2 = wrap(f, cache, "train_step")
    out2 = w2(x)
    assert w2.last_event["hit"] is True
    assert w2.last_event["source"] == "memory"
    assert w2.last_event["key"] == key
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert cache.load_meta(key)["hits"] >= 1
    # Every lookup emitted one schema-shaped `compile` event.
    kinds = [(e["phase"], e["hit"], e["source"]) for e in sink.events]
    assert kinds == [("train_step", False, "miss"),
                     ("train_step", True, "memory")]


def test_wrap_without_cache_is_identity():
    f = jax.jit(lambda x: x + 1)
    assert wrap(f, None, "train_step") is f


def test_cached_flops_served_from_entry(tmp_path):
    """The bench/loop FLOPs probes read the cached artifact's cost
    analysis instead of recompiling (the old bench.py:173 caveat)."""
    from dml_cnn_cifar10_tpu.utils.profiling import compiled_flops

    cache = CompileCache(str(tmp_path))
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((32, 32))
    w = wrap(f, cache, "train_step")
    w(a, a)
    avals = (jax.ShapeDtypeStruct((32, 32), jnp.float32),) * 2
    flops = compiled_flops(w, avals)
    # CPU cost analysis reports flops as a list of per-program dicts;
    # the cache path normalizes it (the bare AOT path returned None
    # here, so a positive figure proves the cached route was taken).
    assert flops and flops > 0
    meta = cache.load_meta(w.last_event["key"])
    assert meta["cost_analysis"]["flops"] > 0


def test_second_signature_falls_back_to_jit(tmp_path):
    """A shape the obtained executable doesn't match must not error —
    the wrapper falls back to the jit call path (safety net)."""
    cache = CompileCache(str(tmp_path), executable_backends=("cpu",))
    w = wrap(jax.jit(lambda x: x * 2), cache, "eval_step")
    np.testing.assert_array_equal(np.asarray(w(jnp.ones((4,)))),
                                  2 * np.ones((4,)))
    np.testing.assert_array_equal(np.asarray(w(jnp.ones((9,)))),
                                  2 * np.ones((9,)))


# ---------------------------------------------------------------------------
# corruption: fail-open recompile, never a crash
# ---------------------------------------------------------------------------

def _store_and_forget(cache, const):
    """Compile+store a unique tiny program, then evict it from the
    process registry so the next lookup exercises the DISK path."""
    f = jax.jit(lambda x: x * const)
    w = wrap(f, cache, "train_step")
    w(jnp.ones((16,)))
    key = w.last_event["key"]
    cc_lib._PROCESS_EXECUTABLES.pop(key, None)
    return f, key


@pytest.mark.parametrize("what", ["payload_flip", "payload_truncate",
                                  "sidecar_flip", "sidecar_truncate"])
def test_corrupt_entry_fails_open_to_recompile(tmp_path, what):
    sink = _EventSink()
    cache = CompileCache(str(tmp_path), logger=sink,
                         executable_backends=("cpu",))
    # A UNIQUE program per case: the process registry spans test cases,
    # and a shared program would memory-hit instead of re-storing into
    # this case's fresh cache dir.
    const = 3.25 + sum(map(ord, what))
    f, key = _store_and_forget(cache, const)
    target = os.path.join(
        str(tmp_path),
        key + (".exec" if what.startswith("payload") else ".exec.sha256"))
    with open(target, "rb") as fh:
        data = bytearray(fh.read())
    if what.endswith("truncate"):
        data = data[:max(1, len(data) // 2)]
    else:
        data[len(data) // 2] ^= 0xFF
    with open(target, "wb") as fh:
        fh.write(bytes(data))
    assert not cache.verify_entry(key)[0]
    # Fail-open: the lookup drops the entry, recompiles, recommits —
    # and records the miss with source="corrupt".
    sink.events.clear()
    cc_lib._PROCESS_EXECUTABLES.pop(key, None)
    w = wrap(f, cache, "train_step")
    out = w(jnp.ones((16,)))
    np.testing.assert_allclose(np.asarray(out), const * np.ones((16,)))
    assert w.last_event["hit"] is False
    assert w.last_event["source"] == "corrupt"
    assert sink.events[0]["source"] == "corrupt"
    ok, reason = cache.verify_entry(key)
    assert ok, reason  # re-stored entry verifies again


# ---------------------------------------------------------------------------
# degraded mode (backends off the executable allowlist, e.g. real TPU)
# ---------------------------------------------------------------------------

def test_degraded_backend_keeps_jit_path_and_telemetry(tmp_path):
    """With the backend off the executable allowlist (the DEFAULT
    posture everywhere: the tunneled-TPU A/B showed swapped-in AOT
    executables corrupting donated state, and CPU resume runs abort
    with heap corruption), execution must stay on the jit call path
    while the cache still fingerprints, stores StableHLO + cost
    analysis, and emits hit/miss events."""
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_floor = jax.config.jax_persistent_cache_min_compile_time_secs
    sink = _EventSink()
    cache = CompileCache(str(tmp_path), logger=sink,
                         executable_backends=())
    assert cache.degraded()
    # Native-cache arming is platform-gated (skipped on CPU — loading
    # cached CPU executables heap-corrupts on this jaxlib); restore the
    # global config anyway in case a future platform change arms it.
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      prev_floor)
    f = jax.jit(lambda x: x * 5 + 1)
    x = jnp.arange(6, dtype=jnp.float32)
    w1 = wrap(f, cache, "train_step")
    np.testing.assert_array_equal(np.asarray(w1(x)),
                                  5 * np.arange(6, dtype=np.float32) + 1)
    assert w1.compiled is None            # nothing swapped in
    assert w1.last_event["hit"] is False
    assert w1.last_event["source"] == "miss"
    key = w1.last_event["key"]
    meta = cache.load_meta(key)
    assert meta is not None and meta["has_executable"] is False
    assert not os.path.isfile(os.path.join(str(tmp_path), key + ".exec"))
    # Second lookup: a stablehlo hit, numerics still from the jit path.
    w2 = wrap(f, cache, "train_step")
    np.testing.assert_array_equal(np.asarray(w2(x)), np.asarray(w1(x)))
    assert w2.last_event["hit"] is True
    assert w2.last_event["source"] == "stablehlo"
    # FLOPs probes are served from the entry without any executable.
    assert w2.cached_flops((jax.ShapeDtypeStruct((6,), jnp.float32),))


def test_executable_swap_is_opt_in(tmp_path):
    """Regression pin for the memory-safety gate: without an explicit
    DML_COMPILECACHE_EXEC_BACKENDS opt-in the allowlist is EMPTY, so
    every backend runs degraded. Re-enabling a default must come back
    through this test: jaxlib's experimental deserialize path aborts
    the process (heap corruption) when donation meets
    checkpoint-restored buffers — observed ~5/6 supervisor resumes on
    CPU jaxlib 0.4.36 — which fail-open cannot catch."""
    assert cc_lib.EXECUTABLE_BACKENDS == ()
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_floor = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        assert CompileCache(str(tmp_path)).degraded()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_floor)


def test_native_cache_arming_is_platform_gated(tmp_path, monkeypatch):
    """arm_native_cache must NOT arm on CPU (loading cached CPU
    executables from jax's native persistent cache heap-corrupts
    ~1/3 of supervisor resumes on jaxlib 0.4.36); the env override
    forces it, and an already-configured dir is respected."""
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_floor = jax.config.jax_persistent_cache_min_compile_time_secs
    monkeypatch.delenv("DML_COMPILECACHE_NATIVE_CACHE", raising=False)
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        # The test env requests platform cpu -> gated off.
        cc_lib.arm_native_cache(str(tmp_path))
        assert jax.config.jax_compilation_cache_dir is None
        # Forced on: arms under <dir>/xla with the floor dropped.
        monkeypatch.setenv("DML_COMPILECACHE_NATIVE_CACHE", "1")
        cc_lib.arm_native_cache(str(tmp_path))
        assert jax.config.jax_compilation_cache_dir \
            == os.path.join(str(tmp_path), "xla")
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
        # A dir the user already configured is never overridden.
        cc_lib.arm_native_cache(str(tmp_path / "other"))
        assert jax.config.jax_compilation_cache_dir \
            == os.path.join(str(tmp_path), "xla")
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_floor)


# ---------------------------------------------------------------------------
# LRU eviction
# ---------------------------------------------------------------------------

def test_lru_eviction_bounds_cache_size(tmp_path):
    cache = CompileCache(str(tmp_path), max_bytes=10**9)
    blob = b"x" * 1000
    for i, key in enumerate(("k_old", "k_mid", "k_new")):
        cache.store(key, "train_step", blob, "hlo text", None, 0.1, {})
        time.sleep(0.02)  # distinct last_used stamps
    assert {k for k, _ in cache.entries()} == {"k_old", "k_mid", "k_new"}
    # A hit on the oldest makes it most-recently-used...
    cache._touch("k_old", cache.load_meta("k_old"))
    per_entry = cache.entry_bytes("k_new")
    # ...so bounding to ~2 entries must evict k_mid (the true LRU), not
    # the just-touched k_old.
    cache.max_bytes = int(per_entry * 2.5)
    cache._evict()
    survivors = {k for k, _ in cache.entries()}
    assert survivors == {"k_old", "k_new"}
    total = sum(cache.entry_bytes(k) for k in survivors)
    assert total <= cache.max_bytes


# ---------------------------------------------------------------------------
# the CLI: inspect / verify / prune (tier-1 smoke, satellite)
# ---------------------------------------------------------------------------

def test_compile_cache_cli_inspect_verify_prune(tmp_path, capsys):
    cache = CompileCache(str(tmp_path), executable_backends=("cpu",))
    f = jax.jit(lambda x: x - 7)
    w = wrap(f, cache, "eval_step")
    w(jnp.ones((4,)))
    key = w.last_event["key"]

    assert compile_cache_cli.main(["inspect", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert key in out and "eval_step" in out

    assert compile_cache_cli.main(["verify", str(tmp_path)]) == 0
    assert "OK" in capsys.readouterr().out

    # Corrupt the payload: verify reports it and exits 1.
    with open(os.path.join(str(tmp_path), key + ".exec"), "ab") as fh:
        fh.write(b"garbage")
    assert compile_cache_cli.main(["verify", str(tmp_path)]) == 1
    assert "CORRUPT" in capsys.readouterr().out

    # prune --corrupt drops it; the cache is then empty and verifies.
    assert compile_cache_cli.main(
        ["prune", str(tmp_path), "--corrupt"]) == 0
    capsys.readouterr()
    assert compile_cache_cli.main(["verify", str(tmp_path)]) == 0
    assert "empty cache" in capsys.readouterr().out

    assert compile_cache_cli.main(["prune", str(tmp_path), "--all"]) == 0
    assert cache.entries() == []


# ---------------------------------------------------------------------------
# goodput attribution (satellite)
# ---------------------------------------------------------------------------

def test_add_secs_attributes_compile_fraction():
    from dml_cnn_cifar10_tpu.utils.telemetry import SpanTracer

    tracer = SpanTracer(enabled=True)
    tracer.add_secs("compile", 0.5)
    gp = tracer.goodput(now=tracer._epoch + 1.0)
    assert gp["compile_frac"] == pytest.approx(0.5, abs=1e-6)
    assert gp["train_frac"] == pytest.approx(0.5, abs=1e-6)
    # Disabled tracers stay no-ops.
    off = SpanTracer(enabled=False)
    off.add_secs("compile", 0.5)
    assert off._cat_secs["compile"] == 0.0


# ---------------------------------------------------------------------------
# the acceptance smoke: sigterm@N + supervisor restart in the same
# cache dir -> compile hits, bit-identical params, schema-clean stream
# ---------------------------------------------------------------------------

def _cached_cfg(data_cfg, tmpdir, cache_dir, jsonl, total_steps=40):
    cfg = tiny_train_cfg(data_cfg, tmpdir, total_steps=total_steps)
    cfg.checkpoint_every = 10
    cfg.output_every = 10
    cfg.eval_every = 20
    cfg.recovery_backoff_s = 0.01
    cfg.compile_cache_dir = cache_dir
    cfg.metrics_jsonl = jsonl
    cfg.telemetry = True
    return cfg


def test_warm_restart_after_sigterm_is_bit_identical(data_cfg, tmp_path):
    cache_dir = str(tmp_path / "ccache")
    jsonl = str(tmp_path / "m.jsonl")
    cfg = _cached_cfg(data_cfg, str(tmp_path / "run"), cache_dir, jsonl)
    cfg.fault_spec = "sigterm@15"
    result = fit_supervised(cfg)
    # SIGTERM -> PreemptionGuard checkpoint + clean preempted exit.
    assert result.preempted and 15 <= result.final_step < 40

    # "Process restart": a fresh supervised run over the same log and
    # cache dirs resumes from the preemption checkpoint and re-enters
    # every compile seam through the cache.
    cfg2 = _cached_cfg(data_cfg, str(tmp_path / "run"), cache_dir,
                       str(tmp_path / "m2.jsonl"))
    result2 = fit_supervised(cfg2)
    assert result2.final_step == 40

    evs = _compile_events(cfg2.metrics_jsonl)
    train_evs = [e for e in evs if e["phase"] == "train_step"]
    assert train_evs and all(e["hit"] for e in train_evs)
    # Default posture: degraded (executable swapping is opt-in), so
    # warm re-entries hit as "stablehlo" (entry present, execution on
    # the jit call path). With an opted-in backend they would be
    # "memory"/"executable" — all three are hits.
    assert {e["source"] for e in evs if e["hit"]} <= {
        "memory", "executable", "stablehlo"}

    # Bit-identical to an uninterrupted (uncached) run: the cache
    # returns the same compiled artifact the cold path produces.
    clean = tiny_train_cfg(data_cfg, str(tmp_path / "clean"))
    clean.checkpoint_every = 10
    clean.output_every = 10
    clean.eval_every = 20
    ref = Trainer(clean).fit()
    for a, b in zip(jax.tree.leaves(result2.state.params),
                    jax.tree.leaves(ref.state.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))

    # Both streams pass the documented-schema lint, and the report
    # prints the compile-cost section.
    assert check_jsonl_schema.check_file(jsonl, strict=True) == []
    assert check_jsonl_schema.check_file(cfg2.metrics_jsonl, strict=True) == []
    from tools import telemetry_report
    out = telemetry_report.summarize(cfg2.metrics_jsonl)
    assert "compile cost" in out

    # The warm run attributed its (near-zero) obtain time to the
    # goodput compile fraction rather than the train remainder.
    gps = [r for r in _read_jsonl(cfg2.metrics_jsonl)
           if r["kind"] == "goodput"]
    assert gps and gps[-1]["compile_frac"] is not None


@pytest.mark.slow
def test_cross_process_warm_start_deserializes(data_cfg, tmp_path):
    """With a backend OPTED IN via DML_COMPILECACHE_EXEC_BACKENDS, a
    genuinely fresh process hits the DISK path: the second run's
    train-step lookup deserializes (source "executable", no compile).
    Small donation-only program — the checkpoint-restore + donation
    combination that heap-corrupts on CPU jaxlib 0.4.36 (why the
    allowlist defaults to empty) is not in play here."""
    cache_dir = str(tmp_path / "ccache")
    script = r"""
import sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu(virtual_devices=8)
import json
import numpy as np, jax, jax.numpy as jnp
from dml_cnn_cifar10_tpu.compilecache import CompileCache, wrap

cache = CompileCache(sys.argv[1])
f = jax.jit(lambda s, x: (s + (x * x).sum(), x * 2), donate_argnums=0)
w = wrap(f, cache, "train_step")
s, y = w(jnp.zeros(()), jnp.arange(16, dtype=jnp.float32))
print("EVENT " + json.dumps({**w.last_event,
                             "out": float(jax.device_get(s))}))
"""
    env = {**os.environ,
           "DML_COMPILECACHE_EXEC_BACKENDS": "cpu",
           "PYTHONPATH": os.path.dirname(
               os.path.dirname(os.path.abspath(__file__)))}

    def run_once():
        proc = subprocess.run([sys.executable, "-c", script, cache_dir],
                              capture_output=True, text=True, env=env,
                              timeout=300)
        assert proc.returncode == 0, proc.stderr
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("EVENT ")][0]
        return json.loads(line[len("EVENT "):])

    ev1 = run_once()
    ev2 = run_once()
    assert ev1["source"] == "miss" and ev1["hit"] is False
    assert ev2["source"] == "executable" and ev2["hit"] is True
    assert ev1["key"] == ev2["key"]          # cross-process determinism
    assert ev1["out"] == ev2["out"]          # identical numerics
