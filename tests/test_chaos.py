"""Chaos campaign engine (ISSUE 10): phase-qualified fault triggers,
the seeded compound-fault sampler, decision-file integrity sidecars,
the progress-based retry-budget reset, and the campaign driver
(tools/chaos.py) — including the tier-1 acceptance sims: a fixed-seed
smoke campaign where every schedule's recovery converges bit-identical
to the fault-free reference, a planted regression (decision-sidecar
revert) that the campaign must catch and shrink to its
``decision_corrupt`` core, phase triggers firing exactly once at their
recovery seams in a 2-process sim, and a chief killed between its
``decide_restart`` commit and its own restore with the survivor
completing recovery via next-chief re-decision."""

import json
import os

import pytest

from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.utils import faults as faults_lib

from tests.test_cluster import FakeLogger, _monitor

from tools import chaos as chaos_lib


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# fault-spec grammar: phase triggers + compound same-step faults
# ---------------------------------------------------------------------------

def test_phase_qualified_spec_parses_and_round_trips():
    events = faults_lib.parse_fault_spec(
        "decision_corrupt@decide,ckpt_corrupt@restore,nan@15,"
        "ckpt_corrupt@15")
    # Step events first in (step, kind) order, then phase events in a
    # stable (phase, kind) order.
    assert [(e.kind, e.trigger) for e in events] == [
        ("ckpt_corrupt", "15"), ("nan", "15"),
        ("decision_corrupt", "decide"), ("ckpt_corrupt", "restore")]
    assert faults_lib.format_fault_spec(events) == \
        "ckpt_corrupt@15,nan@15,decision_corrupt@decide," \
        "ckpt_corrupt@restore"
    # Kinds that need a training step cannot be phase-qualified.
    for bad in ("nan@restore", "collective_hang@adopt",
                "host_return@decide", "nan@bogusphase"):
        with pytest.raises(ValueError):
            faults_lib.parse_fault_spec(bad)


def test_compound_faults_fire_at_one_step():
    """Several faults naming one step fire together at that seam, in
    spec order, each exactly once."""
    log = FakeLogger()
    inj = faults_lib.FaultInjector.from_spec(
        "ckpt_corrupt@10,data_stall@10")
    with pytest.raises(faults_lib.DataStallError):
        # ckpt_corrupt has nothing to corrupt (stays pending);
        # data_stall raises after marking itself fired.
        inj.step_hook(10, None, log_dir="/nonexistent", logger=log)
    assert [e.kind for e in inj.pending()] == ["ckpt_corrupt"]
    assert [r["fault"] for r in log.records] == ["data_stall"]


# ---------------------------------------------------------------------------
# FaultSchedule: the seeded sampler
# ---------------------------------------------------------------------------

def test_fault_schedule_generate_is_seeded_and_bounded():
    a = faults_lib.FaultSchedule.generate(42, 4)
    b = faults_lib.FaultSchedule.generate(42, 4)
    assert a.spec == b.spec                  # same seed, same schedule
    assert 1 <= len(a.events) <= 4
    vocab_kinds = {t.partition("@")[0]
                   for t in faults_lib.CHAOS_VOCABULARY}
    for ev in a.events:
        assert ev.kind in vocab_kinds
        if ev.step is not None:
            assert 1 <= ev.step <= 35
    # Different seeds explore different schedules (across a small pool
    # at least one must differ — the sampler is not constant).
    specs = {faults_lib.FaultSchedule.generate(s, 4).spec
             for s in range(8)}
    assert len(specs) > 1
    with pytest.raises(ValueError):
        faults_lib.FaultSchedule.generate(0, 0)


# ---------------------------------------------------------------------------
# decision-file integrity sidecar (parallel/cluster.py)
# ---------------------------------------------------------------------------

def test_decision_record_commits_payload_then_sidecar(tmp_path):
    logged = []
    c = cluster_lib.RestartCoordinator(
        str(tmp_path), log_fn=lambda k, **f: logged.append((k, f)))
    d = c.record(cluster_lib.RestartDecision(
        epoch=1, world_size=1, restore_step=10, survivors=[0]))
    assert os.path.exists(c.path) and os.path.exists(c.sidecar_path)
    assert c.read() == d
    assert logged == []
    # Monotone epoch still enforced through the verified read.
    with pytest.raises(ValueError, match="monotone"):
        c.record(cluster_lib.RestartDecision(
            epoch=1, world_size=1, restore_step=10, survivors=[0]))


def test_decision_read_classifies_corruption_instead_of_raising(
        tmp_path):
    logged = []
    c = cluster_lib.RestartCoordinator(
        str(tmp_path), log_fn=lambda k, **f: logged.append((k, f)))
    c.record(cluster_lib.RestartDecision(
        epoch=1, world_size=1, restore_step=10, survivors=[0]))
    # Tampered payload, stale sidecar: None + one decision_corrupt
    # record — NOT an unclassified JSON error, NOT a trusted decode.
    with open(c.path, "a") as f:
        f.write("garbage")
    assert c.read() is None
    assert len(logged) == 1 and logged[0][0] == "decision_corrupt"
    assert "mismatch" in logged[0][1]["error"]
    # Rate-limited per payload digest: re-polling the same corpse adds
    # no records (await_decision polls at 20 Hz).
    assert c.read() is None
    assert len(logged) == 1
    # An undecodable payload (valid sidecar removed) classifies too.
    os.remove(c.sidecar_path)
    with open(c.path, "w") as f:
        f.write("{not json")
    assert c.read() is None
    assert logged[-1][0] == "decision_corrupt"
    assert "undecodable" in logged[-1][1]["error"]


def test_decision_read_accepts_legacy_sidecarless_file(tmp_path):
    """A pre-hardening decision file (payload, no sidecar) must still
    decode — mid-upgrade clusters cannot deadlock on their own history."""
    c = cluster_lib.RestartCoordinator(str(tmp_path))
    with open(c.path, "w") as f:
        json.dump({"epoch": 3, "world_size": 2, "restore_step": 20,
                   "survivors": [0, 1]}, f)
    d = c.read()
    assert d is not None and d.epoch == 3 and d.kind == "shrink"


def test_decision_corrupt_fault_is_ignored_by_hardened_monitor(
        tmp_path):
    """The injected corruption (bogus decision + mismatched sidecar)
    must be read as absent by the seam check — training continues; the
    only trace is the classified telemetry."""
    log = FakeLogger()
    mon = _monitor(tmp_path, 0, n=1, logger=log)
    try:
        inj = faults_lib.FaultInjector.from_spec("decision_corrupt@5")
        inj.step_hook(5, None, log_dir=str(tmp_path), logger=log,
                      cluster=mon)
        assert inj.pending() == []
        mon.check_evicted(6)                 # no raise, no adoption
        assert mon.epoch == 0
        kinds = log.kinds()
        assert "fault" in kinds and "decision_corrupt" in kinds
        # Without a monitor the drill fails loudly, like the other
        # cluster kinds.
        with pytest.raises(faults_lib.InjectedFault, match="cluster"):
            faults_lib.FaultInjector.from_spec(
                "decision_corrupt@1").step_hook(2, None, "/tmp")
    finally:
        mon.close()


# ---------------------------------------------------------------------------
# phase-hook mechanics (units; the sims below cover the seams in vivo)
# ---------------------------------------------------------------------------

def test_phase_hook_restore_is_gated_on_recovery(tmp_path):
    log = FakeLogger()
    inj = faults_lib.FaultInjector.from_spec("data_stall@restore")
    # A fresh run's initial restore is NOT the seam.
    inj.phase_hook("restore", str(tmp_path), logger=log)
    assert len(inj.pending()) == 1 and log.records == []
    # The supervisor arms recovery; now the seam fires (once).
    inj.recovering = True
    inj._last_step = 30
    with pytest.raises(faults_lib.DataStallError):
        inj.phase_hook("restore", str(tmp_path), logger=log)
    assert inj.pending() == []
    assert log.records[0]["fault"] == "data_stall"
    assert log.records[0]["phase"] == "restore"
    assert log.records[0]["step"] == 30
    inj.phase_hook("restore", str(tmp_path), logger=log)  # one-shot
    assert len(log.records) == 1
    with pytest.raises(ValueError, match="phase"):
        inj.phase_hook("bogus", str(tmp_path))


def test_phase_hook_decide_and_adopt_fire_without_recovery_gate(
        tmp_path):
    """decide/adopt seams only exist inside recovery, so they fire
    as soon as reached — no arming needed."""
    log = FakeLogger()
    mon = _monitor(tmp_path, 0, n=1, logger=log)
    try:
        inj = faults_lib.FaultInjector.from_spec(
            "decision_corrupt@decide,heartbeat_stall@adopt")
        inj.phase_hook("decide", str(tmp_path), logger=log, cluster=mon)
        inj.phase_hook("adopt", str(tmp_path), logger=log, cluster=mon)
        assert inj.pending() == []
        fired = [(r["fault"], r["phase"]) for r in log.records
                 if r["kind"] == "fault"]
        assert fired == [("decision_corrupt", "decide"),
                         ("heartbeat_stall", "adopt")]
        assert mon._stalled
    finally:
        mon.close()


# ---------------------------------------------------------------------------
# supervisor retry budget: progress-based reset (--retry_budget_window)
# ---------------------------------------------------------------------------

def test_retry_budget_exhaustion_then_reset(data_cfg, tmp_path):
    """Two well-spaced stalls against a budget of ONE: the lifetime
    budget (window off) exhausts and re-raises; with
    --retry_budget_window the checkpoint progress between them refills
    the budget and the run completes."""
    from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised
    from tests.conftest import tiny_train_cfg

    def cfg_for(subdir, window):
        cfg = tiny_train_cfg(data_cfg, str(tmp_path / subdir),
                             total_steps=40)
        cfg.checkpoint_every = 10
        cfg.recovery_retries = 1
        cfg.retry_budget_window = window
        cfg.recovery_backoff_s = 0.01
        cfg.fault_spec = "data_stall@5,data_stall@25"
        cfg.metrics_jsonl = os.path.join(str(tmp_path), subdir + ".jsonl")
        return cfg

    with pytest.raises(faults_lib.DataStallError):
        fit_supervised(cfg_for("exhaust", window=0))

    result = fit_supervised(cfg_for("reset", window=10))
    assert result.final_step == 40
    recs = _read_jsonl(os.path.join(str(tmp_path), "reset.jsonl"))
    resets = [r for r in recs if r["kind"] == "recovery"
              and r["action"] == "budget_reset"]
    assert len(resets) == 1
    restarts = [r for r in recs if r["kind"] == "recovery"
                and r["action"] == "restart"]
    assert len(restarts) == 2            # both stalls recovered


# ---------------------------------------------------------------------------
# the campaign driver: shared harness + tier-1 acceptance smokes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def chaos_workdir(tmp_path_factory):
    return tmp_path_factory.mktemp("chaos")


@pytest.fixture(scope="module")
def chaos_refs(chaos_workdir):
    """Per-scenario fault-free reference digests, computed once for the
    whole module (the sampler/worker/dataset are deterministic, so
    every harness below can share them)."""
    harness = chaos_lib.ChaosHarness(str(chaos_workdir / "refs"))
    return {"train": harness.reference_digest("train"),
            "cluster": harness.reference_digest("cluster")}


def test_chaos_smoke_campaign_fixed_seeds(chaos_workdir, chaos_refs):
    """ISSUE-10 tier-1 wiring: a fixed-seed ≥5-schedule campaign over
    the supervised-train sim passes every invariant — bit-identical
    finals, schema-clean streams, fault/recovery pairing, deadlines —
    and its own chaos/chaos_done stream lints + reports."""
    jsonl = str(chaos_workdir / "campaign.jsonl")
    summary = chaos_lib.run_campaign(
        seeds=range(5), scenario="train",
        workdir=str(chaos_workdir / "smoke"),
        metrics_jsonl=jsonl, refs=chaos_refs)
    assert summary["schedules"] == 5
    assert summary["failed"] == 0, summary
    # Across the fixed seeds the sampler exercised a compound mix, not
    # one lucky kind.
    assert len(summary["faults_by_kind"]) >= 2
    assert sum(summary["faults_by_kind"].values()) >= 5
    from tools import check_jsonl_schema, telemetry_report
    assert check_jsonl_schema.check_file(jsonl, strict=True) == []
    out = telemetry_report.summarize(jsonl)
    assert "chaos campaign" in out and "5 passed" in out


def test_chaos_catches_planted_decision_sidecar_revert(chaos_workdir,
                                                       chaos_refs):
    """Regression drill (ISSUE-10 acceptance): revert the
    RestartCoordinator sidecar check inside the workers and the
    campaign must FAIL the schedule — the bogus corrupted decision gets
    adopted and fences the run — and shrink it to a ≤2-fault reproducer
    centred on decision_corrupt."""
    summary = chaos_lib.run_campaign(
        seeds=[0], scenario="train",
        workdir=str(chaos_workdir / "planted"),
        plant="no_decision_sidecar",
        explicit_spec="data_stall@12,decision_corrupt@18",
        refs=chaos_refs)
    assert summary["failed"] == 1
    rec = summary["results"][0]
    assert not rec["ok"] and rec["invariant"].startswith("completed")
    repro = faults_lib.parse_fault_spec(rec["reproducer"])
    assert len(repro) <= 2
    assert any(e.kind == "decision_corrupt" for e in repro)
    # The SAME schedule passes with the hardening in place: the plant,
    # not the schedule, is what failed.
    clean = chaos_lib.run_campaign(
        seeds=[0], scenario="train",
        workdir=str(chaos_workdir / "unplanted"),
        explicit_spec="data_stall@12,decision_corrupt@18",
        refs=chaos_refs)
    assert clean["failed"] == 0


def test_phase_triggers_fire_once_each_in_cluster_sim(chaos_workdir,
                                                      chaos_refs):
    """ISSUE-10 satellite: @restore / @adopt / @decide each fire
    exactly once at their seam in a 2-process sim (host_lost backbone
    on the peer; the survivor carries the recovery-phase compound) and
    the recovery still converges bit-identical to the fault-free
    reference."""
    harness = chaos_lib.ChaosHarness(
        str(chaos_workdir / "phases"), refs=chaos_refs)
    events = faults_lib.parse_fault_spec(
        "ckpt_corrupt@restore,heartbeat_stall@adopt,"
        "decision_corrupt@decide")
    # Backbone death at 25: the survivor holds ckpt_10 AND ckpt_20 when
    # recovery starts, so the @restore corruption has a fallback
    # candidate to exercise (the phase drill stays pending without one).
    r = harness.run_schedule(events, "cluster", tag="phases",
                             backbone="host_lost@25")
    assert r.ok, r.invariant
    assert r.injected == {"ckpt_corrupt": 1, "heartbeat_stall": 1,
                          "decision_corrupt": 1, "host_lost": 1}
    stream = _read_jsonl(os.path.join(
        harness.workdir, "run_001_phases", "logs_0", "metrics.jsonl"))
    phased = [r for r in stream if r["kind"] == "fault"
              and r.get("phase")]
    assert sorted((r["fault"], r["phase"]) for r in phased) == [
        ("ckpt_corrupt", "restore"), ("decision_corrupt", "decide"),
        ("heartbeat_stall", "adopt")]
    # The @restore corruption forced the restore walk to fall back.
    assert any(r["kind"] == "ckpt_fallback" for r in stream)


def test_chaos_peer_recovery_scenario_smoke(chaos_workdir, chaos_refs):
    """ISSUE-14 satellite: the diskless-recovery chaos scenario — the
    2-process shrink drill with peer redundancy ON and a replica fault
    fired one step before the backbone host loss. The campaign must
    pass every invariant, including the replica-fault pairing rule (a
    damaged replica read by an elastic restart leaves a peer_replica
    reconstruct or disk-fallback record), and the recovery must stay
    bit-identical to the shared fault-free oracle (a peer-path restore
    equals a disk restore by construction)."""
    jsonl = str(chaos_workdir / "peer.jsonl")
    summary = chaos_lib.run_campaign(
        seeds=[0], scenario="peer_recovery",
        workdir=str(chaos_workdir / "peer"),
        metrics_jsonl=jsonl, refs=chaos_refs,
        explicit_spec="replica_corrupt@14")
    assert summary["failed"] == 0, summary
    assert summary["faults_by_kind"].get("replica_corrupt") == 1
    assert summary["faults_by_kind"].get("host_lost") == 1
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(jsonl, strict=True) == []
    # The survivor's stream shows the fallback/reconstruct answer the
    # pairing invariant demands.
    stream = _read_jsonl(os.path.join(
        str(chaos_workdir / "peer"), "run_001_seed0", "logs_0",
        "metrics.jsonl"))
    answers = [r for r in stream if r["kind"] == "peer_replica"
               and r["op"] in ("reconstruct", "fallback")]
    assert answers


def test_chief_killed_between_decide_and_restore(chaos_workdir,
                                                 chaos_refs):
    """ISSUE-10 acceptance: the chief commits a shrink decision and is
    killed before its own restore (`host_lost@decide`). The surviving
    non-chief adopts the orphaned decision, finds the chief's corpse at
    its next seam, inherits chiefship, re-decides at a HIGHER epoch,
    and completes — final params bit-identical to the fault-free
    reference."""
    harness = chaos_lib.ChaosHarness(
        str(chaos_workdir / "chiefloss"), refs=chaos_refs)
    run_dir = str(chaos_workdir / "chiefloss" / "sim")
    cluster = os.path.join(run_dir, "cluster")
    logs = [os.path.join(run_dir, f"logs_{t}") for t in (0, 1)]
    for d in logs:
        os.makedirs(d)
    # Three seats, two processes: seat 2 never starts (a host that
    # failed to even boot — as dead as one that stopped), which is what
    # forces the step-0 shrink decision both live seats agree on.
    procs = [
        harness._spawn([0, 3, harness.data_dir, logs[0], cluster,
                        "host_lost@decide", 40], planted=False),
        harness._spawn([1, 3, harness.data_dir, logs[1], cluster,
                        "", 40], planted=False),
    ]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    # The chief died abruptly at the decide seam...
    assert procs[0].returncode == faults_lib.EXIT_HOST_LOST, outs[0]
    # ...and the survivor completed anyway.
    assert procs[1].returncode == 0, outs[1]
    res = harness._read_result(outs[1])
    assert not res["fenced"] and res["final_step"] == 40
    assert res["digest"] == chaos_refs["train"]

    chief = _read_jsonl(os.path.join(logs[0], "metrics.jsonl"))
    died = [r for r in chief if r["kind"] == "fault"
            and r["fault"] == "host_lost"]
    assert died and died[0]["phase"] == "decide"

    surv = _read_jsonl(os.path.join(logs[1], "metrics.jsonl"))
    adopted = [r for r in surv if r["kind"] == "elastic_restart"]
    # Epoch 1: the dead chief's orphaned decision (world 2, seats 0+1).
    # Epoch 2: the survivor's own re-decision as the new chief
    # (world 1) — strictly higher epoch, monotone file.
    assert [(r["epoch"], r["world_size"]) for r in adopted] == [
        (1, 2), (2, 1)]
    lost = {(r["process_id"], r["reason"]) for r in surv
            if r["kind"] == "peer_lost"}
    # The killed chief is always classified by its stale heartbeats.
    # (The never-booted seat 2 may instead surface as the adopted
    # orphan decision, depending on which the survivor sees first.)
    assert (0, "stale_heartbeat") in lost
    from tools import check_jsonl_schema
    for recs in (chief, surv):
        assert check_jsonl_schema.check_lines(
            (json.dumps(r) for r in recs), strict=True) == []
    # The final decision on disk is the survivor's epoch-2 verdict and
    # verifies through the sidecar walk.
    d = cluster_lib.RestartCoordinator(cluster).read()
    assert d is not None and d.epoch == 2 and d.survivors == [1]


# ---------------------------------------------------------------------------
# the full campaign (slow): 50 seeded schedules over both sims
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_50_schedule_campaign(tmp_path):
    """ISSUE-10 acceptance: `tools/chaos.py --seeds 50` (mixed train +
    cluster sims) passes every invariant."""
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "chaos.py"),
         "--seeds", "50", "--scenario", "mixed",
         "--workdir", str(tmp_path / "campaign"),
         "--metrics_jsonl", str(tmp_path / "campaign.jsonl")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=3600)
    assert proc.returncode == 0, proc.stdout[-4000:]
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(
        str(tmp_path / "campaign.jsonl"), strict=True) == []
