"""Serving export (jax.export) + the eval/export CLI modes.

The reference's only artifact is its checkpoint dir (``cifar10cnn.py:222``)
— no deployment story. ``export.py`` serializes the trained forward
(weights embedded, uint8 input contract, symbolic batch) to StableHLO
bytes loadable without the framework.
"""

import numpy as np
import pytest

import jax

from dml_cnn_cifar10_tpu import export as export_lib
from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
from dml_cnn_cifar10_tpu.models.registry import get_model


@pytest.fixture(scope="module")
def cnn_setup():
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    params = model_def.init(jax.random.key(0), model_cfg, data_cfg)
    return model_def, model_cfg, data_cfg, params


def test_export_roundtrip_matches_live_forward(tmp_path, cnn_setup, rng):
    model_def, model_cfg, data_cfg, params = cnn_setup
    blob = export_lib.export_forward(model_def, model_cfg, data_cfg, params)
    path = str(tmp_path / "model.jaxexport")
    export_lib.save_exported(path, blob)

    served = export_lib.load_exported(path)
    images = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    got = np.asarray(jax.device_get(served(images)))

    live = export_lib.make_serving_fn(model_def, model_cfg, data_cfg,
                                      params)
    want = np.asarray(jax.device_get(jax.jit(live)(images)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert got.shape == (4, 10)


def test_export_symbolic_batch(cnn_setup, rng):
    """One artifact serves any batch size (symbolic leading dim)."""
    model_def, model_cfg, data_cfg, params = cnn_setup
    blob = export_lib.export_forward(model_def, model_cfg, data_cfg, params)
    served = export_lib.load_exported_bytes(blob)
    for b in (1, 4, 7):
        images = rng.integers(0, 256, (b, 32, 32, 3)).astype(np.uint8)
        out = np.asarray(jax.device_get(served(images)))
        assert out.shape == (b, 10)


@pytest.mark.slow
def test_export_resnet_with_bn_state(rng):
    """Stateful models (BatchNorm running stats) export too."""
    model_def = get_model("resnet18")
    model_cfg = ModelConfig(name="resnet18", logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    params = model_def.init(jax.random.key(0), model_cfg, data_cfg)
    mstate = model_def.init_state(params)
    blob = export_lib.export_forward(model_def, model_cfg, data_cfg, params,
                                     model_state=mstate)
    served = export_lib.load_exported_bytes(blob)
    images = rng.integers(0, 256, (2, 32, 32, 3)).astype(np.uint8)
    out = np.asarray(jax.device_get(served(images)))
    assert out.shape == (2, 10)
    assert np.isfinite(out).all()


@pytest.mark.slow
def test_cli_eval_and_export_modes(tmp_path, capsys):
    """--mode train then --mode eval (full sweep, reference format line)
    then --mode export (artifact on disk, loadable)."""
    from dml_cnn_cifar10_tpu.cli.main import main

    data_dir = str(tmp_path / "data")
    log_dir = str(tmp_path / "logs")
    common = ["--dataset", "synthetic", "--data_dir", data_dir,
              "--log_dir", log_dir, "--batch_size", "32",
              "--use_native_loader", "false", "--fidelity", "fixed",
              "--learning_rate", "0.02"]
    assert main(common + ["--total_steps", "6", "--output_every", "2",
                          "--eval_every", "3", "--checkpoint_every",
                          "6"]) == 0
    capsys.readouterr()

    assert main(common + ["--mode", "eval"]) == 0
    out = capsys.readouterr().out
    assert " --- Test Accuracy = " in out
    assert "eval at step 6" in out

    path = str(tmp_path / "m.jaxexport")
    assert main(common + ["--mode", "export", "--export_path", path]) == 0
    out = capsys.readouterr().out
    assert "exported step-6 forward" in out
    served = export_lib.load_exported(path)
    images = np.zeros((2, 32, 32, 3), np.uint8)
    assert np.asarray(jax.device_get(served(images))).shape == (2, 10)
