"""Device-side index stream (data/device_stream.py) — round-4 verdict #4.

The stateless per-epoch pseudo-permutation must be a REAL permutation
(every record exactly once per epoch), deterministic in (seed, step), and
the resident chunk built on it must train identically whether resumed or
not — exact-resume with zero sidecar state.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dml_cnn_cifar10_tpu.data import device_stream as ds


@pytest.mark.parametrize("n", [1, 2, 3, 640, 1000, 49999, 50000])
def test_epoch_is_exact_permutation(n):
    b = 64
    steps = (n + b - 1) // b + 1
    f = jax.jit(lambda s: ds.epoch_shuffle_indices(3, s, b, n))
    rows = np.concatenate([np.asarray(f(s)) for s in range(steps)])[:n]
    assert rows.min() >= 0 and rows.max() < n
    assert len(np.unique(rows)) == n


def test_range_guard_rejects_wrapping_runs():
    """The uint32 position domain is enforced at build time (round-4
    advisor): total_steps x batch >= 2^32 must raise, anything under
    must pass."""
    ds.check_supported_range(20000, 512)              # CIFAR-scale: fine
    ds.check_supported_range((1 << 32) // 512 - 1, 512)
    with pytest.raises(ValueError, match="uint32"):
        ds.check_supported_range((1 << 32) // 512, 512)


def test_epochs_differ_and_seed_matters():
    n, b = 1000, 50
    f = jax.jit(lambda seed, s: ds.epoch_shuffle_indices(seed, s, b, n))
    e0 = np.concatenate([np.asarray(f(7, s)) for s in range(n // b)])
    e1 = np.concatenate([np.asarray(f(7, s))
                         for s in range(n // b, 2 * n // b)])
    other = np.concatenate([np.asarray(f(8, s)) for s in range(n // b)])
    assert not np.array_equal(e0, e1)
    assert not np.array_equal(e0, other)
    # determinism
    again = np.concatenate([np.asarray(f(7, s)) for s in range(n // b)])
    np.testing.assert_array_equal(e0, again)


def test_chunk_matches_per_step_stream():
    """chunk_shuffle_indices(step0, k) must be exactly the k per-step
    batches starting at step0 — the whole-chunk vectorization cannot
    change the stream."""
    n, b, k = 777, 32, 5
    chunk = np.asarray(jax.jit(
        lambda s: ds.chunk_shuffle_indices(11, s, b, k, n))(jnp.uint32(3)))
    per_step = np.stack([
        np.asarray(ds.epoch_shuffle_indices(11, 3 + i, b, n))
        for i in range(k)])
    np.testing.assert_array_equal(chunk, per_step)


def test_resident_chunk_device_stream_resumes_exactly(data_cfg):
    """Two dispatches of the device-stream resident chunk == one run of
    the same four steps: the stream position is state.step, so a resumed
    state continues the data order bit-exactly with NO sidecar."""
    from dml_cnn_cifar10_tpu.config import ModelConfig, OptimConfig
    from dml_cnn_cifar10_tpu.data import pipeline as pipe
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
    from dml_cnn_cifar10_tpu.parallel import step as step_lib
    from dml_cnn_cifar10_tpu.config import ParallelConfig

    mesh = mesh_lib.build_mesh(ParallelConfig(), devices=jax.devices()[:2])
    model_cfg = ModelConfig()
    optim_cfg = OptimConfig()
    model_def = get_model(model_cfg.name)
    it = pipe.input_pipeline(data_cfg, 16, train=True)
    repl = mesh_lib.replicated(mesh)
    ds_images = jax.device_put(it.images, repl)
    ds_labels = jax.device_put(it.labels.astype("int32"), repl)

    def build(k):
        return step_lib.make_train_chunk_resident(
            model_def, model_cfg, optim_cfg, mesh, ds_images, ds_labels,
            data_cfg=data_cfg, index_stream=(data_cfg.seed, 16, k))

    def init():
        return step_lib.init_train_state(
            jax.random.key(0), model_def, model_cfg, data_cfg, optim_cfg,
            mesh)

    chunk2, chunk4 = build(2), build(4)
    s_a = init()
    s_a, _ = chunk2(s_a)
    s_a, m_a = chunk2(s_a)         # "resumed" second dispatch
    s_b = init()
    s_b, m_b = chunk4(s_b)         # uninterrupted
    assert int(jax.device_get(s_a.step)) == 4
    np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s_a.params), jax.tree.leaves(s_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
