"""Streaming alert engine (utils/alerts.py) + live-metrics registry
(utils/metrics_registry.py): a decision-table unit over every built-in
rule (fires on a synthetic unhealthy stream, stays silent on a healthy
one, resolves when the signal recovers, the rate limit holds), the
--alert_rules grammar, and a /metrics exposition-format lint (render →
parse back → same numbers)."""

import json
import urllib.request

import pytest

from dml_cnn_cifar10_tpu.utils.alerts import (AlertEngine, AlertRule,
                                              built_in_rules,
                                              parse_alert_rules)
from dml_cnn_cifar10_tpu.utils.metrics_registry import (
    MetricsRegistry, StatsServer, observe_record, parse_prometheus_text)


class _Sink:
    """Emission collector with (kind, fields) tuples."""

    def __init__(self):
        self.records = []

    def __call__(self, kind, **fields):
        self.records.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.records]

    def last(self):
        return self.records[-1]


def _engine(min_interval_s=0.0):
    return AlertEngine(built_in_rules(slo_ms=50.0),
                       min_interval_s=min_interval_s)


def _serve(requests=100, shed=0, p99=10.0):
    return {"requests": requests, "completed": requests - shed,
            "shed_queue": shed, "shed_deadline": 0, "cache_hit": 0,
            "qps": 10.0, "p50_ms": 5.0, "p95_ms": 8.0, "p99_ms": p99,
            "batch_fill": 0.9, "window_s": 5.0}


# ---------------------------------------------------------------------------
# the built-in decision table: unhealthy fires / healthy silent /
# recovery resolves — one case per built-in rule
# ---------------------------------------------------------------------------

#: (rule, [(kind, fields) unhealthy stream], [(kind, fields) healthy
#: stream], [(kind, fields) recovery tail]). The unhealthy stream must
#: fire EXACTLY its rule; healthy must fire nothing; unhealthy +
#: recovery must end resolved.
DECISION_TABLE = [
    ("goodput_train_collapse",
     [("goodput", {"step": 10, "train_frac": 0.3}),
      ("goodput", {"step": 20, "train_frac": 0.2})],
     [("goodput", {"step": 10, "train_frac": 0.3}),     # one boundary
      ("goodput", {"step": 20, "train_frac": 0.9})],    # is noise
     [("goodput", {"step": 30, "train_frac": 0.9})]),
    ("host_bound_drain",
     # drain_frac = drain / (device * steps): 0.5/(2*10) = 0.025 < 0.1
     # on three consecutive boundaries (the first row only anchors the
     # previous step, so four rows = three readings).
     [("train", {"step": s, "device_step_ms": 2.0,
                 "drain_wait_ms": 0.5})
      for s in (10, 20, 30, 40)],
     # 18/(2*10) = 0.9: the host spends the window blocked on the
     # device — device-bound, healthy.
     [("train", {"step": s, "device_step_ms": 2.0,
                 "drain_wait_ms": 18.0})
      for s in (10, 20, 30, 40)],
     [("train", {"step": s, "device_step_ms": 2.0,
                 "drain_wait_ms": 18.0})
      for s in (50,)]),
    ("nonfinite_burst",
     [("fault", {"step": 15, "fault": "nonfinite", "injected": False})],
     [("fault", {"step": 15, "fault": "data", "injected": False})],
     [("train", {"step": 70, "loss": 0.1})]),          # 50 steps past
    ("recovery_burst",
     [("recovery", {"step": s, "fault": "data", "action": "restart",
                    "attempt": i + 1})
      for i, s in enumerate((10, 12, 14))],
     [("recovery", {"step": 10, "fault": "data", "action": "restart",
                    "attempt": 1})],                   # one is routine
     [("train", {"step": 300, "loss": 0.1})]),         # window passes
    ("serve_shed",
     [("serve", _serve(shed=5))],                      # 5% shed
     [("serve", _serve(shed=0))],
     [("serve", _serve(shed=0))]),
    ("fleet_shed",
     [("fleet", {"replicas": 2, "live": 2, "routed": 90, "shed": 10,
                 "rerouted": 0, "evictions": 0})],
     [("fleet", {"replicas": 2, "live": 2, "routed": 100, "shed": 0,
                 "rerouted": 0, "evictions": 0})],
     [("fleet", {"replicas": 2, "live": 2, "routed": 100, "shed": 0,
                 "rerouted": 0, "evictions": 0})]),
    ("serve_p99_slo",
     [("serve", _serve(p99=80.0)), ("serve", _serve(p99=90.0))],
     [("serve", _serve(p99=80.0)), ("serve", _serve(p99=10.0))],
     [("serve", _serve(p99=10.0))]),
    ("hbm_headroom",
     [("hbm", {"step": 10, "available": True, "devices": 1,
               "bytes_in_use": 95, "peak_bytes": 95,
               "bytes_limit": 100})],
     [("hbm", {"step": 10, "available": True, "devices": 1,
               "bytes_in_use": 50, "peak_bytes": 50,
               "bytes_limit": 100})],
     [("hbm", {"step": 20, "available": True, "devices": 1,
               "bytes_in_use": 50, "peak_bytes": 50,
               "bytes_limit": 100})]),
]


@pytest.mark.parametrize("rule,unhealthy,healthy,recovery",
                         DECISION_TABLE,
                         ids=[c[0] for c in DECISION_TABLE])
def test_builtin_rule_decision_table(rule, unhealthy, healthy,
                                     recovery):
    # Unhealthy stream: exactly this rule fires.
    sink = _Sink()
    eng = _engine()
    now = 100.0
    for kind, fields in unhealthy:
        eng.observe(kind, fields, emit=sink, now=now)
        now += 1.0
    fired = [f["rule"] for k, f in sink.records if k == "alert"]
    assert fired == [rule], (rule, sink.records)
    assert eng.active_names() == [rule]
    rec = sink.last()[1]
    assert set(rec) == {"rule", "severity", "window", "value", "id"}
    assert rec["id"] == f"{rule}#1"

    # Healthy stream: silence.
    sink2 = _Sink()
    eng2 = _engine()
    now = 100.0
    for kind, fields in healthy:
        eng2.observe(kind, fields, emit=sink2, now=now)
        now += 1.0
    eng2.evaluate(emit=sink2, now=now)
    assert sink2.records == [], (rule, sink2.records)

    # Unhealthy + recovery tail: paired fire → resolve, nothing active.
    sink3 = _Sink()
    eng3 = _engine()
    now = 100.0
    for kind, fields in unhealthy + recovery:
        eng3.observe(kind, fields, emit=sink3, now=now)
        now += 1.0
    eng3.evaluate(emit=sink3, now=now)
    kinds = sink3.kinds()
    assert kinds == ["alert", "alert_resolved"], (rule, sink3.records)
    assert sink3.records[0][1]["rule"] == rule
    assert sink3.records[1][1]["rule"] == rule
    assert eng3.active_names() == []


def test_heartbeat_absence_rule():
    """absence rules arm on the first record and fire from evaluate()
    — the flush/control-loop tick — not from record flow."""
    sink = _Sink()
    eng = _engine()
    # Never armed: no heartbeat ever seen, silence forever.
    eng.evaluate(emit=sink, now=1000.0)
    assert sink.records == []
    eng.observe("heartbeat", {"step": 1, "process_id": 0,
                              "phase": "train", "wallclock": 100.0},
                emit=sink, now=100.0)
    eng.evaluate(emit=sink, now=110.0)     # 10s < 15s: fine
    assert sink.records == []
    eng.evaluate(emit=sink, now=120.0)     # 20s stale: page
    assert sink.kinds() == ["alert"]
    assert sink.last()[1]["rule"] == "heartbeat_stale"
    assert sink.last()[1]["severity"] == "page"
    # The next beat resolves it.
    eng.observe("heartbeat", {"step": 2, "process_id": 0,
                              "phase": "train", "wallclock": 121.0},
                emit=sink, now=121.0)
    assert sink.kinds() == ["alert", "alert_resolved"]


def test_rate_limit_holds_and_pairs_stay_paired():
    """A re-fire inside min_interval_s is suppressed — and so is its
    resolution, so the emitted stream is strictly alternating
    alert/alert_resolved pairs; after the interval, firing resumes."""
    sink = _Sink()
    eng = AlertEngine(built_in_rules(), min_interval_s=30.0)
    flap = [("serve", _serve(shed=5)), ("serve", _serve(shed=0))]
    now = 100.0
    for _ in range(4):                     # four flaps inside 30 s
        for kind, fields in flap:
            eng.observe(kind, fields, emit=sink, now=now)
            now += 1.0
    assert sink.kinds() == ["alert", "alert_resolved"]
    # Past the rate-limit window the next breach emits again.
    now = 200.0
    for kind, fields in flap:
        eng.observe(kind, fields, emit=sink, now=now)
        now += 1.0
    assert sink.kinds() == ["alert", "alert_resolved"] * 2
    pairs = [(k, f["rule"]) for k, f in sink.records]
    assert all(r == "serve_shed" for _, r in pairs)


def test_alert_grammar_round_trip():
    rules = parse_alert_rules(
        "lossy=train.loss>10@3;"
        "churn=rate(recovery)>=2@300!page;"
        "nf=rate(fault.fault=nonfinite)>=1@50;"
        "lag=rate(straggler)>=5@60s;"
        "beatless=absent(heartbeat)@20s!page")
    assert [r.name for r in rules] == ["lossy", "churn", "nf", "lag",
                                       "beatless"]
    lossy, churn, nf, lag, beatless = rules
    assert (lossy.rule_type, lossy.kind, lossy.field, lossy.op,
            lossy.value, lossy.window) == \
        ("threshold", "train", "loss", ">", 10.0, 3)
    assert churn.severity == "page" and churn.window_unit == "steps" \
        and churn.window == 300
    assert nf.match == {"fault": "nonfinite"}
    assert lag.window_unit == "seconds" and lag.window == 60.0
    assert beatless.rule_type == "absence" and beatless.window == 20.0
    assert parse_alert_rules(None) == [] and parse_alert_rules("") == []


@pytest.mark.parametrize("bad", [
    "noequals",
    "x=train.loss~10",                  # bad op
    "y=absent(heartbeat)@20",           # absence needs seconds
    "z=rate(fault)<=3",                 # rate is >=/> only
    "w=train.loss>1@3s",                # threshold windows are counts
    "v=train.loss>1!",                  # empty severity
    "a=train.loss>1;a=train.loss>2",    # duplicate name
])
def test_alert_grammar_rejects(bad):
    with pytest.raises(ValueError):
        parse_alert_rules(bad)


def test_custom_rule_fires_and_engine_rejects_shadowing():
    sink = _Sink()
    eng = AlertEngine(parse_alert_rules("lossy=train.loss>10@2!page"),
                      min_interval_s=0.0)
    eng.observe("train", {"step": 10, "loss": 50.0}, emit=sink, now=1.0)
    assert sink.records == []              # 1 of 2 consecutive
    eng.observe("train", {"step": 20, "loss": 60.0}, emit=sink, now=2.0)
    assert sink.kinds() == ["alert"]
    assert sink.last()[1]["severity"] == "page"
    # A custom rule shadowing a built-in name is a config error.
    with pytest.raises(ValueError):
        AlertEngine(built_in_rules()
                    + [AlertRule("serve_shed", "threshold", "serve",
                                 field="qps", op="<", value=1)])


def test_builtin_slo_rule_only_with_slo():
    names = [r.name for r in built_in_rules()]
    assert "serve_p99_slo" not in names
    assert "serve_p99_slo" in [r.name for r in built_in_rules(50.0)]


def test_autoscaler_consumes_alert_state():
    from dml_cnn_cifar10_tpu.fleet.autoscaler import (FleetSignals,
                                                      decide)
    quiet = FleetSignals(live=2, starting=0, mean_queue_depth=0.0,
                         shed_fraction=0.0, p99_ms=5.0)
    # A load-shaped alert is a scale-up signal on its own...
    assert decide(quiet, 1, 4,
                  alerts_active=["serve_shed"]).action == "up"
    assert decide(quiet, 1, 4,
                  alerts_active=["scale_up_custom"]).reason \
        == "alert_scale_up_custom"
    # ...any active alert vetoes scale-down...
    assert decide(quiet, 1, 4,
                  alerts_active=["hbm_headroom"]).action == "hold"
    # ...and no alerts keeps the historical table intact.
    assert decide(quiet, 1, 4).action == "down"


# ---------------------------------------------------------------------------
# /metrics exposition-format lint: render → parse back → same numbers
# ---------------------------------------------------------------------------

def test_exposition_format_round_trip():
    reg = MetricsRegistry()
    # Feed representative records of every translated kind through the
    # SAME path the logger uses.
    observe_record("train", {"step": 40, "loss": 0.25,
                             "images_per_sec": 1234.5,
                             "device_step_ms": 2.5,
                             "drain_wait_ms": 1.25}, reg)
    observe_record("goodput", {"step": 40, "total_s": 10.0,
                               "train_frac": 0.8, "compile_frac": 0.2},
                   reg)
    observe_record("hbm", {"step": 40, "available": True, "devices": 2,
                           "bytes_in_use": 100, "peak_bytes": 120,
                           "bytes_limit": 1000}, reg)
    observe_record("serve", _serve(shed=3), reg)
    observe_record("fleet", {"replicas": 3, "live": 2, "routed": 10,
                             "rerouted": 1, "evictions": 1, "shed": 0},
                   reg)
    observe_record("fault", {"step": 10, "fault": "nonfinite"}, reg)
    observe_record("recovery", {"step": 10, "action": "restart"}, reg)
    observe_record("compile", {"hit": True, "compile_s": 1.5}, reg)
    observe_record("alert", {"rule": "serve_shed", "severity": "warn",
                             "window": "1 consecutive", "value": 0.03},
                   reg)
    reg.histogram("dml_serve_latency_ms", "latency",
                  buckets=(1.0, 10.0)).observe(5.0)

    text = reg.render()
    doc = parse_prometheus_text(text)   # raises on any malformed line

    # Every rendered family carries TYPE + HELP and parses back to the
    # numbers that went in.
    assert doc["dml_train_step"]["type"] == "gauge"
    assert doc["dml_train_step"]["samples"][()] == 40.0
    assert doc["dml_train_images_per_sec"]["samples"][()] == 1234.5
    assert doc["dml_goodput_fraction"]["samples"][
        (("category", "train"),)] == 0.8
    assert doc["dml_hbm_bytes_in_use"]["samples"][()] == 100.0
    assert doc["dml_serve_shed_total"]["type"] == "counter"
    assert doc["dml_serve_shed_total"]["samples"][
        (("reason", "queue_full"),)] == 3.0
    assert doc["dml_faults_total"]["samples"][
        (("fault", "nonfinite"),)] == 1.0
    assert doc["dml_compile_lookups_total"]["samples"][
        (("hit", "true"),)] == 1.0
    assert doc["dml_alert_active"]["samples"][
        (("rule", "serve_shed"), ("severity", "warn"))] == 1.0
    # Histogram: cumulative buckets + +Inf == count.
    b = doc["dml_serve_latency_ms_bucket"]["samples"]
    assert b[(("le", "1"),)] == 0.0 and b[(("le", "10"),)] == 1.0
    assert b[(("le", "+Inf"),)] == 1.0
    assert doc["dml_serve_latency_ms_count"]["samples"][()] == 1.0
    # Counters accumulate window deltas.
    observe_record("serve", _serve(shed=2), reg)
    doc2 = parse_prometheus_text(reg.render())
    assert doc2["dml_serve_shed_total"]["samples"][
        (("reason", "queue_full"),)] == 5.0
    # alert_resolved flips the active gauge to 0.
    observe_record("alert_resolved",
                   {"rule": "serve_shed", "severity": "warn",
                    "window": "1 consecutive", "value": 0.0}, reg)
    doc3 = parse_prometheus_text(reg.render())
    assert doc3["dml_alert_active"]["samples"][
        (("rule", "serve_shed"), ("severity", "warn"))] == 0.0


def test_exposition_parser_rejects_malformed():
    for bad in ("name{unclosed 1", 'name{l="v} 1', "name", "name abc"):
        with pytest.raises(ValueError):
            parse_prometheus_text(bad)


def test_registry_semantics():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "c")
    c.inc(); c.inc(-5)                       # negative deltas dropped
    assert reg.snapshot()["c_total"][()] == 1.0
    g = reg.gauge("g", "g")
    g.set(None); g.set(2.0)                  # None never clobbers
    assert reg.snapshot()["g"][()] == 2.0
    assert reg.counter("c_total", "again") is c      # idempotent
    with pytest.raises(ValueError):
        reg.gauge("c_total", "type clash")
    with pytest.raises(ValueError):
        c.inc(1, wrong_label="x")


def test_stats_server_serves_metrics_and_healthz():
    reg = MetricsRegistry()
    reg.gauge("dml_train_step", "step").set(7)
    srv = StatsServer(reg, port=0)           # ephemeral test bind
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=5) as resp:
            assert "text/plain" in resp.headers["Content-Type"]
            doc = parse_prometheus_text(resp.read().decode())
        assert doc["dml_train_step"]["samples"][()] == 7.0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz",
                timeout=5) as resp:
            assert json.loads(resp.read())["ok"] is True
    finally:
        srv.close()


def test_ensure_stats_server_off_by_default():
    from dml_cnn_cifar10_tpu.utils.metrics_registry import \
        ensure_stats_server
    assert ensure_stats_server(0) is None
    assert ensure_stats_server(None) is None


def test_logger_feeds_engine_and_registry(tmp_path):
    """The MetricsLogger observer seam end to end: records written
    through the logger reach an attached engine, its alert emission
    lands back in the SAME stream, and the registry sees everything —
    with the schema lint clean over the result."""
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    logger = MetricsLogger(path)
    eng = AlertEngine(built_in_rules(), min_interval_s=0.0)
    logger.add_observer(eng.observer(logger))
    logger.log("serve", **_serve(shed=5))
    logger.log("serve", **_serve(shed=0))
    logger.close()
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    kinds = [r["kind"] for r in recs]
    assert kinds == ["serve", "alert", "serve", "alert_resolved"]
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(path, strict=True) == []


# ---------------------------------------------------------------------------
# the alert→action trigger seam (runtime/core.py's control loop rides
# it: one hook call per EMITTED firing, nothing else ever triggers)
# ---------------------------------------------------------------------------

def test_trigger_fires_once_per_emitted_firing():
    eng = AlertEngine(parse_alert_rules("lossy=train.loss>10"),
                      min_interval_s=0.0)
    fired = []
    eng.add_trigger(lambda rule, value: fired.append((rule.name, value)))
    sink = _Sink()
    eng.observe("train", {"step": 1, "loss": 50.0}, emit=sink, now=0.0)
    assert fired == [("lossy", 50.0)]
    # Still active while the condition holds: no re-fire, no re-trigger.
    eng.observe("train", {"step": 2, "loss": 60.0}, emit=sink, now=1.0)
    assert len(fired) == 1
    # Recovery resolves — resolutions never trigger actions.
    eng.observe("train", {"step": 3, "loss": 1.0}, emit=sink, now=2.0)
    assert sink.kinds() == ["alert", "alert_resolved"]
    assert len(fired) == 1
    # A fresh firing after the resolution triggers again.
    eng.observe("train", {"step": 4, "loss": 70.0}, emit=sink, now=3.0)
    assert len(fired) == 2 and sink.kinds()[-1] == "alert"


def test_trigger_suppressed_refire_never_triggers():
    """A re-fire inside the rate-limit window is not emitted — and by
    the seam's contract it must not reach the trigger either (a
    flapping signal cannot burn the runtime's fine-tune budget)."""
    eng = AlertEngine(parse_alert_rules("lossy=train.loss>10"),
                      min_interval_s=60.0)
    fired = []
    eng.add_trigger(lambda rule, value: fired.append(rule.name))
    sink = _Sink()
    eng.observe("train", {"step": 1, "loss": 50.0}, emit=sink, now=0.0)
    eng.observe("train", {"step": 2, "loss": 1.0}, emit=sink, now=1.0)
    eng.observe("train", {"step": 3, "loss": 55.0}, emit=sink, now=2.0)
    assert sink.kinds() == ["alert", "alert_resolved"]   # no 2nd record
    assert fired == ["lossy"]


def test_trigger_fail_open_and_identity_dedup():
    """A raising hook must not take down the metrics path (same
    fail-open contract as logger observers), and add_trigger is
    idempotent by identity — re-attaching on a supervisor restart
    cannot double the action."""
    eng = AlertEngine(parse_alert_rules("lossy=train.loss>10"),
                      min_interval_s=0.0)
    calls = []

    def boom(rule, value):
        calls.append(rule.name)
        raise RuntimeError("hook exploded")

    eng.add_trigger(boom)
    eng.add_trigger(boom)                     # identity dedup
    sink = _Sink()
    eng.observe("train", {"step": 1, "loss": 50.0}, emit=sink, now=0.0)
    assert calls == ["lossy"]                 # once, not twice
    assert sink.kinds() == ["alert"]          # record still emitted


def test_trigger_meta_carries_id_step_severity():
    """A 3-arg hook (the autopilot engine's shape) additionally gets
    the firing's meta — the same monotonic ``rule#N`` id stamped on
    the emitted record, the newest step, and the rule severity — so
    downstream remediation records can link back to the alert."""
    eng = AlertEngine(parse_alert_rules("lossy=train.loss>10!page"),
                      min_interval_s=0.0)
    seen = []
    eng.add_trigger(lambda rule, value, meta: seen.append(meta))
    sink = _Sink()
    eng.observe("train", {"step": 7, "loss": 50.0}, emit=sink, now=0.0)
    (meta,) = seen
    assert meta["id"] == sink.records[0][1]["id"] == "lossy#1"
    assert meta["step"] == 7 and meta["severity"] == "page"
    # A fresh firing gets a fresh id in both places.
    eng.observe("train", {"step": 8, "loss": 1.0}, emit=sink, now=1.0)
    eng.observe("train", {"step": 9, "loss": 60.0}, emit=sink, now=2.0)
    assert seen[1]["id"] == "lossy#2" == sink.records[-1][1]["id"]
