"""Serving fleet (fleet/): router pick/evict/re-route, the autoscaler
decision table, sidecar-gated checkpoint publishing, the engine's
hot-swap seam — and the ISSUE-6 acceptance smoke: a 2-worker fleet
survives a mid-load worker kill with zero failed client requests, then
hot-swaps to a newly published checkpoint with zero failed requests, a
per-replica monotone version flip, and outputs pinned EXACTLY equal to
the single-process ``--mode serve`` path."""

import copy
import dataclasses
import json
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax

from dml_cnn_cifar10_tpu.config import TrainConfig
from dml_cnn_cifar10_tpu.fleet import autoscaler as autoscaler_lib
from dml_cnn_cifar10_tpu.fleet import publisher as publisher_lib
from dml_cnn_cifar10_tpu.fleet import router as router_lib
from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib


class FakeLogger:
    def __init__(self):
        self.records = []

    def log(self, kind, **fields):
        self.records.append({"kind": kind, **fields})

    def flush(self):
        pass

    def kinds(self):
        return [r["kind"] for r in self.records]


def _view(rid, port=1000, version="1", depth=0, phase="serve",
          age=0.1):
    return router_lib.ReplicaView(replica_id=rid, port=port,
                                  version=version, queue_depth=depth,
                                  phase=phase, age_s=age)


# ---------------------------------------------------------------------------
# router placement/eviction logic (pure)
# ---------------------------------------------------------------------------

def test_live_views_filters_stale_warmup_portless_and_excluded():
    views = [_view(0),
             _view(1, age=9.9),            # stale heartbeat
             _view(2, phase="warmup"),     # not ready
             _view(3, port=None),          # never advertised a port
             _view(4),
             _view(5, phase="drain")]      # retiring
    live = router_lib.live_views(views, dead_after_s=3.0, exclude={4})
    assert [v.replica_id for v in live] == [0]


def test_pick_replica_least_depth_then_round_robin():
    views = [_view(0, depth=3), _view(1, depth=0), _view(2, depth=0)]
    assert router_lib.pick_replica(views, rr=0).replica_id == 1
    assert router_lib.pick_replica(views, rr=1).replica_id == 2
    assert router_lib.pick_replica(views, rr=2).replica_id == 1
    # Loaded replica only picked once the idle ones are excluded.
    only = [_view(0, depth=3)]
    assert router_lib.pick_replica(only, rr=7).replica_id == 0
    assert router_lib.pick_replica([], rr=0) is None


def test_router_evicts_stale_replica_and_reroutes_membership(tmp_path):
    log = FakeLogger()
    store0 = cluster_lib.HeartbeatStore(str(tmp_path), 0)
    store1 = cluster_lib.HeartbeatStore(str(tmp_path), 1)
    r = router_lib.Router(str(tmp_path), dead_after_s=0.5, logger=log)
    store0.publish(0, "serve", extra={"port": 1111, "version": "1",
                                      "queue_depth": 0})
    store1.publish(0, "serve", extra={"port": 2222, "version": "1",
                                      "queue_depth": 0})
    assert sorted(v.replica_id for v in r.live()) == [0, 1]
    time.sleep(0.6)
    store0.publish(1, "serve", extra={"port": 1111, "version": "1",
                                      "queue_depth": 0})  # 0 stays fresh
    live = r.live()
    assert [v.replica_id for v in live] == [0]
    lost = [rec for rec in log.records if rec["kind"] == "peer_lost"]
    assert lost and lost[0]["process_id"] == 1
    assert lost[0]["reason"] == "replica_evicted_stale_heartbeat"
    # Eviction is sticky: a late beat does not silently rejoin.
    store1.publish(5, "serve", extra={"port": 2222, "version": "1",
                                      "queue_depth": 0})
    assert [v.replica_id for v in r.live()] == [0]
    # healthz reflects the membership view.
    hz = r.healthz()
    assert hz["live"] == 1 and hz["replicas"]["1"]["live"] is False


def test_router_drain_excludes_from_routing_until_forgotten(tmp_path):
    """Retirement half-step: a draining replica takes no NEW requests
    (it finishes what it has via its own SIGTERM drain), and forget()
    clears the bookkeeping once the process is gone."""
    store = cluster_lib.HeartbeatStore(str(tmp_path), 0)
    r = router_lib.Router(str(tmp_path), dead_after_s=5.0)
    store.publish(0, "serve", extra={"port": 1111, "version": "1",
                                     "queue_depth": 0})
    assert [v.replica_id for v in r.live()] == [0]
    r.drain_replica(0)
    assert r.live() == []
    r.forget(0)
    assert [v.replica_id for v in r.live()] == [0]


def test_beat_extra_payload_roundtrip(tmp_path):
    store = cluster_lib.HeartbeatStore(str(tmp_path), 3)
    store.publish(17, "serve", extra={"port": 9000, "version": "12",
                                      "queue_depth": 4})
    beats = cluster_lib.HeartbeatStore(str(tmp_path), 0).read_all()
    assert set(beats) == {3}           # only 3 published
    beat = beats[3]
    assert beat.step == 17 and beat.phase == "serve"
    assert beat.extra == {"port": 9000, "version": "12",
                          "queue_depth": 4}
    view = router_lib.view_from_beat(beat)
    assert view.port == 9000 and view.version == "12" \
        and view.queue_depth == 4


# ---------------------------------------------------------------------------
# autoscaler decision table (pure)
# ---------------------------------------------------------------------------

def _sig(live=2, starting=0, depth=0.0, shed=0.0, p99=None):
    return autoscaler_lib.FleetSignals(
        live=live, starting=starting, mean_queue_depth=depth,
        shed_fraction=shed, p99_ms=p99)


def test_autoscaler_decision_table():
    d = autoscaler_lib.decide
    # Below the floor: always up — the self-healing path.
    assert d(_sig(live=1), 2, 4).action == "up"
    assert d(_sig(live=1), 2, 4).reason == "below_min"
    # A spawn in flight counts: no second spawn for the same gap.
    assert d(_sig(live=1, starting=1), 2, 4).action == "hold"
    # Shedding scales up...
    assert d(_sig(shed=0.05), 2, 4).reason == "shedding"
    # ...but never past the ceiling.
    assert d(_sig(live=4, shed=0.5), 2, 4).action == "hold"
    # SLO violation scales up; no SLO configured means no signal.
    assert d(_sig(p99=80.0), 2, 4, slo_ms=50.0).reason == \
        "slo_violation"
    assert d(_sig(p99=80.0), 2, 4, slo_ms=None).action == "hold"
    # Queue backpressure scales up.
    assert d(_sig(depth=9.0), 2, 4).reason == "queue_depth"
    # All quiet above the floor: retire one.
    assert d(_sig(live=3), 2, 4).action == "down"
    assert d(_sig(live=3), 2, 4).reason == "idle"
    # Quiet-but-at-floor holds; barely-inside-SLO holds (down needs
    # comfortably inside).
    assert d(_sig(live=2), 2, 4).action == "hold"
    assert d(_sig(live=3, p99=40.0), 2, 4, slo_ms=50.0).action == \
        "hold"
    assert d(_sig(live=3, p99=10.0), 2, 4, slo_ms=50.0).action == \
        "down"


def test_aggregate_signals_reads_serve_windows(tmp_path):
    tele = tmp_path / "telemetry"
    tele.mkdir()
    (tele / "replica_0.jsonl").write_text(
        json.dumps({"kind": "serve", "t": 1.0, "task": 0,
                    "requests": 90, "completed": 80, "shed_queue": 10,
                    "shed_deadline": 0, "qps": 8.0, "p50_ms": 5.0,
                    "p95_ms": 9.0, "p99_ms": 40.0, "batch_fill": 0.5,
                    "window_s": 10.0}) + "\n")
    views = [_view(0, depth=4), _view(1, depth=2)]
    sig = autoscaler_lib.aggregate_signals(views, starting=1,
                                           telemetry_dir=str(tele))
    assert sig.live == 2 and sig.starting == 1
    assert sig.mean_queue_depth == 3.0
    assert sig.shed_fraction == pytest.approx(10 / 90)
    assert sig.p99_ms == 40.0


# ---------------------------------------------------------------------------
# checkpoint publishing: the integrity-sidecar gate
# ---------------------------------------------------------------------------

def _toy_state(scale=1.0):
    return {"w": (np.arange(8, dtype=np.float32) * scale),
            "b": np.float32(scale)}


def test_publish_gate_requires_verifiable_sidecar(tmp_path):
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    ckpt_dir = str(tmp_path / "ckpts")
    fleet_dir = str(tmp_path / "fleet")
    path1 = ckpt_lib.save_checkpoint(ckpt_dir, _toy_state(), 10)
    # Committed save → sidecar exists → publishable.
    rec = publisher_lib.publish_checkpoint(fleet_dir, path1, 10)
    assert rec is not None and rec.seq == 1 and rec.version == "10"
    got = publisher_lib.read_published(fleet_dir)
    assert got == rec
    # Older-or-equal steps never roll the published version back.
    assert publisher_lib.publish_checkpoint(fleet_dir, path1, 10) is None
    # No sidecar → not publishable (stricter than restore).
    bare = os.path.join(ckpt_dir, "ckpt_20.msgpack")
    with open(path1, "rb") as f:
        payload = f.read()
    with open(bare, "wb") as f:
        f.write(payload)
    assert publisher_lib.publish_checkpoint(fleet_dir, bare, 20) is None
    # Corrupt bytes under a valid-looking sidecar → not publishable.
    path3 = ckpt_lib.save_checkpoint(ckpt_dir, _toy_state(2.0), 30)
    with open(path3, "r+b") as f:
        f.truncate(os.path.getsize(path3) // 2)
    assert publisher_lib.publish_checkpoint(fleet_dir, path3, 30) is None
    assert publisher_lib.read_published(fleet_dir).step == 10


def test_directory_publisher_skips_bad_latest(tmp_path):
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    ckpt_dir = str(tmp_path / "ckpts")
    fleet_dir = str(tmp_path / "fleet")
    ckpt_lib.save_checkpoint(ckpt_dir, _toy_state(), 10, keep=10)
    path2 = ckpt_lib.save_checkpoint(ckpt_dir, _toy_state(2.0), 20,
                                     keep=10)
    with open(path2, "r+b") as f:
        f.truncate(os.path.getsize(path2) // 2)   # corrupt the newest
    pub = publisher_lib.DirectoryPublisher(ckpt_dir, fleet_dir)
    rec = pub.scan_once()
    # The corrupt newest is skipped (and remembered); the older
    # verifiable checkpoint is published instead.
    assert rec is not None and rec.step == 10
    assert pub.scan_once() is None                # nothing new
    ckpt_lib.save_checkpoint(ckpt_dir, _toy_state(3.0), 30, keep=10)
    rec = pub.scan_once()
    assert rec.step == 30 and rec.seq == 2


# ---------------------------------------------------------------------------
# the engine hot-swap seam
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def swap_setup():
    from dml_cnn_cifar10_tpu.config import DataConfig, ModelConfig
    from dml_cnn_cifar10_tpu.models.registry import get_model

    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    p1 = model_def.init(jax.random.key(0), model_cfg, data_cfg)
    p2 = jax.tree.map(lambda x: x * 1.25, p1)
    return model_def, model_cfg, data_cfg, p1, p2


def test_try_swap_installs_matching_params(swap_setup, rng):
    from dml_cnn_cifar10_tpu.serve.engine import ServingEngine

    model_def, model_cfg, data_cfg, p1, p2 = swap_setup
    log = FakeLogger()
    eng = ServingEngine.from_params(model_def, model_cfg, data_cfg, p1,
                                    logger=log, version="1")
    ref2 = ServingEngine.from_params(model_def, model_cfg, data_cfg, p2,
                                     version="2")
    img = rng.integers(0, 256, (1, 32, 32, 3)).astype(np.uint8)
    out1, _, v1 = eng.forward_timed_versioned(img)
    assert v1 == "1"
    ok, reason = eng.try_swap(p2, version="2")
    assert ok, reason
    out2, _, v2 = eng.forward_timed_versioned(img)
    assert v2 == "2" and eng.version == "2" and eng.swap_count == 1
    want2, _ = ref2.forward_timed(img)
    assert np.array_equal(out2, want2)       # the NEW weights, exactly
    assert not np.array_equal(out1, out2)    # and they actually differ
    swaps = [r for r in log.records if r["kind"] == "swap"]
    assert swaps and swaps[0]["version"] == "2" \
        and swaps[0]["from_version"] == "1" \
        and swaps[0]["swap_ms"] >= 0


def test_try_swap_rejects_mismatched_candidate(swap_setup, rng):
    from dml_cnn_cifar10_tpu.serve.engine import ServingEngine

    model_def, model_cfg, data_cfg, p1, _ = swap_setup
    log = FakeLogger()
    eng = ServingEngine.from_params(model_def, model_cfg, data_cfg, p1,
                                    logger=log, version="1")
    img = rng.integers(0, 256, (1, 32, 32, 3)).astype(np.uint8)
    want, _ = eng.forward_timed(img)

    # Wrong leaf shape (a differently-sized model's checkpoint).
    leaves, treedef = jax.tree.flatten(p1)
    leaves[0] = np.zeros((3, 3), np.float32)
    bad_shape = jax.tree.unflatten(treedef, leaves)
    ok, reason = eng.try_swap(bad_shape, version="9")
    assert not ok and "leaf" in reason
    # Wrong dtype with right shapes.
    bad_dtype = jax.tree.map(lambda x: np.asarray(x, np.float64), p1)
    ok, reason = eng.try_swap(bad_dtype, version="9")
    assert not ok
    # Wrong tree structure entirely.
    ok, reason = eng.try_swap({"nope": np.zeros(3, np.float32)},
                              version="9")
    assert not ok and "structure" in reason

    rejects = [r for r in log.records if r["kind"] == "swap_rejected"]
    assert len(rejects) == 3 and all(r["version"] == "9"
                                     for r in rejects)
    assert not [r for r in log.records if r["kind"] == "swap"]
    # The old version never stopped serving, bit-identically.
    got, _, v = eng.forward_timed_versioned(img)
    assert v == "1" and eng.swap_count == 0
    assert np.array_equal(got, want)


def test_artifact_engine_refuses_swap(swap_setup):
    from dml_cnn_cifar10_tpu import export as export_lib
    from dml_cnn_cifar10_tpu.serve.engine import ServingEngine

    model_def, model_cfg, data_cfg, p1, _ = swap_setup
    blob = export_lib.export_forward(model_def, model_cfg, data_cfg, p1,
                                     platforms=["cpu"])
    log = FakeLogger()
    eng = ServingEngine.from_artifact(blob=blob, logger=log)
    ok, reason = eng.try_swap(p1, version="2")
    assert not ok and "artifact" in reason
    assert [r["kind"] for r in log.records] == ["swap_rejected"]


def test_batcher_tags_rows_with_version(swap_setup, rng):
    from dml_cnn_cifar10_tpu.serve import MicroBatcher, VersionedLogits
    from dml_cnn_cifar10_tpu.serve.engine import ServingEngine

    model_def, model_cfg, data_cfg, p1, p2 = swap_setup
    eng = ServingEngine.from_params(model_def, model_cfg, data_cfg, p1,
                                    version="1")
    img = rng.integers(0, 256, (32, 32, 3)).astype(np.uint8)
    with MicroBatcher(eng, buckets=(1,)) as b:
        row = b.submit(img).result(timeout=60)
        assert isinstance(row, VersionedLogits) and row.version == "1"
        assert eng.try_swap(p2, version="2")[0]
        row2 = b.submit(img).result(timeout=60)
        assert row2.version == "2"
        assert not np.array_equal(np.asarray(row), np.asarray(row2))


# ---------------------------------------------------------------------------
# satellites: JSONL kinds, report section, loadgen mixes, CLI plumb
# ---------------------------------------------------------------------------

def test_fleet_jsonl_kinds_pass_schema_lint(tmp_path):
    from tools import check_jsonl_schema

    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger

    path = str(tmp_path / "fleet.jsonl")
    logger = MetricsLogger(jsonl_path=path)
    logger.log("fleet", replicas=2, live=2, routed=100, rerouted=1,
               evictions=1, shed=0, version_mix={"1": 60, "2": 40},
               window_s=5.0)
    logger.log("fleet_done", replicas=2, live=2, routed=100,
               rerouted=1, evictions=1, shed=0, version_mix={},
               window_s=9.0)
    logger.log("swap", replica_id=0, version="2", from_version="1",
               swap_ms=3.2)
    logger.log("swap_rejected", replica_id=1, version="3",
               reason="leaf 0: have (3,)/float32, candidate "
                      "(4,)/float32")
    logger.log("scale", action="up", reason="below_min", replicas=2)
    logger.log("fleet_publish", seq=2, version="20", step=20,
               path="/x/ckpt_20.msgpack")
    logger.close()
    assert check_jsonl_schema.check_file(path, strict=True) == []


def test_telemetry_report_prints_fleet_section(tmp_path):
    from tools import telemetry_report

    path = str(tmp_path / "fleet.jsonl")
    recs = [
        {"kind": "fleet", "t": 1.0, "task": 0, "replicas": 2, "live": 2,
         "routed": 50, "rerouted": 0, "evictions": 0, "shed": 0,
         "version_mix": {"1": 50}, "window_s": 2.0},
        {"kind": "fleet", "t": 3.0, "task": 0, "replicas": 3, "live": 1,
         "routed": 40, "rerouted": 2, "evictions": 1, "shed": 0,
         "version_mix": {"1": 10, "2": 30}, "window_s": 2.0},
        {"kind": "swap", "t": 2.5, "task": 0, "replica_id": 0,
         "version": "2", "from_version": "1", "swap_ms": 4.0},
        {"kind": "scale", "t": 2.6, "task": 0, "action": "up",
         "reason": "below_min", "replicas": 2},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    out = telemetry_report.summarize(path)
    assert "fleet health" in out
    assert "1 hot-swap(s)" in out
    assert "autoscale up (below_min)" in out
    assert "re-routed" in out and "eviction" in out


def test_loadgen_mix_rows(tmp_path):
    """Mixes produce one BENCH-style row each; the adversarial mix
    rejects oversize requests without failing well-formed ones; every
    row carries a version_mix."""
    import tools.loadgen as loadgen

    report_path = str(tmp_path / "mix_report.json")
    assert loadgen.main([
        "--mix", "steady,diurnal,adversarial", "--qps", "60",
        "--duration_s", "0.7", "--buckets", "1,8",
        "--report", report_path]) == 0
    with open(report_path) as f:
        report = json.load(f)
    rows = {r["mix"]: r for r in report["mixes"]}
    assert set(rows) == {"steady", "diurnal", "adversarial"}
    for row in rows.values():
        assert row["completed"] > 0
        assert row["requests"] == row["completed"] + row["shed"]
        assert row["latency_ms"]["p50"] > 0
        assert row["latency_ms"]["p99"] >= row["latency_ms"]["p50"]
        assert row["version_mix"]    # every completion tagged
    assert rows["adversarial"]["rejected"] > 0
    assert rows["steady"]["rejected"] == 0


def test_cli_fleet_flags_plumb_into_config():
    from dml_cnn_cifar10_tpu.cli.main import (build_parser,
                                              config_from_args)

    args, _ = build_parser().parse_known_args([
        "--mode", "fleet", "--fleet_min_replicas", "3",
        "--fleet_max_replicas", "5", "--fleet_port", "0",
        "--fleet_dir", "/x/fleet", "--fleet_autoscale", "false",
        "--fleet_replica_dead_after_s", "7.5", "--fleet_publish",
        "true", "--serve_slo_ms", "25"])
    cfg = config_from_args(args)
    assert cfg.fleet.min_replicas == 3
    assert cfg.fleet.max_replicas == 5
    assert cfg.fleet.port == 0
    assert cfg.fleet.dir == "/x/fleet"
    assert cfg.fleet.autoscale is False
    assert cfg.fleet.replica_dead_after_s == 7.5
    assert cfg.fleet.publish is True
    assert cfg.serve.slo_ms == 25
    with pytest.raises(SystemExit, match="min <= max"):
        config_from_args(build_parser().parse_known_args(
            ["--fleet_min_replicas", "4",
             "--fleet_max_replicas", "2"])[0])


# ---------------------------------------------------------------------------
# acceptance smoke: 2 workers + router; worker kill, then hot-swap —
# zero failed client requests throughout, outputs pinned to --mode serve
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fleet_cfg(tmp_path, data_cfg) -> TrainConfig:
    cfg = TrainConfig(
        log_dir=str(tmp_path / "logs"),
        metrics_jsonl=str(tmp_path / "router.jsonl"),
        data=dataclasses.replace(data_cfg, normalize="scale"),
    )
    cfg.model.logit_relu = False
    cfg.serve.buckets = (1, 4)
    cfg.serve.batch_window_ms = 1.0
    cfg.serve.metrics_every_s = 0.5
    cfg.serve.drain_deadline_s = 5.0
    cfg.fleet.dir = str(tmp_path / "fleet")
    cfg.fleet.port = _free_port()
    cfg.fleet.min_replicas = 2
    cfg.fleet.max_replicas = 3
    cfg.fleet.heartbeat_interval_s = 0.1
    cfg.fleet.replica_dead_after_s = 1.5
    cfg.fleet.swap_poll_s = 0.1
    cfg.fleet.publish_poll_s = 0.2
    cfg.fleet.autoscale_every_s = 0.5
    cfg.fleet.scale_cooldown_s = 2.0
    cfg.fleet.metrics_every_s = 0.5
    return cfg


def _save_ckpt(cfg, host_state, step, scale=1.0):
    """Commit a checkpoint at ``step`` (params scaled so versions are
    numerically distinguishable), sidecar included."""
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    opt = dict(host_state.opt)
    opt["step"] = np.asarray(opt["step"]) * 0 + step
    params = jax.tree.map(lambda x: np.asarray(x * scale, x.dtype),
                          host_state.params)
    return ckpt_lib.save_checkpoint(
        cfg.log_dir, host_state._replace(opt=opt, params=params), step,
        keep=10)


#: The single-process ``--mode serve`` reference path, run in a FRESH
#: subprocess with the workers' environment: resolve_engine over the
#: latest checkpoint, one bucket-1 forward per image. In-process
#: reference computation would be polluted by whatever jax state the
#: rest of the suite left behind (device count, config leaks) — the
#: acceptance pin is fleet-vs-serve, both as real deployments.
_REF_SCRIPT = """
import json, sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
import numpy as np
from dml_cnn_cifar10_tpu.config import config_from_dict
with open(sys.argv[1]) as f:
    cfg = config_from_dict(json.load(f))
cfg.metrics_jsonl = None
from dml_cnn_cifar10_tpu.serve.server import resolve_engine
eng = resolve_engine(cfg)
imgs = np.load(sys.argv[2])
out = {}
for i in range(imgs.shape[0]):
    logits, _ = eng.forward_timed(imgs[i:i + 1])
    out[i] = [float(v) for v in logits[0]]
print("RESULT " + json.dumps({"version": eng.version, "logits": out}))
"""


def _serve_path_logits(cfg, tmp_path, images):
    import subprocess
    import sys as _sys

    from dml_cnn_cifar10_tpu.config import config_to_dict

    script = tmp_path / "serve_ref.py"
    script.write_text(_REF_SCRIPT)
    cfg_path = tmp_path / "serve_ref_cfg.json"
    cfg_path.write_text(json.dumps(config_to_dict(cfg)))
    imgs_path = tmp_path / "serve_ref_imgs.npy"
    np.save(imgs_path, images)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, XLA_FLAGS="")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [_sys.executable, str(script), str(cfg_path), str(imgs_path)],
        capture_output=True, text=True, timeout=240, env=env, cwd=repo)
    assert proc.returncode == 0, \
        f"serve reference run failed:\n{proc.stdout}\n{proc.stderr}"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("RESULT ")]
    res = json.loads(lines[-1][len("RESULT "):])
    return res["version"], {int(k): v for k, v in res["logits"].items()}


def _predict(port: int, img: np.ndarray) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=img.tobytes(),
        headers={"Content-Type": "application/octet-stream"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def _healthz(port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        return json.loads(resp.read())


def _worker_log_tails(fleet_dir: str) -> str:
    tele = os.path.join(fleet_dir, "telemetry")
    out = []
    if os.path.isdir(tele):
        for name in sorted(os.listdir(tele)):
            if name.endswith(".log"):
                with open(os.path.join(tele, name), errors="replace") as f:
                    out.append(f"--- {name} ---\n" + f.read()[-3000:])
    return "\n".join(out)


def test_fleet_survives_kill_and_hot_swaps_zero_failures(
        tmp_path, data_cfg, monkeypatch, rng):
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
    from dml_cnn_cifar10_tpu.fleet.controller import main_fleet
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    # Workers are fresh processes: single CPU device (the 8-virtual-
    # device XLA flag is this test process's mesh, not theirs).
    monkeypatch.setenv("XLA_FLAGS", "")
    cfg = _fleet_cfg(tmp_path, data_cfg)
    # Replica 1 dies abruptly (host_lost: os._exit, no cleanup, no
    # drain) at its 15th traffic dispatch.
    cfg.fleet.worker_fault = "1:host_lost@15"

    # Seed checkpoint: version "1".
    seed_cfg = copy.deepcopy(cfg)
    seed_cfg.metrics_jsonl = None
    trainer = Trainer(seed_cfg)
    host_state = ckpt_lib.fetch_to_host(trainer.init_or_restore())
    _save_ckpt(cfg, host_state, 1)

    images = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    v1, direct1 = _serve_path_logits(cfg, tmp_path, images)
    assert v1 == "1"

    ready, stop = threading.Event(), threading.Event()
    rc = {}
    t = threading.Thread(
        target=lambda: rc.setdefault("rc", main_fleet(
            cfg, ready_event=ready, stop_event=stop)),
        daemon=True)
    t.start()
    port = cfg.fleet.port
    responses = []   # (replica_id, version) in client order
    try:
        assert ready.wait(60), "router never became ready"
        deadline = time.time() + 240
        while time.time() < deadline:
            if _healthz(port)["live"] >= 2:
                break
            time.sleep(0.5)
        else:
            pytest.fail("fleet never reached 2 live replicas\n"
                        + _worker_log_tails(cfg.fleet.dir))

        # Phase A: sustained load across both replicas; replica 1 dies
        # mid-way; every request must succeed on version "1" with
        # logits EXACTLY the single-process serve path's.
        for i in range(80):
            resp = _predict(port, images[i % 4])
            assert "class" in resp, f"request {i} failed: {resp}"
            assert resp["version"] == "1"
            assert resp["logits"] == direct1[i % 4], \
                f"fleet output diverged from --mode serve at req {i}"
            responses.append((resp["replica_id"], resp["version"]))
            time.sleep(0.01)
        assert len({rid for rid, _ in responses}) >= 2, \
            "load never reached the second replica"
        # The kill actually happened and was re-routed, not surfaced.
        hz = _healthz(port)
        assert hz["replicas"]["1"]["live"] is False, \
            "replica 1 was never killed/evicted\n" \
            + _worker_log_tails(cfg.fleet.dir)

        # Phase B: publish version "2" (the directory publisher picks
        # it up; workers hot-swap between micro-batches). Zero request
        # errors during the swap; versions flip monotonically
        # per-replica; outputs pin to the new serve path.
        _save_ckpt(cfg, host_state, 2, scale=1.25)
        v2, direct2 = _serve_path_logits(cfg, tmp_path, images)
        assert v2 == "2"
        flip_deadline = time.time() + 90
        consecutive_new = 0
        i = 0
        while consecutive_new < 20:
            assert time.time() < flip_deadline, \
                "fleet never converged to version 2\n" \
                + _worker_log_tails(cfg.fleet.dir)
            resp = _predict(port, images[i % 4])
            assert "class" in resp, f"request failed mid-swap: {resp}"
            assert resp["version"] in ("1", "2")
            if resp["version"] == "2":
                consecutive_new += 1
                assert resp["logits"] == direct2[i % 4], \
                    "post-swap fleet output diverged from --mode serve"
            else:
                consecutive_new = 0
            responses.append((resp["replica_id"], resp["version"]))
            i += 1
            time.sleep(0.01)

        # Per-replica monotone flip: once a replica answers "2" it
        # never answers "1" again.
        seen_new = set()
        for rid, version in responses:
            if version == "2":
                seen_new.add(rid)
            else:
                assert rid not in seen_new, \
                    f"replica {rid} answered version 1 after 2"
    finally:
        stop.set()
        t.join(120)
    assert not t.is_alive(), "fleet loop did not exit on stop"
    assert rc.get("rc") == 0

    # Stream checks: the router's JSONL passes the schema lint and
    # records the eviction + the self-healing scale-up; the report CLI
    # prints the fleet-health section; replica streams lint too.
    from tools import check_jsonl_schema, telemetry_report
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl, strict=True) == []
    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    kinds = {r["kind"] for r in recs}
    assert "fleet" in kinds and "fleet_done" in kinds
    lost = [r for r in recs if r["kind"] == "peer_lost"]
    assert any(r["process_id"] == 1 for r in lost)
    scale_ups = [r for r in recs if r["kind"] == "scale"
                 and r["action"] == "up" and r["reason"] == "below_min"]
    assert scale_ups, "the dead replica was never replaced"
    report = telemetry_report.summarize(cfg.metrics_jsonl)
    assert "fleet health" in report
    tele = os.path.join(cfg.fleet.dir, "telemetry")
    replica0 = os.path.join(tele, "replica_0.jsonl")
    assert check_jsonl_schema.check_file(replica0, strict=True) == []
    with open(replica0) as f:
        r0 = [json.loads(ln) for ln in f if ln.strip()]
    swaps = [r for r in r0 if r["kind"] == "swap"]
    assert swaps and swaps[0]["version"] == "2" \
        and swaps[0]["from_version"] == "1"
