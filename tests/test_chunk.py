"""Chunked training (K steps per dispatch) == K individual steps."""

import jax
import jax.numpy as jnp
import numpy as np

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
import pytest


@pytest.mark.slow
def test_chunk_matches_stepwise(rng):
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    optim_cfg = OptimConfig(learning_rate=0.02, momentum=0.9)
    mesh = mesh_lib.build_mesh(ParallelConfig())

    k, b = 4, 16
    images = rng.normal(0.5, 0.25, (k, b, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, (k, b)).astype(np.int32)

    state0 = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, optim_cfg, mesh)

    step = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh)
    st_a = jax.tree.map(jnp.copy, state0)
    for i in range(k):
        im, lb = mesh_lib.shard_batch(mesh, images[i], labels[i])
        st_a, m_a = step(st_a, im, lb)

    chunk = step_lib.make_train_chunk(model_def, model_cfg, optim_cfg, mesh)
    st_b, m_b = chunk(jax.tree.map(jnp.copy, state0), jnp.asarray(images),
                      jnp.asarray(labels))

    assert int(jax.device_get(st_b.step)) == k
    np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]),
                               rtol=1e-5)
    for a, c in zip(jax.tree.leaves(st_a.params), jax.tree.leaves(st_b.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(c)),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_raw_uint8_chunk_matches_host_decode(rng):
    """The bench path — make_train_chunk(data_cfg=...) fed raw uint8 —
    trains the same math as stepwise training on host-decoded batches."""
    from dml_cnn_cifar10_tpu.data import records as rec

    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="standardize")
    optim_cfg = OptimConfig(learning_rate=0.02)
    mesh = mesh_lib.build_mesh(ParallelConfig())

    k, b = 3, 16
    raw = rng.integers(0, 256, (k, b, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (k, b)).astype(np.int32)

    state0 = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, optim_cfg, mesh)

    # Host decode (the pipeline's _finish deterministic path) + stepwise.
    step = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh)
    st_a = jax.tree.map(jnp.copy, state0)
    for i in range(k):
        ims = rec.normalize(
            rec.center_crop(raw[i].astype(np.float32), data_cfg.crop_height,
                            data_cfg.crop_width), data_cfg.normalize)
        im, lb = mesh_lib.shard_batch(mesh, ims, labels[i])
        st_a, _ = step(st_a, im, lb)

    # Device decode: raw uint8 chunk straight in.
    chunk = step_lib.make_train_chunk(model_def, model_cfg, optim_cfg, mesh,
                                      data_cfg=data_cfg)
    im, lb = mesh_lib.shard_batch(mesh, raw, labels, leading_dims=1)
    st_b, _ = chunk(jax.tree.map(jnp.copy, state0), im, lb)

    # atol bounds float32 reduction-order noise (numpy vs XLA standardize)
    # accumulated over k SGD steps; observed max ~3e-5.
    for a, c in zip(jax.tree.leaves(st_a.params), jax.tree.leaves(st_b.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(c)),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_resident_chunk_matches_raw_chunk(rng):
    """The HBM-resident data path (device-side gather from the in-HBM
    dataset by index) trains the same math as the host-gather raw-uint8
    chunk on the same indices."""
    model_def = get_model("cnn")
    model_cfg = ModelConfig(logit_relu=False)
    data_cfg = DataConfig(normalize="scale")
    optim_cfg = OptimConfig(learning_rate=0.02)
    mesh = mesh_lib.build_mesh(ParallelConfig())

    n, k, b = 256, 3, 16
    ds_images = rng.integers(0, 256, (n, 32, 32, 3)).astype(np.uint8)
    ds_labels = rng.integers(0, 10, n).astype(np.int32)
    idx = rng.integers(0, n, (k, b)).astype(np.int32)

    state0 = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, data_cfg, optim_cfg, mesh)

    # Host gather -> raw chunk path.
    raw = ds_images[idx]                      # [k, b, H, W, C]
    lbs = ds_labels[idx]
    chunk = step_lib.make_train_chunk(model_def, model_cfg, optim_cfg, mesh,
                                      data_cfg=data_cfg)
    im, lb = mesh_lib.shard_batch(mesh, raw, lbs, leading_dims=1)
    st_a, m_a = chunk(jax.tree.map(jnp.copy, state0), im, lb)

    # Device gather from the resident dataset.
    repl = mesh_lib.replicated(mesh)
    resident = step_lib.make_train_chunk_resident(
        model_def, model_cfg, optim_cfg, mesh,
        jax.device_put(ds_images, repl), jax.device_put(ds_labels, repl),
        data_cfg=data_cfg)
    idx_dev = jax.device_put(idx, mesh_lib.batch_sharding(mesh, 2,
                                                          leading_dims=1))
    st_b, m_b = resident(jax.tree.map(jnp.copy, state0), idx_dev)

    np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]),
                               rtol=1e-6)
    for a, c in zip(jax.tree.leaves(st_a.params),
                    jax.tree.leaves(st_b.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(c)))
