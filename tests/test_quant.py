"""Quantized serving subsystem (``quant/``; docs/QUANT.md).

Pins the four contracts the int8 path rides on: the calibration scale
math (per-channel symmetric absmax), the convert roundtrip bound
(dequantized weights within half a quantization step of the float
originals), the publish-time accuracy-delta gate — BOTH verdicts: a
passing candidate hot-swaps the engine to a ``+int8`` version, a
failing one emits ``quant_rejected`` and leaves the float path serving
bit-identically — and the serving-side furniture that rides along
(the exact-match response cache, the JSONL schema of the new record
kinds, and ``tools/loadgen.py --check_labels``).
"""

import json

import numpy as np
import pytest

import jax

from dml_cnn_cifar10_tpu.config import ModelConfig, ServeConfig
from dml_cnn_cifar10_tpu.export import make_variable_serving_fn
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.quant.calibrate import (EPS, calibrate,
                                                 weight_scales)
from dml_cnn_cifar10_tpu.quant.convert import (QuantContext,
                                               accuracy_gate,
                                               dequantize_params,
                                               gate_and_swap,
                                               is_quantized_version,
                                               quantize_params,
                                               quantized_version)
from dml_cnn_cifar10_tpu.serve.cache import ResponseCache
from dml_cnn_cifar10_tpu.serve.engine import ServingEngine

MODEL_CFG = ModelConfig(name="cnn", logit_relu=False)


class RecordingLogger:
    def __init__(self):
        self.records = []

    def log(self, kind, **fields):
        self.records.append({"kind": kind, **fields})

    def of(self, kind):
        return [r for r in self.records if r["kind"] == kind]


@pytest.fixture(scope="module")
def model_def():
    return get_model("cnn")


@pytest.fixture(scope="module")
def params(model_def):
    # data geometry only matters through crop size; use the session
    # defaults (32 -> 24) so the jitted programs are shared.
    from dml_cnn_cifar10_tpu.config import DataConfig
    dcfg = DataConfig()
    return model_def.init(jax.random.key(0), MODEL_CFG, dcfg)


def _images(n, seed=0, hw=32):
    return np.random.default_rng(seed).integers(
        0, 256, (n, hw, hw, 3), dtype=np.uint8)


# ---------------------------------------------------------------------------
# scale math + convert roundtrip
# ---------------------------------------------------------------------------

def test_weight_scales_per_out_channel_absmax(params):
    scales = weight_scales(params)
    assert set(scales) == {"conv1", "conv2", "full1", "full2", "full3"}
    for layer, s in scales.items():
        k = np.asarray(params[layer]["kernel"])
        assert s.shape == (k.shape[-1],)          # one per out channel
        axes = tuple(range(k.ndim - 1))
        want = np.maximum(np.abs(k).max(axis=axes), EPS) / 127.0
        np.testing.assert_allclose(s, want, rtol=1e-6)
        assert (s > 0).all()                      # EPS guard: never 0


def test_weight_scales_zero_channel_guard():
    params = {"full1": {"kernel": np.zeros((4, 3), np.float32),
                        "bias": np.zeros((3,), np.float32)}}
    s = weight_scales(params)["full1"]
    assert (s > 0).all()                          # no divide-by-zero


def test_quantize_roundtrip_within_half_scale(params, data_cfg):
    scales = calibrate(params, _images(64), MODEL_CFG, data_cfg,
                       batch_size=32, num_batches=2)
    assert scales.calib_batches == 2
    qtree = quantize_params(params, scales)
    for layer in ("conv1", "conv2", "full1", "full2", "full3"):
        assert qtree[layer]["w_q"].dtype == np.int8
        assert np.abs(qtree[layer]["w_q"]).max() <= 127
    deq = dequantize_params(qtree)
    for layer, s in scales.weight.items():
        w = np.asarray(params[layer]["kernel"])
        err = np.abs(deq[layer]["kernel"] - w)
        # symmetric rounding: within half a quantization step,
        # per-channel (the scale broadcast over the out axis)
        assert (err <= s / 2 + 1e-7).all()
        np.testing.assert_array_equal(deq[layer]["bias"],
                                      params[layer]["bias"])


def test_version_suffix_helpers():
    assert quantized_version("120") == "120+int8"
    assert quantized_version("120+int8") == "120+int8"   # idempotent
    assert is_quantized_version("120+int8")
    assert not is_quantized_version("120")


# ---------------------------------------------------------------------------
# the accuracy-delta gate
# ---------------------------------------------------------------------------

def test_accuracy_gate_math():
    labels = np.array([0, 1, 2, 3])
    eye = np.eye(4, 10, dtype=np.float32)
    f_logits = eye.copy()                       # float: 4/4
    q_logits = eye.copy()
    q_logits[3] = np.eye(1, 10)[0]              # int8: 3/4 -> delta 0.25
    v = accuracy_gate(f_logits, q_logits, labels, max_delta=0.30)
    assert v["ok"] and v["delta"] == pytest.approx(0.25)
    assert v["float_top1"] == 1.0 and v["quant_top1"] == 0.75
    v = accuracy_gate(f_logits, q_logits, labels, max_delta=0.20)
    assert not v["ok"]
    # A quant candidate BETTER than float never fails the gate.
    v = accuracy_gate(q_logits, f_logits, labels, max_delta=0.0)
    assert v["ok"] and v["delta"] == pytest.approx(-0.25)


def test_gate_on_tiny_cnn_delta_near_zero(model_def, params, data_cfg):
    """Tier-1 pin of the whole calibrate->convert->gate path on the
    real CNN: on synthetic data both variants sit at chance, so the
    int8 top-1 must track float top-1 closely — a generous ceiling
    still catches a broken quantized forward, which scores ~0 delta
    only by accident."""
    serve_cfg = ServeConfig(quant_calib_batches=2, quant_max_delta=0.5)
    ctx = QuantContext.build(model_def, MODEL_CFG, data_cfg, serve_cfg,
                             calib_batch_size=32, holdout=96)
    qtree = ctx.quantize(params)
    v = ctx.gate(params, qtree)
    assert set(v) == {"ok", "float_top1", "quant_top1", "delta",
                      "max_delta", "n"}
    assert v["n"] > 0
    assert abs(v["delta"]) <= 0.5 and v["ok"]


# ---------------------------------------------------------------------------
# engine integration: quantized construction, gate_and_swap both ways
# ---------------------------------------------------------------------------

def test_engine_quantized_construction(model_def, params, data_cfg):
    scales = calibrate(params, _images(64), MODEL_CFG, data_cfg,
                       batch_size=32, num_batches=2)
    eng = ServingEngine.from_params(
        model_def, MODEL_CFG, data_cfg, params, None,
        version="7", quantize="int8", quant_scales=scales)
    assert eng.version == "7+int8"
    logits, _, version = eng.forward_timed_versioned(_images(4, seed=3))
    assert logits.shape == (4, 10) and version == "7+int8"
    assert np.isfinite(logits).all()
    # A float tree does not match the int8 program's spec: rejected,
    # and the quantized weights keep serving bit-identically.
    before = eng.forward_timed_versioned(_images(4, seed=3))[0]
    ok, reason = eng.try_swap(params, None, version="8")
    assert not ok and "structure" in reason
    after, _, version = eng.forward_timed_versioned(_images(4, seed=3))
    assert version == "7+int8"
    np.testing.assert_array_equal(before, after)


def test_gate_and_swap_reject_then_accept(model_def, params, data_cfg):
    """The publish-adoption path end to end on one engine: a candidate
    failing the gate changes NOTHING (quant_rejected logged, float
    logits bit-identical, version untouched); a passing one hot-swaps
    to the ``+int8`` version — and the engine can swap BACK to a float
    publish afterwards."""
    serve_cfg = ServeConfig(quant_calib_batches=1, quant_max_delta=0.5)
    logger = RecordingLogger()
    ctx = QuantContext.build(model_def, MODEL_CFG, data_cfg, serve_cfg,
                             calib_batch_size=32, holdout=64)
    eng = ServingEngine.from_params(model_def, MODEL_CFG, data_cfg,
                                    params, None, version="3",
                                    logger=logger)
    eng.attach_program("int8", ctx.quant_fn,
                       (ctx.quantize(params), None))
    probe = _images(4, seed=5)
    before = eng.forward_timed_versioned(probe)[0]

    # Reject: max_delta=-1 fails any candidate (delta 0 > -1).
    ok, reason = gate_and_swap(eng, ctx, params, "9", logger=logger,
                               max_delta=-1.0)
    assert not ok and "exceeds" in reason
    rejects = logger.of("quant_rejected")
    assert len(rejects) == 1
    assert rejects[0]["version"] == "9+int8"
    assert rejects[0]["delta"] > rejects[0]["max_delta"]
    after, _, version = eng.forward_timed_versioned(probe)
    assert version == "3"                       # float kept serving
    np.testing.assert_array_equal(before, after)

    # Accept: the configured ceiling (generous on untrained weights).
    ok, _ = gate_and_swap(eng, ctx, params, "9", logger=logger)
    assert ok
    logits, _, version = eng.forward_timed_versioned(probe)
    assert version == "9+int8"
    assert np.isfinite(logits).all()
    # And back to float: the primary program still matches its spec.
    ok, _ = eng.try_swap(params, None, version="12")
    assert ok
    back, _, version = eng.forward_timed_versioned(probe)
    assert version == "12"
    np.testing.assert_array_equal(before, back)


# ---------------------------------------------------------------------------
# response cache
# ---------------------------------------------------------------------------

def test_response_cache_hit_miss_lru_and_capacity():
    c = ResponseCache(2)
    assert c.lookup(b"a", "v1") is None                 # miss
    c.store(b"a", "v1", {"class": 1})
    assert c.lookup(b"a", "v1") == {"class": 1}         # hit
    c.store(b"b", "v1", {"class": 2})
    assert c.lookup(b"a", "v1") == {"class": 1}         # refreshes LRU
    c.store(b"c", "v1", {"class": 3})                   # evicts b
    assert c.lookup(b"b", "v1") is None
    assert c.lookup(b"a", "v1") == {"class": 1}
    assert c.hits == 3 and c.misses == 2
    with pytest.raises(ValueError):
        ResponseCache(0)


def test_response_cache_flushes_on_version_change():
    c = ResponseCache(8)
    c.store(b"a", "3", {"class": 1})
    assert c.lookup(b"a", "3") == {"class": 1}
    # Hot-swap: the serving version moves -> every cached entry is for
    # dead weights and must go.
    assert c.lookup(b"a", "3+int8") is None
    assert len(c) == 0 and c.flushes == 1
    c.store(b"a", "3+int8", {"class": 2})
    assert c.lookup(b"a", "3+int8") == {"class": 2}
    # A stale store (computed by the OLD version, landing after the
    # swap) is dropped at lookup time, not served.
    c.store(b"b", "3", {"class": 9})
    assert c.lookup(b"b", "3+int8") is None


# ---------------------------------------------------------------------------
# JSONL schema: the new record kinds
# ---------------------------------------------------------------------------

def test_quant_record_kinds_schema_strict(tmp_path):
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
    from tools import check_jsonl_schema

    path = str(tmp_path / "quant.jsonl")
    logger = MetricsLogger(jsonl_path=path)
    logger.log("calibration", tensor="conv1/kernel", amax=1.25,
               scale=0.0098, channels=64, batches=4)
    logger.log("calibration", tensor="act/in", amax=2.64,
               scale=0.0208, channels=0, batches=4)
    logger.log("quant_rejected", replica_id=0, version="9+int8",
               float_top1=0.61, quant_top1=0.55, delta=0.06,
               max_delta=0.005, reason="accuracy delta 0.06 exceeds")
    logger.close()
    assert check_jsonl_schema.check_file(path, strict=True) == []
    # A calibration record missing its scale is a schema violation.
    with open(path, "a") as f:
        f.write(json.dumps({"kind": "calibration", "t": 1.0, "task": 0,
                            "tensor": "conv2/kernel", "amax": 0.5,
                            "channels": 64, "batches": 4}) + "\n")
    errs = check_jsonl_schema.check_file(path, strict=True)
    assert errs and "scale" in errs[0]


# ---------------------------------------------------------------------------
# loadgen --check_labels
# ---------------------------------------------------------------------------

def test_loadgen_check_labels_smoke(tmp_path, model_def, params):
    """End-to-end prediction verification: labels built from the
    model's own argmax must score accuracy 1.0 through the serving
    stack (any preprocessing/quantization drift in the serve path
    would break the equality)."""
    import tools.loadgen as loadgen
    from dml_cnn_cifar10_tpu.config import DataConfig

    dcfg = DataConfig(normalize="scale")
    imgs = _images(32, seed=11)
    fn = jax.jit(make_variable_serving_fn(model_def, MODEL_CFG, dcfg))
    labels = np.asarray(fn((params, None), imgs)).argmax(-1)
    npz = str(tmp_path / "check.npz")
    np.savez(npz, images=imgs, labels=labels)

    report_path = str(tmp_path / "report.json")
    assert loadgen.main([
        "--mode", "closed", "--concurrency", "2", "--duration_s", "1.0",
        "--buckets", "1,8", "--check_labels", npz,
        "--report", report_path]) == 0
    with open(report_path) as f:
        report = json.load(f)
    assert report["label_checked"] == report["completed"] > 0
    assert report["accuracy"] == 1.0
