"""ZeRO-1 sharded weight update + partition-rule engine + fused
single-pass optimizer kernel (ISSUE 9; docs/SHARDING.md).

Pins: the regex rule engine (ordering, alignment, strict mode, the CLI
grammar, the report); the zero1 layout being REAL (optimizer moments
allocated sharded 1/N on the live state) and PURE (final params within
1e-6 of the replicated path on the 8-device CPU sim, every step
builder); checkpoints interchanging across layouts through both codecs
including the sha256-sidecar fallback walk; and the fused optimizer's
equivalence tolerances (PARITY.md "Update-path equivalence").
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.ops import optimizer as fused_lib
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import shardings
from dml_cnn_cifar10_tpu.parallel import step as step_lib
from dml_cnn_cifar10_tpu.train import optim as optim_lib

DATA = DataConfig(normalize="scale")


def _mesh(data=8, model=1):
    return mesh_lib.build_mesh(
        ParallelConfig(data_axis=data, model_axis=model))


def _batch(rng, n=16, hw=24):
    images = rng.normal(0.5, 0.25, (n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


def _optim(**kw):
    kw.setdefault("learning_rate", 0.01)
    kw.setdefault("momentum", 0.9)
    kw.setdefault("weight_decay", 1e-4)
    return OptimConfig(**kw)


def _build(mesh, optim, model_cfg=None):
    model_cfg = model_cfg or ModelConfig(logit_relu=False)
    model_def = get_model(model_cfg.name)
    zero1 = optim.optimizer_sharding == "zero1"
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim, zero1=zero1)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    return state, train, sh


# ---------------------------------------------------------------------------
# partition-rule engine
# ---------------------------------------------------------------------------

def test_rules_first_match_wins_and_alignment():
    tree = {"blocks": {"qkv": {"kernel": jax.ShapeDtypeStruct(
                (4, 64, 192), jnp.float32)},
                       "step": jax.ShapeDtypeStruct((), jnp.int32)}}
    rules = (shardings.PartitionRule(r"qkv/kernel$", P("model")),
             shardings.PartitionRule(r".*", P("data", None)))
    specs = shardings.match_partition_rules(rules, tree)
    # First match wins (the catch-all never fires for qkv), spec is
    # right-aligned to rank 3; scalars never partition.
    assert specs["blocks"]["qkv"]["kernel"] == P(None, None, "model")
    assert specs["blocks"]["step"] == P()
    # Left alignment anchors at the leading axis, untrimmed.
    left = (shardings.PartitionRule(r".*", P("pipe"), align="left"),)
    assert shardings.match_partition_rules(
        left, tree)["blocks"]["qkv"]["kernel"] == P("pipe")
    # A spec wider than the leaf rank is a loud error, not silent junk.
    wide = (shardings.PartitionRule(
        r"step", P("model", None)),)
    with pytest.raises(ValueError, match="rank"):
        shardings.match_partition_rules(
            wide, {"step": jax.ShapeDtypeStruct((3,), jnp.float32)})


def test_rules_strict_mode_errors_on_unmatched():
    tree = {"a": jax.ShapeDtypeStruct((8,), jnp.float32),
            "b": jax.ShapeDtypeStruct((8,), jnp.float32)}
    rules = (shardings.PartitionRule(r"^a$", P("model")),)
    # Non-strict replicates the miss...
    assert shardings.match_partition_rules(rules, tree)["b"] == P()
    # ...strict names it.
    with pytest.raises(ValueError, match="b"):
        shardings.match_partition_rules(rules, tree, strict=True)
    # The built-in tables all end in a catch-all: strict never trips.
    model_def = get_model("cnn")
    params = jax.eval_shape(
        lambda k: model_def.init(k, ModelConfig(), DATA), jax.random.key(0))
    strict = shardings.param_pspecs("cnn", params, strict=True)
    assert strict["full1"]["kernel"] == P(None, "model")


def test_parse_partition_rules_grammar():
    rules = shardings.parse_partition_rules(
        "full1/(kernel|bias)$=model; full2/kernel$=model,-; "
        "blocks/=^pipe; odd=data+model,*; .*=replicated")
    assert [r.pattern for r in rules] == [
        "full1/(kernel|bias)$", "full2/kernel$", "blocks/", "odd", ".*"]
    assert rules[0].spec == P("model") and rules[0].align == "right"
    assert rules[1].spec == P("model", None)
    assert rules[2].spec == P("pipe") and rules[2].align == "left"
    assert rules[3].spec == P(("data", "model"), None)
    assert rules[4].spec == P()
    assert shardings.parse_partition_rules(None) is None
    assert shardings.parse_partition_rules("") is None
    with pytest.raises(ValueError, match="regex=spec"):
        shardings.parse_partition_rules("no-equals-sign")
    with pytest.raises(ValueError, match="bad regex"):
        shardings.parse_partition_rules("([unclosed=model")
    # The CNN default expressed as an override string reproduces the
    # built-in table's specs leaf-for-leaf.
    model_def = get_model("cnn")
    params = jax.eval_shape(
        lambda k: model_def.init(k, ModelConfig(), DATA), jax.random.key(0))
    override = shardings.parse_partition_rules(
        "full1/(kernel|bias)$=model; full2/kernel$=model,-; .*=")
    assert shardings.param_pspecs("cnn", params, rules=override) \
        == shardings.param_pspecs("cnn", params)


def test_partition_report_names_rule_per_param():
    model_def = get_model("cnn")
    params = jax.eval_shape(
        lambda k: model_def.init(k, ModelConfig(), DATA), jax.random.key(0))
    rows = shardings.explain_partition_rules(shardings.rule_for("cnn"),
                                             params)
    by_path = {r["path"]: r for r in rows}
    assert by_path["full1/kernel"]["rule"] == r"full1/(kernel|bias)$"
    assert by_path["full1/kernel"]["spec"] == P(None, "model")
    assert by_path["conv1/kernel"]["rule"] == r".*"
    report = shardings.format_partition_report(rows)
    assert "full1/kernel" in report and r"full1/(kernel|bias)$" in report


# ---------------------------------------------------------------------------
# zero1: real sharding + HBM win, asserted on the LIVE state
# ---------------------------------------------------------------------------

def test_zero1_state_allocated_sharded_and_smaller():
    """Acceptance: per-replica optimizer-state bytes drop by the dp
    factor on the live state — not computed on paper."""
    mesh = _mesh()
    state_z, _, _ = _build(mesh, _optim(optimizer_sharding="zero1"))
    state_n, _, _ = _build(mesh, _optim())

    k = state_z.opt["momentum"]["full1"]["kernel"]      # [2304, 384]
    assert "data" in str(k.sharding.spec)
    assert k.addressable_shards[0].data.shape[0] == 2304 // 8
    # Params stay in the model layout (replicated here) — zero1 shards
    # the UPDATE state only.
    assert state_z.params["full1"]["kernel"].sharding.spec == P(
        None, "model")
    assert not shardings.specs_name_axis(
        jax.tree.map(lambda x: x.sharding, state_z.params), "data")

    def device0_bytes(tree):
        total = 0
        for leaf in jax.tree.leaves(tree):
            shard = leaf.addressable_shards[0]
            total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
        return total

    z = device0_bytes(state_z.opt["momentum"])
    n = device0_bytes(state_n.opt["momentum"])
    # Every dp-divisible moment leaf holds 1/8 per replica; only the
    # handful of tiny non-divisible biases stay whole.
    assert z < n / 4, (z, n)


def test_zero1_rejects_invalid_compositions():
    mesh = _mesh()
    model_def = get_model("cnn")
    cfg = ModelConfig(logit_relu=False)
    with pytest.raises(ValueError, match="none | zero1"):
        step_lib.make_train_step(model_def, cfg,
                                 _optim(optimizer_sharding="zero3"), mesh)
    with pytest.raises(ValueError, match="explicit_collectives"):
        step_lib.make_train_step(model_def, cfg,
                                 _optim(optimizer_sharding="zero1"),
                                 mesh, explicit_collectives=True)
    with pytest.raises(ValueError, match="async_staleness"):
        step_lib.make_train_step(
            model_def, cfg,
            _optim(optimizer_sharding="zero1", async_staleness=2,
                   weight_decay=0.0), mesh)


def test_zero1_matches_replicated(rng):
    """Acceptance: zero1 is a pure layout/schedule change — final params
    within 1e-6 absolute of the replicated path after 3 steps on the
    8-device sim (the reduce-scatter may reorder the gradient sum;
    PARITY.md pins the tolerance)."""
    mesh = _mesh()
    images, labels = _batch(rng)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    def run(optim):
        state, train, _ = _build(mesh, optim)
        for _ in range(3):
            state, metrics = train(state, im, lb)
        return state, float(jax.device_get(metrics["loss"]))

    st_n, loss_n = run(_optim())
    st_z, loss_z = run(_optim(optimizer_sharding="zero1"))
    assert np.isfinite(loss_n) and np.isfinite(loss_z)
    np.testing.assert_allclose(loss_n, loss_z, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(st_n.params),
                    jax.tree.leaves(st_z.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=0, atol=1e-6)
    # The momentum trace agrees too (it IS the sharded state).
    for a, b in zip(jax.tree.leaves(st_n.opt["momentum"]),
                    jax.tree.leaves(st_z.opt["momentum"])):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=0, atol=1e-6)


@pytest.mark.slow
def test_zero1_chunked_matches_plain_step(rng):
    """The chunked builder rides the same _step_body seam: K scanned
    zero1 steps == K plain-step zero1 steps == K replicated steps."""
    mesh = _mesh()
    images, labels = _batch(rng, n=32)
    k = 2
    ims = images.reshape(k, 16, 24, 24, 3)
    lbs = labels.reshape(k, 16)
    optim = _optim(optimizer_sharding="zero1")
    model_def = get_model("cnn")
    cfg = ModelConfig(logit_relu=False)
    sh = step_lib.train_state_shardings(mesh, model_def, cfg, DATA, optim,
                                        zero1=True)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, cfg, DATA, optim, mesh,
        state_sharding=sh)
    chunk = step_lib.make_train_chunk(model_def, cfg, optim, mesh,
                                      state_sharding=sh)
    im, lb = mesh_lib.shard_batch(mesh, ims, lbs, leading_dims=1)
    state, _ = chunk(state, im, lb)

    ref, train, _ = _build(mesh, optim)
    for i in range(k):
        b = mesh_lib.shard_batch(mesh, ims[i], lbs[i])
        ref, _ = train(ref, *b)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(ref.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=0, atol=1e-6)


@pytest.mark.slow
def test_zero1_composes_with_tp(rng):
    """data=4 x model=2: the col-parallel kernel's momentum carries BOTH
    axes, and zero1 on that mesh matches the replicated update ON THE
    SAME MESH within the pinned tolerance (comparing against a
    different mesh shape would fold unrelated tp-reduction reorderings
    into the delta)."""
    mesh = _mesh(data=4, model=2)
    images, labels = _batch(rng)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    state, train, _ = _build(mesh, _optim(optimizer_sharding="zero1"))
    m = state.opt["momentum"]["full1"]["kernel"]
    assert m.sharding.spec == P("data", "model")
    assert m.addressable_shards[0].data.shape == (2304 // 4, 384 // 2)
    for _ in range(2):
        state, metrics = train(state, im, lb)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))

    ref, rtrain, _ = _build(mesh, _optim())
    for _ in range(2):
        ref, _ = rtrain(ref, im, lb)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(ref.params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=0, atol=1e-6)


# ---------------------------------------------------------------------------
# checkpoints interchange across layouts (both codecs + sidecar fallback)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fmt", ["msgpack", "sharded"])
def test_checkpoint_cross_layout_roundtrip(tmp_path, rng, fmt):
    """Save under zero1, restore under none — and the reverse — through
    the flat AND sharded codecs: params bit-identical, restored state
    trains on (donated-buffer layouts line up)."""
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    mesh = _mesh()
    images, labels = _batch(rng)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)

    state_z, train_z, sh_z = _build(mesh, _optim(optimizer_sharding="zero1"))
    state_z, _ = train_z(state_z, im, lb)
    state_n, train_n, sh_n = _build(mesh, _optim())
    state_n, _ = train_n(state_n, im, lb)

    # zero1 -> none
    d1 = str(tmp_path / f"z2n_{fmt}")
    ckpt_lib.save_checkpoint(d1, state_z, step=1, fmt=fmt)
    fresh = step_lib.init_train_state(
        jax.random.key(7), get_model("cnn"), ModelConfig(logit_relu=False),
        DATA, _optim(), mesh, state_sharding=sh_n)
    restored = ckpt_lib.restore_checkpoint(d1, fresh, sharding=sh_n)
    for a, b in zip(jax.tree.leaves(state_z.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))
    assert restored.opt["momentum"]["full1"]["kernel"].sharding.spec \
        == P(None, "model")
    restored, metrics = train_n(restored, im, lb)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))

    # none -> zero1 (the moments re-shard onto the data axis)
    d2 = str(tmp_path / f"n2z_{fmt}")
    ckpt_lib.save_checkpoint(d2, state_n, step=1, fmt=fmt)
    fresh = step_lib.init_train_state(
        jax.random.key(7), get_model("cnn"), ModelConfig(logit_relu=False),
        DATA, _optim(optimizer_sharding="zero1"), mesh, state_sharding=sh_z)
    restored = ckpt_lib.restore_checkpoint(d2, fresh, sharding=sh_z)
    for a, b in zip(jax.tree.leaves(state_n.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))
    m = restored.opt["momentum"]["full1"]["kernel"]
    assert "data" in str(m.sharding.spec)
    assert m.addressable_shards[0].data.shape[0] == 2304 // 8
    restored, metrics = train_z(restored, im, lb)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_checkpoint_cross_layout_sidecar_fallback(tmp_path, rng):
    """A corrupt LATEST checkpoint (sha256 sidecar catches it) falls
    back to the older candidate, which still restores cross-layout."""
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

    mesh = _mesh()
    images, labels = _batch(rng)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    state_z, train_z, sh_z = _build(mesh, _optim(optimizer_sharding="zero1"))
    state_z, _ = train_z(state_z, im, lb)
    good = jax.device_get(state_z.params)
    d = str(tmp_path / "fb")
    ckpt_lib.save_checkpoint(d, state_z, step=1)
    state_z, _ = train_z(state_z, im, lb)
    path2 = ckpt_lib.save_checkpoint(d, state_z, step=2)
    # Flip a byte mid-file: the sidecar digest no longer matches.
    with open(path2, "r+b") as f:
        f.seek(os.path.getsize(path2) // 2)
        byte = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([byte[0] ^ 0xFF]))

    fallbacks = []
    _, sh_n = _build(mesh, _optim())[1:]
    fresh = step_lib.init_train_state(
        jax.random.key(7), get_model("cnn"), ModelConfig(logit_relu=False),
        DATA, _optim(), mesh, state_sharding=sh_n)
    restored = ckpt_lib.restore_checkpoint(
        d, fresh, sharding=sh_n,
        on_fallback=lambda step, path, reason, walk_ms: fallbacks.append(
            step))
    assert fallbacks == [2]
    assert int(jax.device_get(restored.step)) == 1
    for a, b in zip(jax.tree.leaves(good), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a),
                                      np.asarray(jax.device_get(b)))


# ---------------------------------------------------------------------------
# fused single-pass optimizer (ops/optimizer.py)
# ---------------------------------------------------------------------------

def _leaves(rng):
    # Deliberately tile-hostile shapes: a sub-tile vector, a ragged
    # matrix, and a lane-aligned one — the pad/reshape must be exact.
    shapes = [(37,), (130, 7), (256, 128)]
    mk = lambda: {f"l{i}": jnp.asarray(rng.normal(size=s), jnp.float32)
                  for i, s in enumerate(shapes)}
    return mk(), mk(), mk()


def test_fused_kernel_matches_fallback_interpret(rng):
    """The Pallas kernel (interpret mode on CPU) vs the XLA fallback:
    within a few f32 ULPs (FMA contraction; PARITY.md pins <= 5e-7)."""
    params, grads, mom = _leaves(rng)
    lr = jnp.float32(0.05)
    for m, mu, wd in ((mom, 0.9, 1e-4), (mom, 0.9, 0.0), (None, 0.0, 0.0)):
        pk, mk = fused_lib.fused_sgd_update(
            params, grads, m, lr, mu, wd, use_pallas=True, interpret=True)
        pf, mf = fused_lib.fused_sgd_update(
            params, grads, m, lr, mu, wd, use_pallas=False)
        for key in params:
            np.testing.assert_allclose(np.asarray(pk[key]),
                                       np.asarray(pf[key]),
                                       rtol=0, atol=5e-7)
            if m is not None:
                np.testing.assert_allclose(np.asarray(mk[key]),
                                           np.asarray(mf[key]),
                                           rtol=0, atol=5e-7)
        if m is None:
            assert mk is None and mf is None


def test_fused_update_bit_identical_to_legacy_chain(rng):
    """sgd_update with fused_optimizer on vs off (the historical
    tree_map chain): bit-identical on the XLA path — same expression."""
    params, grads, _ = _leaves(rng)
    for mu, wd in ((0.9, 1e-4), (0.9, 0.0), (0.0, 0.0), (0.0, 1e-4)):
        def run(fused):
            cfg = OptimConfig(learning_rate=0.05, momentum=mu,
                              weight_decay=wd, fused_optimizer=fused)
            state = optim_lib.sgd_init(params, cfg)
            return jax.jit(
                lambda g, s, p: optim_lib.sgd_update(g, s, p, cfg))(
                    grads, state, params)
        (p1, s1), (p0, s0) = run(True), run(False)
        for key in params:
            np.testing.assert_array_equal(np.asarray(p1[key]),
                                          np.asarray(p0[key]))
        if mu:
            for key in params:
                np.testing.assert_array_equal(
                    np.asarray(s1["momentum"][key]),
                    np.asarray(s0["momentum"][key]))
        assert int(s1["step"]) == int(s0["step"]) == 1


def test_fused_platform_selection():
    """The Pallas lowering is TPU-only and never engages under a
    GSPMD-sharded (zero1) update — the partitioner cannot split an
    opaque custom call."""
    assert fused_lib._use_pallas("none") == (
        jax.default_backend() == "tpu")
    assert fused_lib._use_pallas("zero1") is False


# ---------------------------------------------------------------------------
# optimizer_ms attribution (satellite; utils/devprof.py)
# ---------------------------------------------------------------------------

def test_devtime_optimizer_scope_bucket():
    from dml_cnn_cifar10_tpu.utils import devprof

    doc = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "name": "fwd_bwd/conv.1", "pid": 7, "tid": 0,
         "ts": 0.0, "dur": 900.0},
        {"ph": "X", "name": "optimizer/fusion.2", "pid": 7, "tid": 0,
         "ts": 1000.0, "dur": 250.0},
        # Scope carried in profiler metadata args, not the short name.
        {"ph": "X", "name": "fusion.9", "pid": 7, "tid": 0,
         "ts": 1300.0, "dur": 50.0,
         "args": {"long_name": "optimizer/add.3"}},
    ]}
    lane = devprof.parse_trace_doc(doc)[0]
    assert lane["optimizer_ms"] == pytest.approx(0.3)
    # Overlapping scope total: also counted in the exclusive buckets.
    assert lane["compute_ms"] == pytest.approx(1.2)
    assert lane["total_ms"] == pytest.approx(1.2)


def test_profile_window_feeds_optimizer_step_ms(tmp_path, monkeypatch):
    from dml_cnn_cifar10_tpu.utils import devprof

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    lanes = [{"device": "/device:TPU:0", "total_ms": 10.0,
              "compute_ms": 10.0, "collective_ms": 0.0, "infeed_ms": 0.0,
              "optimizer_ms": 4.0, "window_ms": 12.0, "top_ops": []}]
    monkeypatch.setattr(devprof, "parse_profile_dir",
                        lambda d, top_k=12: lanes)
    sink = []

    class Logger:
        def log(self, kind, **fields):
            sink.append({"kind": kind, **fields})

    win = devprof.ProfileWindow(10, 4, str(tmp_path), logger=Logger())
    win.maybe_start(10)
    win.maybe_stop(18, drained=True)        # 8 steps in the window
    assert win.optimizer_step_ms == pytest.approx(0.5)
    assert sink and sink[0]["kind"] == "devtime"
    assert sink[0]["optimizer_ms"] == 4.0


def test_bench_gate_fp32_zero1_row():
    """The zero1 bench row joins the perf gate with its own tolerance
    entry: a within-tolerance candidate passes, a regressed one fails,
    and baselines that predate the row skip it (never fail)."""
    from tools import bench_gate

    assert "fp32_zero1" in bench_gate.ROW_KEYS
    assert "fp32_zero1" in bench_gate.ROW_TOLERANCES

    def report(z_ips=None):
        doc = {"metric": "train_throughput", "value": 1000.0,
               "fp32": {"images_per_sec_per_chip": 1000.0}}
        if z_ips is not None:
            doc["fp32_zero1"] = {"images_per_sec_per_chip": z_ips,
                                 "optimizer_ms": 0.01}
        return doc

    baselines = [report(900.0), report(910.0), report(905.0)]
    ok = bench_gate.gate(report(880.0), baselines)       # -2.8% < 8%
    assert all(c["ok"] for c in ok)
    bad = bench_gate.gate(report(700.0), baselines)      # -22.7%
    assert any(not c["ok"] and c["row"] == "fp32_zero1" for c in bad)
    # Old baselines without the row: the candidate's row is unjudged on
    # throughput-vs-median (no medians) — nothing fails.
    legacy = [report(), report(), report()]
    assert all(c["ok"] for c in bench_gate.gate(report(500.0), legacy))
