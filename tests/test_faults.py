"""Resilience layer: deterministic fault injection (utils/faults.py),
the on_nonfinite policy (train/loop.py), and the recovery supervisor
(train/supervisor.py). Every recovery path the framework claims runs
here on CPU — the ISSUE-3 acceptance smoke injects a non-finite loss
AND a corrupted latest checkpoint and requires the run to finish at the
requested step with a schema-clean fault/recovery JSONL trail."""

import json
import os
import signal
import threading

import numpy as np
import pytest

import jax

from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
from dml_cnn_cifar10_tpu.train.loop import Trainer
from dml_cnn_cifar10_tpu.train.supervisor import (classify_failure,
                                                  fit_supervised)
from dml_cnn_cifar10_tpu.utils import faults as faults_lib
from tests.conftest import tiny_train_cfg


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _resilient_cfg(data_cfg, tmpdir, total_steps=40):
    cfg = tiny_train_cfg(data_cfg, tmpdir, total_steps=total_steps)
    cfg.checkpoint_every = 10
    cfg.output_every = 10
    cfg.eval_every = 20
    cfg.check_numerics = True
    cfg.recovery_backoff_s = 0.01
    cfg.metrics_jsonl = os.path.join(tmpdir, "m.jsonl")
    return cfg


# ---------------------------------------------------------------------------
# fault-spec grammar + injector mechanics
# ---------------------------------------------------------------------------

def test_fault_spec_parsing():
    inj = faults_lib.FaultInjector.from_spec(
        "nan@120, ckpt_corrupt@200,sigterm@300,data_stall@400")
    assert [(e.kind, e.step) for e in inj.events] == [
        ("nan", 120), ("ckpt_corrupt", 200), ("sigterm", 300),
        ("data_stall", 400)]
    # Duplicates allowed (re-poison after a recovery), ordered by step.
    inj2 = faults_lib.FaultInjector.from_spec("nan@50,nan@10")
    assert [(e.kind, e.step) for e in inj2.events] == [("nan", 10),
                                                      ("nan", 50)]
    assert faults_lib.FaultInjector.from_spec(None) is None
    assert faults_lib.FaultInjector.from_spec("") is None
    for bad in ("bogus@10", "nan@x", "nan120", "nan@-3"):
        with pytest.raises(ValueError):
            faults_lib.parse_fault_spec(bad)


def test_injector_fires_once_at_trigger():
    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            OptimConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import step as step_lib

    state = step_lib.init_train_state(
        jax.random.key(0), get_model("cnn"), ModelConfig(), DataConfig(),
        OptimConfig())
    inj = faults_lib.FaultInjector.from_spec("nan@10")
    # Below the trigger: untouched, still pending.
    s1 = inj.step_hook(9, state, log_dir="/nonexistent")
    assert s1 is state and len(inj.pending()) == 1
    # At the trigger: exactly one leaf poisoned, event consumed.
    s2 = inj.step_hook(10, state, log_dir="/nonexistent")
    leaves = jax.tree.leaves(s2.params)
    assert any(not np.isfinite(np.asarray(x)).all() for x in leaves)
    assert inj.pending() == []
    # One-shot: a later step does not re-poison.
    s3 = inj.step_hook(11, state, log_dir="/nonexistent")
    assert s3 is state


def test_ckpt_corrupt_defers_until_checkpoint_exists(tmp_path):
    inj = faults_lib.FaultInjector.from_spec("ckpt_corrupt@1")
    assert inj.step_hook(5, None, log_dir=str(tmp_path)) is None
    assert len(inj.pending()) == 1  # nothing to corrupt yet

    from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig,
                                            OptimConfig)
    from dml_cnn_cifar10_tpu.models.registry import get_model
    from dml_cnn_cifar10_tpu.parallel import step as step_lib
    state = step_lib.init_train_state(
        jax.random.key(0), get_model("cnn"), ModelConfig(), DataConfig(),
        OptimConfig())
    path = ckpt_lib.save_checkpoint(str(tmp_path), state, step=3)
    inj.step_hook(6, None, log_dir=str(tmp_path))
    assert inj.pending() == []
    ok, reason = ckpt_lib.verify_checkpoint(path)
    assert not ok and "mismatch" in reason


def test_cluster_fault_kinds_parse_and_require_monitor():
    """The cluster kinds parse like any other; firing one without a
    ClusterMonitor fails loudly — a cluster drill that silently no-ops
    would void its test (tests/test_cluster.py runs the real ones)."""
    inj = faults_lib.FaultInjector.from_spec(
        "heartbeat_stall@5,host_lost@9,collective_hang@12")
    assert [(e.kind, e.step) for e in inj.events] == [
        ("heartbeat_stall", 5), ("host_lost", 9),
        ("collective_hang", 12)]
    for spec in ("heartbeat_stall@1", "collective_hang@1"):
        with pytest.raises(faults_lib.InjectedFault, match="cluster_dir"):
            faults_lib.FaultInjector.from_spec(spec).step_hook(
                2, None, log_dir="/nonexistent")


def test_classify_failure():
    from dml_cnn_cifar10_tpu.data.pipeline import DataPipelineError
    from dml_cnn_cifar10_tpu.parallel.cluster import PeerLostError
    assert classify_failure(PeerLostError([1], "stale")) == "peer_lost"
    assert classify_failure(faults_lib.DataStallError("x")) == "data"
    assert classify_failure(DataPipelineError("x")) == "data"
    assert classify_failure(FloatingPointError("nan")) == "nonfinite"
    assert classify_failure(
        ValueError("failed to restore checkpoint /x: bad")) \
        == "ckpt_restore"
    assert classify_failure(ValueError("something else")) is None
    assert classify_failure(RuntimeError("boom")) is None


# ---------------------------------------------------------------------------
# the acceptance smoke: nan + ckpt_corrupt, supervised recovery
# ---------------------------------------------------------------------------

def test_supervisor_recovers_nan_and_corrupt_checkpoint(data_cfg,
                                                        tmp_path):
    """Inject a poisoned state at step 25 and corrupt the latest
    checkpoint (step 20) at step 26. Under on_nonfinite=rollback the
    boundary at 30 raises, the supervisor restores — walking past the
    corrupt ckpt_20 to the verified ckpt_10 — rewinds the data streams,
    and the run completes to the requested 40 steps with final params
    BIT-IDENTICAL to a fault-free run (the exact-resume contract)."""
    cfg = _resilient_cfg(data_cfg, str(tmp_path / "faulty"))
    cfg.on_nonfinite = "rollback"
    cfg.fault_spec = "nan@25,ckpt_corrupt@26"
    result = fit_supervised(cfg)
    assert result.final_step == 40

    clean = _resilient_cfg(data_cfg, str(tmp_path / "clean"))
    clean.metrics_jsonl = None
    ref = Trainer(clean).fit()
    for a, b in zip(jax.tree.leaves(result.state.params),
                    jax.tree.leaves(ref.state.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))

    recs = _read_jsonl(cfg.metrics_jsonl)
    injected = {r["fault"] for r in recs
                if r["kind"] == "fault" and r.get("injected")}
    assert injected == {"nan", "ckpt_corrupt"}
    detected = [r for r in recs
                if r["kind"] == "fault" and not r.get("injected")]
    assert any(r["fault"] == "nonfinite" for r in detected)
    restarts = [r for r in recs if r["kind"] == "recovery"
                and r["action"] == "restart"]
    assert restarts and restarts[0]["fault"] == "nonfinite"
    rollbacks = [r for r in recs if r["kind"] == "rollback"]
    assert rollbacks and rollbacks[0]["restore_step"] == 20
    # The corrupt ckpt_20 was skipped by the restore walk: fallback
    # record names it, and training actually resumed from ckpt_10.
    fallbacks = [r for r in recs if r["kind"] == "ckpt_fallback"]
    assert any(r["step"] == 20 for r in fallbacks)
    # The stream passes the documented-schema lint.
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl, strict=True) == []
    # And the report CLI summarizes the recovery.
    from tools import telemetry_report
    out = telemetry_report.summarize(cfg.metrics_jsonl)
    assert "resilience" in out and "restart" in out


def test_supervisor_recovers_injected_data_stall(data_cfg, tmp_path):
    cfg = _resilient_cfg(data_cfg, str(tmp_path), total_steps=30)
    cfg.fault_spec = "data_stall@15"
    result = fit_supervised(cfg)
    assert result.final_step == 30
    recs = _read_jsonl(cfg.metrics_jsonl)
    restarts = [r for r in recs if r["kind"] == "recovery"
                and r["action"] == "restart"]
    assert restarts and restarts[0]["fault"] == "data"


def test_supervisor_budget_exhaustion_reraises(data_cfg, tmp_path):
    """Every recovery has a bounded budget: more stalls than retries
    must surface the original failure, not loop forever."""
    cfg = _resilient_cfg(data_cfg, str(tmp_path), total_steps=30)
    cfg.recovery_retries = 1
    cfg.fault_spec = "data_stall@5,data_stall@15"
    with pytest.raises(faults_lib.DataStallError):
        fit_supervised(cfg)


def test_supervisor_does_not_retry_halt_policy(data_cfg, tmp_path):
    """on_nonfinite=halt means halt even under the supervisor — the
    policy flag, not the wrapper, decides."""
    cfg = _resilient_cfg(data_cfg, str(tmp_path), total_steps=30)
    cfg.on_nonfinite = "halt"
    cfg.fault_spec = "nan@5"
    with pytest.raises(FloatingPointError):
        fit_supervised(cfg)


# ---------------------------------------------------------------------------
# on_nonfinite=skip inside one fit()
# ---------------------------------------------------------------------------

def test_on_nonfinite_skip_discards_update_and_continues(data_cfg,
                                                         tmp_path):
    cfg = _resilient_cfg(data_cfg, str(tmp_path))
    cfg.on_nonfinite = "skip"
    cfg.fault_spec = "nan@15"
    result = Trainer(cfg).fit()
    assert result.final_step == 40
    # Final state is finite: the poisoned updates were discarded.
    assert all(np.isfinite(np.asarray(jax.device_get(x))).all()
               for x in jax.tree.leaves(result.state.params))
    recs = _read_jsonl(cfg.metrics_jsonl)
    skips = [r for r in recs if r["kind"] == "recovery"
             and r["action"] == "skip"]
    assert len(skips) == 1 and skips[0]["attempt"] == 1
    # Boundaries after the skip are finite again.
    trains = [r for r in recs if r["kind"] == "train"]
    assert trains[-1]["loss"] is not None
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl, strict=True) == []


def test_on_nonfinite_skip_budget_degrades_to_halt(data_cfg, tmp_path):
    cfg = _resilient_cfg(data_cfg, str(tmp_path))
    cfg.on_nonfinite = "skip"
    cfg.recovery_retries = 1
    cfg.fault_spec = "nan@3,nan@13"   # re-poison after the first skip
    with pytest.raises(FloatingPointError, match="non-finite"):
        Trainer(cfg).fit()


def test_bad_on_nonfinite_rejected(data_cfg, tmp_path):
    cfg = tiny_train_cfg(data_cfg, str(tmp_path))
    cfg.on_nonfinite = "explode"
    with pytest.raises(ValueError, match="on_nonfinite"):
        Trainer(cfg)


# ---------------------------------------------------------------------------
# sigterm injection → PreemptionGuard clean exit
# ---------------------------------------------------------------------------

def test_sigterm_fault_checkpoints_and_exits_cleanly(data_cfg, tmp_path):
    before = signal.getsignal(signal.SIGTERM)
    cfg = _resilient_cfg(data_cfg, str(tmp_path), total_steps=100)
    cfg.fault_spec = "sigterm@12"
    result = Trainer(cfg).fit()
    assert result.preempted
    assert 12 <= result.final_step < 100
    # The forced preemption save landed and verifies.
    steps = ckpt_lib.all_checkpoint_steps(cfg.log_dir)
    assert result.final_step in steps
    ok, _ = ckpt_lib.verify_checkpoint(
        ckpt_lib.latest_checkpoint(cfg.log_dir))
    assert ok
    # Guard restored the previous handler on exit.
    assert signal.getsignal(signal.SIGTERM) is before


# ---------------------------------------------------------------------------
# guarded_save: a due save must never persist a non-finite state
# ---------------------------------------------------------------------------

def test_guarded_save_refuses_to_persist_nonfinite_state(data_cfg,
                                                         tmp_path):
    """Checkpoint cadence fires between metrics boundaries while the
    state is poisoned: the save-time numerics fetch must halt BEFORE
    writing, leaving only pre-poison checkpoints on disk."""
    cfg = _resilient_cfg(data_cfg, str(tmp_path), total_steps=20)
    cfg.checkpoint_every = 5
    cfg.fault_spec = "nan@11"       # poison after the step-10 save
    with pytest.raises(FloatingPointError, match="non-finite"):
        Trainer(cfg).fit()
    steps = sorted(ckpt_lib.all_checkpoint_steps(cfg.log_dir))
    assert steps == [5, 10]         # the due step-15 save was refused
    for s in steps:
        ok, _ = ckpt_lib.verify_checkpoint(
            os.path.join(cfg.log_dir, f"ckpt_{s}.msgpack"))
        assert ok


# ---------------------------------------------------------------------------
# PreemptionGuard off the main thread (satellite)
# ---------------------------------------------------------------------------

def test_preemption_guard_is_noop_off_main_thread():
    before = signal.getsignal(signal.SIGTERM)
    out = {}

    def run():
        from dml_cnn_cifar10_tpu.utils.preemption import PreemptionGuard
        guard = PreemptionGuard()
        with guard:
            out["saved"] = dict(guard._saved)
            out["requested"] = guard.requested
            out["handler_during"] = signal.getsignal(signal.SIGTERM)

    t = threading.Thread(target=run)
    t.start()
    t.join()
    assert out["saved"] == {}            # no handlers touched
    assert out["requested"] is False
    assert out["handler_during"] is before
    assert signal.getsignal(signal.SIGTERM) is before
