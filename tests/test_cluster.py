"""Cluster resilience (parallel/cluster.py): heartbeats, the collective
watchdog, coordinated elastic restart — and the ISSUE-4 acceptance
smokes: 2-process CPU lockstep simulations where one host stalls its
heartbeats / dies abruptly, the survivor classifies the fault, executes
a coordinated elastic restart at reduced world size, and finishes with
params BIT-IDENTICAL to a fault-free single-process run restored from
the same checkpoint."""

import hashlib
import json
import os
import shutil
import subprocess
import sys
import time

import numpy as np
import pytest

from dml_cnn_cifar10_tpu.parallel import cluster as cluster_lib
from dml_cnn_cifar10_tpu.utils import backoff

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeLogger:
    def __init__(self):
        self.records = []

    def log(self, kind, **fields):
        self.records.append({"kind": kind, **fields})

    def flush(self):
        pass

    def kinds(self):
        return [r["kind"] for r in self.records]


# ---------------------------------------------------------------------------
# backoff helper (satellite): deterministic, reproducible, capped
# ---------------------------------------------------------------------------

def test_backoff_schedule_is_deterministic_and_capped():
    plan = backoff.schedule(0.5, 30.0, 10)
    assert plan == [0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 30.0, 30.0, 30.0,
                    30.0]
    # Reproducible: the same budget always yields the same sleep plan.
    assert plan == backoff.schedule(0.5, 30.0, 10)
    assert backoff.delay_s(0.5, 30.0, 3) == 2.0
    with pytest.raises(ValueError):
        backoff.delay_s(0.5, 30.0, 0)
    # The supervisor's sleeps ARE this plan (same helper, same args).
    from dml_cnn_cifar10_tpu.config import TrainConfig
    cfg = TrainConfig()
    assert backoff.schedule(cfg.recovery_backoff_s,
                            cfg.recovery_backoff_max_s, 3) == \
        [0.5, 1.0, 2.0]


# ---------------------------------------------------------------------------
# heartbeat store
# ---------------------------------------------------------------------------

def test_heartbeat_store_roundtrip(tmp_path):
    a = cluster_lib.HeartbeatStore(str(tmp_path), 0)
    b = cluster_lib.HeartbeatStore(str(tmp_path), 1)
    a.publish(7, "train")
    beat = b.read(0)
    assert beat.process_id == 0 and beat.step == 7
    assert beat.phase == "train" and beat.age_s() < 5.0
    assert b.read(3) is None                      # never published
    b.publish(0, "init")
    peers = a.read_peers([0, 1])                  # self excluded
    assert list(peers) == [1] and peers[1].step == 0


# ---------------------------------------------------------------------------
# restart coordinator
# ---------------------------------------------------------------------------

def test_restart_coordinator_record_await_and_monotone_epoch(tmp_path):
    c = cluster_lib.RestartCoordinator(str(tmp_path))
    assert c.read() is None
    d = c.record(cluster_lib.RestartDecision(
        epoch=1, world_size=1, restore_step=10, survivors=[0]))
    got = c.await_decision(min_epoch=1, timeout_s=1.0)
    assert got == d
    with pytest.raises(ValueError, match="monotone"):
        c.record(cluster_lib.RestartDecision(
            epoch=1, world_size=1, restore_step=10, survivors=[0]))
    # A chief that never decides is a coordinator loss, not a hang.
    with pytest.raises(cluster_lib.PeerLostError) as ei:
        c.await_decision(min_epoch=2, timeout_s=0.15, poll_s=0.02)
    assert ei.value.process_ids == [0]


def _monitor(tmp_path, pid, n=2, logger=None, **kw):
    kw.setdefault("heartbeat_interval_s", 0.05)
    kw.setdefault("straggler_after_s", 0.1)
    kw.setdefault("peer_dead_after_s", 0.5)
    kw.setdefault("collective_timeout_s", 60.0)
    return cluster_lib.ClusterMonitor(
        str(tmp_path), pid, n, logger=logger or FakeLogger(), **kw)


# ---------------------------------------------------------------------------
# watchdog classification: straggler vs. host loss
# ---------------------------------------------------------------------------

def test_watchdog_classifies_straggler_then_dead(tmp_path):
    log = FakeLogger()
    mon = _monitor(tmp_path, 0, logger=log)
    peer = cluster_lib.HeartbeatStore(str(tmp_path), 1)
    try:
        peer.publish(3, "train")
        mon.watchdog.arm(8)
        # Fresh beat, behind my step: straggler telemetry, not death.
        mon.watchdog.check_peers()
        assert [r for r in log.records if r["kind"] == "straggler"
                and r["process_id"] == 1 and r["behind_steps"] == 5]
        assert not mon.watchdog.dead_peers
        # The SAME beat, read after its age passed peer_dead_after_s:
        # hang/host-loss. Synthetic `now` — no wall-clock sleeps.
        mon.watchdog.check_peers(now=time.time() + 1.0)
        assert mon.watchdog.dead_peers == {1}
        lost = [r for r in log.records if r["kind"] == "peer_lost"]
        assert lost and lost[0]["process_id"] == 1
        assert lost[0]["reason"] == "stale_heartbeat"
        with pytest.raises(cluster_lib.PeerLostError) as ei:
            mon.begin_step(9)
        assert ei.value.process_ids == [1]
    finally:
        mon.close()


def test_watchdog_aborts_wedged_seam(tmp_path):
    """Main thread presumed stuck in XLA past collective_timeout_s: the
    watchdog must abort the process (stubbed here) after classifying —
    self_hang when peers are fine, peer_dead when a corpse was found."""
    aborted = []
    log = FakeLogger()
    mon = cluster_lib.ClusterMonitor(
        str(tmp_path), 0, 2, heartbeat_interval_s=0.05,
        straggler_after_s=0.05, peer_dead_after_s=30.0,
        collective_timeout_s=0.2, logger=log,
        abort_fn=lambda verdict: aborted.append(verdict))
    peer = cluster_lib.HeartbeatStore(str(tmp_path), 1)
    try:
        mon.watchdog.arm(4)
        deadline = time.time() + 5.0
        while not aborted and time.time() < deadline:
            peer.publish(9, "train")      # alive and ahead: I am the hang
            time.sleep(0.05)
        assert aborted and aborted[0] == "self_hang"
        assert any(r["kind"] == "peer_lost"
                   and r["reason"] == "watchdog_abort_self_hang"
                   for r in log.records)
    finally:
        mon.close()


def test_heartbeat_stall_freezes_beats(tmp_path):
    mon = _monitor(tmp_path, 0, n=1)
    try:
        mon.begin_step(5)
        mon.end_step(6)
        mon.stall_heartbeats()
        time.sleep(0.1)    # let any in-flight background publish land
        before = mon.store.read(0)
        time.sleep(0.2)                   # >> heartbeat_interval_s
        after = mon.store.read(0)
        assert after.wallclock == before.wallclock
    finally:
        mon.close()


# ---------------------------------------------------------------------------
# eviction + world-shrink decisions
# ---------------------------------------------------------------------------

def test_eviction_fences_excluded_process(tmp_path):
    log = FakeLogger()
    mon = _monitor(tmp_path, 1, logger=log)
    try:
        mon.coordinator.record(cluster_lib.RestartDecision(
            epoch=1, world_size=1, restore_step=20, survivors=[0]))
        with pytest.raises(cluster_lib.EvictedError):
            mon.check_evicted(25)
        assert any(r["kind"] == "peer_lost" and r["reason"] == "evicted"
                   for r in log.records)
        # await_restart fences too (the non-chief survivor seat).
        mon.epoch = 0
        with pytest.raises(cluster_lib.EvictedError):
            mon.await_restart(timeout_s=1.0)
    finally:
        mon.close()


def test_decide_restart_shrinks_world_and_enforces_min_hosts(tmp_path):
    mon = _monitor(tmp_path, 0, n=3, min_hosts=2)
    try:
        d = mon.decide_restart([2], restore_step=30)
        assert d.world_size == 2 and d.survivors == [0, 1]
        assert d.epoch == 1 and d.restore_step == 30
        mon.adopt(d)
        assert mon.world_size() == 2 and mon.epoch == 1
        # Next loss would leave 1 < min_hosts=2: halt, don't degrade.
        with pytest.raises(cluster_lib.PeerLostError, match="min_hosts"):
            mon.decide_restart([1], restore_step=30)
    finally:
        mon.close()


def test_chief_role_falls_to_lowest_live_process(tmp_path):
    mon = _monitor(tmp_path, 1, n=3)
    try:
        assert not mon.is_chief
        mon.watchdog.dead_peers.add(0)    # coordinator-loss: 0 is gone
        assert mon.is_chief               # 1 inherits the decision pen
    finally:
        mon.close()


def test_from_config_is_off_without_cluster_dir():
    from dml_cnn_cifar10_tpu.config import ParallelConfig
    assert cluster_lib.ClusterMonitor.from_config(ParallelConfig()) is None


# ---------------------------------------------------------------------------
# the acceptance smokes: 2-process lockstep simulation, one host fails,
# the survivor elastically restarts, params stay bit-identical
# ---------------------------------------------------------------------------

WORKER = """
import json, sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
task, n, data_dir, log_dir, cluster_dir, fault_spec, total_steps = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6], int(sys.argv[7]))
import hashlib
import numpy as np
import jax
from dml_cnn_cifar10_tpu.config import TrainConfig, DataConfig
from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised

cfg = TrainConfig(
    batch_size=32, total_steps=total_steps, output_every=10,
    eval_every=20, checkpoint_every=10, log_dir=log_dir,
    metrics_jsonl=f"{log_dir}/metrics.jsonl",
    data=DataConfig(dataset="synthetic", data_dir=data_dir,
                    synthetic_train_records=256, synthetic_test_records=64,
                    normalize="scale", use_native_loader=False),
)
cfg.model.logit_relu = False
cfg.optim.learning_rate = 0.05
cfg.keep_checkpoints = 20   # retention must not prune the restore point
cfg.recovery_backoff_s = 0.05
cfg.recovery_backoff_max_s = 0.2
cfg.fault_spec = fault_spec or None
cfg.parallel.process_id = task
cfg.parallel.num_processes = n
if cluster_dir:
    cfg.parallel.cluster_dir = cluster_dir
    cfg.parallel.cluster_lockstep = True
    cfg.parallel.heartbeat_interval_s = 0.1
    cfg.parallel.straggler_after_s = 0.4
    cfg.parallel.peer_dead_after_s = 2.5
    cfg.parallel.collective_timeout_s = 300.0

res = fit_supervised(cfg, task_index=task)
if res is None:
    print("RESULT " + json.dumps({"task": task, "fenced": True}))
    sys.exit(0)
h = hashlib.sha256()
for leaf in jax.tree.leaves(jax.device_get(res.state.params)):
    h.update(np.ascontiguousarray(leaf).tobytes())
print("RESULT " + json.dumps({
    "task": task, "fenced": False, "final_step": res.final_step,
    "digest": h.hexdigest()}))
"""

_REF_DIGEST_CACHE = {}


def _read_result(out):
    lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
    assert lines, f"no RESULT line in:\n{out}"
    return json.loads(lines[-1][len("RESULT "):])


def _spawn(script, args, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, str(script)] + [str(a) for a in args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)


def _ensure_data(tmp_path, data_cfg):
    import dataclasses
    from dml_cnn_cifar10_tpu.data import ensure_dataset
    data_dir = str(tmp_path / "data")
    ensure_dataset(dataclasses.replace(
        data_cfg, data_dir=data_dir, synthetic_train_records=256,
        synthetic_test_records=64))
    return data_dir


def _reference_digest(tmp_path, data_dir, survivor_logs, restore_step,
                      script):
    """Digest of a fault-free SINGLE-process run restored from the same
    checkpoint the survivor restarted from (copied into a fresh dir).
    Cached on the checkpoint bytes: both scenarios restart from an
    identical step-10 checkpoint, so one reference run serves both."""
    ckpt = os.path.join(survivor_logs, f"ckpt_{restore_step}.msgpack")
    with open(ckpt, "rb") as f:
        key = hashlib.sha256(f.read()).hexdigest()
    if key in _REF_DIGEST_CACHE:
        return _REF_DIGEST_CACHE[key]
    ref_logs = str(tmp_path / "ref_logs")
    os.makedirs(ref_logs)
    for name in (f"ckpt_{restore_step}.msgpack",
                 f"ckpt_{restore_step}.msgpack.sha256",
                 f"data_state_{restore_step}.json"):
        src = os.path.join(survivor_logs, name)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(ref_logs, name))
    proc = _spawn(script, [0, 1, data_dir, ref_logs, "", "", 40],
                  tmp_path)
    out = proc.communicate(timeout=300)[0]
    assert proc.returncode == 0, f"reference run failed:\n{out}"
    res = _read_result(out)
    assert res["final_step"] == 40
    _REF_DIGEST_CACHE[key] = res["digest"]
    return res["digest"]


def _run_failure_scenario(tmp_path, data_cfg, fault_spec,
                          faulty_exit_code):
    """Two lockstep sim hosts; task 1 carries the fault at step 15 (one
    checkpoint interval past the step-10 save). Returns (survivor
    result, survivor JSONL records, reference digest)."""
    data_dir = _ensure_data(tmp_path, data_cfg)
    cluster_dir = str(tmp_path / "cluster")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    logs = [str(tmp_path / f"logs_{t}") for t in (0, 1)]
    procs = [
        _spawn(script, [t, 2, data_dir, logs[t], cluster_dir,
                        fault_spec if t == 1 else "", 40], tmp_path)
        for t in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert procs[0].returncode == 0, f"survivor failed:\n{outs[0]}"
    assert procs[1].returncode == faulty_exit_code, \
        f"faulty host exit {procs[1].returncode}:\n{outs[1]}"

    survivor = _read_result(outs[0])
    assert not survivor["fenced"]
    assert survivor["final_step"] == 40

    with open(os.path.join(logs[0], "metrics.jsonl")) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    kinds = {r["kind"] for r in recs}
    # The watchdog classified the fault and the restart was coordinated
    # and elastic: world shrank to the survivor, restore at the last
    # checkpoint.
    assert {"heartbeat", "peer_lost", "elastic_restart"} <= kinds
    lost = [r for r in recs if r["kind"] == "peer_lost"
            and r["reason"] == "stale_heartbeat"]
    assert lost and lost[0]["process_id"] == 1
    er = [r for r in recs if r["kind"] == "elastic_restart"]
    assert er and er[0]["world_size"] == 1 and er[0]["epoch"] == 1
    assert er[0]["restore_step"] == 10
    # The stream passes the documented-schema lint, and the report CLI
    # prints the cluster-health section.
    from tools import check_jsonl_schema, telemetry_report
    assert check_jsonl_schema.check_lines(
        (json.dumps(r) for r in recs), strict=True) == []
    out = telemetry_report.summarize(os.path.join(logs[0],
                                                  "metrics.jsonl"))
    assert "cluster health" in out and "elastic restart" in out

    # Run-wide aggregation (ISSUE 8): both processes' streams merge
    # onto one clock-aligned timeline whose per-host step counts match
    # the individual streams EXACTLY, with the survivor's peer_lost /
    # elastic_restart on the merged event list; and the merged Perfetto
    # document builds.
    from tools import trace_aggregate
    streams = [os.path.join(d, "metrics.jsonl") for d in logs]
    agg = trace_aggregate.aggregate(streams)
    assert agg["aligned_hosts"] == 2       # heartbeat wallclock anchors
    for host in agg["hosts"]:
        direct = [r["step"]
                  for r in trace_aggregate.load_stream(host["path"])
                  if r["kind"] == "train"]
        assert host["train_steps"] == direct
        assert sorted(agg["timeline"][host["task"]]) == sorted(
            {r["step"]
             for r in trace_aggregate.load_stream(host["path"])
             if isinstance(r.get("step"), int)})
    ev_kinds = {e["kind"] for e in agg["events"]}
    assert {"fault", "peer_lost", "elastic_restart"} <= ev_kinds
    merged_path = os.path.join(str(tmp_path), "merged_trace.json")
    assert trace_aggregate.main(streams + ["--out", merged_path]) == 0
    with open(merged_path) as f:
        doc = json.load(f)
    assert doc["traceEvents"]

    ref = _reference_digest(tmp_path, data_dir, logs[0], 10, script)
    return survivor, recs, ref


def test_sim_host_loss_elastic_restart_bit_identical(tmp_path,
                                                     data_cfg):
    """host_lost@15 on task 1 (os._exit, no cleanup): the survivor
    declares it dead on stale heartbeats, restarts elastically at world
    size 1 from ckpt_10, and finishes with params bit-identical to a
    fault-free single-process run restored from the same checkpoint."""
    from dml_cnn_cifar10_tpu.utils.faults import EXIT_HOST_LOST
    survivor, recs, ref = _run_failure_scenario(
        tmp_path, data_cfg, "host_lost@15", EXIT_HOST_LOST)
    assert survivor["digest"] == ref


def test_sim_heartbeat_stall_evicts_and_restarts_bit_identical(
        tmp_path, data_cfg):
    """heartbeat_stall@15 on task 1: it keeps training but looks dead
    from outside. The survivor restarts without it; the stalled host
    discovers the decision that excluded it and fences itself (clean
    exit 0, no result)."""
    survivor, recs, ref = _run_failure_scenario(
        tmp_path, data_cfg, "heartbeat_stall@15", 0)
    assert survivor["digest"] == ref


# ---------------------------------------------------------------------------
# satellite: SIGTERM on a non-chief host exits cleanly WITHOUT saving
# ---------------------------------------------------------------------------

def test_preempted_nonchief_exits_without_saving(data_cfg, tmp_path):
    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=100)
    cfg.checkpoint_every = 50
    cfg.metrics_jsonl = os.path.join(str(tmp_path), "m.jsonl")
    cfg.fault_spec = "sigterm@12"
    cfg.parallel.cluster_dir = str(tmp_path / "cluster")
    cfg.parallel.num_processes = 2
    cfg.parallel.process_id = 1          # non-chief
    # Generous thresholds: the lone peer never beats in this test and
    # must not be declared dead inside the short run.
    cfg.parallel.straggler_after_s = 60.0
    cfg.parallel.peer_dead_after_s = 600.0
    result = Trainer(cfg).fit()
    assert result.preempted
    # No drain save: the chief owns the checkpoint decision.
    assert ckpt_lib.all_checkpoint_steps(cfg.log_dir) == []
    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    notice = [r for r in recs if r["kind"] == "peer_lost"]
    assert notice and notice[0]["reason"] == "preempt_nonchief_exit"
    assert notice[0]["process_id"] == 1
    assert any(r["kind"] == "preempt" for r in recs)
    from tools import check_jsonl_schema
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl, strict=True) == []
