"""Async-PS staleness emulation (the reference's one semantic delta).

The reference's workers apply gradients computed on parameters up to
W-1 updates old (async PS, no SyncReplicasOptimizer —
``cifar10cnn.py:162``; SURVEY §2.3). ``async_staleness=S`` reproduces
that staleness deterministically via a round-robin snapshot ring, so
async-vs-sync convergence is directly comparable — the validation the
SURVEY's "hard parts" list asks for, without nondeterministic racing.
"""

import pytest
import jax
import numpy as np

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib
from dml_cnn_cifar10_tpu.train import optim

DATA = DataConfig(normalize="scale")
CFG = ModelConfig(logit_relu=False)


def _run(seed, staleness, nsteps=6, lr=0.05, grad_accum=1):
    rng = np.random.default_rng(seed)  # same batch for every run
    ocfg = OptimConfig(learning_rate=lr, schedule="constant",
                       async_staleness=staleness, grad_accum=grad_accum)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    model_def = get_model("cnn")
    sh = step_lib.train_state_shardings(mesh, model_def, CFG, DATA, ocfg)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, CFG, DATA, ocfg, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, CFG, ocfg, mesh,
                                     state_sharding=sh)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def test_staleness_ring_semantics():
    """Ring init shape + division of labor: sgd_update owns the param
    update, the step body owns the slot write."""
    cfg = OptimConfig(learning_rate=0.1, schedule="constant",
                      async_staleness=2)
    w = {"w": np.asarray([1.0], np.float32)}
    state = optim.sgd_init(w, cfg)
    np.testing.assert_array_equal(
        np.asarray(state["stale"]["w"]), [[1.0], [1.0]])
    # The ring update itself lives in the step body; here we pin init
    # shape + that plain sgd_update leaves the ring untouched (the step
    # body owns the slot write).
    _, new_state = optim.sgd_update({"w": np.ones(1, np.float32)}, state,
                                    w, cfg)
    assert "stale" not in new_state  # re-attached by the step body


@pytest.mark.slow
def test_stale_ring_trajectory():
    """With S=2 (same batch every step): step 0 reads slot 0 = init, so
    it matches the sync run; step 1 reads slot 1 which is STILL init —
    the loss repeats step 0's exactly (the fingerprint of a worker
    computing on params it fetched before any update landed); from step
    2 the trajectory diverges from sync."""
    _, sync_losses = _run(0, staleness=0, nsteps=4)
    _, stale_losses = _run(0, staleness=2, nsteps=4)
    np.testing.assert_allclose(sync_losses[0], stale_losses[0], rtol=1e-6)
    np.testing.assert_allclose(stale_losses[1], stale_losses[0], rtol=1e-6)
    assert not np.allclose(sync_losses[1], stale_losses[1])
    assert not np.allclose(sync_losses[2:], stale_losses[2:])


@pytest.mark.slow
def test_stale_still_converges():
    """Staleness 3 on a separable problem still trains (loss decreases)
    — the async semantics are a different trajectory, not divergence."""
    _, losses = _run(0, staleness=3, nsteps=10, lr=0.02)
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_staleness_rejects_explicit_collectives():

    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    with pytest.raises(ValueError, match="async_staleness"):
        step_lib.make_train_step(
            get_model("cnn"), CFG,
            OptimConfig(async_staleness=2), mesh,
            explicit_collectives=True)


def test_staleness_guards():
    """SGD-coupled wd and pipeline meshes are rejected with explanations
    (both would silently break the async-semantics claim)."""

    with pytest.raises(ValueError, match="weight_decay"):
        optim.sgd_init({"w": np.ones(2, np.float32)},
                       OptimConfig(async_staleness=2, weight_decay=1e-4))
    # decoupled decay is fine
    optim.sgd_init({"w": np.ones((4, 4), np.float32)},
                   OptimConfig(optimizer="adamw", async_staleness=2,
                               weight_decay=1e-4))
    pipe_mesh = mesh_lib.build_mesh(
        ParallelConfig(data_axis=4, pipe_axis=2))
    with pytest.raises(ValueError, match="pipeline"):
        step_lib.make_train_step(
            get_model("vit_tiny"),
            ModelConfig(name="vit_tiny", vit_depth=2, vit_dim=32,
                        vit_heads=2, patch_size=8, logit_relu=False),
            OptimConfig(async_staleness=2), pipe_mesh)


def test_explicit_path_actually_selected(monkeypatch):
    """Guard order must leave the explicit-collectives branch reachable:
    make_train_step(explicit_collectives=True) returns the shard_map
    step, never silently the GSPMD one (regression: a guard insertion
    once made the branch's return unreachable)."""
    sentinel = object()
    monkeypatch.setattr(step_lib, "_make_explicit_train_step",
                        lambda *a, **k: sentinel)
    mesh = mesh_lib.build_mesh(ParallelConfig(data_axis=8))
    got = step_lib.make_train_step(get_model("cnn"), CFG, OptimConfig(),
                                   mesh, explicit_collectives=True)
    assert got is sentinel


def test_lars_coupled_wd_also_guarded():

    with pytest.raises(ValueError, match="lars-coupled"):
        optim.sgd_init({"w": np.ones((4, 4), np.float32)},
                       OptimConfig(optimizer="lars", async_staleness=2,
                                   weight_decay=1e-4))


@pytest.mark.slow
def test_staleness_composes_with_grad_accum():
    """Microbatched gradients at the stale snapshot must equal the
    unaccumulated stale trajectory (mean of equal microbatch means ==
    full-batch mean; the CNN has no BN so the equivalence is exact to
    fp32 tolerance), on the same batches."""
    st_acc, acc_losses = _run(0, staleness=2, nsteps=4, lr=0.02,
                              grad_accum=2)
    st_ref, ref_losses = _run(0, staleness=2, nsteps=4, lr=0.02)
    np.testing.assert_allclose(acc_losses, ref_losses, rtol=1e-5,
                               atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(st_acc.params)),
                    jax.tree.leaves(jax.device_get(st_ref.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    # Staleness fingerprint survives accumulation: steps 0 and 1 both
    # read an init slot -> identical loss.
    np.testing.assert_allclose(acc_losses[0], acc_losses[1], rtol=1e-6)
    assert int(jax.device_get(st_acc.step)) == 4
