"""The --autopilot remediation policy engine (autopilot/engine.py):
the --autopilot_policies grammar, the RemediationBudget, step-based
cooldown determinism, fail-open action failures, the trigger-seam
contract (one remediation record per matching policy per EMITTED
firing — never for suppressed re-fires or resolutions), idempotent
attach — and the tier-1 acceptance smoke: a supervised sim with
``nan@15`` plus an HBM-shaped custom rule, where every qualifying
firing is answered by exactly ONE ``remediation`` record linked to the
alert's id and its postmortem bundle, the run completes bit-identical
to the fault-free reference, and the stream passes strict lint."""

import hashlib
import json
import os

import pytest

from dml_cnn_cifar10_tpu.autopilot import (
    ACTIONS,
    AutopilotEngine,
    RemediationBudget,
    RemediationPolicy,
    default_policies,
    parse_policies,
    required_extra_rules,
)
from dml_cnn_cifar10_tpu.utils.alerts import (
    AlertEngine,
    parse_alert_rules,
)


class _Sink:
    def __init__(self):
        self.records = []

    def __call__(self, kind, **fields):
        self.records.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.records]


class _Ns:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class _Cfg:
    """The cfg surface the engine's actions mutate."""

    def __init__(self):
        self.rollback_lr_scale = 0.5
        self.on_nonfinite = "halt"
        self.steps_per_dispatch = 4
        self.batch_size = 32
        self.optim = _Ns(learning_rate=0.05)
        self.parallel = _Ns(replica_keep=2)


class _Rule:
    def __init__(self, name):
        self.name = name


# ---------------------------------------------------------------------------
# the --autopilot_policies grammar
# ---------------------------------------------------------------------------

def test_parse_policies_full_grammar():
    got = parse_policies(
        "roll=nonfinite_burst->rollback:lr_scale=0.25@50;"
        "shed=serve_*|fleet_shed->scale_up_shed:tier=2@60s")
    assert [p.name for p in got] == ["roll", "shed"]
    assert got[0].rules == ("nonfinite_burst",)
    assert got[0].action == "rollback"
    assert got[0].params == {"lr_scale": 0.25}
    assert (got[0].cooldown, got[0].cooldown_unit) == (50.0, "steps")
    assert got[1].rules == ("serve_*", "fleet_shed")
    assert (got[1].cooldown, got[1].cooldown_unit) == (60.0, "seconds")
    assert got[1].matches("serve_p99_slo") and got[1].matches("fleet_shed")
    assert not got[1].matches("nonfinite_burst")


def test_parse_policies_empty_and_defaults():
    assert parse_policies(None) == []
    assert parse_policies("") == []
    # Every default maps to a known action and carries a cooldown.
    for p in default_policies():
        assert p.action in ACTIONS and p.cooldown > 0


@pytest.mark.parametrize("bad", [
    "noarrow=nonfinite_burst@50",
    "x=->rollback",
    "x=a->not_an_action",
    "x=a->rollback:lr_scale=fast",
    "=a->rollback",
    "x=a->rollback;x=b->rollback",           # duplicate names
])
def test_parse_policies_rejects(bad):
    with pytest.raises(ValueError):
        parse_policies(bad)


def test_required_extra_rules_only_when_matched():
    assert required_extra_rules(
        parse_policies("r=nonfinite_burst->rollback")) == []
    (rule,) = required_extra_rules(default_policies())
    assert rule.name == "peer_churn" and rule.match == {
        "fault": "peer_lost"}


# ---------------------------------------------------------------------------
# budget
# ---------------------------------------------------------------------------

def test_budget_charge_refund_per_policy():
    b = RemediationBudget(2)
    assert b.try_charge("a") and b.try_charge("b")
    assert not b.try_charge("a")             # spent
    assert (b.spent, b.remaining()) == (2, 0)
    b.refund("a")
    assert b.per_policy == {"a": 0, "b": 1}
    assert b.try_charge("c") and not b.try_charge("c")


# ---------------------------------------------------------------------------
# engine decisions: cooldown, budget, fail-open, actions
# ---------------------------------------------------------------------------

def _fire(engine, rule_name, step, value=1.0, alert_id=None):
    engine.on_alert(_Rule(rule_name), value,
                    {"id": alert_id or f"{rule_name}#{step}",
                     "step": step, "severity": "page"})


def test_rollback_applies_lr_scale_then_step_cooldown():
    cfg = _Cfg()
    eng = AutopilotEngine(cfg, policies=parse_policies(
        "roll=nonfinite_burst->rollback@50"), budget=8)
    _fire(eng, "nonfinite_burst", step=20)
    assert cfg.on_nonfinite == "rollback"
    assert cfg.optim.learning_rate == pytest.approx(0.025)
    # A second firing 30 steps later is inside the 50-step cooldown:
    # explicit suppression record, NO second LR scale.
    _fire(eng, "nonfinite_burst", step=50)
    assert cfg.optim.learning_rate == pytest.approx(0.025)
    # Past the cooldown the policy acts again.
    _fire(eng, "nonfinite_burst", step=80)
    assert cfg.optim.learning_rate == pytest.approx(0.0125)
    assert [r["status"] for r in eng.history] == [
        "applied", "suppressed_cooldown", "applied"]
    assert "remaining" in eng.history[1]["detail"]


def test_budget_exhaustion_emits_explicit_suppression():
    eng = AutopilotEngine(_Cfg(), policies=parse_policies(
        "roll=nonfinite_burst->rollback"), budget=1)
    _fire(eng, "nonfinite_burst", step=10)
    _fire(eng, "nonfinite_burst", step=20)   # no cooldown configured
    assert [r["status"] for r in eng.history] == [
        "applied", "suppressed_budget"]


def test_failed_hook_is_fail_open_and_refunds_budget():
    eng = AutopilotEngine(_Cfg(), policies=parse_policies(
        "shed=serve_shed->scale_up_shed"), budget=1)

    def boom(tier):
        raise RuntimeError("no live batcher")

    eng.bind("shed_tier", boom)
    _fire(eng, "serve_shed", step=5)         # must not raise
    (rec,) = eng.history
    assert rec["status"] == "failed" and "no live batcher" in rec["detail"]
    # The failure refunded the unit: the next firing can still act.
    eng.bind("shed_tier", lambda tier: None)
    _fire(eng, "serve_shed", step=6)
    assert eng.history[-1]["status"] == "applied"


def test_scale_up_shed_uses_bound_seams_or_noops():
    cfg = _Cfg()
    calls = []
    eng = AutopilotEngine(cfg, policies=parse_policies(
        "shed=serve_*->scale_up_shed:tier=2"), budget=8)
    _fire(eng, "serve_p99_slo", step=1)
    assert eng.history[-1]["status"] == "noop"       # nothing bound
    eng.bind("scale_up", lambda rule: calls.append(("up", rule)))
    eng.bind("shed_tier", lambda tier: calls.append(("shed", tier)))
    _fire(eng, "serve_p99_slo", step=2)
    assert eng.history[-1]["status"] == "applied"
    assert calls == [("up", "serve_p99_slo"), ("shed", 2)]


def test_shrink_memory_halves_dispatch_then_batch_then_noops():
    cfg = _Cfg()
    eng = AutopilotEngine(cfg, policies=parse_policies(
        "mem=hbm_headroom->shrink_memory:shrink_batch=1"), budget=8)
    _fire(eng, "hbm_headroom", step=10)
    assert cfg.steps_per_dispatch == 2
    assert eng.poll_restart().startswith("shrink_memory")
    assert eng.poll_restart() is None                # one-shot
    _fire(eng, "hbm_headroom", step=20)
    assert cfg.steps_per_dispatch == 1
    _fire(eng, "hbm_headroom", step=30)              # K exhausted: batch
    assert cfg.batch_size == 16
    assert "NOT bit-identical" in eng.history[-1]["detail"]
    cfg.batch_size = 1
    _fire(eng, "hbm_headroom", step=40)
    assert eng.history[-1]["status"] == "noop"


def test_raise_replica_keep_bounded():
    cfg = _Cfg()
    eng = AutopilotEngine(cfg, policies=parse_policies(
        "rk=peer_churn->raise_replica_keep:max=3"), budget=8)
    _fire(eng, "peer_churn", step=10)
    _fire(eng, "peer_churn", step=20)
    assert cfg.parallel.replica_keep == 3
    _fire(eng, "peer_churn", step=30)
    assert cfg.parallel.replica_keep == 3            # capped
    assert eng.history[-1]["status"] == "noop"


def test_handles_by_rule_and_action():
    eng = AutopilotEngine(_Cfg(), budget=8)
    assert eng.handles("nonfinite_burst")
    assert eng.handles("nonfinite_burst", "rollback")
    assert not eng.handles("nonfinite_burst", "shrink_memory")
    assert not eng.handles("no_such_rule")


def test_decisions_deterministic_under_replay():
    """Identical firing sequences (step-based cooldowns) produce
    identical remediation histories — the chaos campaign's replay
    determinism in miniature."""
    def run():
        eng = AutopilotEngine(_Cfg(), budget=2)
        for step in (20, 40, 75, 130, 200):
            _fire(eng, "nonfinite_burst", step=step)
        return [(r["status"], r["step"], r["alert_id"])
                for r in eng.history]

    assert run() == run()


# ---------------------------------------------------------------------------
# the trigger seam: suppressed re-fires / resolutions never remediate
# ---------------------------------------------------------------------------

def _attached(policies_spec="lossy_pol=lossy->rollback",
              rules_spec="lossy=train.loss>10", min_interval_s=60.0):
    alerts = AlertEngine(parse_alert_rules(rules_spec),
                         min_interval_s=min_interval_s)
    eng = AutopilotEngine(_Cfg(), policies=parse_policies(policies_spec),
                          budget=8)
    eng.attach(alerts)
    return alerts, eng


def test_no_remediation_for_suppressed_refire_or_resolution():
    alerts, eng = _attached()
    sink = _Sink()
    alerts.observe("train", {"step": 1, "loss": 50.0}, emit=sink, now=0.0)
    assert len(eng.history) == 1
    # Resolution, then a re-fire inside the rate-limit window: the
    # engine emits nothing, so the autopilot must see nothing.
    alerts.observe("train", {"step": 2, "loss": 1.0}, emit=sink, now=1.0)
    alerts.observe("train", {"step": 3, "loss": 60.0}, emit=sink, now=2.0)
    assert sink.kinds() == ["alert", "alert_resolved"]
    assert len(eng.history) == 1
    # The one record carries the emitted firing's id.
    assert eng.history[0]["alert_id"] == sink.records[0][1]["id"]


def test_attach_is_idempotent_one_record_per_firing():
    """Re-attaching (the Runtime attaches, then injects the engine
    into fit_supervised, which attaches again) must not double the
    remediations."""
    alerts, eng = _attached(min_interval_s=0.0)
    eng.attach(alerts)
    eng.attach(alerts)
    sink = _Sink()
    alerts.observe("train", {"step": 1, "loss": 50.0}, emit=sink, now=0.0)
    assert len(eng.history) == 1


def test_attach_injects_required_rules_once():
    alerts = AlertEngine(parse_alert_rules("lossy=train.loss>10"))
    eng = AutopilotEngine(_Cfg(), budget=8)   # defaults want peer_churn
    eng.attach(alerts)
    eng.attach(alerts)
    assert [r.name for r in alerts.rules].count("peer_churn") == 1


def test_from_config_gated_on_flag():
    class AP:
        enabled = False
        policies = None
        budget = 8

    class Cfg:
        autopilot = AP()

    assert AutopilotEngine.from_config(Cfg()) is None
    Cfg.autopilot.enabled = True
    Cfg.autopilot.policies = "r=nonfinite_burst->rollback@50"
    Cfg.autopilot.budget = 3
    eng = AutopilotEngine.from_config(Cfg())
    assert [p.name for p in eng.policies] == ["r"]
    assert eng.budget.total == 3


# ---------------------------------------------------------------------------
# tier-1 acceptance smoke: supervised nan@15 + HBM-shaped rule
# ---------------------------------------------------------------------------

def _params_digest(result):
    import jax
    import numpy as np
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(result.state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


def test_autopilot_acceptance_supervised_nan(data_cfg, tmp_path,
                                             monkeypatch):
    from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised
    from dml_cnn_cifar10_tpu.utils import flightrec as flightrec_lib
    from tests.conftest import tiny_train_cfg
    from tools import check_jsonl_schema

    # Two alert rules fire here and each capture arms a profiled
    # devprof dispatch — minutes on a starved CPU box. The remediation
    # linkage under test is the BUNDLE path, not its devprof payload
    # (test_flightrec.py owns that); skip the profiler.
    monkeypatch.setattr(flightrec_lib.FlightRecorder,
                        "pop_devprof_window",
                        lambda self, step, logger=None: None)

    def run(sub, fault_spec):
        cfg = tiny_train_cfg(data_cfg, str(tmp_path / sub),
                             total_steps=30)
        cfg.checkpoint_every = 10
        cfg.output_every = 10
        cfg.eval_every = 30
        cfg.check_numerics = True
        cfg.on_nonfinite = "rollback"
        cfg.recovery_backoff_s = 0.01
        cfg.fault_spec = fault_spec
        cfg.metrics_jsonl = os.path.join(str(tmp_path / sub), "m.jsonl")
        if fault_spec:
            cfg.postmortem_dir = os.path.join(str(tmp_path / sub), "pm")
        cfg.autopilot.enabled = True
        # rollback_lr_scale stays 1.0: the applied remediation keeps
        # the exact-resume contract, so the faulted run must end
        # bit-identical to the reference. The custom HBM-shaped rule
        # (always-true threshold) exercises a second policy arc; with
        # steps_per_dispatch=1 its shrink degrades to an explicit noop.
        cfg.autopilot.policies = (
            "rollback_nonfinite=nonfinite_burst->rollback@50;"
            "hbm=hbm_tight->shrink_memory@100")
        cfg.alert_rules = "hbm_tight=train.loss>0@2!warn"
        result = fit_supervised(cfg)
        assert result.final_step == 30
        return cfg, result

    cfg, result = run("faulted", "nan@15")
    with open(cfg.metrics_jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]

    # Strict lint: the stream (with its remediation records) is schema
    # clean.
    assert check_jsonl_schema.check_file(cfg.metrics_jsonl,
                                         strict=True) == []

    # Exactly one remediation per firing alert, linked by id.
    alerts = [r for r in recs if r["kind"] == "alert"]
    rems = [r for r in recs if r["kind"] == "remediation"]
    policies = parse_policies(cfg.autopilot.policies)
    for a in alerts:
        matching = [r for r in rems if r["alert_id"] == a["id"]]
        if any(p.matches(a["rule"]) for p in policies):
            assert len(matching) == 1, (a, rems)
        else:
            assert matching == []

    # The nonfinite arc: applied rollback, linked to the firing AND to
    # the flight-recorder bundle captured at that moment.
    (roll,) = [r for r in rems if r["rule"] == "nonfinite_burst"]
    assert roll["status"] == "applied"
    assert roll["policy"] == "rollback_nonfinite"
    pm = [r for r in recs if r["kind"] == "postmortem"
          and r["rule"] == "nonfinite_burst"]
    assert roll["postmortem"] == pm[0]["dir"]
    assert os.path.isdir(roll["postmortem"])

    # The HBM-shaped arc answered explicitly (noop: nothing to shrink
    # at steps_per_dispatch=1 without shrink_batch opt-in).
    (hbm,) = [r for r in rems if r["rule"] == "hbm_tight"]
    assert hbm["status"] == "noop"

    # The supervisor's own LR scale stayed off (the autopilot handles
    # nonfinite_burst): LR is unscaled with lr_scale=1.
    rollbacks = [r for r in recs if r["kind"] == "rollback"]
    assert rollbacks and rollbacks[0]["lr"] == pytest.approx(0.05)

    # Return-to-SLO, bit-identical: the recovered run's final params
    # match the fault-free reference exactly.
    _, ref = run("reference", None)
    assert _params_digest(result) == _params_digest(ref)
