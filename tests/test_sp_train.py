"""Sequence parallelism in the TRAINING step: ring attention wired into the
ViT forward/backward under jit, composed with dp and tp on one mesh.

Complements test_ring_attention.py (op-level correctness) — here the whole
train step runs sequence-sharded and must match the dp-only run.
"""

import dataclasses

import jax
import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import step as step_lib

# 32x32 inputs, patch 4 -> 8x8 = 64 tokens: divisible by seq axes 2 and 4.
DATA = DataConfig(crop_height=32, crop_width=32, normalize="scale")
VIT = ModelConfig(name="vit_tiny", pool="mean", logit_relu=False,
                  vit_depth=2, vit_dim=64, vit_heads=2, patch_size=4)


def _mesh(data, model=1, seq=1):
    return mesh_lib.build_mesh(
        ParallelConfig(data_axis=data, model_axis=model, seq_axis=seq))


def _run(model_cfg, mesh, images, labels, nsteps=2):
    model_def = get_model(model_cfg.name)
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def _batch(rng, n=8):
    images = rng.normal(0.5, 0.25, (n, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return images, labels


@pytest.mark.parametrize("axes", [(2, 1, 4), (4, 1, 2), (2, 2, 2)])
@pytest.mark.slow
def test_sp_train_matches_dp(axes, rng):
    """dp×tp×sp must be a pure layout change vs the dp-only mesh."""
    images, labels = _batch(rng)
    _, loss_dp = _run(VIT, _mesh(8), images, labels)
    st, loss_sp = _run(VIT, _mesh(*axes), images, labels)
    np.testing.assert_allclose(loss_dp, loss_sp, rtol=2e-5, atol=2e-6)
    assert np.isfinite(loss_sp).all()


def test_sp_eval_step(rng):
    mesh = _mesh(2, 1, 4)
    model_def = get_model("vit_tiny")
    optim = OptimConfig()
    sh = step_lib.train_state_shardings(mesh, model_def, VIT, DATA, optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, VIT, DATA, optim, mesh,
        state_sharding=sh)
    ev = step_lib.make_eval_step(model_def, VIT, mesh, state_sharding=sh)
    images, labels = _batch(rng)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    m = ev(state, im, lb)
    assert 0.0 <= float(m["accuracy"]) <= 1.0


@pytest.mark.slow
def test_sp_requires_mean_pool(rng):
    cfg = dataclasses.replace(VIT, pool="cls")
    images, labels = _batch(rng)
    with pytest.raises(ValueError, match="mean"):
        _run(cfg, _mesh(2, 1, 4), images, labels, nsteps=1)


@pytest.mark.slow
def test_sp_rejects_indivisible_tokens(rng):
    # 24x24 / patch 4 -> 36 tokens; seq axis 8 does not divide 36.
    data = dataclasses.replace(DATA, crop_height=24, crop_width=24)
    mesh = _mesh(1, 1, 8)
    model_def = get_model("vit_tiny")
    optim = OptimConfig()
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, VIT, data, optim, mesh)
    train = step_lib.make_train_step(model_def, VIT, optim, mesh)
    rng2 = np.random.default_rng(0)
    images = rng2.normal(0.5, 0.25, (8, 24, 24, 3)).astype(np.float32)
    labels = rng2.integers(0, 10, 8).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    with pytest.raises(ValueError, match="divisible"):
        train(state, im, lb)


@pytest.mark.slow
def test_mean_pool_vit_no_cls_param():
    params = get_model("vit_tiny").init(jax.random.key(0), VIT, DATA)
    assert "cls" not in params
    assert params["pos"].shape == (1, 64, 64)
