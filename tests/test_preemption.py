"""Preemption handling: SIGTERM mid-training → clean exit with a
checkpoint; restart resumes from the saved step (SURVEY §5 failure
detection, upgraded from the reference's restart-only story)."""

import pytest
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The driver forces CPU via utils.platform.force_cpu (env alone is not
# enough under this box's sitecustomize), then runs the real CLI.
DRIVER = """
import sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
from dml_cnn_cifar10_tpu.cli.main import main
sys.exit(main(sys.argv[1:]))
"""


def _args(data_dir, log_dir, total_steps, jsonl=None):
    a = ["--dataset", "synthetic", "--data_dir", data_dir,
         "--log_dir", log_dir, "--total_steps", str(total_steps),
         "--batch_size", "16", "--output_every", "5",
         "--eval_every", "1000000"]
    if jsonl:
        a += ["--metrics_jsonl", jsonl]
    return a


@pytest.mark.slow
def test_sigterm_checkpoints_and_resumes(tmp_path, data_cfg):
    data_dir = data_cfg.data_dir
    log_dir = str(tmp_path / "logs")
    jsonl = str(tmp_path / "m.jsonl")
    script = tmp_path / "driver.py"
    script.write_text(DRIVER)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    p = subprocess.Popen(
        [sys.executable, str(script)] + _args(data_dir, log_dir, 100000,
                                              jsonl),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        # Wait until training demonstrably progresses (first metrics line),
        # then deliver the preemption signal.
        deadline = time.time() + 180
        while time.time() < deadline:
            if os.path.exists(jsonl) and os.path.getsize(jsonl) > 0:
                break
            if p.poll() is not None:
                break
            time.sleep(0.5)
        assert p.poll() is None, \
            f"trainer died early:\n{p.communicate()[0]}"
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()

    assert p.returncode == 0, f"non-zero exit after SIGTERM:\n{out}"
    assert "[preempt]" in out, f"no preempt line:\n{out}"
    ckpts = [f for f in os.listdir(log_dir) if f.startswith("ckpt_")]
    assert ckpts, f"no checkpoint written on SIGTERM: {os.listdir(log_dir)}"
    saved = max(int(f.split("_")[1].split(".")[0]) for f in ckpts)
    assert saved > 0

    # Restart with a slightly higher stop step: must RESUME (global_step
    # continues past `saved`), not start over.
    out2 = subprocess.run(
        [sys.executable, str(script)] + _args(data_dir, log_dir, saved + 3),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO, timeout=300).stdout
    assert f"done at step {saved + 3}" in out2, out2


# ---- multi-host: one preempted process must not strand its peer ----

MH_WORKER = """
import sys
from dml_cnn_cifar10_tpu.utils.platform import force_cpu
force_cpu()
task_index, n_procs, port, data_dir, log_dir, jsonl = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6])
import jax
from dml_cnn_cifar10_tpu.config import TrainConfig, DataConfig
from dml_cnn_cifar10_tpu.parallel import multihost
from dml_cnn_cifar10_tpu.train.loop import Trainer

multihost.initialize_from_hosts([f"localhost:{port}"] * n_procs, task_index)
cfg = TrainConfig(
    batch_size=16, total_steps=100000, output_every=5, eval_every=10**6,
    checkpoint_every=10**6, log_dir=log_dir, preempt_sync_every=2,
    metrics_jsonl=jsonl,
    data=DataConfig(dataset="synthetic", data_dir=data_dir,
                    synthetic_train_records=256, synthetic_test_records=64,
                    normalize="scale", use_native_loader=False),
)
cfg.model.logit_relu = False
res = Trainer(cfg, task_index=task_index).fit()
print(f"RESULT step={res.final_step} preempted={res.preempted}", flush=True)
"""


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_multihost_preemption_agrees(tmp_path, data_cfg):
    """SIGTERM delivered to ONE of two SPMD processes: the flag is
    allgathered at a sync boundary, BOTH processes checkpoint and exit
    cleanly at the same step (no peer stranded in a collective)."""
    import dataclasses as dc

    from dml_cnn_cifar10_tpu.data import ensure_dataset

    n = 2
    port = _free_port()
    data_dir = str(tmp_path / "data")
    log_dir = str(tmp_path / "logs")
    ensure_dataset(dc.replace(
        data_cfg, data_dir=data_dir, synthetic_train_records=256,
        synthetic_test_records=64))

    script = tmp_path / "mh_worker.py"
    script.write_text(MH_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    jsonls = [str(tmp_path / f"m{i}.jsonl") for i in range(n)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(n), str(port),
             data_dir, log_dir, jsonls[i]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for i in range(n)
    ]
    try:
        # Wait until training demonstrably progresses (worker 0's metrics
        # line at step 5), then preempt ONLY process 0.
        deadline = time.time() + 240
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs):
                break  # a worker died — fail below with its output
            if os.path.exists(jsonls[0]) and os.path.getsize(jsonls[0]) > 0:
                break
            time.sleep(0.5)
        assert all(p.poll() is None for p in procs), \
            "worker died before preemption:\n" + "\n".join(
                p.communicate()[0] for p in procs if p.poll() is not None)
        procs[0].send_signal(signal.SIGTERM)
        outs = [p.communicate(timeout=240)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    steps = []
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, f"worker {i} produced no RESULT:\n{out}"
        assert "preempted=True" in lines[-1], lines[-1]
        steps.append(int(lines[-1].split("step=")[1].split()[0]))
    assert steps[0] == steps[1], f"processes exited at different steps {steps}"


@pytest.mark.slow
def test_check_numerics_halts_without_poisoned_checkpoint(tmp_path,
                                                          data_cfg):
    """The faithful LR-0.1-on-raw-pixels combo NaNs within a few steps (a
    reference property); with check_numerics the driver halts at the
    metrics boundary and the NaN state is NOT checkpointed."""
    import dataclasses


    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
    from dml_cnn_cifar10_tpu.train.loop import Trainer
    from tests.conftest import tiny_train_cfg

    cfg = tiny_train_cfg(data_cfg, str(tmp_path), total_steps=20)
    cfg.data = dataclasses.replace(cfg.data, normalize="none")  # raw 0-255
    cfg.optim.learning_rate = 0.1
    cfg.output_every = 10
    cfg.eval_every = 20
    # Checkpoint cadence FIRES BEFORE the first metrics boundary: the
    # guard must halt at the save itself, never persisting NaN weights.
    cfg.checkpoint_every = 5
    cfg.check_numerics = True
    with pytest.raises(FloatingPointError, match="non-finite"):
        Trainer(cfg).fit()
    assert ckpt_lib.all_checkpoint_steps(cfg.log_dir) == []
