"""Native C++ loader tests: decode parity with the NumPy path, bounded
shuffle-pool semantics, label/pixel integrity, error paths."""

import os

import numpy as np
import pytest

from dml_cnn_cifar10_tpu.config import DataConfig
from dml_cnn_cifar10_tpu.data import download, native
from dml_cnn_cifar10_tpu.data import pipeline as pipe
from dml_cnn_cifar10_tpu.data import records as rec


@pytest.fixture(scope="module")
def lib():
    return native.load_library()


def _native_it(data_cfg, batch_size=32, **kw):
    files = download.train_files(data_cfg)
    return native.NativeShuffleBatchIterator(files, data_cfg, batch_size,
                                             **kw)


def test_library_builds_and_loads(lib):
    assert lib is not None


def test_batch_shapes_and_ranges(data_cfg):
    it = _native_it(data_cfg)
    batch = next(it)
    assert batch.images.shape == (32, 24, 24, 3)
    assert batch.images.dtype == np.float32
    assert batch.labels.shape == (32,)
    assert batch.labels.dtype == np.int32
    assert (batch.labels >= 0).all() and (batch.labels < 10).all()
    assert 0.0 <= batch.images.min() and batch.images.max() <= 255.0
    it.close()


def test_decode_parity_with_numpy(data_cfg):
    """Every (label, decoded image) pair the native loader emits must exist
    in the NumPy-decoded split — bitwise (uint8 decode + same center
    crop)."""
    it = _native_it(data_cfg, batch_size=64)
    # Reference decode of the whole split, cropped the same way.
    ref_imgs = rec.center_crop(it.images.astype(np.float32), 24, 24)
    # Index reference images by label for fast membership check.
    by_label = {}
    for i in range(ref_imgs.shape[0]):
        by_label.setdefault(int(it.labels[i]), []).append(ref_imgs[i])
    batch = next(it)
    for img, lab in zip(batch.images, batch.labels):
        candidates = by_label.get(int(lab), [])
        assert any(np.array_equal(img, c) for c in candidates), (
            "native-decoded image not found in NumPy-decoded split "
            f"(label {lab})")
    it.close()


def test_bounded_pool_reaches_min_after(data_cfg):
    it = _native_it(data_cfg, batch_size=8)
    next(it)  # first dequeue waits for min_after
    assert it.buffered() >= 1
    it.close()


def test_stream_is_shuffled_and_endless(data_cfg):
    """More batches than the dataset holds (endless epochs), and two
    differently-seeded streams disagree on order."""
    n_total = data_cfg.synthetic_train_records
    it1 = _native_it(data_cfg, batch_size=64, seed=1)
    it2 = _native_it(data_cfg, batch_size=64, seed=2)
    l1, l2 = [], []
    for _ in range(n_total // 64 + 3):  # > one epoch
        l1.append(next(it1).labels)
        l2.append(next(it2).labels)
    l1, l2 = np.concatenate(l1), np.concatenate(l2)
    assert not np.array_equal(l1, l2), "different seeds must differ"
    # Long-run label distribution should cover all classes.
    assert len(np.unique(l1)) == 10
    it1.close()
    it2.close()


def test_create_rejects_bad_geometry(lib, data_cfg):
    files = download.train_files(data_cfg)
    paths = b"\0".join(p.encode() for p in files) + b"\0"
    handle = lib.recordio_create(paths, len(files), 3073, 1, 0,
                                 32, 32, 3, 100, 50, 7, 0)  # min_after>capacity
    assert not handle


def test_missing_file_surfaces_error(lib):
    import ctypes
    paths = b"/nonexistent/nope.bin\0"
    handle = lib.recordio_create(paths, 1, 3073, 1, 0, 32, 32, 3, 10, 50, 7, 0)
    assert handle
    imgs = np.empty((8, 32, 32, 3), np.uint8)
    labs = np.empty((8,), np.int32)
    ret = lib.recordio_next_batch(
        handle, 8, imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        labs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    assert ret == -1
    assert b"cannot open" in lib.recordio_error(handle)
    lib.recordio_destroy(handle)


def test_empty_record_files_surface_error(lib, tmp_path):
    """Files that exist but hold zero complete records must error, not hang
    the consumer while the producer spins epochs."""
    import ctypes
    f = tmp_path / "empty.bin"
    f.write_bytes(b"\x01" * 100)  # < one 3073-byte record
    paths = str(f).encode() + b"\0"
    handle = lib.recordio_create(paths, 1, 3073, 1, 0, 32, 32, 3, 10, 50, 7, 0)
    assert handle
    imgs = np.empty((4, 32, 32, 3), np.uint8)
    labs = np.empty((4,), np.int32)
    ret = lib.recordio_next_batch(
        handle, 4, imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        labs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    assert ret == -1
    assert b"no complete records" in lib.recordio_error(handle)
    lib.recordio_destroy(handle)


def test_closed_iterator_raises(data_cfg):
    it = _native_it(data_cfg, batch_size=8)
    next(it)
    it.close()
    with pytest.raises(RuntimeError, match="closed"):
        next(it)
    with pytest.raises(RuntimeError, match="closed"):
        it.buffered()


def test_pipeline_uses_native_when_enabled(data_cfg):
    import dataclasses
    cfg = dataclasses.replace(data_cfg, use_native_loader=True)
    it = pipe.input_pipeline(cfg, 16, train=True)
    assert isinstance(it, native.NativeShuffleBatchIterator)
    batch = next(it)
    assert batch.images.shape == (16, 24, 24, 3)
    it.close()


def test_wide_label_decode_parity(tmp_path):
    """imagenet_synth wide labels (big-endian uint16) through the C++
    pool: every streamed label must be a label that exists in the NumPy
    decode of the same files, and ids past 255 must appear."""
    cfg = DataConfig(dataset="imagenet_synth", data_dir=str(tmp_path),
                     image_height=8, image_width=8, crop_height=8,
                     crop_width=8, num_classes=1000,
                     synthetic_train_records=256,
                     synthetic_test_records=32, shuffle_buffer=64)
    download.generate_synthetic_dataset(cfg)
    imgs, labs = pipe._load_split(download.train_files(cfg), cfg)
    want = set(int(x) for x in labs)
    assert max(want) > 255
    it = _native_it(cfg, batch_size=64)
    seen = set()
    for _ in range(4):
        batch = next(it)
        seen.update(int(x) for x in batch.labels)
    it.close()
    assert seen <= want
    assert max(seen) > 255


def test_stale_abi_fails_loudly(tmp_path, monkeypatch):
    """ADVICE r2: a prebuilt .so that predates an ABI change must be
    rejected at load (the mtime rebuild heuristic can miss, e.g. sources
    absent on a deploy host) — silently mis-bound arguments would decode
    wrong training data."""
    import subprocess

    from dml_cnn_cifar10_tpu.data import native

    src = tmp_path / "stub.cc"
    # A v1-era library: has entry points but no recordio_abi_version.
    src.write_text('extern "C" { void* recordio_create() { return 0; } }\n')
    so = tmp_path / "librecordio.so"
    subprocess.run(["g++", "-shared", "-fPIC", "-o", str(so), str(src)],
                   check=True, capture_output=True)
    monkeypatch.setattr(native, "_LIB_PATH", str(so))
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_needs_build", lambda: False)
    with pytest.raises(RuntimeError, match="ABI v1 != expected"):
        native.load_library()
