"""Pipeline parallelism: GPipe schedule over the ``pipe`` mesh axis.

Op-level: pipeline_blocks == sequential scan (fwd AND grad). Step-level:
a pipelined ViT training step matches the dp-only run on the 8-device CPU
mesh; stage sharding is real (each stage holds depth/P layers).
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dml_cnn_cifar10_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                        ParallelConfig)
from dml_cnn_cifar10_tpu.models.registry import get_model
from dml_cnn_cifar10_tpu.parallel import mesh as mesh_lib
from dml_cnn_cifar10_tpu.parallel import pipeline
from dml_cnn_cifar10_tpu.parallel import shardings
from dml_cnn_cifar10_tpu.parallel import step as step_lib

DATA = DataConfig(normalize="scale")
VIT_PP = ModelConfig(name="vit_tiny", pool="mean", logit_relu=False,
                     vit_depth=4, vit_dim=64, vit_heads=2, patch_size=8)


def _mesh(data=1, model=1, seq=1, pipe=1):
    return mesh_lib.build_mesh(ParallelConfig(
        data_axis=data, model_axis=model, seq_axis=seq, pipe_axis=pipe))


def _toy_stack(depth=4, dim=8):
    ks = jax.random.split(jax.random.key(0), depth)
    blocks = [{"w": jax.random.normal(k, (dim, dim)) * 0.3,
               "b": jnp.zeros((dim,))} for k in ks]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def _toy_block(h, p):
    return jnp.tanh(h @ p["w"] + p["b"])


def _sequential(x, stacked):
    return jax.lax.scan(lambda c, p: (_toy_block(c, p), None), x, stacked)[0]


@pytest.mark.parametrize("pipe,micro", [(4, None), (4, 8), (2, 4)])
@pytest.mark.slow
def test_pipeline_matches_sequential(pipe, micro):
    mesh = _mesh(data=8 // pipe, pipe=pipe)
    stacked = _toy_stack()
    x = jax.random.normal(jax.random.key(1), (16, 6, 8))
    ref = _sequential(x, stacked)
    out = jax.jit(functools.partial(
        pipeline.pipeline_blocks, block_fn=_toy_block, mesh=mesh,
        num_microbatches=micro))(x, stacked_params=stacked)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)


def test_pipeline_gradient_matches_sequential():
    """The reverse pipeline (autodiff through scan-of-ppermute) must give
    the same gradients as the sequential stack."""
    mesh = _mesh(data=2, pipe=4)
    stacked = _toy_stack()
    x = jax.random.normal(jax.random.key(2), (8, 4, 8))

    def loss_pp(params):
        return jnp.sum(pipeline.pipeline_blocks(
            x, params, _toy_block, mesh) ** 2)

    def loss_seq(params):
        return jnp.sum(_sequential(x, params) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-5)


def test_pipeline_rejects_indivisible_depth():
    mesh = _mesh(data=2, pipe=4)
    stacked = _toy_stack(depth=6)
    x = jnp.zeros((8, 4, 8))
    with pytest.raises(ValueError, match="depth"):
        pipeline.pipeline_blocks(x, stacked, _toy_block, mesh)


def test_pp_rules_stage_shard_blocks():
    cfg = VIT_PP
    model_def = get_model("vit_tiny")
    params = jax.eval_shape(
        lambda k: model_def.init(k, cfg, DATA), jax.random.key(0))
    specs = shardings.param_pspecs("vit_tiny", params, pipe=True)
    assert specs["blocks"]["qkv"]["kernel"] == P("pipe")
    assert specs["head"]["kernel"] == P()
    with pytest.raises(ValueError, match="pipeline"):
        shardings.rule_for("cnn", pipe=True)


def _run(model_cfg, mesh, images, labels, nsteps=2):
    model_def = get_model(model_cfg.name)
    optim = OptimConfig(learning_rate=0.01)
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim, mesh,
                                     state_sharding=sh)
    im, lb = mesh_lib.shard_batch(mesh, images, labels)
    losses = []
    for _ in range(nsteps):
        state, metrics = train(state, im, lb)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


@pytest.mark.slow
def test_pp_train_step_matches_dp(rng):
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    _, loss_dp = _run(VIT_PP, _mesh(data=8), images, labels)
    st_pp, loss_pp = _run(VIT_PP, _mesh(data=2, pipe=4), images, labels)
    np.testing.assert_allclose(loss_dp, loss_pp, rtol=2e-5, atol=2e-6)
    # stage sharding is real: each stage holds depth/P = 1 of 4 layers
    k = st_pp.params["blocks"]["qkv"]["kernel"]
    assert k.shape[0] == 4
    assert k.addressable_shards[0].data.shape[0] == 1
    assert shardings.assert_some_leaf_sharded(st_pp.params, axis="pipe")


@pytest.mark.slow
def test_pp_and_sp_both_raise(rng):
    images = rng.normal(0.5, 0.25, (8, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    with pytest.raises(ValueError, match="cannot both"):
        _run(VIT_PP, _mesh(data=2, seq=2, pipe=2), images, labels, nsteps=1)


@pytest.mark.slow
def test_pp_more_microbatches_matches_dp(rng):
    """M > P (the bubble-amortizing schedule, tools/bench_pp.py): same
    math as dp, with the microbatch count actually threaded through."""
    cfg = dataclasses.replace(VIT_PP, pipe_microbatches=8)
    images = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    _, loss_dp = _run(VIT_PP, _mesh(data=8), images, labels)
    _, loss_pp = _run(cfg, _mesh(data=2, pipe=4), images, labels)
    np.testing.assert_allclose(loss_dp, loss_pp, rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_pp_microbatch_divisibility_error():
    """Global batch must divide data_axis * M."""
    cfg = dataclasses.replace(VIT_PP, pipe_microbatches=8)
    images = np.zeros((8, 24, 24, 3), np.float32)  # 8 % (2*8) != 0
    labels = np.zeros((8,), np.int32)
    with pytest.raises(ValueError, match="not divisible"):
        _run(cfg, _mesh(data=2, pipe=4), images, labels, nsteps=1)


@pytest.mark.slow
@pytest.mark.parametrize("micro", [None, 8, 2])
def test_1f1b_gradients_match_gpipe_and_sequential(micro):
    """Round-2 verdict weak #3: the 1F1B schedule (default) must agree
    with both the GPipe baseline and plain sequential autodiff — values
    AND gradients — at M=P, M>P, and M<P. The 1F1B backward is a manual
    combined re-forward+backward pipeline (custom_vjp), so this is the
    test that pins its schedule/ring-buffer geometry."""
    mesh = _mesh(data=2, pipe=4)
    stacked = _toy_stack(depth=8, dim=8)
    x = jax.random.normal(jax.random.key(2), (16, 6, 8))

    def loss(x, p, schedule):
        out = pipeline.pipeline_blocks(x, p, _toy_block, mesh,
                                       num_microbatches=micro,
                                       schedule=schedule)
        return jnp.sum(jnp.sin(out))

    g_seq = jax.grad(
        lambda x, p: jnp.sum(jnp.sin(_sequential(x, p))),
        argnums=(0, 1))(x, stacked)
    for schedule in ("1f1b", "1f1b_ring", "gpipe"):
        g = jax.grad(functools.partial(loss, schedule=schedule),
                     argnums=(0, 1))(x, stacked)
        for got, want in zip(jax.tree.leaves(g), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_1f1b_backward_memory_flat_in_microbatches():
    """1F1B's point: live activations are O(P) — the backward's ring
    buffer holds 2P microbatch inputs regardless of M, so the compiled
    step's temp bytes must NOT grow when M quadruples (GPipe-autodiff's
    checkpointed scan carries DO grow)."""
    mesh = _mesh(data=2, pipe=4)
    stacked = _toy_stack(depth=8, dim=32)
    x = jax.random.normal(jax.random.key(3), (32, 8, 32))

    def temp_bytes(schedule, micro):
        def loss(x, p):
            out = pipeline.pipeline_blocks(x, p, _toy_block, mesh,
                                           num_microbatches=micro,
                                           schedule=schedule)
            return jnp.sum(jnp.sin(out))

        f = jax.jit(jax.grad(loss, argnums=(0, 1)))
        return f.lower(x, stacked).compile().memory_analysis() \
            .temp_size_in_bytes

    # M = P -> M = 4P: microbatches shrink 4x, and the 1F1B rings (2P
    # slots per live microbatch) shrink with them — total temp must not
    # grow, for BOTH backward flavors (recompute and residual ring).
    # (It typically *drops*; "not grow" keeps the assertion robust to
    # constant overheads.)
    for schedule in ("1f1b", "1f1b_ring"):
        t_p = temp_bytes(schedule, 4)
        t_4p = temp_bytes(schedule, 16)
        assert t_4p <= t_p * 1.1, (schedule, t_p, t_4p)
    # And recompute-1F1B (default) must be under GPipe at the same
    # geometry (the residual ring deliberately trades memory for the
    # replay forward, so only the minimal-memory flavor makes this
    # claim).
    t_gpipe = temp_bytes("gpipe", 4)
    t_rec = temp_bytes("1f1b", 4)
    assert t_rec < t_gpipe, (t_rec, t_gpipe)


@pytest.mark.slow
def test_pp_1f1b_composes_with_grad_accum(rng):
    """Round-2 verdict: pipe x grad_accum. The custom_vjp makes the
    pipeline an ordinary differentiable op, so the step's grad-accum
    scan wraps it; the accumulated step must stay finite and train."""
    mesh = _mesh(data=2, pipe=4)
    model_cfg = dataclasses.replace(VIT_PP, vit_depth=4)
    optim_cfg = OptimConfig(learning_rate=0.01, grad_accum=2)
    model_def = get_model("vit_tiny")
    sh = step_lib.train_state_shardings(mesh, model_def, model_cfg, DATA,
                                        optim_cfg)
    state = step_lib.init_train_state(
        jax.random.key(0), model_def, model_cfg, DATA, optim_cfg, mesh,
        state_sharding=sh)
    train = step_lib.make_train_step(model_def, model_cfg, optim_cfg, mesh,
                                     state_sharding=sh)
    im = rng.normal(0.5, 0.25, (16, 24, 24, 3)).astype(np.float32)
    lb = rng.integers(0, 10, 16).astype(np.int32)
    im, lb = mesh_lib.shard_batch(mesh, im, lb)
    losses = []
    for _ in range(4):
        state, m = train(state, im, lb)
        losses.append(float(jax.device_get(m["loss"])))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
