// Native record loader: fixed-length record reading + bounded random-shuffle
// batching, off the Python GIL.
//
// TPU-native replacement for the reference's C++ input-queue runtime —
// string_input_producer -> FixedLengthRecordReader -> RandomShuffleQueue fed
// by queue-runner threads (cifar10cnn.py:82-90,223; SURVEY §2.2 "Queue
// runtime"). Same semantics, same roles:
//
//   * a reader thread streams 3073-byte records from the shard files,
//     reshuffling file order each epoch (string_input_producer's
//     shuffle=True default),
//   * a bounded shuffle pool of `capacity` records; dequeue picks uniformly
//     at random among buffered records once at least `min_after` are
//     present (RandomShuffleQueue semantics: min_after_dequeue=5000,
//     capacity=5000+3*batch in the reference),
//   * batch assembly decodes CHW uint8 -> HWC uint8 into a caller-provided
//     buffer (the transpose runs here, not in NumPy).
//
// C ABI for ctypes (no pybind11 in the image). One Loader per iterator;
// handles are opaque pointers. Thread-safety: one producer thread inside,
// any single consumer thread outside (the Python iterator).
//
// Build: `make -C runtime` -> librecordio.so (see runtime/Makefile);
// data/native.py auto-builds on first import if the .so is missing.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Loader {
  // immutable config
  std::vector<std::string> files;
  int64_t record_bytes = 0;   // full record: label byte(s) + C*H*W
  int64_t label_offset = 0;   // which label byte (CIFAR-100 fine = 1)
  int64_t label_bytes = 0;    // 1 (CIFAR-10) or 2 (CIFAR-100/imagenet_synth)
  int64_t label_wide = 0;     // 2 leading bytes are ONE big-endian uint16
  int64_t height = 0, width = 0, channels = 0;
  int64_t min_after = 0;      // min buffered records before dequeue
  int64_t capacity = 0;       // shuffle pool capacity

  // shuffle pool: flat record storage, swap-remove on dequeue
  std::vector<uint8_t> pool;        // capacity * record_bytes
  int64_t pool_count = 0;
  std::mutex mu;
  std::condition_variable can_produce, can_consume;
  std::atomic<bool> stop{false};
  std::string error;                 // sticky producer error, "" = ok
  bool producer_done = false;

  std::mt19937_64 rng;        // consumer-side (dequeue sampling) only
  uint64_t file_seed = 0;     // producer-side file-order stream, fixed at
                              // create time so the two threads never share
                              // an engine
  std::thread producer;

  ~Loader() {
    stop.store(true);
    can_produce.notify_all();
    can_consume.notify_all();  // wake any consumer blocked in next_batch
    if (producer.joinable()) producer.join();
  }
};

void producer_loop(Loader* L) {
  std::mt19937_64 file_rng(L->file_seed);  // file-order shuffle stream
  std::vector<size_t> order(L->files.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = i;
  std::vector<uint8_t> rec(L->record_bytes);

  while (!L->stop.load()) {  // endless epochs (string_input_producer loop)
    std::shuffle(order.begin(), order.end(), file_rng);
    size_t produced_this_epoch = 0;
    for (size_t fi : order) {
      if (L->stop.load()) return;
      FILE* f = std::fopen(L->files[fi].c_str(), "rb");
      if (!f) {
        std::lock_guard<std::mutex> g(L->mu);
        L->error = "cannot open " + L->files[fi];
        L->producer_done = true;
        L->can_consume.notify_all();
        return;
      }
      while (std::fread(rec.data(), 1, rec.size(), f) == rec.size()) {
        std::unique_lock<std::mutex> lk(L->mu);
        L->can_produce.wait(lk, [L] {
          return L->stop.load() || L->pool_count < L->capacity;
        });
        if (L->stop.load()) { std::fclose(f); return; }
        std::memcpy(L->pool.data() + L->pool_count * L->record_bytes,
                    rec.data(), L->record_bytes);
        ++L->pool_count;
        ++produced_this_epoch;
        lk.unlock();
        L->can_consume.notify_one();
      }
      // trailing partial record (corrupt tail) is dropped, matching the
      // fixed-length reader's behavior
      std::fclose(f);
    }
    if (produced_this_epoch == 0) {
      // Every file exists but holds zero complete records: spinning epochs
      // forever would starve the consumer silently. Surface it instead.
      std::lock_guard<std::mutex> g(L->mu);
      L->error = "no complete records in input files";
      L->producer_done = true;
      L->can_consume.notify_all();
      return;
    }
  }
}

// Decode one record from the pool into batch slot b: CHW uint8 -> HWC.
void decode_into(const Loader* L, const uint8_t* rec, uint8_t* images,
                 int32_t* labels, int64_t b) {
  labels[b] = L->label_wide
                  ? (static_cast<int32_t>(rec[0]) << 8) |
                        static_cast<int32_t>(rec[1])
                  : static_cast<int32_t>(rec[L->label_offset]);
  const uint8_t* img = rec + L->label_bytes;
  const int64_t H = L->height, W = L->width, C = L->channels;
  uint8_t* out = images + b * H * W * C;
  for (int64_t c = 0; c < C; ++c) {
    const uint8_t* plane = img + c * H * W;
    for (int64_t hw = 0; hw < H * W; ++hw) {
      out[hw * C + c] = plane[hw];
    }
  }
}

}  // namespace

extern "C" {

// Bump on ANY C-ABI change (argument added/removed/reordered, struct
// layout, semantics of a flag). native.py verifies this at load time:
// a prebuilt .so that survived a source change (mtime heuristics can
// miss, e.g. sources absent on a deploy host) must fail loudly instead
// of silently mis-binding arguments.
//   v2: recordio_create grew the label_wide argument (imagenet_synth
//       2-byte big-endian labels).
int64_t recordio_abi_version(void) { return 2; }

// paths: NUL-separated concatenation of n_files file paths.
// label_wide != 0: the 2 leading bytes are one big-endian uint16 label
// (imagenet_synth framing, class counts past 255).
void* recordio_create(const char* paths, int64_t n_files,
                      int64_t record_bytes, int64_t label_bytes,
                      int64_t label_offset, int64_t height, int64_t width,
                      int64_t channels, int64_t min_after, int64_t capacity,
                      uint64_t seed, int64_t label_wide) {
  if (n_files <= 0 || record_bytes <= 0 || capacity <= 0 ||
      min_after <= 0 || min_after > capacity ||
      label_bytes + height * width * channels != record_bytes ||
      (label_wide && label_bytes != 2)) {
    return nullptr;
  }
  Loader* L = new Loader();
  const char* p = paths;
  for (int64_t i = 0; i < n_files; ++i) {
    L->files.emplace_back(p);
    p += L->files.back().size() + 1;
  }
  L->record_bytes = record_bytes;
  L->label_bytes = label_bytes;
  L->label_offset = label_offset;
  L->label_wide = label_wide;
  L->height = height;
  L->width = width;
  L->channels = channels;
  L->min_after = min_after;
  L->capacity = capacity;
  L->pool.resize(capacity * record_bytes);
  L->rng.seed(seed);
  L->file_seed = L->rng();  // drawn before the producer thread exists
  L->producer = std::thread(producer_loop, L);
  return L;
}

// Fill a [batch, H, W, C] uint8 image buffer + [batch] int32 labels.
// Returns 0 on success, -1 on producer error (recordio_error has details).
int recordio_next_batch(void* handle, int64_t batch, uint8_t* images,
                        int32_t* labels) {
  Loader* L = static_cast<Loader*>(handle);
  std::uniform_int_distribution<int64_t> dist;
  for (int64_t b = 0; b < batch; ++b) {
    std::unique_lock<std::mutex> lk(L->mu);
    L->can_consume.wait(lk, [L] {
      return L->pool_count >= L->min_after || L->producer_done ||
             L->stop.load();
    });
    if (L->stop.load()) return -1;      // destroy() raced a blocked consumer
    if (!L->error.empty()) return -1;
    if (L->pool_count == 0) return -1;  // producer died with empty pool
    int64_t idx = dist(L->rng,
                       decltype(dist)::param_type(0, L->pool_count - 1));
    decode_into(L, L->pool.data() + idx * L->record_bytes, images, labels,
                b);
    // swap-remove: O(1) dequeue, uniform over the pool
    --L->pool_count;
    if (idx != L->pool_count) {
      std::memcpy(L->pool.data() + idx * L->record_bytes,
                  L->pool.data() + L->pool_count * L->record_bytes,
                  L->record_bytes);
    }
    lk.unlock();
    L->can_produce.notify_one();
  }
  return 0;
}

const char* recordio_error(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> g(L->mu);
  return L->error.c_str();  // valid until destroy
}

int64_t recordio_buffered(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> g(L->mu);
  return L->pool_count;
}

void recordio_destroy(void* handle) {
  delete static_cast<Loader*>(handle);
}

}  // extern "C"
