#!/usr/bin/env python
"""Drop-in entrypoint named after the reference's single script.

Same flags (``--ps_hosts --worker_hosts --job_name --task_index --data_dir
--log_dir``), same defaults, same console output format — but running the
TPU-native SPMD framework instead of a TF1 parameter-server cluster.
"""

import sys

from dml_cnn_cifar10_tpu.cli.main import main

if __name__ == "__main__":
    sys.exit(main())
