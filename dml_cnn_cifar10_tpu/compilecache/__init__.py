"""Persistent compilation cache + AOT warm-start (docs/COMPILECACHE.md).

Every compile seam in the framework — the train step/chunk, state init,
the eval steps, the serving buckets, the bench/FLOPs probes — can route
through one disk-backed, fail-open executable cache, so supervisor
restarts, elastic world-shrink re-entries, and serve bucket warmups pay
XLA's retrace+compile cost once per program instead of once per process.
"""

from dml_cnn_cifar10_tpu.compilecache.cache import (CachedFunction,
                                                    CompileCache,
                                                    arm_native_cache,
                                                    mesh_context,
                                                    wrap)

__all__ = ["CompileCache", "CachedFunction", "arm_native_cache",
           "mesh_context", "wrap"]
